#!/usr/bin/env python
"""TreeLSTM sentiment example — constituency-tree sentiment classification
(reference ``example/treeLSTMSentiment/Train.scala:33`` +
``TreeSentiment.scala:25``: GloVe embeddings -> BinaryTreeLSTM ->
per-node TimeDistributed classifier, trained with Adagrad under
TimeDistributedCriterion).

Data: each sample is (token ids [L], tree [N, 3]) with a sentiment class
per tree node (SST labels every constituent).  Tree rows are
(left, right, leaf) 1-based node indices, children before parents — the
repo's ``BinaryTreeLSTM`` scan order.  Real SST data (prepared per the
reference's ``fetch_and_preprocess.py``) can be dropped in; without it
the example synthesizes a word-polarity corpus so it always runs.

Run: ``python examples/treelstm_sentiment.py [-b 16] [-e 4]``
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

PAD, OOV, INDEX_FROM = 1, 2, 3  # the reference's paddingValue/oovChar


def build_model(vocab_size, embed_dim, hidden, classes, p=0.5,
                embeddings=None):
    """``TreeSentiment.scala:25`` re-built on the repo's layer family."""
    import bigdl_tpu.nn as nn

    embedding = nn.LookupTable(vocab_size, embed_dim)
    if embeddings is not None:
        embedding.weight = np.asarray(embeddings, np.float32)
    embedding.set_scale_w(2.0)

    return nn.Sequential(
        nn.ParallelTable().add(embedding).add(nn.Identity()),
        nn.BinaryTreeLSTM(embed_dim, hidden),
        nn.TimeDistributed(nn.Sequential(
            nn.Dropout(p), nn.Linear(hidden, classes), nn.LogSoftMax())),
    )


def synthetic_corpus(n=256, vocab=50, leaves=8, seed=0):
    """Word-polarity sentences under random binary trees: each word
    INDEX_FROM.. is positive (even id) or negative (odd id); every node is
    labeled by its subtree's majority polarity — the SST per-constituent
    labeling scheme at toy scale."""
    rng = np.random.default_rng(seed)
    samples = []
    n_nodes = 2 * leaves - 1
    for _ in range(n):
        tokens = rng.integers(INDEX_FROM, vocab, leaves)
        polarity = np.where(tokens % 2 == 0, 1, -1)
        # random binary tree: combine two subtree roots until one remains
        tree = np.zeros((n_nodes, 3), np.int32)
        score = {}
        for i in range(leaves):
            tree[i] = (0, 0, i + 1)
            score[i + 1] = int(polarity[i])
        roots = list(range(1, leaves + 1))
        nxt = leaves + 1
        while len(roots) > 1:
            i = rng.integers(0, len(roots) - 1)
            l, r = roots.pop(i), roots.pop(i)
            tree[nxt - 1] = (l, r, 0)
            score[nxt] = score[l] + score[r]
            roots.append(nxt)
            nxt += 1
        labels = np.array([0 if score[i + 1] <= 0 else 1
                           for i in range(n_nodes)], np.int64)
        samples.append((tokens.astype(np.int64), tree, labels))
    return samples


def root_accuracy(model, samples, batch_size=32):
    """Root-node accuracy (TreeNNAccuracy's job; the repo's trees list the
    root LAST, so index -1)."""
    import jax.numpy as jnp

    model.evaluate()
    hits = total = 0
    for i in range(0, len(samples), batch_size):
        chunk = samples[i:i + batch_size]
        toks = jnp.asarray(np.stack([s[0] for s in chunk]))
        trees = jnp.asarray(np.stack([s[1] for s in chunk]))
        out = np.asarray(model.forward([toks, trees]))
        pred = out[:, -1, :].argmax(-1)
        hits += int((pred == np.stack([s[2] for s in chunk])[:, -1]).sum())
        total += len(chunk)
    model.training_mode()
    return hits / total


def main(argv=None):
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("-b", "--batchSize", type=int, default=16)
    p.add_argument("-e", "--maxEpoch", type=int, default=4)
    p.add_argument("--hiddenSize", type=int, default=32)
    p.add_argument("--embedDim", type=int, default=16)
    p.add_argument("--learningRate", type=float, default=0.1)
    p.add_argument("--dropout", type=float, default=0.2)
    args = p.parse_args(argv)

    from bigdl_tpu.utils.engine import honor_platform_request

    honor_platform_request()

    from bigdl_tpu.utils.logging import redirect_thirdparty_logs

    redirect_thirdparty_logs()

    import bigdl_tpu.nn as nn
    import bigdl_tpu.optim as optim
    from bigdl_tpu.dataset.sample import Sample
    from bigdl_tpu.utils.rng import RNG

    RNG.set_seed(5)
    vocab, classes = 50, 2
    data = synthetic_corpus(n=256, vocab=vocab)
    train = [Sample([t, tr], lb) for t, tr, lb in data[:192]]
    dev = data[192:]

    model = build_model(vocab, args.embedDim, args.hiddenSize, classes,
                        p=args.dropout)
    crit = nn.TimeDistributedCriterion(nn.ClassNLLCriterion(),
                                       size_average=True)
    before = root_accuracy(model, dev)
    o = optim.LocalOptimizer(model, train, crit,
                             batch_size=args.batchSize,
                             end_trigger=optim.Trigger.max_epoch(args.maxEpoch))
    o.set_optim_method(optim.Adagrad(learning_rate=args.learningRate))
    o.optimize()
    after = root_accuracy(model, dev)
    print(f"dev root accuracy: {before:.3f} -> {after:.3f} "
          f"({len(train)} train / {len(dev)} dev trees)")
    return before, after


if __name__ == "__main__":
    main()
