#!/usr/bin/env python
"""ML-pipeline LeNet example — train LeNet-5 on MNIST through the
DLClassifier estimator/transformer contract (reference
``example/MLPipeline/DLClassifierLeNet.scala:40``: a DLClassifier fit
over a DataFrame of (feature, label) rows, then transform over the
validation split).

The sklearn-style analogue: ``DLClassifier.fit(X, y)`` over normalized
MNIST pixels, ``transform(X_val)`` for predictions.  Real IDX files are
used when ``--folder`` has them; otherwise the loader synthesizes data so
the example always runs.

Run: ``python examples/mlpipeline_lenet.py [--folder mnist/] [-b 64]``
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


def prepare(split: str, folder=None, limit=2048):
    """IDX files -> normalized float rows, like the reference's
    BytesToGreyImg -> GreyImgNormalizer chain."""
    from bigdl_tpu.dataset.datasets import (TRAIN_MEAN, TRAIN_STD,
                                            load_mnist)

    x, y = load_mnist(folder, split=split, synthetic_size=limit)
    x = (x.reshape(len(x), -1).astype(np.float32) - TRAIN_MEAN) / TRAIN_STD
    return x[:limit], y[:limit]


def main(argv=None):
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("-f", "--folder", default=None,
                   help="MNIST IDX folder (synthetic data when absent)")
    p.add_argument("-b", "--batchSize", type=int, default=64)
    p.add_argument("-e", "--maxEpoch", type=int, default=4)
    p.add_argument("--limit", type=int, default=2048,
                   help="cap on rows (keeps the example fast)")
    args = p.parse_args(argv)

    from bigdl_tpu.utils.engine import honor_platform_request

    honor_platform_request()

    # the reference example's first two lines: log redirection on
    from bigdl_tpu.utils.logging import redirect_thirdparty_logs

    redirect_thirdparty_logs()

    import bigdl_tpu.nn as nn
    import bigdl_tpu.optim as optim
    from bigdl_tpu.models.lenet import build_lenet5
    from bigdl_tpu.pipeline import DLClassifier
    from bigdl_tpu.utils.rng import RNG

    RNG.set_seed(1)
    if args.folder:
        x_train, y_train = prepare("train", args.folder, args.limit)
        x_val, y_val = prepare("test", args.folder, args.limit)
    else:
        # synthetic fallback draws disjoint class patterns per split, so
        # hold validation out of the train split instead
        x, y = prepare("train", None, args.limit)
        cut = max(len(x) // 4, 1)
        x_train, y_train = x[cut:], y[cut:]
        x_val, y_val = x[:cut], y[:cut]

    estimator = DLClassifier(build_lenet5(10), nn.ClassNLLCriterion(),
                             feature_size=(28, 28)) \
        .set_batch_size(args.batchSize) \
        .set_max_epoch(args.maxEpoch) \
        .set_optim_method(optim.Adam(learning_rate=1e-3))
    transformer = estimator.fit(x_train, y_train)

    pred = transformer.transform(x_val)
    acc = float((pred == y_val).mean())
    for i in range(min(10, len(pred))):  # transformed.show() analogue
        print(f"label={y_val[i]} predict={pred[i]}")
    print(f"validation accuracy: {acc:.4f} over {len(y_val)} rows")
    return acc


if __name__ == "__main__":
    main()
