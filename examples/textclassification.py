#!/usr/bin/env python
"""Text classification example — CNN over word embeddings on the
20-newsgroups layout (reference ``example/textclassification/`` +
``example/utils/TextClassifier.scala``, SURVEY §2.13).

Pipeline (mirroring ``TextClassifier.scala``): tokenize -> build the
vocabulary -> embed each document into a ``[embed_dim, 1, seq_len]`` map
(GloVe vectors when ``--glove`` points at ``glove.6B.<dim>d.txt``;
deterministic random vectors otherwise, this image has no egress) ->
the 5x1 conv/pool stack (``TextClassifier.scala:171-194``) -> Optimizer
with ClassNLLCriterion -> Top1Accuracy validation.

Run: ``python examples/textclassification.py --max-epoch 2``
"""

import argparse
import os
import re
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


def tokenize(text):
    """Lowercase word split (SimpleTokenizer.scala equivalent)."""
    return re.findall(r"[a-z']+", text.lower())


def build_word_index(texts, max_words):
    """Most-frequent-first vocabulary; index 0 is the padding slot."""
    from collections import Counter

    counts = Counter(w for t in texts for w in tokenize(t))
    return {w: i + 1 for i, (w, _) in
            enumerate(counts.most_common(max_words))}


def load_embeddings(word_index, embed_dim, glove_path=None):
    """[vocab+1, embed_dim] embedding matrix: GloVe rows when available,
    seeded random otherwise; row 0 (padding) stays zero."""
    rng = np.random.default_rng(42)
    table = rng.normal(0, 0.4, (len(word_index) + 1, embed_dim)) \
        .astype(np.float32)
    table[0] = 0.0
    if glove_path and os.path.exists(glove_path):
        with open(glove_path, errors="ignore") as f:
            for line in f:
                parts = line.rstrip().split(" ")
                if parts[0] in word_index and len(parts) == embed_dim + 1:
                    table[word_index[parts[0]]] = np.asarray(
                        parts[1:], np.float32)
    return table


def vectorize(text, word_index, table, seq_len):
    """One document -> [embed_dim, 1, seq_len] (the reference's
    Reshape(embeddingDim, 1, maxSequenceLength) input layout)."""
    ids = [word_index.get(w, 0) for w in tokenize(text)][:seq_len]
    ids = ids + [0] * (seq_len - len(ids))
    return table[np.asarray(ids)].T[:, None, :]  # (D, 1, S)


def build_model(class_num, embed_dim, seq_len):
    """The conv stack of ``TextClassifier.scala:171-194`` (pool sizes
    scaled to the configured sequence length)."""
    import bigdl_tpu.nn as nn

    # spatial extent left before the last (global) pool: conv5 -> pool5
    # -> conv5 -> pool5 -> conv5 (the reference's 35 for seq_len 1000)
    final = ((seq_len - 4) // 5 - 4) // 5 - 4
    if final < 1:
        raise ValueError(f"seq_len {seq_len} too short for the conv stack")
    return nn.Sequential(
        nn.SpatialConvolution(embed_dim, 128, 5, 1),
        nn.ReLU(),
        nn.SpatialMaxPooling(5, 1, 5, 1),
        nn.SpatialConvolution(128, 128, 5, 1),
        nn.ReLU(),
        nn.SpatialMaxPooling(5, 1, 5, 1),
        nn.SpatialConvolution(128, 128, 5, 1),
        nn.ReLU(),
        nn.SpatialMaxPooling(final, 1, final, 1),
        nn.Reshape([128]),
        nn.Linear(128, 100),
        nn.Linear(100, class_num),
        nn.LogSoftMax(),
    )


def main(argv=None):
    from bigdl_tpu.utils.engine import honor_platform_request

    honor_platform_request()  # a user-pinned JAX_PLATFORMS must beat the plugin

    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--data-dir", help="20-newsgroups directory "
                   "(one subdir per group); synthetic when absent")
    p.add_argument("--glove", help="path to glove.6B.<dim>d.txt")
    p.add_argument("--embed-dim", type=int, default=50)
    p.add_argument("--seq-len", type=int, default=500)
    p.add_argument("--max-words", type=int, default=5000)
    p.add_argument("--batch-size", type=int, default=32)
    p.add_argument("--max-epoch", type=int, default=3)
    p.add_argument("--learning-rate", type=float, default=0.05)
    p.add_argument("--synthetic-size", type=int, default=400)
    args = p.parse_args(argv)

    import bigdl_tpu.nn as nn
    import bigdl_tpu.optim as optim
    from bigdl_tpu.dataset.datasets import load_news20
    from bigdl_tpu.dataset.sample import Sample
    from bigdl_tpu.utils.rng import RNG

    RNG.set_seed(1)

    pairs = load_news20(args.data_dir, synthetic_size=args.synthetic_size)
    texts = [t for t, _ in pairs]
    labels = [l for _, l in pairs]
    class_num = max(labels) + 1
    word_index = build_word_index(texts, args.max_words)
    table = load_embeddings(word_index, args.embed_dim, args.glove)

    samples = [Sample(vectorize(t, word_index, table, args.seq_len),
                      np.int64(l)) for t, l in pairs]
    # real 20-newsgroups data arrives grouped by class directory — a
    # seeded shuffle keeps every class on both sides of the split
    order = np.random.default_rng(7).permutation(len(samples))
    samples = [samples[i] for i in order]
    split = int(0.8 * len(samples))
    train, val = samples[:split], samples[split:]

    model = build_model(class_num, args.embed_dim, args.seq_len)
    o = optim.Optimizer(model=model, dataset=train,
                        criterion=nn.ClassNLLCriterion(),
                        batch_size=args.batch_size,
                        end_trigger=optim.Trigger.max_epoch(args.max_epoch))
    o.set_optim_method(optim.SGD(learning_rate=args.learning_rate,
                                 momentum=0.9))
    o.set_validation(optim.Trigger.every_epoch(), val,
                     [optim.Top1Accuracy()], batch_size=args.batch_size)
    trained = o.optimize()

    res = optim.Evaluator(trained).evaluate(val, [optim.Top1Accuracy()])
    acc = res[0][0].result()[0]
    print(f"[textclassification] validation accuracy: {acc:.4f}")
    return trained, word_index, table, acc


if __name__ == "__main__":
    main()
