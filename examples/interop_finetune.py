#!/usr/bin/env python
"""Interop chain example — load a Caffe model, fine-tune the head on new
classes, fold BatchNorm for serving, and save both a BTPU checkpoint and
a Caffe round-trip (reference capability chain:
``example/loadmodel/LoadModel.scala`` + ``utils/caffe/CaffePersister``,
SURVEY §2.9/§2.13).

The script is self-contained: it first EMITS a small "pretrained" Caffe
model with the repo's own persister (standing in for a downloaded
caffemodel), then walks the chain a user migrating from the reference
would: import -> freeze trunk -> replace head -> train -> optimize for
serving -> export.

Run: ``python examples/interop_finetune.py``
"""

import os
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


def make_pretrained_caffe(tmp):
    """A tiny conv trunk saved as prototxt+caffemodel (the 'pretrained
    zoo model'; classifier heads are dropped at fine-tune time anyway)."""
    import bigdl_tpu.nn as nn
    from bigdl_tpu.utils.caffe_persister import save_caffe
    from bigdl_tpu.utils.rng import RNG

    RNG.set_seed(0)
    trunk = nn.Sequential(
        nn.SpatialConvolution(1, 8, 3, 3, 1, 1, 1, 1).set_name("conv1"),
        nn.ReLU(True),
        nn.SpatialMaxPooling(2, 2).set_name("pool1"),
        nn.SpatialConvolution(8, 16, 3, 3, 1, 1, 1, 1).set_name("conv2"),
        nn.ReLU(True),
        nn.SpatialMaxPooling(2, 2).set_name("pool2"),
    )
    proto, caffemodel = os.path.join(tmp, "net.prototxt"), os.path.join(tmp, "net.caffemodel")
    save_caffe(trunk, proto, caffemodel, input_shapes=(1, 1, 16, 16))
    return proto, caffemodel


def main():
    from bigdl_tpu.utils.engine import honor_platform_request

    honor_platform_request()  # a user-pinned JAX_PLATFORMS must beat the plugin

    import jax.numpy as jnp

    import bigdl_tpu.nn as nn
    import bigdl_tpu.optim as optim
    from bigdl_tpu.dataset.sample import Sample
    from bigdl_tpu.nn.fuse import fold_batchnorm
    from bigdl_tpu.utils import serializer
    from bigdl_tpu.utils.caffe import CaffeLoader

    tmp = tempfile.mkdtemp()
    proto, caffemodel = make_pretrained_caffe(tmp)

    # 1. import the pretrained trunk (utils/caffe.py wire-level loader)
    trunk, _ins, _outs = CaffeLoader(proto, caffemodel).load()
    n_params = len(list(trunk.named_parameters()))
    print(f"imported Caffe trunk: {n_params} param tensors")

    # 2. freeze the trunk, graft a fresh conv+BN head for 3 new classes
    trunk.freeze()
    finetune = nn.Sequential(
        trunk,
        nn.SpatialConvolution(16, 32, 1, 1, with_bias=False)
        .set_name("head_conv"),
        nn.SpatialBatchNormalization(32),
        nn.ReLU(True),
        nn.View(32 * 4 * 4),
        nn.Linear(512, 3).set_name("cls"),
        nn.LogSoftMax())

    # 3. fine-tune on a synthetic 3-class task: class-dependent intensity
    rng = np.random.RandomState(0)
    ys = rng.randint(0, 3, 240)
    xs = (rng.randn(240, 1, 16, 16) * 0.5
          + (ys - 1)[:, None, None, None]).astype(np.float32)
    samples = [Sample(x, np.array(y)) for x, y in zip(xs, ys)]
    opt = optim.Optimizer(finetune, samples, nn.ClassNLLCriterion(),
                          batch_size=48,
                          end_trigger=optim.Trigger.max_epoch(30))
    opt.set_optim_method(optim.SGD(learning_rate=0.1, momentum=0.9))
    trained = opt.optimize()
    out = np.asarray(trained.evaluate().forward(jnp.asarray(xs[:64])))
    acc = (out.argmax(1) == ys[:64]).mean()
    print(f"fine-tuned accuracy on train slice: {acc:.2f}")
    assert acc > 0.9, "fine-tune failed to learn the synthetic task"

    # 4. serving-time graph optimization: fold the BN into head_conv
    n_before = len(trained.layers)
    fold_batchnorm(trained)
    print(f"fold_batchnorm: {n_before} -> {len(trained.layers)} layers")
    assert len(trained.layers) == n_before - 1

    # 5. persist: BTPU checkpoint (the native no-code-exec format)
    ckpt = os.path.join(tmp, "finetuned.btpu")
    serializer.save_module(trained, ckpt, overwrite=True)
    reloaded = serializer.load_module(ckpt)
    np.testing.assert_allclose(
        np.asarray(reloaded.evaluate().forward(jnp.asarray(xs[:8]))),
        np.asarray(trained.evaluate().forward(jnp.asarray(xs[:8]))),
        rtol=1e-5, atol=1e-6)
    print(f"BTPU round-trip OK -> {ckpt}")

    # 6. export the folded serving model back to Caffe and reload it —
    # the full CaffePersister round-trip on a model we trained here
    from bigdl_tpu.utils.caffe_persister import save_caffe

    out_proto = os.path.join(tmp, "served.prototxt")
    out_cm = os.path.join(tmp, "served.caffemodel")
    # export the trained+folded head conv (the part Caffe can express)
    serving = nn.Sequential(trained.get(1), nn.ReLU(True))
    save_caffe(serving, out_proto, out_cm, input_shapes=(1, 16, 4, 4))
    back, _, _ = CaffeLoader(out_proto, out_cm).load()
    probe = jnp.asarray(rng.randn(4, 16, 4, 4).astype(np.float32))
    np.testing.assert_allclose(
        np.asarray(back.evaluate().forward(probe)),
        np.asarray(serving.evaluate().forward(probe)),
        rtol=1e-4, atol=1e-5)
    print(f"Caffe export round-trip OK -> {out_proto}")


if __name__ == "__main__":
    main()
