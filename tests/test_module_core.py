"""Core module-system tests: registration, forward/backward-vs-autodiff,
functional_call purity, flattened parameters, freeze/scale semantics.
Oracle: torch (CPU) where a reference formula exists."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
import torch

import bigdl_tpu.nn as nn
from bigdl_tpu.nn.module import functional_call, state_dict, load_state_dict


def test_linear_forward_matches_torch():
    layer = nn.Linear(5, 3)
    tl = torch.nn.Linear(5, 3)
    with torch.no_grad():
        tl.weight.copy_(torch.tensor(np.asarray(layer.weight)))
        tl.bias.copy_(torch.tensor(np.asarray(layer.bias)))
    x = np.random.randn(4, 5).astype(np.float32)
    out = layer.forward(jnp.asarray(x))
    ref = tl(torch.tensor(x)).detach().numpy()
    np.testing.assert_allclose(np.asarray(out), ref, rtol=1e-5, atol=1e-5)


def test_backward_matches_torch_grads():
    layer = nn.Linear(5, 3)
    tl = torch.nn.Linear(5, 3)
    with torch.no_grad():
        tl.weight.copy_(torch.tensor(np.asarray(layer.weight)))
        tl.bias.copy_(torch.tensor(np.asarray(layer.bias)))
    x = np.random.randn(4, 5).astype(np.float32)
    g = np.random.randn(4, 3).astype(np.float32)

    layer.zero_grad_parameters()
    layer.forward(jnp.asarray(x))
    grad_in = layer.backward(jnp.asarray(x), jnp.asarray(g))

    tx = torch.tensor(x, requires_grad=True)
    tl(tx).backward(torch.tensor(g))
    np.testing.assert_allclose(np.asarray(grad_in), tx.grad.numpy(), rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(
        np.asarray(layer._grads["weight"]), tl.weight.grad.numpy(), rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(
        np.asarray(layer._grads["bias"]), tl.bias.grad.numpy(), rtol=1e-5, atol=1e-5)


def test_sequential_chain_and_naming():
    model = nn.Sequential(nn.Linear(4, 8), nn.ReLU(), nn.Linear(8, 2))
    x = jnp.ones((3, 4))
    out = model.forward(x)
    assert out.shape == (3, 2)
    params = dict(model.named_parameters())
    assert set(params) == {"0.weight", "0.bias", "2.weight", "2.bias"}


def test_functional_call_is_pure():
    model = nn.Sequential(nn.Linear(4, 4), nn.Tanh())
    x = jnp.ones((2, 4))
    eager = model.forward(x)
    params = state_dict(model)
    before = {k: np.asarray(v) for k, v in params.items()}

    @jax.jit
    def f(p, x):
        out, new_p = functional_call(model, p, x)
        return out

    out = f(params, x)
    np.testing.assert_allclose(np.asarray(out), np.asarray(eager), rtol=1e-6)
    after = state_dict(model)
    for k in before:
        np.testing.assert_array_equal(before[k], np.asarray(after[k]))
        assert isinstance(after[k], jax.Array) and not isinstance(
            after[k], jax.core.Tracer)


def test_functional_call_grad():
    model = nn.Linear(3, 1, with_bias=False)
    x = jnp.ones((2, 3))

    def loss(p):
        out, _ = functional_call(model, p, x)
        return jnp.sum(out)

    g = jax.grad(loss)(state_dict(model))
    np.testing.assert_allclose(np.asarray(g["weight"]), np.full((1, 3), 2.0), rtol=1e-6)


def test_get_parameters_flat_roundtrip():
    model = nn.Sequential(nn.Linear(4, 3), nn.Linear(3, 2))
    flat, _ = model.get_parameters()
    assert flat.shape == (4 * 3 + 3 + 3 * 2 + 2,)
    model.set_flat_parameters(flat * 2.0)
    flat2, _ = model.get_parameters()
    np.testing.assert_allclose(np.asarray(flat2), np.asarray(flat) * 2.0, rtol=1e-6)


def test_freeze_blocks_grad_accumulation():
    model = nn.Sequential(nn.Linear(3, 3), nn.Linear(3, 2))
    model.get(0).freeze()
    x = jnp.ones((2, 3))
    model.zero_grad_parameters()
    model.forward(x)
    model.backward(x, jnp.ones((2, 2)))
    assert "weight" not in model.get(0)._grads
    assert "weight" in model.get(1)._grads


def test_scale_w_applied_to_grads():
    layer = nn.Linear(3, 2).set_scale_w(0.5)
    x = jnp.ones((2, 3))
    layer.zero_grad_parameters()
    layer.forward(x)
    layer.backward(x, jnp.ones((2, 2)))
    base = nn.Linear(3, 2)
    load_state_dict(base, state_dict(layer))
    base.zero_grad_parameters()
    base.forward(x)
    base.backward(x, jnp.ones((2, 2)))
    np.testing.assert_allclose(
        np.asarray(layer._grads["weight"]),
        0.5 * np.asarray(base._grads["weight"]), rtol=1e-6)
    np.testing.assert_allclose(
        np.asarray(layer._grads["bias"]), np.asarray(base._grads["bias"]), rtol=1e-6)


def test_train_eval_modes_and_clone():
    model = nn.Sequential(nn.Linear(2, 2), nn.ReLU())
    model.evaluate()
    assert not model.is_training()
    clone = model.clone_module()
    clone.train()
    assert clone.is_training() and not model.is_training()
    x = jnp.ones((1, 2))
    np.testing.assert_allclose(
        np.asarray(model.forward(x)), np.asarray(clone.forward(x)), rtol=1e-6)


def test_update_parameters_sgd_step():
    layer = nn.Linear(2, 2, with_bias=False)
    w0 = np.asarray(layer.weight)
    x = jnp.ones((1, 2))
    layer.zero_grad_parameters()
    layer.forward(x)
    layer.backward(x, jnp.ones((1, 2)))
    layer.update_parameters(0.1)
    np.testing.assert_allclose(
        np.asarray(layer.weight), w0 - 0.1 * np.asarray(layer._grads["weight"]), rtol=1e-6)


def test_layer_exception_wraps_path():
    layer = nn.Linear(3, 2).set_name("clf")
    with pytest.raises(nn.LayerException, match="clf"):
        layer.forward(jnp.ones((2, 4)))  # wrong input size


def test_eager_backward_memoized_no_retrace():
    """Second backward() with same shapes must reuse the compiled vjp
    (round-1 weak item: O(2x forward) retrace per call)."""
    import time as _time

    model = nn.Sequential(nn.Linear(8, 32), nn.Tanh(), nn.Linear(32, 4),
                          nn.LogSoftMax())
    x = jnp.asarray(np.random.RandomState(0).randn(16, 8), jnp.float32)
    out = model.forward(x)
    g = jnp.ones_like(out)
    model.backward(x, g)
    cache = model.__dict__["_bwd_cache"]
    assert len(cache) == 1
    fn = next(iter(cache.values()))
    n_traces = fn._cache_size()
    for _ in range(20):
        model.zero_grad_parameters()
        model.backward(x, g)
    assert fn._cache_size() == n_traces  # NOT retraced
    # train/evaluate flips the key (cache keeps one live trace)
    model.evaluate()
    model.backward(x, g)
    assert len(cache) == 1 and next(iter(cache.values())) is not fn
    # shape change reuses the same key; jit handles the new shape
    x2 = jnp.asarray(np.random.RandomState(1).randn(4, 8), jnp.float32)
    model.forward(x2)
    model.backward(x2, jnp.ones((4, 4), jnp.float32))
    assert len(cache) == 1


def test_eager_backward_cache_invalidation_and_serialization():
    """Hyperparameter edits and buffer updates must not replay stale
    traces; a used eager model must still serialize (BTPU)."""
    bn_model = nn.Sequential(nn.Linear(4, 4),
                             nn.BatchNormalization(4))
    x = jnp.asarray(np.random.RandomState(0).randn(8, 4), jnp.float32)
    # train once (advances running stats), then eval-mode backward
    bn_model.forward(x)
    bn_model.evaluate()
    g1 = np.asarray(bn_model.backward(x, jnp.ones((8, 4), jnp.float32)))
    # advance the running stats and backward again in eval mode: the
    # gradient must REFLECT the new stats (buffers are traced args)
    bn = bn_model.get(1)
    bn.running_var = jnp.asarray(bn.running_var) * 9.0
    g2 = np.asarray(bn_model.backward(x, jnp.ones((8, 4), jnp.float32)))
    assert not np.allclose(g1, g2), "stale buffer baked into cached trace"

    # dropout p edit invalidates via _hyper_version
    dmodel = nn.Sequential(nn.Linear(4, 4), nn.Dropout(0.0))
    dmodel.forward(x)
    dmodel.backward(x, jnp.ones((8, 4), jnp.float32))
    key0 = next(iter(dmodel.__dict__["_bwd_cache"]))
    dmodel.get(1).set_p(0.9)
    dmodel.forward(x)
    dmodel.backward(x, jnp.ones((8, 4), jnp.float32))
    assert next(iter(dmodel.__dict__["_bwd_cache"])) != key0

    # serialization after eager use (the _bwd_cache must be skipped)
    from bigdl_tpu.utils.module_format import dumps, loads

    blob = dumps(bn_model)
    back = loads(blob)
    y0 = np.asarray(bn_model.forward(x))
    np.testing.assert_allclose(np.asarray(back.evaluate().forward(x)), y0,
                               rtol=1e-5, atol=1e-6)


def test_eager_backward_fresh_ambient_rng_key():
    """A per-step rng_context key must flow into the memoized backward as
    a traced argument — never baked into the cached trace."""
    from bigdl_tpu.utils.rng import rng_context

    model = nn.Sequential(nn.Linear(6, 6), nn.Dropout(0.5))
    x = jnp.asarray(np.random.RandomState(0).randn(16, 6), jnp.float32)
    grads = []
    for step in range(2):
        with rng_context(jax.random.key(step)):
            model.forward(x)
            g = model.backward(x, jnp.ones((16, 6), jnp.float32))
        grads.append(np.asarray(g))
    assert len(model.__dict__["_bwd_cache"]) == 1  # cache reused...
    assert not np.allclose(grads[0], grads[1])     # ...but keys differ
