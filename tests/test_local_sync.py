"""Straggler-tolerant local SGD (ISSUE 20,
``bigdl_tpu/parallel/local_sync.py`` + ``parameter_sync='local'`` in
``parallel/train_step.py``; docs/fault_tolerance.md "Straggler
tolerance").

Three layers:

* the pure :class:`StalenessBarrier` state machine — behind-by-<S
  continues on stale contributions, behind-by-S sheds, inactive
  statuses and excused peers never delay anyone;
* the :class:`LocalSyncDriver` protocol against a fake cluster — the
  averaging cadence, the grace window charged to ``straggler`` badput,
  the hard-shed marker + excuse, the p0 soft-shed carve-out, and the
  victim's status-then-exit ordering;
* the compiled-program claims — the local-mode scan contains ZERO
  cross-island collectives, the amortized averaging traffic beats the
  synchronous all-reduce by >= 0.8·H, and the synchronous path is
  byte-identical whether or not the local-SGD knobs are set.

The live multi-process shed e2e rides tests/test_multihost.py
(``test_two_process_local_sgd_sheds_straggler``).
"""

import json
import threading
import time

import jax
import numpy as np
import pytest

import bigdl_tpu.nn as nn
import bigdl_tpu.optim as optim
from bigdl_tpu import telemetry
from bigdl_tpu.parallel import local_sync
from bigdl_tpu.parallel.local_sync import (BarrierDecision, LocalSyncDriver,
                                           StalenessBarrier, _weighted_mean)
from bigdl_tpu.parallel.mesh import make_mesh
from bigdl_tpu.parallel.train_step import TrainStep
from bigdl_tpu.utils.config import BigDLConfig, set_config


@pytest.fixture(autouse=True)
def _fresh():
    set_config(None)
    yield
    telemetry.end_run()
    set_config(None)


def _instants(sink, name):
    return [e for e in sink.events
            if e.get("kind") == "event" and e.get("name") == name]


# -- the pure staleness barrier ----------------------------------------------
def test_barrier_under_bound_continues():
    """Peers behind by < S never delay the round: survivors average
    their latest (stale) contribution — the SSP contract."""
    b = StalenessBarrier(0, 4, stale=3)
    d = b.decide(5, {1: 3, 2: 5, 3: 4})
    assert isinstance(d, BarrierDecision)
    assert d.ready and d.laggards == [] and d.max_lag == 2


def test_barrier_at_bound_sheds():
    b = StalenessBarrier(0, 3, stale=2)
    d = b.decide(6, {1: 5, 2: 3})
    assert not d.ready and d.laggards == [2] and d.max_lag == 3
    # a peer that never published counts from round 0
    d2 = b.decide(2, {})
    assert sorted(d2.laggards) == [1, 2] and d2.max_lag == 2


def test_barrier_skips_inactive_and_excused():
    """done/preempted/shed/failed peers left on purpose (or are the
    watchdog's problem); excused peers were already shed by US.
    Neither is waited for, neither is shed again."""
    b = StalenessBarrier(0, 5, stale=1)
    statuses = {1: "done", 2: "shed", 3: "preempted"}
    assert b.decide(9, {4: 9}, statuses=statuses).ready
    d = b.decide(9, {}, statuses=statuses, excused=(4,))
    assert d.ready and d.max_lag == 0
    d = b.decide(9, {}, statuses={1: "failed", 2: "running"},
                 excused=(3, 4))
    assert d.laggards == [2]


def test_barrier_rejects_bad_bound():
    with pytest.raises(ValueError, match="staleness bound"):
        StalenessBarrier(0, 2, stale=0)


# -- the weighted merge ------------------------------------------------------
def test_weighted_mean_by_island_count_and_skips_mismatch():
    own = (2.0, {"w": np.array([0.0, 0.0], np.float32),
                 "step": np.array(7, np.int64)}, {})
    peer = (1.0, {"w": np.array([3.0, 3.0], np.float32),
                  "step": np.array(9, np.int64)}, {})
    odd = (4.0, {"w": np.array([1.0, 2.0, 3.0], np.float32)}, {})
    params, buffers = _weighted_mean([own, peer, odd])
    # 2 islands at 0.0 + 1 island at 3.0 -> 1.0; the mis-shaped (and
    # the key-missing) contribution never pollutes the fold
    np.testing.assert_allclose(params["w"], [1.0, 1.0])
    # integer leaves (step counters) keep this process's own value
    assert params["step"] == 7
    assert buffers == {}


# -- the driver against a fake cluster ---------------------------------------
class _FakeHeartbeat:
    def __init__(self):
        self.beats = []

    def beat(self, neval, status=None):
        self.beats.append((neval, status))


class _FakeCluster:
    """The slice of ClusterService the driver touches, minus the
    processes: a directory, a peer table, and the excuse book."""

    def __init__(self, directory, pidx, count, statuses=None):
        self.directory = str(directory)
        self.process_index = pidx
        self.process_count = count
        self.statuses = dict(statuses or {})
        self.beats = []
        self.excused = []
        self.heartbeat = _FakeHeartbeat()
        self.monitor = self

    def peer_table(self):
        return {f"p{p}": {"process_index": p, "status": s}
                for p, s in self.statuses.items()}

    def beat(self, neval):
        self.beats.append(neval)

    def excuse_peer(self, peer, reason):
        self.excused.append((peer, reason))


def _tiny_local_step():
    model = nn.Sequential(nn.Linear(2, 2), nn.LogSoftMax())
    return TrainStep(model, nn.ClassNLLCriterion(),
                     optim.SGD(learning_rate=0.1), mesh=None,
                     parameter_sync="local")


def _peer_payload(path, params, islands=1.0):
    payload = {"__islands__": np.asarray(islands)}
    payload.update({f"p::{k}": np.asarray(v) for k, v in params.items()})
    np.savez(str(path), **payload)


def test_driver_sheds_laggard_after_grace(tmp_path, monkeypatch):
    """p1 never publishes: after one grace window the survivor writes
    the ``shed.p1.json`` marker, excuses p1 everywhere, emits
    ``cluster/shed`` (hard), arms its own teardown bypass — and the
    wait lands in ``sync/staleness`` ``waited_s`` for the ledger."""
    armed = []
    monkeypatch.setattr(local_sync, "_arm_survivor_exit",
                        lambda w=None: armed.append(w))
    fake = _FakeCluster(tmp_path, 0, 2, statuses={1: "running"})
    drv = LocalSyncDriver(_tiny_local_step(), cluster=fake, h=1,
                          stale=1, grace=0.15, poll=0.02)
    sink = telemetry.MemorySink()
    with telemetry.run(sinks=[sink]):
        drv.on_step(1)
        drv.on_step(2)  # excused: the gone peer never delays again
    marker = json.loads((tmp_path / "shed.p1.json").read_text())
    assert marker["peer"] == 1 and marker["by"] == 0
    assert marker["lag"] >= 1 and marker["stale"] == 1
    assert [p for p, _ in fake.excused] == [1]
    assert fake.beats, "survivor must keep beating while it waits"
    assert armed, "shed must arm the survivor's os._exit teardown"
    sheds = _instants(sink, "cluster/shed")
    assert len(sheds) == 1
    assert sheds[0]["role"] == "survivor" and sheds[0]["mode"] == "hard"
    stale = _instants(sink, "sync/staleness")
    assert stale[0]["waited_s"] >= 0.15   # the grace window, charged
    assert stale[1]["waited_s"] < 0.1     # round 2: nobody to wait for
    avgs = _instants(sink, "sync/average")
    assert [e["peers"] for e in avgs] == [1, 1]


def test_driver_merges_peer_within_bound_no_shed(tmp_path, monkeypatch):
    """A peer that HAS published within the bound is merged (weighted
    by island count) and nothing is shed — including its contribution
    being up to S rounds stale."""
    monkeypatch.setattr(local_sync, "_arm_survivor_exit",
                        lambda w=None: pytest.fail("must not shed"))
    fake = _FakeCluster(tmp_path, 0, 2, statuses={1: "running"})
    step = _tiny_local_step()
    drv = LocalSyncDriver(step, cluster=fake, h=1, stale=2,
                          grace=0.2, poll=0.02)
    own = step.island_mean_host(step.params)
    _peer_payload(tmp_path / "sync.p1.r1.npz",
                  {k: np.asarray(v) + 2.0 for k, v in own.items()})
    sink = telemetry.MemorySink()
    with telemetry.run(sinks=[sink]):
        drv.on_step(1)  # round 1: peer current
        merged = step.island_mean_host(step.params)
        for k in own:
            np.testing.assert_allclose(
                np.asarray(merged[k]), np.asarray(own[k]) + 1.0,
                rtol=1e-6, atol=1e-6,
                err_msg=f"merge of {k} is not the equal-weight mean")
        drv.on_step(2)  # round 2: peer stale by 1 < S=2 -> still merged
    assert not fake.excused and not list(tmp_path.glob("shed.*"))
    avgs = _instants(sink, "sync/average")
    assert [e["peers"] for e in avgs] == [2, 2]


def test_driver_soft_sheds_process_zero(tmp_path, monkeypatch):
    """p0 hosts the jax.distributed coordination service: making it
    exit would fatally abort every survivor's runtime client.  A slow
    p0 is excused (survivors stop waiting and stop merging it) but gets
    NO marker — it keeps running."""
    monkeypatch.setattr(local_sync, "_arm_survivor_exit",
                        lambda w=None: None)
    fake = _FakeCluster(tmp_path, 1, 2, statuses={0: "running"})
    drv = LocalSyncDriver(_tiny_local_step(), cluster=fake, h=1,
                          stale=1, grace=0.1, poll=0.02)
    sink = telemetry.MemorySink()
    with telemetry.run(sinks=[sink]):
        drv.on_step(1)
    assert not (tmp_path / "shed.p0.json").exists()
    assert [p for p, _ in fake.excused] == [0]
    sheds = _instants(sink, "cluster/shed")
    assert len(sheds) == 1 and sheds[0]["mode"] == "soft"


def test_driver_grace_window_lets_peer_catch_up(tmp_path, monkeypatch):
    """A peer AT the bound that publishes before the window closes is
    NOT shed — the barrier re-decides while it holds the door."""
    monkeypatch.setattr(local_sync, "_arm_survivor_exit",
                        lambda w=None: pytest.fail("must not shed"))
    fake = _FakeCluster(tmp_path, 0, 2, statuses={1: "running"})
    step = _tiny_local_step()
    drv = LocalSyncDriver(step, cluster=fake, h=1, stale=1,
                          grace=2.0, poll=0.02)
    own = step.island_mean_host(step.params)

    def late_publish():
        time.sleep(0.15)
        _peer_payload(tmp_path / "sync.p1.r1.npz", own)

    t = threading.Thread(target=late_publish)
    t.start()
    sink = telemetry.MemorySink()
    with telemetry.run(sinks=[sink]):
        drv.on_step(1)
    t.join()
    assert not fake.excused
    st = _instants(sink, "sync/staleness")[0]
    assert 0.1 <= st["waited_s"] < 1.5  # waited, but far short of grace


def test_victim_beats_shed_status_then_exits(tmp_path, monkeypatch):
    """The victim's side of the protocol: finding our own marker means
    publish heartbeat status ``shed`` as the LAST act (survivors hold
    their service-killing teardown until they see it), then exit 43
    into the supervisor."""
    codes = []

    def fake_exit(code):
        codes.append(code)
        raise RuntimeError("exited")

    monkeypatch.setattr(local_sync.os, "_exit", fake_exit)
    fake = _FakeCluster(tmp_path, 1, 2, statuses={0: "running"})
    drv = LocalSyncDriver(_tiny_local_step(), cluster=fake, h=4,
                          stale=1, grace=0.1)
    (tmp_path / "shed.p1.json").write_text(json.dumps(
        {"peer": 1, "by": 0, "round": 3, "lag": 1, "stale": 1}))
    sink = telemetry.MemorySink()
    with telemetry.run(sinks=[sink]):
        with pytest.raises(RuntimeError, match="exited"):
            drv.on_step(1)
    from bigdl_tpu.parallel.cluster import EXIT_PEER_LOST

    assert codes == [EXIT_PEER_LOST]
    assert fake.heartbeat.beats == [(1, "shed")]
    sheds = _instants(sink, "cluster/shed")
    assert len(sheds) == 1 and sheds[0]["role"] == "victim"
    assert sheds[0]["by"] == 0


def test_driver_grace_defaults_derive_from_heartbeat_interval():
    set_config(BigDLConfig(heartbeat_interval=3.0))
    drv = LocalSyncDriver(_tiny_local_step(), cluster=None)
    assert drv.grace == pytest.approx(6.0)
    set_config(BigDLConfig(heartbeat_interval=0.1,
                           local_sync_grace=0.25))
    assert LocalSyncDriver(_tiny_local_step(), cluster=None).grace \
        == pytest.approx(0.25)


# -- single-process cadence over a real mesh ---------------------------------
def test_single_process_rounds_collapse_islands():
    """H local steps, then the in-graph average: ``sync/average`` fires
    exactly at round boundaries (plus the finalize round), and after
    the final average every island holds the same parameters."""
    mesh = make_mesh((2,), ("data",), devices=jax.devices()[:2])
    model = nn.Sequential(nn.Linear(6, 8), nn.Tanh(), nn.Linear(8, 4),
                          nn.LogSoftMax())
    step = TrainStep(model, nn.ClassNLLCriterion(),
                     optim.SGD(learning_rate=0.1), mesh=mesh,
                     parameter_sync="local")
    assert step.island_count() == 2
    drv = LocalSyncDriver(step, cluster=None, h=2, stale=1)
    rng = np.random.RandomState(0)
    x = rng.randn(8, 6).astype(np.float32)
    y = rng.randint(0, 4, 8)
    sink = telemetry.MemorySink()
    with telemetry.run(sinks=[sink]):
        for i in range(1, 6):
            loss = step.run(x, y, jax.random.key(i))
            assert np.isfinite(loss)
            drv.on_step(i)
        drv.finalize(5)
    avgs = _instants(sink, "sync/average")
    assert [e["step"] for e in avgs] == [2, 4, 5]
    assert all(e["islands"] == 2 and e["peers"] == 1 for e in avgs)
    assert all(e["waited_s"] == 0 for e in
               _instants(sink, "sync/staleness"))
    for k, v in step.params.items():
        rows = step._island_rows(v)
        np.testing.assert_allclose(
            rows[0], rows[1], rtol=1e-6, atol=1e-6,
            err_msg=f"islands of {k} did not collapse to their mean")


def test_metrics_sink_folds_local_sync_status():
    """The live surface: sync/average + sync/staleness + cluster/shed
    fold into /status.local_sync — the block tpu_watch prints as
    ``sync=local H=8 stale=1/3``."""
    from bigdl_tpu.telemetry.metrics_http import MetricsSink

    sink = MetricsSink()
    base = {"v": 1, "ts": 1.0, "pid": 1, "tid": 1, "kind": "event"}
    sink.emit({**base, "name": "sync/average", "round": 2, "step": 16,
               "h": 8, "bytes": 1024, "dur": 0.01, "peers": 2,
               "islands": 2})
    sink.emit({**base, "name": "sync/staleness", "round": 2,
               "waited_s": 0.4, "lag": 1, "stale": 3, "step": 16})
    sink.emit({**base, "name": "sync/staleness", "round": 3,
               "waited_s": 0.1, "lag": 0, "stale": 3, "step": 24})
    sink.emit({**base, "name": "cluster/shed", "peer": 1, "round": 3,
               "lag": 3, "stale": 3, "process_index": 0,
               "role": "survivor", "mode": "hard"})
    # the victim's own instant (and a duplicate verdict) never
    # double-counts the shed list
    sink.emit({**base, "name": "cluster/shed", "peer": 1, "round": 3,
               "lag": 3, "stale": 3, "process_index": 1,
               "role": "victim"})
    st = sink.status()["local_sync"]
    assert st["h"] == 8 and st["round"] == 2 and st["peers"] == 2
    assert st["islands"] == 2 and st["bytes"] == 1024
    assert st["lag"] == 0 and st["stale"] == 3  # latest verdict wins
    assert st["waited_s"] == pytest.approx(0.5)  # ...but waits sum
    assert st["shed"] == [1]


# -- compiled-program claims -------------------------------------------------
def _registry_pieces(batch=8):
    model = nn.Sequential(nn.Linear(6, 16), nn.Tanh(), nn.Linear(16, 4),
                          nn.LogSoftMax())
    rng = np.random.RandomState(0)
    x = rng.randn(batch, 6).astype(np.float32)
    y = rng.randint(0, 4, batch)
    return model, x, y


def test_local_scan_has_zero_collectives_and_beats_sync_comms():
    """The tentpole's comms claim, off the EXACT compiled programs: the
    local-mode scan body contains no collective at all (island locality
    is structural under shard_map), and the one averaging program paid
    every H steps keeps the reduction at >= 0.8·H of the synchronous
    per-step all-reduce."""
    from bigdl_tpu.telemetry.comms import comms_facts

    mesh = make_mesh((2,), ("data",), devices=jax.devices()[:2])
    crit = nn.ClassNLLCriterion()
    h = 8

    model, x, y = _registry_pieces()
    sync_step = TrainStep(model, crit, optim.SGD(learning_rate=0.1),
                          mesh=mesh, parameter_sync="allreduce")
    sync_step.aot_scan(x, y, jax.random.key(0), 4)
    sync_bytes = comms_facts(sync_step._scan_cache[1],
                             mesh=mesh)["bytes"]
    assert sync_bytes > 0

    model2, _, _ = _registry_pieces()
    local_step = TrainStep(model2, crit, optim.SGD(learning_rate=0.1),
                           mesh=mesh, parameter_sync="local")
    local_step.aot_scan(x, y, jax.random.key(0), 4)
    lf = comms_facts(local_step._scan_cache[1], mesh=mesh)
    assert lf["count"] == 0 and lf["bytes"] == 0, lf
    local_step.average_islands()
    avg_bytes = comms_facts(local_step._avg_cache, mesh=mesh)["bytes"]
    assert avg_bytes > 0
    reduction = sync_bytes / (avg_bytes / h)
    assert reduction >= 0.8 * h, (sync_bytes, avg_bytes, reduction)


def test_sync_path_byte_identical_when_local_mode_off():
    """The do-no-harm acceptance: with ``parameter_sync != local`` the
    compiled program must be BYTE-IDENTICAL whether or not the
    local-SGD knobs are set — the mode leaves zero residue on the
    synchronous path."""
    from bigdl_tpu.telemetry.comms import comms_facts

    mesh = make_mesh((2,), ("data",), devices=jax.devices()[:2])

    def compile_sync():
        model, x, y = _registry_pieces()
        step = TrainStep(model, nn.ClassNLLCriterion(),
                         optim.SGD(learning_rate=0.1), mesh=mesh,
                         parameter_sync="allreduce")
        step.aot_scan(x, y, jax.random.key(0), 3)
        return step

    plain = compile_sync()
    set_config(BigDLConfig(local_sync_h=4, local_sync_stale=1,
                           local_sync_grace=0.25))
    knobbed = compile_sync()
    a = comms_facts(plain._scan_cache[1], mesh=mesh)
    b = comms_facts(knobbed._scan_cache[1], mesh=mesh)
    assert (a["bytes"], a["count"]) == (b["bytes"], b["count"])
    assert plain._scan_cache[1].as_text() \
        == knobbed._scan_cache[1].as_text()
