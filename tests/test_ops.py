"""TF-style op catalog (nn.ops), TF-support layers (nn.tf), control flow,
and the TFRecord/Example reader."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import bigdl_tpu.nn as nn
from bigdl_tpu.nn import ops, tf


def test_conv2d_biasadd_maxpool():
    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.randn(2, 8, 8, 3), jnp.float32)
    w = jnp.asarray(rng.randn(3, 3, 3, 4), jnp.float32)
    b = jnp.asarray(rng.randn(4), jnp.float32)
    y = ops.Conv2D(padding="SAME").forward((x, w))
    assert y.shape == (2, 8, 8, 4)
    y = ops.BiasAdd().forward((y, b))
    p = ops.MaxPool((2, 2), (2, 2)).forward(y)
    assert p.shape == (2, 4, 4, 4)
    a = ops.AvgPool((2, 2), (2, 2)).forward(y)
    np.testing.assert_allclose(
        np.asarray(a),
        np.asarray(y).reshape(2, 4, 2, 4, 2, 4).mean(axis=(2, 4)),
        rtol=1e-5, atol=1e-5)


def test_comparison_and_logical_ops():
    a = jnp.asarray([1.0, 2.0, 3.0])
    b = jnp.asarray([2.0, 2.0, 2.0])
    assert np.array_equal(np.asarray(ops.Equal().forward((a, b))),
                          [False, True, False])
    assert np.array_equal(np.asarray(ops.Greater().forward((a, b))),
                          [False, False, True])
    assert np.array_equal(np.asarray(ops.Less().forward((a, b))),
                          [True, False, False])
    t = jnp.asarray([True, False, True])
    f = jnp.asarray([True, True, False])
    assert np.array_equal(np.asarray(ops.LogicalAnd().forward((t, f))),
                          [True, False, False])
    assert np.array_equal(np.asarray(ops.LogicalOr().forward((t, f))),
                          [True, True, True])
    assert np.array_equal(np.asarray(ops.LogicalNot().forward(t)),
                          [False, True, False])


def test_elementwise_and_reduction_ops():
    x = jnp.asarray([[1.7, -2.3], [0.5, 4.0]])
    np.testing.assert_array_equal(np.asarray(ops.Floor().forward(x)),
                                  np.floor(np.asarray(x)))
    assert float(ops.L2Loss().forward(x)) == pytest.approx(
        float(np.sum(np.asarray(x) ** 2) / 2))
    np.testing.assert_allclose(
        np.asarray(ops.Prod(axis=1).forward(x)),
        np.prod(np.asarray(x), axis=1), rtol=1e-6)
    oh = ops.OneHot(depth=4).forward(jnp.asarray([0, 2]))
    np.testing.assert_array_equal(np.asarray(oh),
                                  [[1, 0, 0, 0], [0, 0, 1, 0]])
    assert int(ops.Rank().forward(x)) == 2
    assert ops.Cast(jnp.int32).forward(x).dtype == jnp.int32


def test_pad_slice_resize():
    x = jnp.arange(12.0).reshape(3, 4)
    p = ops.Pad([[1, 1], [0, 2]]).forward(x)
    assert p.shape == (5, 6)
    s = ops.Slice((1, 0), (2, -1)).forward(x)
    np.testing.assert_array_equal(np.asarray(s), np.asarray(x)[1:3, :])
    img = jnp.ones((1, 4, 4, 3))
    r = ops.ResizeBilinearOps().forward((img, (8, 8)))
    assert r.shape == (1, 8, 8, 3)


def test_random_ops_and_rng_determinism():
    from bigdl_tpu.utils.rng import RNG

    RNG.set_seed(7)
    u1 = ops.RandomUniform((64,), 2.0, 5.0).forward(None)
    assert float(jnp.min(u1)) >= 2.0 and float(jnp.max(u1)) < 5.0
    tn = ops.TruncatedNormal((512,), stddev=2.0).forward(None)
    assert float(jnp.max(jnp.abs(tn))) <= 4.0 + 1e-5


def test_operation_backward_raises():
    with pytest.raises(RuntimeError):
        ops.Floor().backward(jnp.ones(3), jnp.ones(3))


def test_while_loop_lowering():
    class CondM(nn.Module):
        def update_output(self, vs):
            i, acc = vs
            return i < 5

    class BodyM(nn.Module):
        def update_output(self, vs):
            i, acc = vs
            return (i + 1, acc * 2.0)

    w = ops.While(CondM(), BodyM())
    i, acc = w.forward((jnp.asarray(0), jnp.asarray(1.0)))
    assert int(i) == 5 and float(acc) == 32.0
    # must also compile under jit
    i2, acc2 = jax.jit(lambda v: w.forward(v))((jnp.asarray(0),
                                                jnp.asarray(1.0)))
    assert int(i2) == 5 and float(acc2) == 32.0


def test_cond_switch_merge():
    double = nn.MulConstant(2.0)
    halve = nn.MulConstant(0.5)
    c = ops.Cond(double, halve)
    assert float(c.forward((jnp.asarray(True), jnp.asarray(3.0)))) == 6.0
    assert float(c.forward((jnp.asarray(False), jnp.asarray(3.0)))) == 1.5

    x = jnp.asarray([1.0, 2.0])
    f_out, t_out, pred = ops.Switch().forward((x, jnp.asarray(True)))
    merged = ops.Merge().forward((f_out * 0.5, t_out * 2.0, pred))
    np.testing.assert_allclose(np.asarray(merged), [2.0, 4.0])


def test_tf_support_layers_in_graph():
    inp = nn.Input()
    const = nn.Node(tf.Const(jnp.asarray([10.0, 20.0])))
    add = nn.CAddTable()
    out = nn.graph.node_from_module(add, [inp, const])
    g = nn.Graph(inp, out)
    y = g.forward(jnp.asarray([1.0, 2.0]))
    np.testing.assert_allclose(np.asarray(y), [11.0, 22.0])


def test_tf_variable_trains():
    from bigdl_tpu.nn.module import functional_call, state_dict

    v = tf.Variable(jnp.zeros((3,)))
    params = state_dict(v, kind="param")

    def loss(p):
        out, _ = functional_call(v, p, None)
        return jnp.sum((out - 2.0) ** 2)

    g = jax.grad(loss)(params)
    np.testing.assert_allclose(np.asarray(list(g.values())[0]),
                               [-4.0, -4.0, -4.0])


def test_tf_shape_fill_slice_layers():
    x = jnp.ones((2, 3, 4))
    np.testing.assert_array_equal(np.asarray(tf.Shape().forward(x)),
                                  [2, 3, 4])
    f = tf.Fill().forward(((2, 2), 7.0))
    np.testing.assert_array_equal(np.asarray(f), [[7.0, 7.0], [7.0, 7.0]])
    s = tf.SplitAndSelect(1, 1, 3).forward(x)
    assert s.shape == (2, 1, 4)
    st = tf.StrideSlice([(0, 0, 2, 1), (1, 0, 3, 2)]).forward(x)
    assert st.shape == (2, 2, 4)
    cd = tf.ControlDependency().forward((x, jnp.zeros(1)))
    assert cd.shape == x.shape


def test_tfrecord_roundtrip_and_parse_example():
    import struct
    import tempfile

    from bigdl_tpu.dataset.tfrecord import (TFRecordIterator, parse_example,
                                            write_tfrecord)

    # hand-encode an Example proto: {"x": float_list [1.5, -2.5],
    #                                "y": int64_list [3], "s": bytes "ab"}
    def varint(n):
        out = b""
        while True:
            b7 = n & 0x7F
            n >>= 7
            out += bytes([b7 | (0x80 if n else 0)])
            if not n:
                return out

    def ld(field, payload):
        return varint((field << 3) | 2) + varint(len(payload)) + payload

    float_list = ld(1, struct.pack("<2f", 1.5, -2.5))  # packed
    feat_x = ld(2, float_list)
    int_list = ld(1, varint(3))
    feat_y = ld(3, int_list)
    bytes_list = ld(1, b"ab")  # BytesList{value: "ab"}
    feat_s = ld(1, bytes_list)  # Feature{bytes_list: ...}
    entry_x = ld(1, b"x") + ld(2, feat_x)
    entry_y = ld(1, b"y") + ld(2, feat_y)
    entry_s = ld(1, b"s") + ld(2, feat_s)
    features = ld(1, entry_x) + ld(1, entry_y) + ld(1, entry_s)
    example = ld(1, features)

    feats = parse_example(example)
    np.testing.assert_allclose(feats["x"], [1.5, -2.5])
    np.testing.assert_array_equal(feats["y"], [3])
    assert feats["s"] == [b"ab"]

    with tempfile.NamedTemporaryFile(suffix=".tfrecord", delete=False) as f:
        path = f.name
    write_tfrecord(path, [example, example])
    recs = list(TFRecordIterator(path))
    assert len(recs) == 2 and recs[0] == example

    pe = ops.ParseExample(["x"], [np.float32], [(2,)])
    out = pe.forward(recs)
    assert out.shape == (2, 2)
