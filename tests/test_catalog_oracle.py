"""Catalog-closure oracle tests (VERDICT r2 item 10; SURVEY §4's
117-layer + 124-Torch-oracle-spec discipline): every exported nn layer,
criterion, and nn.ops class gets >= 1 numeric check against a
PyTorch/NumPy oracle.  `test_catalog_is_fully_covered` scans the test
sources and FAILS when a new exported class ships without a test."""

import inspect
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest
import torch
import torch.nn.functional as F

import bigdl_tpu.nn as nn
from bigdl_tpu.nn import ops

R = np.random.RandomState


def _c(ours, theirs, rtol=1e-4, atol=1e-5):
    np.testing.assert_allclose(np.asarray(ours), np.asarray(theirs),
                               rtol=rtol, atol=atol)


def _j(*arrays):
    return tuple(jnp.asarray(a) for a in arrays) if len(arrays) > 1 \
        else jnp.asarray(arrays[0])


# ------------------------- simple activations -----------------------------

def test_abs_sqrt_square_log_exp_power():
    x = np.abs(R(0).randn(3, 5).astype(np.float32)) + 0.1
    _c(nn.Abs().forward(_j(-x)), np.abs(x))
    _c(nn.Sqrt().forward(_j(x)), np.sqrt(x))
    _c(nn.Square().forward(_j(x)), x * x)
    _c(nn.Log().forward(_j(x)), np.log(x))
    _c(nn.Exp().forward(_j(x)), np.exp(x))
    _c(nn.Power(2.0, 3.0, 1.0).forward(_j(x)), (3.0 * x + 1.0) ** 2)


def test_clamp_threshold_rrelu_gradientreversal():
    x = R(1).randn(4, 6).astype(np.float32)
    _c(nn.Clamp(-2, 2).forward(_j(x)), np.clip(x, -2, 2))
    # Threshold: x > th ? x : value
    _c(nn.Threshold(0.2, -1.0).forward(_j(x)), np.where(x > 0.2, x, -1.0))
    # RReLU eval mode: deterministic (lower+upper)/2 slope (torch parity)
    rr = nn.RReLU(0.1, 0.3).evaluate()
    ref = F.rrelu(torch.tensor(x), 0.1, 0.3, training=False)
    _c(rr.forward(_j(x)), ref.numpy())
    # GradientReversal: identity fwd, -lambda * grad bwd
    gr = nn.GradientReversal(2.0)
    _c(gr.forward(_j(x)), x)
    g = gr.backward(_j(x), _j(np.ones_like(x)))
    _c(g, -2.0 * np.ones_like(x))


# ------------------------- linear-algebra layers ---------------------------

def test_add_mul_cadd_cmul_constants():
    x = R(2).randn(3, 4).astype(np.float32)
    add = nn.Add(4)
    _c(add.forward(_j(x)), x + np.asarray(add.bias))
    mul = nn.Mul()
    _c(mul.forward(_j(x)), x * float(np.asarray(mul.weight).reshape(())))
    cadd = nn.CAdd((1, 4))
    _c(cadd.forward(_j(x)), x + np.asarray(cadd.bias))
    cmul = nn.CMul((1, 4))
    _c(cmul.forward(_j(x)), x * np.asarray(cmul.weight))
    _c(nn.MulConstant(2.5).forward(_j(x)), 2.5 * x)
    _c(nn.AddConstant(1.5).forward(_j(x)), x + 1.5)
    sc = nn.Scale((1, 4))
    _c(sc.forward(_j(x)), x * np.asarray(sc.weight) + np.asarray(sc.bias))


def test_bilinear_matches_torch():
    layer = nn.Bilinear(3, 4, 5)
    tb = torch.nn.Bilinear(3, 4, 5)
    with torch.no_grad():
        tb.weight.copy_(torch.tensor(np.asarray(layer.weight)))
        tb.bias.copy_(torch.tensor(np.asarray(layer.bias)))
    a = R(3).randn(6, 3).astype(np.float32)
    b = R(4).randn(6, 4).astype(np.float32)
    _c(layer.forward([_j(a), _j(b)]),
       tb(torch.tensor(a), torch.tensor(b)).detach().numpy(),
       rtol=1e-3, atol=1e-4)


def test_mm_mv_dotproduct():
    a = R(5).randn(2, 3, 4).astype(np.float32)
    b = R(6).randn(2, 4, 5).astype(np.float32)
    _c(nn.MM().forward([_j(a), _j(b)]), a @ b)
    _c(nn.MM(trans_a=True).forward([_j(a.transpose(0, 2, 1)), _j(b)]), a @ b)
    v = R(7).randn(2, 4).astype(np.float32)
    _c(nn.MV().forward([_j(a), _j(v)]), np.einsum("bij,bj->bi", a, v))
    x1 = R(8).randn(3, 6).astype(np.float32)
    x2 = R(9).randn(3, 6).astype(np.float32)
    _c(nn.DotProduct().forward([_j(x1), _j(x2)]), (x1 * x2).sum(1))


def test_cosine_euclidean_pairwise():
    x = R(10).randn(5, 3).astype(np.float32)
    cos = nn.Cosine(3, 4)
    w = np.asarray(cos.weight)  # (4, 3)
    want = (x / np.linalg.norm(x, axis=1, keepdims=True)) @ \
        (w / np.linalg.norm(w, axis=1, keepdims=True)).T
    _c(cos.forward(_j(x)), want, rtol=1e-3, atol=1e-4)
    eu = nn.Euclidean(3, 4)
    we = np.asarray(eu.weight)
    want_e = np.linalg.norm(x[:, None, :] - we[None, :, :], axis=2)
    _c(eu.forward(_j(x)), want_e, rtol=1e-3, atol=1e-4)
    y = R(11).randn(5, 3).astype(np.float32)
    _c(nn.PairwiseDistance(2).forward([_j(x), _j(y)]),
       F.pairwise_distance(torch.tensor(x), torch.tensor(y)).numpy(),
       rtol=1e-3, atol=1e-4)
    _c(nn.CosineDistance().forward([_j(x), _j(y)]),
       F.cosine_similarity(torch.tensor(x), torch.tensor(y)).numpy(),
       rtol=1e-3, atol=1e-4)


def test_lookup_table_matches_embedding():
    lt = nn.LookupTable(10, 6)
    idx = R(12).randint(0, 10, (4, 3))
    ref = F.embedding(torch.tensor(idx),
                      torch.tensor(np.asarray(lt.weight)))
    _c(lt.forward(_j(idx.astype(np.int32))), ref.numpy())


def test_mixture_table():
    gates = np.abs(R(13).randn(4, 3).astype(np.float32))
    gates = gates / gates.sum(1, keepdims=True)
    e1, e2, e3 = (R(s).randn(4, 5).astype(np.float32) for s in (14, 15, 16))
    out = nn.MixtureTable().forward([_j(gates), [_j(e1), _j(e2), _j(e3)]])
    want = gates[:, 0:1] * e1 + gates[:, 1:2] * e2 + gates[:, 2:3] * e3
    _c(out, want)


# ------------------------- shape / table layers ----------------------------

def test_shape_and_table_layers():
    x = R(17).randn(3, 4, 5).astype(np.float32)
    _c(nn.Narrow(1, 1, 2).forward(_j(x)), x[:, 1:3])
    _c(nn.Select(1, 2).forward(_j(x)), x[:, 2])
    _c(nn.Replicate(4, 1).forward(_j(x)),
       np.repeat(x[:, None], 4, axis=1))
    _c(nn.Reverse(1).forward(_j(x)), x[:, ::-1])
    _c(nn.Contiguous().forward(_j(x)), x)
    _c(nn.SpatialZeroPadding(1, 2, 3, 4).forward(_j(x[None])),
       np.pad(x[None], ((0, 0), (0, 0), (3, 4), (1, 2))))
    _c(nn.Max(1).forward(_j(x)), x.max(1))
    _c(nn.Min(1).forward(_j(x)), x.min(1))
    _c(nn.Mean(1).forward(_j(x)), x.mean(1))
    _c(nn.Sum(1).forward(_j(x)), x.sum(1))
    pad = nn.Padding(0, 2, n_input_dim=2)  # dim 0 of the 2 sample dims
    padded = np.asarray(pad.forward(_j(x)))
    assert padded.shape == (3, 6, 5)
    np.testing.assert_allclose(padded[:, :4], x)
    np.testing.assert_allclose(padded[:, 4:], 0)
    # tables
    parts = [x[:, i] for i in range(4)]
    jparts = [_j(p) for p in parts]
    _c(nn.SelectTable(1).forward(jparts), parts[1])
    nt = nn.NarrowTable(1, 2).forward(jparts)
    assert len(nt) == 2
    _c(nt[0], parts[1])
    st = nn.SplitTable(1).forward(_j(x))
    assert len(st) == 4
    _c(st[2], parts[2])
    bs = nn.BifurcateSplitTable(1).forward(_j(x))
    assert len(bs) == 2 and np.asarray(bs[0]).shape == (3, 2, 5)
    ft = nn.FlattenTable().forward([jparts[0], [jparts[1], [jparts[2]]]])
    assert len(ft) == 3
    _c(nn.Pack(1).forward(jparts), np.stack(parts, axis=1))
    _c(nn.JoinTable(1, 0).forward(jparts), np.concatenate(parts, axis=1))
    idx = np.asarray([2, 0, 1], np.int32)
    _c(nn.Index(0).forward([_j(x), _j(idx)]), x[idx])
    mask = x[:, :, 0] > 0
    _c(nn.MaskedSelect().forward([_j(x[:, :, 0]), _j(mask)]),
       x[:, :, 0][mask])


def test_bottle_applies_inner_over_flattened_dims():
    lin = nn.Linear(5, 7)
    bottle = nn.Bottle(lin, 2, 2)
    x = R(18).randn(3, 4, 5).astype(np.float32)
    want = (x.reshape(12, 5) @ np.asarray(lin.weight).T
            + np.asarray(lin.bias)).reshape(3, 4, 7)
    _c(bottle.forward(_j(x)), want, rtol=1e-3, atol=1e-4)


def test_maptable_and_paralleltable():
    lin = nn.Linear(4, 2)
    xs = [R(19).randn(3, 4).astype(np.float32) for _ in range(2)]
    outs = nn.MapTable(lin).forward([_j(xs[0]), _j(xs[1])])
    for o, xi in zip(outs, xs):
        _c(o, xi @ np.asarray(lin.weight).T + np.asarray(lin.bias),
           rtol=1e-3, atol=1e-4)


# ------------------------- recurrent variants ------------------------------

def test_lstm_peephole_and_convlstm_shapes_and_grads():
    for cell, x_shape in [
            (nn.LSTMPeephole(6, 8), (2, 5, 6)),
            (nn.ConvLSTMPeephole(3, 4, 3, 3, 1), (2, 5, 3, 7, 7)),
    ]:
        rec = nn.Recurrent(cell)
        x = R(20).randn(*x_shape).astype(np.float32)
        out = rec.forward(_j(x))
        assert np.asarray(out).shape[:2] == x_shape[:2]
        g = rec.backward(_j(x), jnp.ones_like(out))
        assert np.asarray(g).shape == x_shape
        assert np.isfinite(np.asarray(g)).all()


def test_convlstm3d_shape():
    rec = nn.Recurrent(nn.ConvLSTMPeephole3D(2, 3, 3, 3, 1))
    x = R(21).randn(1, 3, 2, 5, 5, 5).astype(np.float32)
    out = rec.forward(_j(x))
    assert np.asarray(out).shape == (1, 3, 3, 5, 5, 5)


def test_time_distributed_applies_per_step():
    lin = nn.Linear(4, 3)
    td = nn.TimeDistributed(lin)
    x = R(22).randn(2, 6, 4).astype(np.float32)
    want = np.stack([xt @ np.asarray(lin.weight).T + np.asarray(lin.bias)
                     for xt in x.transpose(1, 0, 2)], axis=1)
    _c(td.forward(_j(x)), want, rtol=1e-3, atol=1e-4)


def test_tree_lstm_hierarchy():
    # TreeLSTM is the abstract base (nn/TreeLSTM.scala); its concrete
    # subclass BinaryTreeLSTM carries the numerics (test_tree_pipeline)
    assert issubclass(nn.BinaryTreeLSTM, nn.TreeLSTM)
    assert nn.TreeLSTM(4, 6).hidden_size == 6


# ------------------------- detection helpers -------------------------------

def test_roi_pooling_numpy_reference():
    feat = R(24).randn(1, 2, 8, 8).astype(np.float32)
    rois = np.asarray([[0, 0, 0, 3, 3], [0, 2, 2, 7, 7]], np.float32)
    out = np.asarray(nn.RoiPooling(2, 2, 1.0).forward(
        [_j(feat), _j(rois)]))
    # manual: roi 0 covers rows/cols 0..3 -> 2x2 cells of 2x2 maxes
    want00 = feat[0, :, 0:2, 0:2].max(axis=(1, 2))
    np.testing.assert_allclose(out[0, :, 0, 0], want00, rtol=1e-5)
    assert out.shape == (2, 2, 2, 2)


def test_nms_suppresses_overlaps():
    boxes = np.asarray([[0, 0, 10, 10], [1, 1, 11, 11], [50, 50, 60, 60]],
                       np.float32)
    scores = np.asarray([0.9, 0.8, 0.7], np.float32)
    keep, count = nn.Nms(0.5).forward([_j(boxes), _j(scores)])
    kept = [i for i in np.asarray(keep).tolist() if i >= 0]
    assert int(count) == 2
    assert 0 in kept and 2 in kept and 1 not in kept


# ------------------------- remaining convs/pools ---------------------------

def test_share_convolution_and_conv_map():
    x = R(25).randn(2, 4, 6, 6).astype(np.float32)
    share = nn.SpatialShareConvolution(4, 3, 3, 3)
    ref = F.conv2d(torch.tensor(x), torch.tensor(np.asarray(share.weight)),
                   torch.tensor(np.asarray(share.bias)))
    _c(share.forward(_j(x)), ref.numpy(), rtol=1e-3, atol=1e-4)
    table = np.asarray([[0, 0], [1, 0], [1, 1], [2, 1], [3, 1]])
    cm = nn.SpatialConvolutionMap(table, 3, 3)
    out = np.asarray(cm.forward(_j(x)))
    assert out.shape == (2, 2, 4, 4)
    # oracle: masked dense conv
    w = np.asarray(cm.weight) * np.asarray(cm.mask)
    ref2 = F.conv2d(torch.tensor(x), torch.tensor(w),
                    torch.tensor(np.asarray(cm.bias)))
    _c(out, ref2.numpy(), rtol=1e-3, atol=1e-4)


def test_volumetric_full_conv_and_avg_pool():
    x = R(26).randn(1, 3, 4, 4, 4).astype(np.float32)
    vf = nn.VolumetricFullConvolution(3, 2, 3, 3, 3, 2, 2, 2, 1, 1, 1)
    ref = F.conv_transpose3d(
        torch.tensor(x), torch.tensor(np.asarray(vf.weight)),
        torch.tensor(np.asarray(vf.bias)), stride=2, padding=1)
    _c(vf.forward(_j(x)), ref.numpy(), rtol=1e-3, atol=1e-4)
    vp = nn.VolumetricAveragePooling(2, 2, 2)
    _c(vp.forward(_j(x)), F.avg_pool3d(torch.tensor(x), 2).numpy())


def test_temporal_max_pooling():
    x = R(27).randn(2, 8, 5).astype(np.float32)
    out = nn.TemporalMaxPooling(2).forward(_j(x))
    ref = F.max_pool1d(torch.tensor(x.transpose(0, 2, 1)), 2)
    _c(np.asarray(out).transpose(0, 2, 1), ref.numpy())


def test_within_channel_lrn_and_contrastive_norms():
    x = np.abs(R(28).randn(2, 3, 8, 8).astype(np.float32))
    out = np.asarray(nn.SpatialWithinChannelLRN(3, 0.01, 0.75).forward(_j(x)))
    # oracle: numpy window mean of squares
    sq = x ** 2
    win = np.zeros_like(x)
    pad = np.pad(sq, ((0, 0), (0, 0), (1, 1), (1, 1)))
    for dy in range(3):
        for dx in range(3):
            win += pad[:, :, dy:dy + 8, dx:dx + 8]
    scale = 1.0 + win / 9.0 * 0.01
    _c(out, x * scale ** -0.75, rtol=1e-4, atol=1e-5)
    for cls in (nn.SpatialSubtractiveNormalization,
                nn.SpatialDivisiveNormalization,
                nn.SpatialContrastiveNormalization):
        y = np.asarray(cls(3).forward(_j(x)))
        assert y.shape == x.shape and np.isfinite(y).all()


# ------------------------- criterions vs torch -----------------------------

def _crit(c, out, tgt):
    return float(c.forward(_j(out), _j(tgt)))


def test_regression_criterions_match_torch():
    x = R(30).randn(6, 4).astype(np.float32)
    y = R(31).randn(6, 4).astype(np.float32)
    assert _crit(nn.MSECriterion(), x, y) == pytest.approx(
        float(F.mse_loss(torch.tensor(x), torch.tensor(y))), rel=1e-5)
    assert _crit(nn.AbsCriterion(), x, y) == pytest.approx(
        float(F.l1_loss(torch.tensor(x), torch.tensor(y))), rel=1e-5)
    assert _crit(nn.SmoothL1Criterion(), x, y) == pytest.approx(
        float(F.smooth_l1_loss(torch.tensor(x), torch.tensor(y))), rel=1e-5)
    assert _crit(nn.L1Cost(), x, x) == pytest.approx(
        float(np.abs(x).sum()), rel=1e-5)
    p = np.abs(x) + 0.5
    q = np.abs(y) + 0.5
    assert _crit(nn.DistKLDivCriterion(), np.log(p), q) == pytest.approx(
        float(F.kl_div(torch.tensor(np.log(p)), torch.tensor(q),
                       reduction="batchmean") * q.shape[0] / q.size),
        rel=1e-4)


def test_classification_criterions_match_torch():
    logits = R(32).randn(6, 5).astype(np.float32)
    tgt = R(33).randint(0, 5, 6)
    logp = F.log_softmax(torch.tensor(logits), 1).numpy()
    assert _crit(nn.ClassNLLCriterion(), logp, tgt) == pytest.approx(
        float(F.nll_loss(torch.tensor(logp), torch.tensor(tgt))), rel=1e-5)
    assert _crit(nn.CrossEntropyCriterion(), logits, tgt) == pytest.approx(
        float(F.cross_entropy(torch.tensor(logits), torch.tensor(tgt))),
        rel=1e-5)
    probs = 1 / (1 + np.exp(-logits))
    bins = (R(34).rand(6, 5) > 0.5).astype(np.float32)
    assert _crit(nn.BCECriterion(), probs, bins) == pytest.approx(
        float(F.binary_cross_entropy(torch.tensor(probs),
                                     torch.tensor(bins))), rel=1e-4)
    assert _crit(nn.MultiLabelSoftMarginCriterion(), logits, bins) == \
        pytest.approx(float(F.multilabel_soft_margin_loss(
            torch.tensor(logits), torch.tensor(bins))), rel=1e-4)
    assert _crit(nn.MultiMarginCriterion(), logits, tgt) == pytest.approx(
        float(F.multi_margin_loss(torch.tensor(logits),
                                  torch.tensor(tgt))), rel=1e-4)
    # multilabel margin: targets are padded label lists (-1 terminated)
    ml_tgt = np.full((6, 5), -1, np.int64)
    ml_tgt[:, 0] = tgt
    assert _crit(nn.MultiLabelMarginCriterion(), logits, ml_tgt) == \
        pytest.approx(float(F.multilabel_margin_loss(
            torch.tensor(logits), torch.tensor(ml_tgt))), rel=1e-4)
    assert _crit(nn.SoftmaxWithCriterion(), logits, tgt) == pytest.approx(
        float(F.cross_entropy(torch.tensor(logits), torch.tensor(tgt))),
        rel=1e-4)


def test_embedding_margin_criterions_match_torch():
    x1 = R(35).randn(6, 4).astype(np.float32)
    x2 = R(36).randn(6, 4).astype(np.float32)
    yy = np.where(R(37).rand(6) > 0.5, 1.0, -1.0).astype(np.float32)
    assert nn.CosineEmbeddingCriterion(0.3).forward(
        [_j(x1), _j(x2)], _j(yy)) == pytest.approx(
        float(F.cosine_embedding_loss(torch.tensor(x1), torch.tensor(x2),
                                      torch.tensor(yy), margin=0.3)),
        rel=1e-4)
    d = np.abs(R(38).randn(6).astype(np.float32))
    assert float(nn.HingeEmbeddingCriterion(1.0).forward(
        _j(d), _j(yy))) == pytest.approx(
        float(F.hinge_embedding_loss(torch.tensor(d), torch.tensor(yy))),
        rel=1e-4)
    assert float(nn.MarginRankingCriterion(0.5).forward(
        [_j(x1[:, 0]), _j(x2[:, 0])], _j(yy))) == pytest.approx(
        float(F.margin_ranking_loss(torch.tensor(x1[:, 0]),
                                    torch.tensor(x2[:, 0]),
                                    torch.tensor(yy), margin=0.5)),
        rel=1e-4)
    # soft margin
    assert float(nn.SoftMarginCriterion().forward(
        _j(x1), _j(np.sign(x2)))) == pytest.approx(
        float(F.soft_margin_loss(torch.tensor(x1),
                                 torch.tensor(np.sign(x2)))), rel=1e-4)
    # margin criterion (binary hinge): mean(max(0, margin - x*y))
    got = float(nn.MarginCriterion(1.0).forward(_j(x1), _j(np.sign(x2))))
    want = np.maximum(0.0, 1.0 - x1 * np.sign(x2)).mean()
    assert got == pytest.approx(float(want), rel=1e-4)
    # L1 hinge embedding: ONE pair per call (torch convention)
    l1 = float(np.abs(x1[0] - x2[0]).sum())
    got_pos = float(nn.L1HingeEmbeddingCriterion(1.0).forward(
        [_j(x1[0]), _j(x2[0])], _j(np.asarray(1.0))))
    assert got_pos == pytest.approx(l1, rel=1e-4)
    got_neg = float(nn.L1HingeEmbeddingCriterion(9e9).forward(
        [_j(x1[0]), _j(x2[0])], _j(np.asarray(-1.0))))
    assert got_neg == pytest.approx(9e9 - l1, rel=1e-4)


def test_structured_criterions():
    x = R(39).randn(4, 3).astype(np.float32)
    y = R(40).randn(4, 3).astype(np.float32)
    tgt = R(41).randint(0, 3, 4)
    logp = F.log_softmax(torch.tensor(x), 1).numpy()
    # MultiCriterion: weighted sum
    mc = nn.MultiCriterion().add(nn.MSECriterion(), 0.5) \
        .add(nn.AbsCriterion(), 2.0)
    want = 0.5 * F.mse_loss(torch.tensor(x), torch.tensor(y)) \
        + 2.0 * F.l1_loss(torch.tensor(x), torch.tensor(y))
    assert _crit(mc, x, y) == pytest.approx(float(want), rel=1e-5)
    # ParallelCriterion over a table
    pc = nn.ParallelCriterion().add(nn.MSECriterion(), 1.0) \
        .add(nn.ClassNLLCriterion(), 0.5)
    got = float(pc.forward([_j(x), _j(logp)], [_j(y), _j(tgt)]))
    want = float(F.mse_loss(torch.tensor(x), torch.tensor(y))) \
        + 0.5 * float(F.nll_loss(torch.tensor(logp), torch.tensor(tgt)))
    assert got == pytest.approx(want, rel=1e-5)
    # TimeDistributedCriterion == mean over time of the inner criterion
    seq = R(42).randn(2, 5, 3).astype(np.float32)
    seq_t = R(43).randint(0, 3, (2, 5))
    logp_seq = np.asarray(F.log_softmax(torch.tensor(seq), -1))
    tdc = nn.TimeDistributedCriterion(nn.ClassNLLCriterion(),
                                      size_average=True)
    per_step = [float(F.nll_loss(torch.tensor(logp_seq[:, t]),
                                 torch.tensor(seq_t[:, t])))
                for t in range(5)]
    # reference sizeAverage: accumulated loss / nstep
    assert float(tdc.forward(_j(logp_seq), _j(seq_t))) == pytest.approx(
        float(np.mean(per_step)), rel=1e-4)
    # ClassSimplexCriterion: MSE against simplex-embedded targets
    csc = nn.ClassSimplexCriterion(3)
    loss = float(csc.forward(_j(x), _j(tgt)))
    assert np.isfinite(loss) and loss > 0
    # CosineDistanceCriterion: 1 - cos(x, y)
    got = float(nn.CosineDistanceCriterion().forward(_j(x), _j(y)))
    want = float(np.mean(1.0 - np.asarray(F.cosine_similarity(
        torch.tensor(x), torch.tensor(y)))))
    assert got == pytest.approx(want, rel=1e-4)
    # Dice coefficient: 1 - 2|xy|/(|x|+|y|)
    probs = 1 / (1 + np.exp(-x))
    bins = (y > 0).astype(np.float32)
    dice = nn.DiceCoefficientCriterion(epsilon=1.0)
    got = float(dice.forward(_j(probs), _j(bins)))
    inter = (probs * bins).sum(1)
    want = np.mean(1 - (2 * inter + 1.0)
                   / (probs.sum(1) + bins.sum(1) + 1.0))
    assert got == pytest.approx(float(want), rel=1e-3)


# ------------------------- nn.ops ------------------------------------------

def test_comparison_and_logical_ops():
    a = R(44).randn(3, 4).astype(np.float32)
    b = R(45).randn(3, 4).astype(np.float32)
    _c(ops.Equal().forward([_j(a), _j(a)]), np.ones_like(a, bool))
    _c(ops.NotEqual().forward([_j(a), _j(b)]), a != b)
    _c(ops.Greater().forward([_j(a), _j(b)]), a > b)
    _c(ops.GreaterEqual().forward([_j(a), _j(b)]), a >= b)
    _c(ops.Less().forward([_j(a), _j(b)]), a < b)
    _c(ops.LessEqual().forward([_j(a), _j(b)]), a <= b)
    ba = a > 0
    bb = b > 0
    _c(ops.LogicalAnd().forward([_j(ba), _j(bb)]), ba & bb)
    _c(ops.LogicalOr().forward([_j(ba), _j(bb)]), ba | bb)
    _c(ops.LogicalNot().forward(_j(ba)), ~ba)
    _c(ops.Ceil().forward(_j(a)), np.ceil(a))
    _c(ops.Round().forward(_j(a)), np.round(a))
    _c(ops.L2Loss().forward(_j(a)), (a * a).sum() / 2)
    _c(ops.Select().forward([_j(ba), _j(a), _j(b)]), np.where(ba, a, b))
    _c(ops.Assign().forward([_j(a), _j(b)]), b)
    _c(ops.Assert().forward([_j(np.asarray(True)), _j(a)]), a)


def test_decode_image_op():
    import io

    from PIL import Image

    img = (R(46).rand(5, 4, 3) * 255).astype(np.uint8)
    buf = io.BytesIO()
    Image.fromarray(img).save(buf, format="PNG")
    _c(ops.DecodeImage(3).update_output(buf.getvalue()), img)


def test_elementwise_tables_and_view():
    a = R(47).randn(3, 4).astype(np.float32)
    b = np.abs(R(48).randn(3, 4).astype(np.float32)) + 0.5
    _c(nn.CSubTable().forward([_j(a), _j(b)]), a - b)
    _c(nn.CDivTable().forward([_j(a), _j(b)]), a / b)
    _c(nn.CMaxTable().forward([_j(a), _j(b)]), np.maximum(a, b))
    _c(nn.CMinTable().forward([_j(a), _j(b)]), np.minimum(a, b))
    _c(nn.View(2, 6).forward(_j(a)), a.reshape(2, 6))


def test_l1penalty_and_weighted_smoothl1():
    x = R(49).randn(3, 4).astype(np.float32)
    pen = nn.L1Penalty(0.1)
    _c(pen.forward(_j(x)), x)  # identity forward
    g = pen.backward(_j(x), _j(np.zeros_like(x)))
    _c(g, 0.1 * np.sign(x))    # pure sparsity gradient
    swc = nn.SmoothL1CriterionWithWeights(sigma=1.0, num=x.size)
    inw = np.ones_like(x)
    outw = np.ones_like(x)
    got = float(swc.forward(_j(x), [_j(np.zeros_like(x)), _j(inw), _j(outw)]))
    want = float(F.smooth_l1_loss(torch.tensor(x),
                                  torch.zeros_like(torch.tensor(x)),
                                  reduction="sum")) / x.size
    assert got == pytest.approx(want, rel=1e-4)


# ------------------------- coverage closure --------------------------------

_INFRA = {
    # abstract/infrastructure classes with no standalone numerics
    "Module", "Container", "Cell", "Operation", "_PoolOp", "Criterion",
    "AbstractCriterion", "ModuleToOperation", "Echo", "Identity", "Graph",
    "Sequential", "Node", "Input",
}


def _catalog():
    from bigdl_tpu.nn.module import Module as M

    import bigdl_tpu.nn.criterion as crit

    out = set()
    for mod, base in ((nn, M), (ops, M)):
        for name in dir(mod):
            obj = getattr(mod, name)
            if inspect.isclass(obj) and issubclass(obj, base) \
                    and not name.startswith("_"):
                out.add(name)
    for name in dir(crit):
        obj = getattr(crit, name)
        if inspect.isclass(obj) and not name.startswith("_"):
            out.add(name)
    return out - _INFRA


def test_catalog_is_fully_covered():
    """Every exported class must be exercised by at least one test file
    (the reference ships a spec per layer, SURVEY §4) — adding a class
    without a test fails here."""
    test_dir = os.path.dirname(os.path.abspath(__file__))
    source = ""
    for fn in os.listdir(test_dir):
        if fn.endswith(".py"):
            with open(os.path.join(test_dir, fn)) as f:
                source += f.read()
    import re

    missing = sorted(c for c in _catalog()
                     if not re.search(rf"\b{re.escape(c)}\b", source))
    assert not missing, f"classes with no test coverage: {missing}"
