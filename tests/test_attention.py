"""Attention stack tests: Pallas flash kernel vs dense oracle, ring and
Ulysses sequence parallelism on the 8-device CPU mesh, and the nn-level
MultiHeadAttention / TransformerBlock layers."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from bigdl_tpu.ops import dot_product_attention, flash_attention
from bigdl_tpu.parallel.sequence import make_sequence_parallel_attention


def _rand_qkv(b=2, h=2, s=64, d=16, seed=0, dtype=jnp.float32):
    rng = np.random.RandomState(seed)
    mk = lambda: jnp.asarray(rng.randn(b, h, s, d).astype(np.float32),
                             dtype=dtype)
    return mk(), mk(), mk()


@pytest.mark.parametrize("causal", [False, True])
def test_flash_matches_dense(causal):
    q, k, v = _rand_qkv(s=64)
    out_ref = dot_product_attention(q, k, v, causal=causal)
    out = flash_attention(q, k, v, causal=causal, block_q=16, block_k=16)
    np.testing.assert_allclose(np.asarray(out), np.asarray(out_ref),
                               rtol=2e-5, atol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_flash_grads_match_dense(causal):
    q, k, v = _rand_qkv(s=32, d=8)

    def loss_dense(q, k, v):
        return jnp.sum(dot_product_attention(q, k, v, causal=causal) ** 2)

    def loss_flash(q, k, v):
        return jnp.sum(flash_attention(q, k, v, causal=causal,
                                       block_q=8, block_k=8) ** 2)

    g_ref = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
    g = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g, g_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-4)


def test_flash_cross_attention_lengths():
    rng = np.random.RandomState(1)
    q = jnp.asarray(rng.randn(1, 2, 16, 8).astype(np.float32))
    k = jnp.asarray(rng.randn(1, 2, 48, 8).astype(np.float32))
    v = jnp.asarray(rng.randn(1, 2, 48, 8).astype(np.float32))
    out_ref = dot_product_attention(q, k, v, causal=True)
    out = flash_attention(q, k, v, causal=True, block_q=8, block_k=16)
    np.testing.assert_allclose(np.asarray(out), np.asarray(out_ref),
                               rtol=2e-5, atol=2e-5)


@pytest.fixture
def seq_mesh():
    from jax.sharding import Mesh

    devs = np.array(jax.devices()[:8])
    return Mesh(devs, ("seq",))


@pytest.mark.parametrize("strategy", ["ring", "ulysses"])
@pytest.mark.parametrize("causal", [False, True])
def test_sequence_parallel_matches_dense(seq_mesh, strategy, causal):
    # heads divisible by 8 for ulysses; seq sharded 8 ways
    q, k, v = _rand_qkv(b=1, h=8, s=64, d=8, seed=2)
    fn = make_sequence_parallel_attention(seq_mesh, strategy=strategy,
                                          causal=causal)
    out = fn(q, k, v)
    out_ref = dot_product_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(out_ref),
                               rtol=2e-5, atol=2e-5)


def test_data_x_seq_ring_matches_dense():
    """Ring attention composed with data parallelism on a (data, seq)
    mesh: batch shards over 'data', each data row runs its own k/v ring
    over 'seq' — forward AND gradients must equal dense attention on the
    global arrays (TrainStep differentiates through this form)."""
    from jax.sharding import Mesh

    if len(jax.devices()) < 8:
        pytest.skip("needs 8 devices (virtual CPU mesh)")
    devs = np.array(jax.devices()[:8]).reshape(2, 4)
    mesh = Mesh(devs, ("data", "seq"))
    q, k, v = _rand_qkv(b=4, h=2, s=32, d=8, seed=6)
    fn = make_sequence_parallel_attention(mesh, strategy="ring",
                                          causal=True, batch_axis="data")
    out = jax.jit(fn)(q, k, v)
    out_ref = dot_product_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(out_ref),
                               rtol=2e-5, atol=2e-5)
    g = jax.grad(lambda q, k, v: jnp.sum(fn(q, k, v) ** 2),
                 argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(
        lambda q, k, v: jnp.sum(
            dot_product_attention(q, k, v, causal=True) ** 2),
        argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g, g_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-4)


def test_ring_attention_differentiable(seq_mesh):
    q, k, v = _rand_qkv(b=1, h=2, s=32, d=8, seed=3)
    fn = make_sequence_parallel_attention(seq_mesh, strategy="ring",
                                          causal=True)

    def loss_sp(q, k, v):
        return jnp.sum(fn(q, k, v) ** 2)

    def loss_ref(q, k, v):
        return jnp.sum(dot_product_attention(q, k, v, causal=True) ** 2)

    g = jax.grad(loss_sp, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g, g_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-4)


def test_ring_attention_jits_under_mesh(seq_mesh):
    """The shard_map'd ring attention must compile inside jit (the form the
    train step uses)."""
    q, k, v = _rand_qkv(b=1, h=2, s=64, d=8, seed=4)
    fn = make_sequence_parallel_attention(seq_mesh, strategy="ring",
                                          causal=True)
    out = jax.jit(fn)(q, k, v)
    out_ref = dot_product_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(out_ref),
                               rtol=2e-5, atol=2e-5)


def test_multihead_attention_layer():
    import bigdl_tpu.nn as nn
    from bigdl_tpu.nn.module import functional_call, state_dict

    mha = nn.MultiHeadAttention(32, 4, causal=True, backend="dense")
    x = jnp.asarray(np.random.RandomState(5).randn(2, 10, 32),
                    dtype=jnp.float32)
    out = mha.forward(x)
    assert out.shape == (2, 10, 32)

    # functional path + grads flow to all four projections
    params = state_dict(mha, kind="param")

    def loss(p):
        y, _ = functional_call(mha, p, x, training=True)
        return jnp.sum(y ** 2)

    grads = jax.grad(loss)(params)
    assert set(grads) == set(params)
    assert all(np.isfinite(np.asarray(g)).all() for g in grads.values())


def test_mha_flash_backend_matches_dense():
    import bigdl_tpu.nn as nn

    mha = nn.MultiHeadAttention(32, 4, causal=True, backend="dense")
    x = jnp.asarray(np.random.RandomState(6).randn(1, 16, 32),
                    dtype=jnp.float32)
    out_dense = mha.forward(x)
    mha.backend = "flash"
    out_flash = mha.forward(x)
    np.testing.assert_allclose(np.asarray(out_flash), np.asarray(out_dense),
                               rtol=2e-5, atol=2e-5)


def test_transformer_block_trains():
    import bigdl_tpu.nn as nn
    import bigdl_tpu.optim as optim
    from bigdl_tpu.parallel.train_step import TrainStep

    model = nn.Sequential(
        nn.TransformerBlock(16, 2, causal=True, backend="dense"))
    crit = nn.MSECriterion()
    step = TrainStep(model, crit, optim.SGD(learning_rate=0.05))
    rng = np.random.RandomState(7)
    x = rng.randn(4, 8, 16).astype(np.float32)
    y = rng.randn(4, 8, 16).astype(np.float32)
    losses = [float(step.run(x, y, jax.random.PRNGKey(i)))
              for i in range(5)]
    assert losses[-1] < losses[0]


def test_layernorm():
    import bigdl_tpu.nn as nn

    ln = nn.LayerNorm(8)
    x = jnp.asarray(np.random.RandomState(8).randn(3, 8) * 5 + 2,
                    dtype=jnp.float32)
    out = np.asarray(ln.forward(x))
    np.testing.assert_allclose(out.mean(-1), 0.0, atol=1e-5)
    np.testing.assert_allclose(out.std(-1), 1.0, atol=1e-3)


def test_mha_mask_and_dropout():
    import bigdl_tpu.nn as nn

    mha = nn.MultiHeadAttention(16, 2, backend="dense")
    x = jnp.asarray(np.random.RandomState(9).randn(2, 6, 16), jnp.float32)
    mask = jnp.ones((2, 1, 6, 6), bool).at[:, :, :, 3:].set(False)
    out_masked = mha.forward((x, mask))
    out_full = mha.forward(x)
    assert out_masked.shape == (2, 6, 16)
    assert not np.allclose(np.asarray(out_masked), np.asarray(out_full))

    # dropout is live in training mode, off in eval
    mhad = nn.MultiHeadAttention(16, 2, dropout=0.5, backend="dense")
    o1 = mhad.forward(x)
    o2 = mhad.forward(x)
    assert not np.allclose(np.asarray(o1), np.asarray(o2))
    mhad.evaluate()
    e1 = mhad.forward(x)
    e2 = mhad.forward(x)
    np.testing.assert_allclose(np.asarray(e1), np.asarray(e2))


def test_fused_qkv_matches_separate_projections():
    """Self-attention takes the fused [E,3E] projection path; feeding the
    same VALUES as distinct (q, k, v) objects takes the separate-GEMM
    path — both must agree, and the fused path's gradients must land in
    the separate q/k/v parameters."""
    import bigdl_tpu.nn as nn
    from bigdl_tpu.utils.rng import RNG

    RNG.set_seed(21)
    mha = nn.MultiHeadAttention(24, 4, causal=True).evaluate()
    x = jnp.asarray(np.random.RandomState(0).randn(2, 6, 24)
                    .astype(np.float32))
    fused = np.asarray(mha.forward(x))
    apart = np.asarray(mha.forward((x, x + 0.0, x + 0.0)))
    np.testing.assert_allclose(fused, apart, rtol=1e-5, atol=1e-6)

    mha.training_mode()
    mha.zero_grad_parameters()
    gy = jnp.asarray(np.random.RandomState(1).randn(2, 6, 24)
                     .astype(np.float32))
    mha.backward(x, gy)
    for proj in (mha.q_proj, mha.k_proj, mha.v_proj):
        g = np.asarray(proj._grads["weight"])
        assert np.abs(g).max() > 0, "fused path left a projection gradient-free"


def test_auto_backend_threshold_routing(monkeypatch):
    """backend='auto' must route by max(Sq, Sk) against flash_min_seq
    (default 512 after the round-5 block-size sweep flipped the
    decision) — and always dense off-TPU."""
    import bigdl_tpu.ops as O
    import bigdl_tpu.ops.attention as A

    calls = []
    real_dense = A.dot_product_attention

    def spy_flash(q, k, v, **kw):
        calls.append("flash")
        return real_dense(q, k, v, causal=kw.get("causal", False),
                          scale=kw.get("scale"))

    def spy_dense(q, k, v, **kw):
        calls.append("dense")
        return real_dense(q, k, v, **kw)

    # the layer lazily does `from bigdl_tpu.ops import ...` for the
    # kernels and `from bigdl_tpu.ops.attention import ...` for the
    # gate — patch both namespaces
    monkeypatch.setattr(O, "flash_attention", spy_flash)
    monkeypatch.setattr(O, "dot_product_attention", spy_dense)
    import numpy as np

    rng = np.random.default_rng(0)

    def run(seq, tpu):
        calls.clear()
        monkeypatch.setattr(A, "is_tpu_device", lambda: tpu)
        import bigdl_tpu.nn as nn
        mha = nn.MultiHeadAttention(16, 2, causal=True, backend="auto")
        x = jnp.asarray(rng.normal(size=(1, seq, 16)).astype(np.float32))
        mha.forward(x)
        return calls[-1] if calls else "dense"

    assert run(512, tpu=True) == "flash"    # at the threshold: flash
    assert run(256, tpu=True) == "dense"    # below: dense (no spy call)
    assert run(512, tpu=False) == "dense"   # off-TPU: always dense
