"""The bench replay fallback: a wedged tunnel at driver time must emit
the banked (committed, clearly-marked) measurement instead of a bare
``backend_init_failed`` — the round-3/4 lesson, where two rounds of real
optimization work produced zero recorded TPU numbers.

Reference protocol being protected: the per-iteration throughput record
of ``models/utils/DistriOptimizerPerf.scala:33-124``."""

import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _run_bench(env_extra):
    env = dict(os.environ, **env_extra)
    env.pop("XLA_FLAGS", None)  # single-device is fine and faster here
    return subprocess.run(
        [sys.executable, os.path.join(REPO, "bench.py")],
        capture_output=True, text=True, timeout=180, env=env, cwd=REPO)


def test_backend_init_failure_replays_banked_artifact():
    banked = os.path.join(REPO, "BENCH_banked_r5.json")
    assert os.path.exists(banked), "banked artifact must be committed"
    proc = _run_bench({"JAX_PLATFORMS": "cpu",
                       "BENCH_BACKEND_TIMEOUT": "0.001",
                       "BIGDL_SINGLETON_WAIT": "1"})
    # a replay is still an infrastructure failure — nonzero exit, but the
    # one-line JSON contract holds and carries the banked measurement
    assert proc.returncode == 3, proc.stderr[-2000:]
    line = json.loads(proc.stdout.strip().splitlines()[-1])
    assert line["replayed"] is True
    assert "replay_reason" in line
    assert "live_error" in line
    with open(banked) as f:
        ref = json.load(f)
    assert line["value"] == ref["value"]
    assert line["metric"] == ref["metric"]


def test_replay_refuses_mismatched_configs():
    """Replaying the inception headline against a resnet-only run would
    mislabel the measurement — the fallback must error out instead."""
    proc = _run_bench({"JAX_PLATFORMS": "cpu",
                       "BENCH_BACKEND_TIMEOUT": "0.001",
                       "BIGDL_SINGLETON_WAIT": "1",
                       "BENCH_CONFIGS": "resnet50_imagenet"})
    assert proc.returncode == 3
    line = json.loads(proc.stdout.strip().splitlines()[-1])
    assert line["metric"] == "backend_init_failed"
    assert "replay_unavailable" in line
    assert line.get("replayed") is not True


def test_corrupt_banked_artifact_still_emits_one_json_line(tmp_path):
    """JSONDecodeError (a torn harvest write) must not break the
    one-line contract or kill the watchdog thread silently."""
    bad = tmp_path / "BENCH_banked_bad.json"
    bad.write_text("{not json")
    proc = _run_bench({"JAX_PLATFORMS": "cpu",
                       "BENCH_BACKEND_TIMEOUT": "0.001",
                       "BIGDL_SINGLETON_WAIT": "1",
                       "BENCH_BANKED": str(bad)})
    assert proc.returncode == 3
    line = json.loads(proc.stdout.strip().splitlines()[-1])
    assert line["metric"] == "backend_init_failed"
    assert "replay_unavailable" in line


def test_flash_attn_flop_correction(monkeypatch):
    """The dense-equivalent attention FLOPs (12*L*B*H*S^2*D) are added
    only when the auto backend would route the config to flash — off-TPU
    (dense) the correction must be zero so MFU accounting matches what
    XLA already counted."""
    import bench
    from bigdl_tpu.ops import attention

    assert bench._flash_attn_flops("transformer_lm", 32) == 0.0  # cpu

    monkeypatch.setattr(attention, "is_tpu_device", lambda: True)
    got = bench._flash_attn_flops("transformer_lm", 32)
    assert got == 12.0 * 6 * 32 * 8 * 512 * 512 * 64
    # below the flash threshold: dense path, already counted
    monkeypatch.setenv("BIGDL_FLASH_MIN_SEQ", "1024")
    assert bench._flash_attn_flops("transformer_lm", 32) == 0.0
    # non-transformer configs have no correction
    assert bench._flash_attn_flops("inception_v1_imagenet", 256) == 0.0
