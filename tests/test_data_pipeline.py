"""Image/text transforms + dataset readers (SURVEY §2.6 test analogue of
the reference's dataset/ specs: transformer composition, batch shapes,
normalization statistics)."""

import numpy as np

import bigdl_tpu.dataset.image as im
import bigdl_tpu.dataset.text as tx
from bigdl_tpu.dataset.datasets import (load_cifar10, load_mnist,
                                        load_news20, TRAIN_MEAN, TRAIN_STD)
from bigdl_tpu.dataset.sample import Sample


def _imgs(n=8, h=12, w=12, c=3, seed=0):
    rng = np.random.default_rng(seed)
    return [im.LabeledImage(rng.integers(0, 255, (h, w, c), dtype=np.uint8),
                            float(i % 3)) for i in range(n)]


def test_normalize_then_crop_chain():
    pipeline = im.ImageNormalizer([100, 110, 120], [50, 55, 60]) \
        >> im.CenterCropper(8, 8) >> im.ImageToSample()
    out = list(pipeline(iter(_imgs())))
    assert len(out) == 8
    assert out[0].feature.shape == (3, 8, 8)
    assert out[0].label.dtype == np.int64


def test_random_crop_and_flip_shapes():
    pipe = im.RandomCropper(10, 10)
    out = list(pipe(iter(_imgs())))
    assert all(o.data.shape == (10, 10, 3) for o in out)
    flipped = list(im.HFlip(1.0)(iter(_imgs(2))))
    orig = _imgs(2)
    np.testing.assert_array_equal(flipped[0].data, orig[0].data[:, ::-1])


def test_color_jitter_and_lighting_run():
    out = list(im.ColorJitter()(iter(_imgs(4))))
    assert all(o.data.shape == (12, 12, 3) for o in out)
    out = list(im.Lighting()(iter(_imgs(4))))
    assert all(o.data.dtype == np.float32 for o in out)


def test_mt_image_to_batch_native_path():
    batcher = im.MTImageToBatch(4, 8, 8, [100.0] * 3, [50.0] * 3,
                                random_crop=True, hflip=True)
    batches = list(batcher(iter(_imgs(10))))
    assert [b[0].shape[0] for b in batches] == [4, 4, 2]
    feats, labels = batches[0]
    assert feats.shape == (4, 3, 8, 8) and feats.dtype == np.float32
    assert labels.dtype == np.int64


def test_grey_img_mnist_path():
    imgs, labels = load_mnist(None, "train", synthetic_size=64)
    assert imgs.shape == (64, 28, 28) and labels.max() < 10
    records = [im.LabeledImage(x, float(y)) for x, y in zip(imgs, labels)]
    pipe = im.GreyImgNormalizer(TRAIN_MEAN, TRAIN_STD) >> im.GreyImgToSample()
    out = list(pipe(iter(records)))
    assert out[0].feature.shape == (1, 28, 28)
    # normalized data roughly zero-centered
    assert abs(np.mean([o.feature.mean() for o in out])) < 2.0


def test_cifar_reader_synthetic():
    imgs, labels = load_cifar10(None, "train", synthetic_size=32)
    assert imgs.shape == (32, 32, 32, 3)
    assert labels.dtype == np.int64


def test_channel_mean_std():
    mean, std = im.channel_mean_std(iter(_imgs(16, seed=1)))
    assert mean.shape == (3,) and std.shape == (3,)
    assert (std > 0).all()


# ---------------- text ----------------
def test_tokenize_dictionary_roundtrip():
    docs = ["The cat sat. The dog ran!", "A cat and a dog."]
    sents = list(tx.SentenceSplitter()(iter(docs)))
    assert len(sents) == 3
    toks = list(tx.SentenceTokenizer()(iter(sents)))
    d = tx.Dictionary(toks, vocab_size=10)
    assert d.vocab_size <= 11
    assert d.index("cat") != d.index(tx.Dictionary.UNK)
    assert d.index("zebra") == d.index(tx.Dictionary.UNK)
    assert d.word(d.index("cat")) == "cat"


def test_dictionary_save_load(tmp_path):
    d = tx.Dictionary([["a", "b", "c"]])
    p = str(tmp_path / "vocab.txt")
    d.save(p)
    d2 = tx.Dictionary.load(p)
    assert d2.index("b") == d.index("b")


def test_text_to_sample_lm_convention():
    toks = [["a", "b", "c", "d"]]
    d = tx.Dictionary(toks)
    ls = list(tx.TextToLabeledSentence(d)(iter(toks)))[0]
    # next-word labels: label[i] == data[i+1]'s source token
    assert len(ls.data) == 3 and len(ls.label) == 3
    assert ls.label[0] == d.index("b")
    samples = list(tx.LabeledSentenceToSample(d.vocab_size, fixed_length=5,
                                              one_hot=True)(iter([ls])))
    assert samples[0].feature.shape == (5, d.vocab_size)


def test_bucketed_padding():
    sents = [tx.LabeledSentence(np.arange(n), np.arange(n))
             for n in (3, 7, 12)]
    out = list(tx.BucketedPadding([4, 8, 16])(iter(sents)))
    assert [len(o.data) for o in out] == [4, 8, 16]


def test_news20_synthetic_and_sentence_padding():
    docs = load_news20(None, synthetic_size=10)
    assert len(docs) == 10
    toks = list(tx.SentenceTokenizer()(iter([t for t, _ in docs])))
    padded = list(tx.SentenceBiPadding()(iter(toks)))
    assert padded[0][0] == tx.SENTENCE_START
    assert padded[0][-1] == tx.SENTENCE_END


def test_mt_batch_float_input_after_jitter():
    pipe = im.ColorJitter() >> im.MTImageToBatch(
        4, 8, 8, [0.0] * 3, [255.0] * 3, random_crop=False, hflip=False)
    feats, labels = next(iter(pipe(iter(_imgs(4)))))
    assert feats.shape == (4, 3, 8, 8) and feats.dtype == np.float32
    assert np.isfinite(feats).all() and feats.max() <= 4.0


def test_channel_mean_std_grey():
    imgs, labels = load_mnist(None, "train", synthetic_size=8)
    mean, std = im.channel_mean_std(
        iter([im.LabeledImage(x, 0.0) for x in imgs]))
    assert mean.shape == (1,) and std.shape == (1,)


def test_movielens_loader():
    import os
    import tempfile

    from bigdl_tpu.dataset.datasets import (load_movielens,
                                            movielens_id_pairs,
                                            movielens_id_ratings)

    data = load_movielens()  # synthetic fallback
    assert data.shape[1] == 4 and data.dtype.kind == "i"
    assert movielens_id_pairs().shape[1] == 2
    assert movielens_id_ratings().shape[1] == 3
    # real ratings.dat parse ("::"-separated, movielens.py read_data_sets)
    d = tempfile.mkdtemp()
    os.makedirs(os.path.join(d, "ml-1m"))
    with open(os.path.join(d, "ml-1m", "ratings.dat"), "w") as f:
        f.write("1::31::4::978300019\n2::1029::5::978302205\n")
    parsed = load_movielens(d)
    assert parsed.tolist() == [[1, 31, 4, 978300019], [2, 1029, 5, 978302205]]
