"""Request-level tracing for the serving path
(bigdl_tpu/telemetry/request_trace.py, docs/observability.md "Tracing a
request"): trace-id minting + X-Request-Id propagation/echo, span
completeness (every ms of wall time owned by exactly one span, ±5%),
tail-aware retention (the slowest-k survive eviction pressure), the
slow-request blame verdict on crafted slow requests (injected queue
backlog -> queue_wait, injected prefill flood -> prefill_interference),
terminal-span traces for rejected requests, OpenMetrics latency
histograms + SLO burn gauges, chrome request lanes, the offline
`telemetry trace` waterfall, schema validity of `request` events, and
the bench_serving.py --slo-* exit-4 gate in a live subprocess."""

import json
import os
import subprocess
import sys
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from bigdl_tpu.telemetry import request_trace as rt

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

VOCAB = 50


# -- ids ---------------------------------------------------------------------
def test_mint_and_valid_ids():
    a, b = rt.mint_id(), rt.mint_id()
    assert a != b and rt.valid_id(a) and len(a) == 16
    assert rt.valid_id("client-id_1.A")
    # anything unsafe for a header/log line is replaced, not rejected
    for bad in (None, "", "a b", "x" * 129, "id\nSet-Cookie: h"):
        assert not rt.valid_id(bad)


# -- store: tail-aware retention ---------------------------------------------
def _trace(tid, ms, endpoint="predict", status="ok", reason=None):
    tr = rt.RequestTrace(tid, endpoint, started_at=1000.0)
    tr.add_span("infer", 1000.0, ms, component="compute")
    tr.finish(status, reason, now=1000.0 + ms / 1000.0)
    return tr


def test_store_slowest_k_survives_eviction_pressure():
    store = rt.TraceStore(ring=8, slowest_k=2)
    store.add(_trace("slowest", 500.0))
    store.add(_trace("second", 400.0))
    for i in range(100):  # a flood of healthy requests
        store.add(_trace(f"fast{i}", 1.0))
    # the p99 exemplars were NOT evicted by recency...
    assert store.get("slowest")["ms"] == 500.0
    assert store.get("second")["ms"] == 400.0
    # ...while plain old traces age out of the ring
    assert store.get("fast0") is None
    assert store.get("fast99") is not None
    slow = store.slowest("predict", n=2)
    assert [d["trace_id"] for d in slow] == ["slowest", "second"]
    summary = store.summary()
    assert summary["count"] == 102
    assert summary["by_endpoint"]["predict"] == 102
    assert summary["slowest"]["predict"][0]["trace_id"] == "slowest"
    # bounded: ring + pinned, not one dict per request ever seen
    assert summary["kept"] <= 8 + 2


def test_store_reused_client_id_holds_exactly_one_slot():
    """A client retrying with the same X-Request-Id (the docs encourage
    reuse) must not burn two tail slots or leave a stale doc behind —
    the newest doc wins everywhere."""
    store = rt.TraceStore(ring=8, slowest_k=2)
    store.add(_trace("ticket-1", 300.0))
    store.add(_trace("other", 200.0))
    store.add(_trace("ticket-1", 50.0))  # the retry, faster
    assert store.get("ticket-1")["ms"] == 50.0
    slow = store.slowest("predict", n=4)
    ids = [d["trace_id"] for d in slow]
    assert ids.count("ticket-1") == 1
    # the stale 300ms entry no longer occupies a pinned slot: both
    # distinct requests hold exactly one each
    assert set(ids) == {"other", "ticket-1"}
    assert [d["ms"] for d in slow] == [200.0, 50.0]


def test_slo_and_histograms_survive_trace_off():
    """BIGDL_TRACE=off disables trace RECORDING only: the declared
    budgets keep burning and the bench gate keeps gating — an SLO
    violation must never pass CI because tracing was off."""
    import urllib.request as _url

    from bigdl_tpu.models import registry
    from bigdl_tpu.serving import serve_model
    from bigdl_tpu.utils.config import BigDLConfig, set_config

    set_config(BigDLConfig(trace_requests=False))
    try:
        model = registry.build_model("lenet")
        server = serve_model(model, registry.input_spec("lenet", 1),
                             name="lenet", host="127.0.0.1", port=0,
                             max_batch=4, batch_buckets=[4],
                             max_wait_ms=1.0, slo_p99_ms=0.001)
        try:
            code, body, hdrs = _post(
                server.port, {"inputs": np.zeros((1, 784)).tolist()})
            assert code == 200
            # the id echo stays (propagation is the contract)...
            assert rt.valid_id(hdrs["X-Request-Id"])
            # ...recording is off...
            assert server.traces is None
            with pytest.raises(urllib.error.HTTPError) as ei:
                _get(server.port,
                     f"/v1/trace/{hdrs['X-Request-Id']}")
            assert ei.value.code == 404
            # ...but the budgets burned and the histograms filled
            assert server.slo.violations >= 1
            assert server.slo.burn()["p99"]["burn"] > 1.0
            metrics = _url.urlopen(
                f"http://127.0.0.1:{server.port}/metrics",
                timeout=10).read().decode()
            assert 'bigdl_serve_latency_ms_count{model="lenet",' \
                   'endpoint="predict"} 1' in metrics
            assert "bigdl_slo_p99_burn_ratio" in metrics
        finally:
            server.stop(drain=False)
    finally:
        set_config(None)


def test_store_rejected_requests_counted_but_never_pin_tail_slots():
    store = rt.TraceStore(ring=4, slowest_k=1)
    store.add(_trace("rej", 900.0, status="rejected",
                     reason="queue_full"))
    store.add(_trace("slow", 50.0))
    assert store.rejections == {"queue_full": 1}
    # a rejected request is fast by construction: the tail slot belongs
    # to the slowest COMPLETED request even though the rejection's
    # recorded wall was larger
    assert [d["trace_id"] for d in store.slowest()] == ["slow"]


def test_trace_span_cap_keeps_component_accounting_complete():
    from bigdl_tpu.serving.server import ModelServer

    tr = rt.RequestTrace("t", "generate", started_at=1000.0,
                         max_spans=4)
    for i in range(10):
        tr.add_span("decode", 1000.0 + i, 2.0, component="compute")
    assert len(tr.spans) == 4 and tr.spans_dropped == 6
    # spans past the cap still landed in the tally
    assert tr.components["compute"] == pytest.approx(20.0)
    tr.finish(now=1000.025)  # 25ms wall: 20 accounted + 5 residual
    assert tr.to_dict()["spans_dropped"] == 6
    # the host residual is judged against the COMPONENT tally, not the
    # truncated span list — dropped iterations must not be re-counted
    ModelServer._close_books(tr)
    assert tr.components.get("host", 0.0) == pytest.approx(5.0, abs=0.1)
    assert sum(tr.components.values()) == pytest.approx(25.0, abs=0.1)


# -- blame verdict ------------------------------------------------------------
def _warm_baseline(**medians):
    base = rt.ComponentBaseline()
    for _ in range(rt.BASELINE_MIN_SAMPLES):
        base.observe_components(dict(medians))
    return base


def test_blame_needs_a_warmed_baseline():
    base = rt.ComponentBaseline()
    base.observe_components({"compute": 5.0})
    assert rt.blame_verdict({"queue_wait": 500.0}, base) is None


def test_blame_names_the_attributable_excess_not_compute():
    base = _warm_baseline(queue_wait=1.0, compute=10.0)
    # healthy request: no verdict
    assert rt.blame_verdict({"queue_wait": 1.2, "compute": 10.5},
                            base) is None
    # a queue stall is blamed on queue_wait even though compute also
    # drifted a little
    v = rt.blame_verdict({"queue_wait": 250.0, "compute": 11.0}, base)
    assert v["cause"] == "queue_wait"
    assert v["excess_ms"] == pytest.approx(249.0)
    assert v["baseline_ms"] == pytest.approx(1.0)
    # compute is the residual verdict: blamed only when nothing
    # attributable explains the excess
    v = rt.blame_verdict({"queue_wait": 1.0, "compute": 80.0}, base)
    assert v["cause"] == "compute"
    # sub-floor blips are not verdicts (2ms excess on a tiny request)
    assert rt.blame_verdict({"queue_wait": 3.0, "compute": 10.0},
                            base) is None


# -- histograms + SLO ---------------------------------------------------------
def test_latency_histogram_openmetrics_cumulative():
    h = rt.LatencyHistogram()
    for ms in (0.5, 3.0, 3.0, 40.0, 99999.0):
        h.observe(ms)
    h.observe(float("nan"))  # dropped, not corrupting
    lines = h.openmetrics("bigdl_serve_latency_ms",
                          'model="m",endpoint="predict"')
    assert lines[0] == "# TYPE bigdl_serve_latency_ms histogram"
    by_le = {}
    for ln in lines:
        if "_bucket" in ln:
            le = ln.split('le="')[1].split('"')[0]
            by_le[le] = int(ln.rsplit(" ", 1)[1])
    assert by_le["1"] == 1       # 0.5
    assert by_le["5"] == 3       # + the two 3.0s
    assert by_le["50"] == 4      # + 40.0
    assert by_le["10000"] == 4   # 99999 is over every bound
    assert by_le["+Inf"] == 5
    assert lines[-1].endswith(" 5")  # _count
    # cumulative counts never decrease (the OpenMetrics contract)
    seq = [by_le[f"{b:g}"] for b in rt.LATENCY_BUCKETS_MS]
    assert seq == sorted(seq)


def test_slo_tracker_burn_rates_and_violation_ledger():
    slo = rt.SLOTracker(p99_ms=10.0, ttft_ms=5.0)
    assert slo.active()
    for i in range(20):
        slo.observe(2.0, f"ok{i}", ttft_ms=1.0)
    assert slo.observe(50.0, "bad", ttft_ms=20.0) == ["p99", "ttft"]
    burn = slo.burn()
    assert burn["p99"]["burn"] == pytest.approx(5.0)   # 50 / 10
    assert burn["ttft"]["burn"] == pytest.approx(4.0)  # 20 / 5
    st = slo.status()
    assert st["violations"] == 1
    assert st["violating"][0]["trace_id"] == "bad"
    assert st["violating"][0]["violated"] == ["p99", "ttft"]
    assert not rt.SLOTracker().active()  # no budgets -> no gate


# -- offline: chrome lanes, waterfall text, summary ---------------------------
def _request_event(tid="abc123", endpoint="predict"):
    tr = rt.RequestTrace(tid, endpoint, started_at=1000.0)
    tr.add_span("queue_wait", 1000.0, 3.0, component="queue_wait")
    tr.add_span("infer", 1000.003, 7.0, component="compute")
    tr.note_token(1000.004)
    tr.finish(now=1000.010)
    doc = tr.to_dict()
    doc.update(kind="request", ts=1000.0, pid=0)
    return doc


def test_chrome_trace_renders_request_lanes():
    from bigdl_tpu.telemetry.chrome_trace import chrome_trace

    evs = [_request_event("req-a"), _request_event("req-b", "generate")]
    out = chrome_trace(evs)["traceEvents"]
    names = [e for e in out if e.get("ph") == "M"
             and e.get("name") == "thread_name"]
    labels = {e["args"]["name"] for e in names}
    assert "req req-a [predict]" in labels
    assert "req req-b [generate]" in labels
    # each request gets its OWN lane (distinct tid), spans ride it as
    # complete events, token emits as instants
    lanes = {e["args"]["name"]: e["tid"] for e in names}
    assert lanes["req req-a [predict]"] != lanes["req req-b [generate]"]
    spans = [e for e in out if e.get("ph") == "X"
             and e.get("cat") == "request"]
    assert {e["name"] for e in spans} == {"queue_wait", "infer"}
    assert all(e["args"]["trace_id"] in ("req-a", "req-b")
               for e in spans)
    toks = [e for e in out if e.get("ph") == "i"
            and e.get("cat") == "request"]
    assert len(toks) == 2


def test_format_trace_and_summarize_requests():
    doc = _request_event()
    doc["blame"] = {"cause": "queue_wait", "excess_ms": 2.0,
                    "baseline_ms": 1.0, "floor_ms": 5.0}
    text = rt.format_trace(doc)
    assert "abc123" in text and "blame=queue_wait" in text
    assert "queue_wait" in text and "infer" in text
    rej = {"kind": "request", "trace_id": "r1", "endpoint": "predict",
           "ms": 0.2, "status": "rejected", "reason": "queue_full",
           "ts": 1.0}
    summary = rt.summarize_requests([doc, rej])
    assert summary["requests"] == 2
    assert summary["rejections"] == {"queue_full": 1}
    ep = summary["endpoints"]["predict"]
    assert ep["count"] == 2 and ep["completed"] == 1
    assert ep["slowest"][0]["trace_id"] == "abc123"
    assert ep["slowest"][0]["blame"] == "queue_wait"


# -- live HTTP: predict -------------------------------------------------------
@pytest.fixture(scope="module")
def lenet_server():
    from bigdl_tpu.models import registry
    from bigdl_tpu.serving import serve_model

    model = registry.build_model("lenet")
    server = serve_model(model, registry.input_spec("lenet", 1),
                         name="lenet", host="127.0.0.1", port=0,
                         max_batch=8, batch_buckets=[1, 2, 4, 8],
                         max_wait_ms=2.0, slo_p99_ms=10_000.0)
    try:
        yield server
    finally:
        server.stop(drain=False)


def _post(port, payload, headers=None, path="/v1/predict",
          timeout=30.0):
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}{path}",
        data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json", **(headers or {})},
        method="POST")
    with urllib.request.urlopen(req, timeout=timeout) as r:
        return r.status, json.loads(r.read()), dict(r.headers)


def _get(port, path, timeout=10.0):
    with urllib.request.urlopen(
            f"http://127.0.0.1:{port}{path}", timeout=timeout) as r:
        return json.loads(r.read())


def test_header_propagation_minting_and_echo(lenet_server):
    server = lenet_server
    x = {"inputs": np.zeros((1, 784)).tolist()}
    # a valid client id is propagated and echoed...
    code, body, hdrs = _post(server.port, x,
                             headers={"X-Request-Id": "ticket-4711"})
    assert code == 200
    assert hdrs["X-Request-Id"] == "ticket-4711"
    assert body["trace_id"] == "ticket-4711"
    # ...and names the retained trace
    doc = _get(server.port, "/v1/trace/ticket-4711")
    assert doc["trace_id"] == "ticket-4711"
    assert doc["endpoint"] == "predict" and doc["status"] == "ok"
    # no header -> a minted id, still echoed
    code, body, hdrs = _post(server.port, x)
    assert rt.valid_id(hdrs["X-Request-Id"])
    assert body["trace_id"] == hdrs["X-Request-Id"]
    # an unsafe header value is REPLACED by a minted id, not propagated
    code, body, hdrs = _post(
        server.port, x, headers={"X-Request-Id": "x" * 200})
    assert hdrs["X-Request-Id"] != "x" * 200
    assert rt.valid_id(hdrs["X-Request-Id"])
    # unknown ids 404 with the retention window named
    with pytest.raises(urllib.error.HTTPError) as ei:
        _get(server.port, "/v1/trace/never-seen")
    assert ei.value.code == 404


def test_predict_span_completeness_and_status_traces(lenet_server):
    server = lenet_server
    code, body, _ = _post(server.port,
                          {"inputs": np.zeros((3, 784)).tolist()})
    assert code == 200
    doc = _get(server.port, f"/v1/trace/{body['trace_id']}")
    # every millisecond of wall time is owned by exactly one span: the
    # span sum equals the recorded wall within 5% (the residual becomes
    # an explicit `host` span, never a silent gap)
    span_sum = sum(s["ms"] for s in doc["spans"])
    assert span_sum == pytest.approx(doc["ms"], rel=0.05)
    names = [s["name"] for s in doc["spans"]]
    assert "parse" in names and "queue_wait" in names
    assert "infer" in names
    comp = doc["components"]
    assert comp["compute"] > 0 and "queue_wait" in comp
    # /status.traces: the evidence index
    st = _get(server.port, "/status")
    traces = st["serving"]["traces"]
    assert traces["count"] >= 1
    assert traces["by_endpoint"]["predict"] >= 1
    assert traces["slowest"]["predict"][0]["trace_id"]
    # declared budget -> /status.slo + burn gauges on /metrics
    assert st["serving"]["slo"]["budgets"]["p99_ms"] == 10_000.0
    metrics = urllib.request.urlopen(
        f"http://127.0.0.1:{server.port}/metrics",
        timeout=10).read().decode()
    assert "bigdl_serve_latency_ms_bucket" in metrics
    assert 'le="+Inf"' in metrics
    assert "bigdl_slo_p99_burn_ratio" in metrics
    # the ring-buffer gauges tpu_watch.sh keys on stayed
    assert "bigdl_serve_p99_ms" in metrics


def test_rejected_requests_leave_terminal_traces(lenet_server):
    server = lenet_server
    release = threading.Event()
    inner = server.batcher.runner
    old_limit, old_timeout = (server.batcher.queue_limit,
                              server.request_timeout_s)

    def slow(xx, **kw):
        release.wait(10.0)
        return inner(xx, **kw)

    server.batcher.runner = slow
    server.batcher.queue_limit = 1
    server.batcher._q.maxsize = 1
    codes, lock = {}, threading.Lock()

    def client(i):
        try:
            code, _, _ = _post(server.port,
                               {"inputs": np.zeros((1, 784)).tolist()},
                               headers={"X-Request-Id": f"rej-{i}"})
        except urllib.error.HTTPError as e:
            code = e.code
        with lock:
            codes[i] = code

    try:
        threads = [threading.Thread(target=client, args=(i,))
                   for i in range(5)]
        for t in threads:
            t.start()
            time.sleep(0.05)
        release.set()
        for t in threads:
            t.join(30.0)
    finally:
        release.set()
        server.batcher.runner = inner
        server.batcher.queue_limit = old_limit
        server.batcher._q.maxsize = old_limit
        server.request_timeout_s = old_timeout
    rejected = [i for i, c in codes.items() if c == 429]
    assert rejected, codes
    # a 429 leaves a terminal-span trace with the rejection reason —
    # rejection spikes stay diagnosable post-hoc
    doc = _get(server.port, f"/v1/trace/rej-{rejected[0]}")
    assert doc["status"] == "rejected"
    assert doc["reason"] == "queue_full"
    assert doc["spans"][-1]["name"] == "rejected"
    assert sum(s["ms"] for s in doc["spans"]) == \
        pytest.approx(doc["ms"], rel=0.05)
    # counted per reason in the store and on /metrics
    st = _get(server.port, "/status")
    assert st["serving"]["traces"]["rejections"]["queue_full"] >= 1
    metrics = urllib.request.urlopen(
        f"http://127.0.0.1:{server.port}/metrics",
        timeout=10).read().decode()
    assert 'bigdl_serve_rejected_by_reason_total' in metrics
    assert 'reason="queue_full"' in metrics


def test_dispatch_failure_keeps_the_id_contract(lenet_server):
    """A worker exception (500) still echoes X-Request-Id and lands a
    terminal error trace — server-side failures are the requests most
    in need of post-hoc evidence."""
    server = lenet_server
    inner = server.batcher.runner

    def boom(xx, **kw):
        server.batcher.runner = inner
        raise RuntimeError("injected dispatch failure")

    server.batcher.runner = boom
    try:
        with pytest.raises(urllib.error.HTTPError) as ei:
            _post(server.port, {"inputs": np.zeros((1, 784)).tolist()},
                  headers={"X-Request-Id": "boom-1"})
    finally:
        server.batcher.runner = inner
    assert ei.value.code == 500
    assert ei.value.headers["X-Request-Id"] == "boom-1"
    doc = _get(server.port, "/v1/trace/boom-1")
    assert doc["status"] == "error"
    assert "injected dispatch failure" in doc["reason"]


def test_draining_rejection_leaves_a_trace(lenet_server):
    server = lenet_server
    server._term.set()
    try:
        with pytest.raises(urllib.error.HTTPError) as ei:
            _post(server.port, {"inputs": np.zeros((1, 784)).tolist()},
                  headers={"X-Request-Id": "drained-1"})
        assert ei.value.code == 503
        assert ei.value.headers["X-Request-Id"] == "drained-1"
    finally:
        server._term.clear()
    doc = _get(server.port, "/v1/trace/drained-1")
    assert doc["status"] == "rejected" and doc["reason"] == "draining"


def test_predict_dispatch_timeout_burns_the_slo_budget(lenet_server):
    """A 504's wall is real waiting the client did: it must enter the
    SLO burn, the violation ledger, and the latency histogram — an
    overloaded server timing out its requests must not pass the SLO
    gate on the strength of the requests it managed to answer."""
    server = lenet_server
    release = threading.Event()
    inner = server.batcher.runner

    def wedge(xx, **kw):
        release.wait(10.0)
        return inner(xx, **kw)

    old_timeout = server.request_timeout_s
    old_budget = server.slo.p99_ms
    hist_before = server._hist["predict"]._count
    server.batcher.runner = wedge
    server.request_timeout_s = 0.2
    server.slo.p99_ms = 50.0  # the ~200ms timeout wall must violate
    try:
        with pytest.raises(urllib.error.HTTPError) as ei:
            _post(server.port, {"inputs": np.zeros((1, 784)).tolist()},
                  headers={"X-Request-Id": "slow-504"})
        assert ei.value.code == 504
        assert ei.value.headers["X-Request-Id"] == "slow-504"
    finally:
        release.set()
        server.batcher.runner = inner
        server.request_timeout_s = old_timeout
        server.slo.p99_ms = old_budget
    doc = _get(server.port, "/v1/trace/slow-504")
    assert doc["status"] == "rejected"
    assert doc["reason"] == "dispatch_timeout"
    assert "p99" in doc.get("slo_violated", [])
    ledger = server.slo.status()["violating"]
    assert any(v["trace_id"] == "slow-504" for v in ledger), ledger
    assert server._hist["predict"]._count == hist_before + 1


def test_slo_ledger_keeps_the_worst_violators_not_the_newest():
    """Under a sustained burn the ledger is bounded at VIOLATING_KEEP
    — and keeps the WORST violators by budget overshoot, worst-first,
    so a long burn cannot evict its own catastrophic evidence with a
    tail of mild ones."""
    slo = rt.SLOTracker(p99_ms=10.0)
    # one catastrophic early violator, then a long tail of mild ones
    slo.observe(500.0, "catastrophe")
    for i in range(rt.VIOLATING_KEEP + 8):
        slo.observe(11.0 + i * 0.01, f"mild-{i}")
    st = slo.status()
    assert slo.violations == rt.VIOLATING_KEEP + 9
    assert len(st["violating"]) == rt.VIOLATING_KEEP
    assert st["violating"][0]["trace_id"] == "catastrophe"
    assert st["violating"][0]["severity"] == pytest.approx(50.0)
    kept = {v["trace_id"] for v in st["violating"]}
    assert "mild-0" not in kept  # the mildest fell off, not the worst


def test_slo_tracker_rejects_a_zero_budget_loudly():
    """`--slo-p99-ms 0` must not silently DISABLE the gate (0 is falsy
    — the old check dropped the budget and the bench exited 0 with no
    burn accounting at all)."""
    with pytest.raises(ValueError, match="must be > 0"):
        rt.SLOTracker(p99_ms=0.0)
    with pytest.raises(ValueError, match="must be > 0"):
        rt.SLOTracker(p99_ms=10.0, ttft_ms=0)
    assert rt.SLOTracker(p99_ms=None).active() is False  # None still ok


def test_diff_run_log_counts_rejected_504_violations(tmp_path):
    """`telemetry diff` run-log metrics: a rejected-504 that blew the
    budget counts in slo_violations (the zero-slack gate must see it)
    and its wall enters the request percentiles, while an instant 429
    stays out of the latency set."""
    from bigdl_tpu.telemetry.diff import run_log_metrics

    log = tmp_path / "run.jsonl"
    base = {"v": 1, "kind": "request", "ts": 1000.0}
    rows = [
        dict(base, trace_id="ok1", endpoint="predict", ms=10.0,
             status="ok"),
        dict(base, trace_id="t504", endpoint="predict", ms=30000.0,
             status="rejected", reason="dispatch_timeout",
             slo_violated=["p99"]),
        dict(base, trace_id="t429", endpoint="predict", ms=0.1,
             status="rejected", reason="queue_full"),
    ]
    log.write_text("".join(json.dumps(r) + "\n" for r in rows))
    m = run_log_metrics(str(log))
    assert m["slo_violations"] == 1
    # the 504's wall dominates the p99; the 429's 0.1ms is excluded
    assert m["request_p99_ms"] > 10_000.0
    assert m["request_p50_ms"] >= 10.0


def test_untraced_generate_timeout_burns_the_real_wall(gen_server):
    """With BIGDL_TRACE=off a token-less generate 504 must observe the
    enqueue-to-retire wall (stats()['dur_s'] reads 0.0 with no tokens
    — a window of zeros would read as a healthy burn)."""
    server = gen_server
    old_traces, old_timeout = server.traces, server.request_timeout_s
    old_budget = server.slo.p99_ms
    server.traces = None  # recording off; budgets must keep burning
    server.request_timeout_s = 0.02
    server.slo.p99_ms = 5.0
    lat_before = len(server.slo._lat)
    try:
        with pytest.raises(urllib.error.HTTPError) as ei:
            _generate(server.port,
                      {"prompt": [1, 2, 3], "max_new_tokens": 60,
                       "stream": False})
        assert ei.value.code == 504
        deadline = time.monotonic() + 30.0
        while time.monotonic() < deadline \
                and len(server.slo._lat) == lat_before:
            time.sleep(0.02)
    finally:
        server.traces = old_traces
        server.request_timeout_s = old_timeout
        server.slo.p99_ms = old_budget
    assert len(server.slo._lat) > lat_before
    assert server.slo._lat[-1] >= 15.0  # the ~20ms wall, not dur_s=0


def test_request_fold_counts_rejected_violations_in_both_tallies():
    """The shared RequestFold: a 504 dispatch timeout is BOTH a
    per-reason rejection and (its full wall observed) an SLO violation
    — and the MetricsSink and fleet HostState fold through the one
    implementation."""
    from bigdl_tpu.telemetry.fleet import HostState
    from bigdl_tpu.telemetry.metrics_http import MetricsSink

    fold = rt.RequestFold()
    fold.fold({"kind": "request", "trace_id": "t1", "endpoint":
               "predict", "ms": 30000.0, "status": "rejected",
               "reason": "dispatch_timeout", "slo_violated": ["p99"]})
    assert fold.rejections == {"dispatch_timeout": 1}
    assert fold.slo_violations == 1
    # rejected requests never become the slowest-completed exemplar
    assert fold.slowest == {}
    assert isinstance(MetricsSink().requests, rt.RequestFold)
    assert isinstance(HostState("p0.jsonl").requests, rt.RequestFold)


def test_each_frontend_status_reports_itself(lenet_server, gen_server):
    """With several live servers in one process, each port's /status
    must carry ITS OWN serving block — the observer merge used to
    overwrite it with whichever server registered serving.get() last."""
    st_l = _get(lenet_server.port, "/status")
    st_g = _get(gen_server.port, "/status")
    assert st_l["serving"]["model"] == "lenet"
    assert st_g["serving"]["model"] == "tlm"


# -- the acceptance e2e: injected queue stall -> queue_wait blame -------------
@pytest.mark.deadline(240)
def test_queue_stall_is_blamed_on_queue_wait_not_the_cobatch(
        lenet_server, tmp_path):
    """Mixed load with one injected ~250ms queue stall: the stalled
    request's waterfall sums to its wall time within 5%, the blame
    verdict names queue_wait, a healthy co-batched request is NOT
    blamed, and `telemetry trace --slowest` reproduces the waterfall
    offline from the run log."""
    from bigdl_tpu import telemetry

    server = lenet_server
    x = {"inputs": np.zeros((1, 784)).tolist()}
    log = str(tmp_path / "run.jsonl")
    with telemetry.run(log):
        # warm the endpoint baseline: blame verdicts need
        # BASELINE_MIN_SAMPLES healthy requests to judge against
        for _ in range(rt.BASELINE_MIN_SAMPLES + 4):
            _post(server.port, x)
        # inject the stall: the worker blocks ~250ms inside a dispatch
        # while the victim sits in the queue behind it
        inner = server.batcher.runner
        stalled, release = threading.Event(), threading.Event()

        def stall_once(xx, **kw):
            server.batcher.runner = inner
            stalled.set()
            release.wait(10.0)
            return inner(xx, **kw)

        results = {}

        def client(name, headers):
            code, body, _ = _post(server.port, x, headers=headers)
            results[name] = (code, body)

        server.batcher.runner = stall_once
        t_blocker = threading.Thread(
            target=client, args=("blocker", {}))
        t_blocker.start()
        assert stalled.wait(10.0)
        t0 = time.perf_counter()
        t_victim = threading.Thread(
            target=client,
            args=("victim", {"X-Request-Id": "victim-1"}))
        t_victim.start()
        time.sleep(0.25)  # the victim's injected queue wait
        # the rider lands in the queue JUST before the stall lifts, so
        # it co-batches with the victim but waited almost nothing
        t_rider = threading.Thread(
            target=client, args=("rider", {"X-Request-Id": "rider-1"}))
        t_rider.start()
        deadline = time.time() + 10.0
        while server.batcher._q.qsize() < 2 and time.time() < deadline:
            time.sleep(0.001)
        release.set()
        for t in (t_blocker, t_victim, t_rider):
            t.join(30.0)
        victim_wall_ms = (time.perf_counter() - t0) * 1000.0
    assert all(code == 200 for code, _ in results.values()), results

    doc = _get(server.port, "/v1/trace/victim-1")
    # complete waterfall: spans sum to the observed wall within 5%
    span_sum = sum(s["ms"] for s in doc["spans"])
    assert span_sum == pytest.approx(doc["ms"], rel=0.05)
    assert doc["ms"] <= victim_wall_ms * 1.05
    # the verdict names the stall...
    assert doc["components"]["queue_wait"] > 200.0
    assert doc["blame"]["cause"] == "queue_wait"
    assert doc["blame"]["excess_ms"] > 150.0
    # ...and does NOT blame the healthy co-batched request that rode
    # the same dispatch (its own queue wait was a few ms)
    rider = _get(server.port, "/v1/trace/rider-1")
    assert rider["components"].get("queue_wait", 0.0) < 100.0
    assert (rider.get("blame") or {}).get("cause") != "queue_wait"
    # the victim is now the retained tail exemplar
    st = _get(server.port, "/status")
    slowest = st["serving"]["traces"]["slowest"]["predict"]
    assert "victim-1" in [r["trace_id"] for r in slowest]

    # offline twin: `telemetry trace run.jsonl --slowest 3` reproduces
    # the same waterfall from the run log's `request` events
    out = subprocess.run(
        [sys.executable, "-m", "bigdl_tpu.telemetry", "trace", log,
         "--slowest", "3"],
        capture_output=True, text=True, cwd=REPO,
        env=dict(os.environ, JAX_PLATFORMS="cpu"), timeout=120)
    assert out.returncode == 0, out.stderr
    assert "victim-1" in out.stdout
    assert "blame=queue_wait" in out.stdout
    assert "queue_wait" in out.stdout and "infer" in out.stdout
    # --id renders exactly the victim; --chrome exports request lanes
    chrome = str(tmp_path / "req.json")
    out = subprocess.run(
        [sys.executable, "-m", "bigdl_tpu.telemetry", "trace", log,
         "--id", "victim-1", "--chrome", chrome],
        capture_output=True, text=True, cwd=REPO,
        env=dict(os.environ, JAX_PLATFORMS="cpu"), timeout=120)
    assert out.returncode == 0, out.stderr
    lanes = json.load(open(chrome))["traceEvents"]
    assert any(e.get("args", {}).get("name") ==
               "req victim-1 [predict]" for e in lanes)


# -- live HTTP: generate ------------------------------------------------------
@pytest.fixture(scope="module")
def gen_server():
    import jax

    from bigdl_tpu.models.transformer import build_transformer_lm
    from bigdl_tpu.serving import serve_model
    from bigdl_tpu.utils.rng import RNG

    RNG.set_seed(7)
    model = build_transformer_lm(vocab_size=VOCAB, num_layers=2,
                                 embed_dim=32, num_heads=2, max_len=64,
                                 scan=False).evaluate()
    spec = jax.ShapeDtypeStruct((1, 16), np.int32)
    server = serve_model(model, spec, name="tlm", host="127.0.0.1",
                         port=0, max_batch=2, batch_buckets=[1, 2],
                         seq_buckets=[16], max_wait_ms=1.0,
                         generate=True, decode_buckets=[1, 2],
                         cache_buckets=[64])
    try:
        yield server
    finally:
        server.stop(drain=False)


def _generate(port, payload, headers=None, timeout=60.0):
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/v1/generate",
        data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json", **(headers or {})},
        method="POST")
    with urllib.request.urlopen(req, timeout=timeout) as r:
        return (r.status, [json.loads(l) for l in r if l.strip()],
                dict(r.headers))


def test_generate_trace_decomposes_ttft_and_inter_token(gen_server):
    server = gen_server
    code, lines, hdrs = _generate(
        server.port, {"prompt": [1, 2, 3], "max_new_tokens": 5},
        headers={"X-Request-Id": "gen-1"})
    assert code == 200
    assert hdrs["X-Request-Id"] == "gen-1"
    done = lines[-1]
    assert done["done"] is True and done["trace_id"] == "gen-1"
    doc = _get(server.port, "/v1/trace/gen-1")
    assert doc["endpoint"] == "generate" and doc["status"] == "ok"
    names = [s["name"] for s in doc["spans"]]
    # TTFT decomposes: parse -> queue_wait -> prefill; inter-token time
    # decomposes into the decode iterations the request actually rode
    assert names.index("queue_wait") < names.index("prefill")
    decodes = [s for s in doc["spans"] if s["name"] == "decode"]
    assert len(decodes) == 4  # 5 tokens: 1 off the prefill + 4 decodes
    assert all("co_batch" in s for s in decodes)
    assert len(doc["token_ts"]) == 5
    assert doc["ttft_ms"] > 0 and doc["n_tokens"] == 5
    # span completeness holds on the generate path too
    span_sum = sum(s["ms"] for s in doc["spans"])
    assert span_sum == pytest.approx(doc["ms"], rel=0.05)
    metrics = urllib.request.urlopen(
        f"http://127.0.0.1:{server.port}/metrics",
        timeout=10).read().decode()
    assert 'bigdl_serve_latency_ms_bucket{model="tlm",' \
           'endpoint="generate"' in metrics
    assert "bigdl_serve_ttft_ms_bucket" in metrics
    # exactly ONE TYPE line per metric family even with both endpoint
    # label sets present — a duplicate makes strict scrapers drop the
    # whole scrape
    assert metrics.count("# TYPE bigdl_serve_latency_ms histogram") == 1


@pytest.mark.deadline(240)
def test_prefill_flood_is_blamed_on_interference(gen_server):
    """A healthy decode stream that stalls because the worker keeps
    prefilling OTHER requests is blamed on prefill_interference — not
    on its own compute."""
    server = gen_server
    rng = np.random.default_rng(3)
    # warm the generate baseline with sequential healthy requests
    for _ in range(rt.BASELINE_MIN_SAMPLES + 2):
        code, _, _ = _generate(server.port,
                               {"prompt": rng.integers(
                                   1, VOCAB, 3).tolist(),
                                "max_new_tokens": 3})
        assert code == 200
    results, errors = {}, []

    def client(name, prompt, n):
        try:
            hdr = {"X-Request-Id": name}
            results[name] = _generate(
                server.port, {"prompt": prompt, "max_new_tokens": n},
                headers=hdr)
        except Exception as e:  # noqa: BLE001 - surfaced below
            errors.append((name, e))

    # the victim decodes many tokens; the flood keeps forcing prefill
    # dispatches into the worker loop while the victim is active
    victim = threading.Thread(
        target=client,
        args=("flood-victim", rng.integers(1, VOCAB, 4).tolist(), 55))
    victim.start()
    time.sleep(0.01)
    flood = [threading.Thread(
        target=client,
        args=(f"flood-{i}", rng.integers(1, VOCAB, 12).tolist(), 2))
        for i in range(8)]
    for t in flood:
        t.start()
        time.sleep(0.005)
    victim.join(120.0)
    for t in flood:
        t.join(120.0)
    assert errors == []
    doc = _get(server.port, "/v1/trace/flood-victim")
    assert doc["components"].get("prefill_interference", 0.0) > 0.0
    assert any(s["name"] == "prefill_interference"
               for s in doc["spans"])
    assert doc["blame"]["cause"] == "prefill_interference", doc["blame"]


@pytest.mark.deadline(120)
def test_generate_dispatch_timeout_is_a_counted_rejection(gen_server):
    """A non-streamed /v1/generate 504 leaves a dispatch_timeout
    REJECTION record (per-reason counters, /status.traces.rejections)
    exactly like the predict path — not an anonymous cancellation."""
    server = gen_server
    old_timeout = server.request_timeout_s
    server.request_timeout_s = 0.02  # 60 tokens cannot finish in 20ms
    try:
        with pytest.raises(urllib.error.HTTPError) as ei:
            _generate(server.port,
                      {"prompt": [1, 2, 3], "max_new_tokens": 60,
                       "stream": False},
                      headers={"X-Request-Id": "gen-504"})
        assert ei.value.code == 504
        assert ei.value.headers["X-Request-Id"] == "gen-504"
    finally:
        server.request_timeout_s = old_timeout
    # the retire hook lands the trace asynchronously after the cancel
    deadline = time.monotonic() + 30.0
    doc = None
    while time.monotonic() < deadline:
        try:
            doc = _get(server.port, "/v1/trace/gen-504")
            if doc.get("status") == "rejected":
                break
        except urllib.error.HTTPError:
            pass
        time.sleep(0.05)
    assert doc is not None and doc["status"] == "rejected", doc
    assert doc["reason"] == "dispatch_timeout"
    st = _get(server.port, "/status")
    assert st["serving"]["traces"]["rejections"][
        "dispatch_timeout"] >= 1


# -- schema -------------------------------------------------------------------
def test_request_events_are_schema_valid():
    from bigdl_tpu import telemetry
    from bigdl_tpu.models import registry
    from bigdl_tpu.serving import serve_model
    from bigdl_tpu.telemetry import schema

    sink = telemetry.MemorySink()
    with telemetry.run(sinks=[sink]):
        model = registry.build_model("lenet")
        server = serve_model(model, registry.input_spec("lenet", 1),
                             host="127.0.0.1", port=0, max_batch=4,
                             batch_buckets=[4], max_wait_ms=1.0,
                             slo_p99_ms=10_000.0)
        try:
            _post(server.port, {"inputs": np.zeros((2, 784)).tolist()})
        finally:
            server.stop(drain=True)
    reqs = [e for e in sink.events if e.get("kind") == "request"]
    assert len(reqs) == 1
    ev = reqs[0]
    assert ev["endpoint"] == "predict" and ev["status"] == "ok"
    assert rt.valid_id(ev["trace_id"]) and ev["ms"] > 0
    assert ev["spans"] and ev["components"]
    assert ev["slo_p99_ms"] == 10_000.0
    assert schema.validate_events(sink.events) == []
    # the serve batch event now carries the min/mean queue waits beside
    # the worst-case anchor, so aggregates stop overstating the typical
    serves = [e for e in sink.events if e.get("kind") == "serve"]
    assert serves
    batch = serves[0]
    assert {"queue_ms", "queue_ms_min", "queue_ms_mean"} <= set(batch)
    assert batch["queue_ms_min"] <= batch["queue_ms_mean"] \
        <= batch["queue_ms"]


def test_metrics_sink_and_fleet_fold_request_events():
    from bigdl_tpu.telemetry.fleet import HostState
    from bigdl_tpu.telemetry.metrics_http import MetricsSink

    events = [
        _request_event("fast-1"),
        dict(_request_event("slow-1"), ms=777.0,
             blame={"cause": "queue_wait"}, slo_violated=["p99"]),
        {"kind": "request", "trace_id": "r", "endpoint": "predict",
         "ms": 0.1, "status": "rejected", "reason": "queue_full",
         "ts": 2.0},
        {"kind": "gauge", "name": "serve/slo_p99_burn", "value": 0.8,
         "ts": 3.0},
        {"kind": "gauge", "name": "serve/slo_ttft_burn", "value": 0.3,
         "ts": 3.0},
    ]
    sink = MetricsSink()
    for ev in events:
        sink.emit(ev)
    snap = sink.status()["requests"]
    assert snap["count"] == 3
    assert snap["rejections"] == {"queue_full": 1}
    assert snap["slo_violations"] == 1
    assert snap["slowest"]["trace_id"] == "slow-1"
    assert snap["slowest"]["blame"] == "queue_wait"
    body = sink.openmetrics()
    assert "bigdl_request_traces_total" in body
    assert "bigdl_request_slo_violations_total" in body
    # the fleet view folds the same events into per-replica SLO columns
    host = HostState("p0.jsonl")
    host.fold(events)
    row = host.row(now=4.0)
    assert row["slo_p99_burn"] == pytest.approx(0.8)
    assert row["slo_ttft_burn"] == pytest.approx(0.3)
    assert row["slo_violations"] == 1
    assert row["slowest_request"]["trace_id"] == "slow-1"


# -- bench_serving SLO gate (live subprocess) ---------------------------------
@pytest.mark.deadline(240)
def test_bench_serving_slo_gate_exits_4_with_trace_evidence(tmp_path):
    """An impossible p99 budget must burn: exit 4 (the --diff-against
    regression code), the bench JSON row carrying the violating
    requests' trace ids — the failing artifact names its own
    evidence."""
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    out = subprocess.run(
        [sys.executable, "bench_serving.py", "--model", "lenet",
         "--qps", "40", "--duration", "2", "-b", "8",
         "--buckets", "4,8", "--max-wait-ms", "2",
         "--slo-p99-ms", "0.001"],
        capture_output=True, text=True, cwd=REPO, env=env, timeout=220)
    assert out.returncode == 4, (out.returncode, out.stderr[-2000:])
    assert "SLO VIOLATED" in out.stderr
    line = [l for l in out.stdout.splitlines() if l.startswith("{")][-1]
    row = json.loads(line)["configs"]["serve_lenet"]
    assert row["slo_violations"] > 0
    slo = row["slo"]
    assert slo["burn"]["p99"]["burn"] > 1.0
    violating = slo["violating"]
    assert violating and all(rt.valid_id(v["trace_id"])
                             for v in violating)
