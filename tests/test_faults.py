"""Fault-matrix suite (ISSUE 5): every ``FaultPlan`` kind, injected into
a seeded run, must either RECOVER (the run completes, the final loss is
finite, and — for the crash/preempt/kill classes, whose recovery path
replays the exact interrupted trajectory — the final params match the
no-fault run) or HALT WITH EVIDENCE (``HealthError`` + flight dump).
Torn checkpoints must never be partially loaded: restore verifies the
content digests and either loads fully or quarantines and falls back to
the previous good step; ``prune_old`` never deletes the last
verified-good checkpoint."""

import json
import os
import signal
import subprocess
import sys

import numpy as np
import pytest

import bigdl_tpu.nn as nn
import bigdl_tpu.optim as optim
from bigdl_tpu import faults, telemetry
from bigdl_tpu.dataset.sample import Sample
from bigdl_tpu.nn.module import state_dict
from bigdl_tpu.optim.trigger import Trigger
from bigdl_tpu.telemetry.health import HealthError
from bigdl_tpu.utils.config import set_config
from bigdl_tpu.utils.rng import RNG

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def setup_function(_fn):
    faults.reset()


def teardown_function(_fn):
    telemetry.end_run()
    set_config(None)
    faults.reset()


def _instants(sink, name):
    return [e for e in sink.events
            if e.get("kind") == "event" and e.get("name") == name]


def _data(n=64, dim=4, seed=0):
    rng = np.random.default_rng(seed)
    x = rng.normal(size=(n, dim)).astype(np.float32)
    y = (x[:, 0] > 0).astype(np.int64)
    return [Sample(x[i], np.int64(y[i])) for i in range(n)]


def _optimizer(tmp_path, iters=8, ckpt_every=2, backend="btpu", seed=11,
               lr=0.1):
    RNG.set_seed(seed)
    model = nn.Sequential(nn.Linear(4, 16), nn.Tanh(),
                          nn.Linear(16, 2), nn.LogSoftMax())
    o = optim.LocalOptimizer(model, _data(), nn.ClassNLLCriterion(),
                             batch_size=16,
                             end_trigger=Trigger.max_iteration(iters))
    o.set_optim_method(optim.SGD(learning_rate=lr, momentum=0.9))
    if ckpt_every:
        o.set_checkpoint(str(tmp_path), Trigger.several_iteration(ckpt_every),
                         backend=backend)
        o.overwrite_checkpoint()
    return o


def _run(tmp_path, monkeypatch, fault_spec="", sink=None, **env):
    """One seeded training run under a fault plan; returns (optimizer,
    final params dict)."""
    monkeypatch.setenv("BIGDL_RETRY_BACKOFF", "0.05")  # fast matrix
    if fault_spec:
        monkeypatch.setenv("BIGDL_FAULTS", fault_spec)
    else:
        monkeypatch.delenv("BIGDL_FAULTS", raising=False)
    for k, v in env.items():
        monkeypatch.setenv(k, str(v))
    faults.reset()
    o = _optimizer(tmp_path)
    if sink is not None:
        with telemetry.run(sinks=[sink]):
            trained = o.optimize()
    else:
        trained = o.optimize()
    return o, {k: np.asarray(v) for k, v in state_dict(
        trained, kind="param").items()}


def _assert_params_equal(a, b, tol=1e-6):
    assert set(a) == set(b) and a
    for k in a:
        np.testing.assert_allclose(a[k], b[k], rtol=tol, atol=tol,
                                   err_msg=f"param {k} diverged")


# -- plan parsing ------------------------------------------------------------
def test_plan_parse_full_syntax():
    plan = faults.FaultPlan.parse(
        "crash@12,nan_grads@30,wedge@45,kill_worker@20:p1,torn_ckpt,"
        "data_err@7", seed=3)
    kinds = [(s.kind, s.step, s.process) for s in plan.specs]
    assert kinds == [("crash", 12, None), ("nan_grads", 30, None),
                     ("wedge", 45, None), ("kill_worker", 20, 1),
                     ("torn_ckpt", None, None), ("data_err", 7, None)]
    assert plan.has("torn_ckpt") and not plan.has("preempt")


def test_plan_rejects_bad_specs():
    for bad in ("explode@3", "crash@", "crash@x", "crash:px", "crash@3:q1"):
        with pytest.raises(ValueError, match="bad fault spec"):
            faults.FaultPlan.parse(bad)


def test_plan_parse_straggle_requires_delay():
    plan = faults.FaultPlan.parse("straggle@4:p1:250")
    s = plan.specs[0]
    assert (s.kind, s.step, s.process, s.ms) == ("straggle", 4, 1, 250)
    with pytest.raises(ValueError, match="straggle needs a delay"):
        faults.FaultPlan.parse("straggle@4:p1")
    with pytest.raises(ValueError, match="only straggle takes"):
        faults.FaultPlan.parse("crash@3:250")


def test_straggle_sleep_persists_and_announces_once():
    """Unlike every other kind, ``straggle`` is NOT exactly-once: a slow
    host stays slow, so every fetch from ``@step`` on is delayed; only
    the ``fault/injected`` announcement fires once."""
    plan = faults.FaultPlan.parse("straggle@3:250")
    sink = telemetry.MemorySink()
    with telemetry.run(sinks=[sink]):
        assert plan.straggle_sleep(1) == 0.0
        assert plan.straggle_sleep(2) == 0.0
        assert plan.straggle_sleep(3) == pytest.approx(0.25)
        assert plan.straggle_sleep(9) == pytest.approx(0.25)
    marks = _instants(sink, "fault/injected")
    assert len(marks) == 1 and marks[0]["fault"] == "straggle"
    # a :pP selector for another process never slows THIS one
    other = faults.FaultPlan.parse("straggle@1:p1:250")
    assert other.straggle_sleep(5) == 0.0
    # overlapping specs: the worst delay wins, not the sum
    both = faults.FaultPlan.parse("straggle@1:100,straggle@2:50")
    assert both.straggle_sleep(2) == pytest.approx(0.1)


def test_straggle_delays_data_iter_in_place():
    """The injection point: ``wrap_data_iter`` sleeps ON the fetching
    thread, so under prefetch the delay lands inside the ``data_wait``
    span that fleet blame attributes (telemetry/fleet.py)."""
    import time as _time

    plan = faults.FaultPlan.parse("straggle@2:60")
    it = plan.wrap_data_iter(iter([1, 2, 3]))
    t0 = _time.perf_counter()
    assert next(it) == 1
    fast = _time.perf_counter() - t0
    t1 = _time.perf_counter()
    assert list(it) == [2, 3]
    slow = _time.perf_counter() - t1
    assert slow >= 0.12 > fast


def test_bad_plan_fails_fast_not_retried(tmp_path, monkeypatch):
    """A typo'd BIGDL_FAULTS is a CONFIG error: optimize() must surface
    it immediately, not burn the retry budget on it."""
    monkeypatch.setenv("BIGDL_FAULTS", "kaboom@3")
    faults.reset()
    o = _optimizer(tmp_path, ckpt_every=0)
    with pytest.raises(ValueError, match="bad fault spec"):
        o.optimize()


def test_fault_fires_exactly_once():
    plan = faults.FaultPlan.parse("nan_grads@3")
    assert plan.grad_scale(2) == 1.0
    assert np.isnan(plan.grad_scale(3))
    assert plan.grad_scale(3) == 1.0  # already fired
    assert plan.grad_scale(4) == 1.0


def test_process_selector_gates_firing(monkeypatch):
    plan = faults.FaultPlan.parse("nan_grads@3:p1")
    # this test process is process_index 0 -> the p1 fault never fires
    assert plan.grad_scale(3) == 1.0
    assert not plan.specs[0].fired


# -- the matrix: recover-or-halt ---------------------------------------------
def test_crash_recovers_and_matches_no_fault_run(tmp_path, monkeypatch):
    """``crash@6``: the retry loop restores model.4, the resume replays
    iterations 5-8 on the SAME batches and step keys, and the final
    params equal the uninterrupted run's — crash-consistent restore is
    trajectory-exact, not merely 'finishes'."""
    _, want = _run(tmp_path / "clean", monkeypatch)
    sink = telemetry.MemorySink()
    o, got = _run(tmp_path / "faulty", monkeypatch, "crash@6", sink=sink)
    assert o.state["neval"] == 8
    assert np.isfinite(o.state["loss"])
    _assert_params_equal(got, want)
    injected = _instants(sink, "fault/injected")
    assert len(injected) == 1 and injected[0]["fault"] == "crash" \
        and injected[0]["step"] == 6
    retries = _instants(sink, "run/retry")
    assert len(retries) == 1 and retries[0]["backoff_s"] >= 0


def test_nan_grads_halts_with_flight_evidence(tmp_path, monkeypatch):
    """``nan_grads@3`` under the halt policy: the in-graph probe sees
    nonfinite GRADS at exactly step 3, the policy halts (HealthError is
    a verdict — never retried), and the flight recorder dumps the
    evidence."""
    tele_dir = tmp_path / "tele"
    o = None
    with pytest.raises(HealthError) as err:
        o, _ = _run(tmp_path, monkeypatch, "nan_grads@3",
                    BIGDL_HEALTH="halt", BIGDL_HEALTH_HALT_AFTER="1",
                    BIGDL_TELEMETRY=str(tele_dir))
    assert err.value.step == 3
    assert err.value.evidence["nonfinite_grads"] > 0
    dumps = [f for f in os.listdir(tele_dir) if f.startswith("flight-")]
    assert len(dumps) == 1
    payload = json.loads((tele_dir / dumps[0]).read_text())
    assert payload["reason"] == "health_halt"
    assert any(e.get("name") == "fault/injected"
               for e in payload["events"])


def test_nan_grads_skip_policy_recovers(tmp_path, monkeypatch):
    """Same poison under ``BIGDL_HEALTH=skip``: the in-graph select
    drops the poisoned update, params stay finite, the run completes."""
    sink = telemetry.MemorySink()
    o, got = _run(tmp_path, monkeypatch, "nan_grads@3", sink=sink,
                  BIGDL_HEALTH="skip")
    assert o.state["neval"] == 8
    assert np.isfinite(o.state["loss"])
    for k, v in got.items():
        assert np.isfinite(v).all(), f"param {k} went nonfinite"
    assert len(_instants(sink, "fault/injected")) == 1
    assert len(_instants(sink, "health/skip")) >= 1


def test_wedge_trips_straggler_watchdog_and_recovers(tmp_path, monkeypatch):
    """``wedge@3``: the iteration stalls inside the straggler-guarded
    region, the watchdog fires at the budget, the retry loop restores
    the step-2 checkpoint, and the run completes with a flight dump for
    the stall."""
    tele_dir = tmp_path / "tele"
    sink = telemetry.MemorySink()
    o, got = _run(tmp_path, monkeypatch, "wedge@3", sink=sink,
                  BIGDL_ITERATION_TIMEOUT="1.5",
                  BIGDL_TELEMETRY=str(tele_dir))
    assert o.state["neval"] == 8
    assert np.isfinite(o.state["loss"])
    assert len(_instants(sink, "fault/injected")) == 1
    assert len(_instants(sink, "straggler/timeout")) == 1
    dumps = [f for f in os.listdir(tele_dir) if f.startswith("flight-")]
    assert len(dumps) == 1  # the straggler firing dumped the lead-in


def test_data_err_relays_through_prefetch_and_recovers(tmp_path,
                                                       monkeypatch):
    """``data_err@5``: the injected fetch failure surfaces on the
    prefetch producer thread, relays to the driver exactly like a
    compute error, and the retry loop restores + completes."""
    sink = telemetry.MemorySink()
    o, _ = _run(tmp_path, monkeypatch, "data_err@5", sink=sink)
    assert o.state["neval"] == 8
    assert np.isfinite(o.state["loss"])
    injected = _instants(sink, "fault/injected")
    assert len(injected) == 1 and injected[0]["point"] == "data"
    assert len(_instants(sink, "run/retry")) == 1


def test_preempt_commits_final_checkpoint_and_resume_matches(tmp_path,
                                                             monkeypatch):
    """``preempt@5``: a REAL SIGTERM is delivered mid-run; the grace
    handler finishes iteration 5, commits a final checkpoint carrying
    the mid-epoch position + RNG state, and optimize() returns cleanly
    with ``preempted=True``.  A FRESH optimizer pointed at the same
    checkpoint dir auto-resumes and lands on the uninterrupted run's
    exact final params."""
    _, want = _run(tmp_path / "clean", monkeypatch)
    sink = telemetry.MemorySink()
    o, _ = _run(tmp_path / "ckpt", monkeypatch, "preempt@5", sink=sink)
    assert o.preempted
    assert o.state["neval"] == 5  # finished the in-flight step, no more
    assert any(f == "model.5" for f in os.listdir(tmp_path / "ckpt"))
    marks = _instants(sink, "run/preempted")
    assert len(marks) == 1 and marks[0]["step"] == 5 \
        and marks[0]["signum"] == signal.SIGTERM
    # fresh process analogue: new optimizer, same ckpt dir, no faults
    sink2 = telemetry.MemorySink()
    o2, got = _run(tmp_path / "ckpt", monkeypatch, sink=sink2)
    assert len(_instants(sink2, "run/resumed")) == 1
    assert o2.state["neval"] == 8
    _assert_params_equal(got, want)


def test_resume_off_disables_auto_resume(tmp_path, monkeypatch):
    o, _ = _run(tmp_path, monkeypatch, "preempt@5")
    assert o.preempted
    monkeypatch.setenv("BIGDL_RESUME", "off")
    o2, _ = _run(tmp_path, monkeypatch)
    # started from scratch: the full 8 iterations, no resumed marker
    assert "_resumed_from" not in o2.state


def test_kill_worker_is_ungraceful_and_restart_resumes(tmp_path,
                                                       monkeypatch):
    """``kill_worker@4``: SIGKILL, no handler, no final checkpoint — the
    subprocess dies at the injected step; a restarted process resumes
    from the last TRIGGERED checkpoint and matches the uninterrupted
    run.  (Subprocess test: SIGKILL in-process would take pytest with
    it.)  Synchronous checkpointing pins the last committed step."""
    worker = os.path.join(REPO, "tests", "multihost_worker.py")
    ckpt = tmp_path / "ckpt"
    ckpt.mkdir()

    def run_single(tag, **extra):
        env = {k: v for k, v in os.environ.items()
               if k not in ("XLA_FLAGS", "JAX_PLATFORMS", "BIGDL_FAULTS")}
        env.update(BIGDL_REPO=REPO, BIGDL_TEST_OUT=str(tmp_path / tag),
                   BIGDL_TEST_ITERS="6", BIGDL_ASYNC_CHECKPOINT="0",
                   **{k: str(v) for k, v in extra.items()})
        return subprocess.run([sys.executable, worker], env=env,
                              capture_output=True, timeout=420)

    r = run_single("clean.npz", BIGDL_TEST_CKPT=str(tmp_path / "ckpt_un"),
                   BIGDL_TEST_CKPT_EVERY=2)
    assert r.returncode == 0, r.stdout[-2000:]

    r = run_single("killed.npz", BIGDL_TEST_CKPT=str(ckpt),
                   BIGDL_TEST_CKPT_EVERY=2, BIGDL_FAULTS="kill_worker@4")
    assert r.returncode == -signal.SIGKILL, (r.returncode, r.stdout[-2000:])
    assert not (tmp_path / "killed.npz").exists()
    assert any(f.startswith("model.2") for f in os.listdir(ckpt))

    r = run_single("resumed.npz", BIGDL_TEST_CKPT=str(ckpt),
                   BIGDL_TEST_CKPT_EVERY=2)
    assert r.returncode == 0, r.stdout[-2000:]
    a = np.load(tmp_path / "clean.npz")
    b = np.load(tmp_path / "resumed.npz")
    assert set(a.files) == set(b.files) and len(a.files) > 0
    for k in a.files:
        np.testing.assert_allclose(a[k], b[k], rtol=1e-6, atol=1e-7,
                                   err_msg=f"param {k} diverged")


# -- torn checkpoints: verify, quarantine, fall back -------------------------
def test_torn_sharded_checkpoint_quarantined_and_fallback(tmp_path,
                                                          monkeypatch):
    """``torn_ckpt@4`` + ``crash@5``: the step-4 sharded checkpoint is
    torn AFTER its complete-marker committed (the tear the marker can't
    catch); the crash's restore verifies digests, quarantines sharded.4
    as ``*.corrupt``, falls back to sharded.2, and the run still
    completes — a torn checkpoint is never partially loaded."""
    monkeypatch.setenv("BIGDL_RETRY_BACKOFF", "0.05")
    monkeypatch.setenv("BIGDL_FAULTS", "torn_ckpt@4,crash@5")
    faults.reset()
    sink = telemetry.MemorySink()
    o = _optimizer(tmp_path, backend="sharded")
    with telemetry.run(sinks=[sink]):
        o.optimize()
    assert o.state["neval"] == 8
    names = sorted(os.listdir(tmp_path))
    assert "sharded.4.corrupt" in names, names
    assert "sharded.8" in names  # post-recovery checkpoints kept landing
    q = _instants(sink, "checkpoint/quarantined")
    assert len(q) == 1 and q[0]["path"].endswith("sharded.4")
    assert len(_instants(sink, "fault/injected")) == 2


def test_torn_btpu_checkpoint_quarantined_and_fallback(tmp_path,
                                                       monkeypatch):
    """Same story on the BTPU (gather-and-write) backend: ckptmeta
    digests reject the torn model.4, the pair moves to ``*.corrupt``,
    restore falls back to the step-2 pair, and the final params still
    match the no-fault run (trajectory-exact recovery)."""
    _, want = _run(tmp_path / "clean", monkeypatch)
    sink = telemetry.MemorySink()
    o, got = _run(tmp_path / "faulty", monkeypatch, "torn_ckpt@4,crash@5",
                  sink=sink)
    assert o.state["neval"] == 8
    names = sorted(os.listdir(tmp_path / "faulty"))
    assert "model.4.corrupt" in names, names
    q = _instants(sink, "checkpoint/quarantined")
    assert len(q) == 1 and q[0]["step"] == 4
    _assert_params_equal(got, want)


def test_restore_never_partially_loads_torn_sharded(tmp_path):
    """Direct API check: a bit-flipped shard makes restore_train_step
    raise BEFORE any state is touched — the step keeps its live params
    wholesale."""
    import jax

    from bigdl_tpu.parallel.train_step import TrainStep
    from bigdl_tpu.utils.sharded_ckpt import (CorruptCheckpointError,
                                              restore_train_step,
                                              save_train_step)

    RNG.set_seed(3)
    model = nn.Sequential(nn.Linear(4, 8), nn.Tanh(), nn.Linear(8, 2),
                          nn.LogSoftMax())
    step = TrainStep(model, nn.ClassNLLCriterion(),
                     optim.SGD(learning_rate=0.1))
    x = np.random.default_rng(0).normal(size=(8, 4)).astype(np.float32)
    step.run(x, np.zeros(8, np.int64), jax.random.key(0))
    d = str(tmp_path / "sharded.1")
    save_train_step(step, d, extra={"neval": 1})
    # flip payload bytes via the plan's own corruptor
    torn = faults.FaultPlan.parse("torn_ckpt")._corrupt_one_file(d)
    assert torn is not None and not torn.endswith(".json")
    before = {k: np.asarray(v) for k, v in step.params.items()}
    with pytest.raises(CorruptCheckpointError, match="digest mismatch"):
        restore_train_step(step, d)
    for k, v in step.params.items():
        np.testing.assert_array_equal(np.asarray(v), before[k])


def test_prune_old_keeps_last_verified_good(tmp_path):
    """Retention must never strand the run: when every checkpoint inside
    the keep window is torn, the newest verified-good one survives
    pruning even though it falls outside keep."""
    import jax

    from bigdl_tpu.parallel.train_step import TrainStep
    from bigdl_tpu.utils.sharded_ckpt import (latest_verified_step_dir,
                                              prune_old, save_train_step)

    RNG.set_seed(3)
    model = nn.Sequential(nn.Linear(4, 8), nn.Tanh(), nn.Linear(8, 2),
                          nn.LogSoftMax())
    step = TrainStep(model, nn.ClassNLLCriterion(),
                     optim.SGD(learning_rate=0.1))
    x = np.random.default_rng(0).normal(size=(8, 4)).astype(np.float32)
    for n in (2, 4):
        step.run(x, np.zeros(8, np.int64), jax.random.key(n))
        save_train_step(step, str(tmp_path / f"sharded.{n}"),
                        extra={"neval": n})
    faults.FaultPlan.parse("torn_ckpt")._corrupt_one_file(
        str(tmp_path / "sharded.4"))
    pruned = prune_old(str(tmp_path), keep=1)
    assert pruned == []  # sharded.2 is the last verified-good: retained
    assert sorted(os.listdir(tmp_path)) == ["sharded.2", "sharded.4"]
    # discovery falls back past the torn one (quarantining it)
    good = latest_verified_step_dir(str(tmp_path))
    assert good is not None and good.endswith("sharded.2")
    assert "sharded.4.corrupt" in os.listdir(tmp_path)
