"""End-to-end training-loop tests on the virtual 8-device CPU mesh — the
analogue of the reference's DistriOptimizerSpec strategy (SURVEY §4):
distributed path exercised locally, correctness vs a naive reference
optimizer (RefDistriOptimizer/RefLocalOptimizer), fault-injection for the
retry path (ExceptionTest)."""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import bigdl_tpu.nn as nn
import bigdl_tpu.optim as optim
from bigdl_tpu.dataset.sample import Sample
from bigdl_tpu.nn.module import Module, functional_call, state_dict
from bigdl_tpu.optim.trigger import Trigger
from bigdl_tpu.parallel.mesh import make_mesh
from bigdl_tpu.parallel.train_step import TrainStep, bf16_truncate


def _make_data(n=64, dim=4, seed=0):
    rng = np.random.default_rng(seed)
    x = rng.normal(size=(n, dim)).astype(np.float32)
    w = rng.normal(size=(dim,)).astype(np.float32)
    y = (x @ w > 0).astype(np.int64)
    return [Sample(x[i], np.int64(y[i])) for i in range(n)], x, y


def _mlp(dim=4, seed=42):
    from bigdl_tpu.utils.rng import RNG

    RNG.set_seed(seed)
    return nn.Sequential(nn.Linear(dim, 16), nn.Tanh(), nn.Linear(16, 2), nn.LogSoftMax())


def test_local_optimizer_trains():
    samples, x, y = _make_data()
    model = _mlp()
    o = optim.LocalOptimizer(model, samples, nn.ClassNLLCriterion(), batch_size=16,
                             end_trigger=Trigger.max_epoch(8))
    o.set_optim_method(optim.SGD(learning_rate=0.5))
    trained = o.optimize()
    res = optim.Evaluator(trained).evaluate(samples, [optim.Top1Accuracy()])
    acc = res[0][0].result()[0]
    assert acc > 0.9, acc


def test_distri_optimizer_on_8dev_mesh_matches_local():
    """RefDistriOptimizer-style equivalence: mesh-sharded training must
    follow the same trajectory as single-device training."""
    samples, x, y = _make_data()
    crit = nn.ClassNLLCriterion()
    mesh = make_mesh()

    m1 = _mlp(seed=7)
    o1 = optim.DistriOptimizer(m1, samples, crit, batch_size=16,
                               end_trigger=Trigger.max_iteration(12), mesh=mesh)
    o1.set_optim_method(optim.SGD(learning_rate=0.5))
    o1.optimize()

    from bigdl_tpu.utils.rng import RNG

    m2 = _mlp(seed=7)
    o2 = optim.LocalOptimizer(m2, samples, crit, batch_size=16,
                              end_trigger=Trigger.max_iteration(12))
    o2.set_optim_method(optim.SGD(learning_rate=0.5))
    o2.optimize()

    p1, p2 = state_dict(m1), state_dict(m2)
    for k in p1:
        np.testing.assert_allclose(np.asarray(p1[k]), np.asarray(p2[k]),
                                   rtol=1e-4, atol=1e-5)


def test_zero1_sharded_matches_allreduce():
    """Sharded-optimizer (ZeRO-1) layout must be numerically equivalent to
    plain allreduce (the reference's RefDistriOptimizer check for its
    owner-node sharded update)."""
    samples, _, _ = _make_data(n=64, dim=8)
    crit = nn.ClassNLLCriterion()
    mesh = make_mesh()
    results = {}
    for mode in ("allreduce", "sharded"):
        m = _mlp(dim=8, seed=3)
        o = optim.DistriOptimizer(m, samples, crit, batch_size=32,
                                  end_trigger=Trigger.max_iteration(8), mesh=mesh)
        o.set_optim_method(optim.Adam(learning_rate=0.05))
        o.set_parameter_sync(mode)
        o.optimize()
        results[mode] = state_dict(m)
    for k in results["allreduce"]:
        np.testing.assert_allclose(np.asarray(results["allreduce"][k]),
                                   np.asarray(results["sharded"][k]),
                                   rtol=1e-4, atol=1e-5)


def test_tensor_parallel_trajectory_matches_replicated():
    """Ref-optimizer discipline for the model axis: a megatron-sharded
    (column-parallel fc1 / row-parallel fc2) training run on a
    ``data x model`` mesh must follow the SAME weight trajectory as the
    fully-replicated run — wrong TP math (a missing psum, a transposed
    shard) diverges within a step and fails the allclose
    (``RefDistriOptimizer.scala:30`` applied to tensor parallelism)."""
    from jax.sharding import PartitionSpec as P

    from bigdl_tpu.utils.rng import RNG

    def build():
        RNG.set_seed(21)
        return nn.Sequential(
            nn.Linear(8, 32).set_name("tp_fc1"), nn.Tanh(),
            nn.Linear(32, 16).set_name("tp_fc2"), nn.Tanh(),
            nn.Linear(16, 2), nn.LogSoftMax())

    def tp_rules(path, arr):
        if path.startswith("0.weight"):
            return P("model", None)   # column-parallel: split out-features
        if path.startswith("0.bias"):
            return P("model")
        if path.startswith("2.weight"):
            return P(None, "model")   # row-parallel: split in-features
        return None

    mesh = make_mesh((2, 4), ("data", "model"))
    rng = np.random.default_rng(9)
    batches = [(rng.normal(size=(16, 8)).astype(np.float32),
                rng.integers(0, 2, 16)) for _ in range(10)]

    final = {}
    for tag, rules in (("tp", tp_rules), ("replicated", None)):
        step = TrainStep(build(), nn.ClassNLLCriterion(),
                         optim.SGD(learning_rate=0.3, momentum=0.9),
                         mesh=mesh, extra_sharding_rules=rules)
        for i, (x, y) in enumerate(batches):
            loss = step.run(x, y, jax.random.key(i))
        assert np.isfinite(float(loss))
        final[tag] = {k: np.asarray(v) for k, v in step.params.items()}

    # the TP run really sharded the weights over the model axis
    assert final["tp"]["0.weight"].shape == (32, 8)
    for k in final["replicated"]:
        np.testing.assert_allclose(final["tp"][k], final["replicated"][k],
                                   rtol=2e-4, atol=2e-5, err_msg=k)


def test_tensor_parallel_wrong_sharding_detected():
    """Negative control for the trajectory test: a WRONG megatron layout
    (row-parallel applied to the first linear's out-features while its
    bias stays replicated-summed... i.e. a transposed column split) must
    NOT silently reproduce the replicated trajectory.  Guards the guard:
    if pjit somehow ignored extra_sharding_rules, both this and the
    positive test would pass and we'd know the gate is vacuous."""
    from jax.sharding import PartitionSpec as P

    from bigdl_tpu.utils.rng import RNG

    def build():
        RNG.set_seed(21)
        return nn.Sequential(
            nn.Linear(8, 32).set_name("tp_fc1"), nn.Tanh(),
            nn.Linear(32, 16).set_name("tp_fc2"), nn.Tanh(),
            nn.Linear(16, 2), nn.LogSoftMax())

    mesh = make_mesh((2, 4), ("data", "model"))
    x = np.random.default_rng(9).normal(size=(16, 8)).astype(np.float32)
    y = np.random.default_rng(9).integers(0, 2, 16)

    step = TrainStep(build(), nn.ClassNLLCriterion(),
                     optim.SGD(learning_rate=0.3), mesh=mesh,
                     extra_sharding_rules=lambda p, a: (
                         P(None, "model") if p.startswith("0.weight") else None))
    # GSPMD treats the spec as a LAYOUT, not math: dims that don't divide
    # the axis raise at placement; a divisible-but-transposed layout still
    # computes the same math (resharding inserted automatically), so the
    # correct outcome for this wrong-layout case is an error OR identical
    # trajectory — what must never happen is a silently DIFFERENT result.
    try:
        loss = float(step.run(x, y, jax.random.key(0)))
    except Exception:
        return  # rejected outright: acceptable
    ref = TrainStep(build(), nn.ClassNLLCriterion(),
                    optim.SGD(learning_rate=0.3), mesh=mesh)
    ref.run(x, y, jax.random.key(0))
    for k in ref.params:
        np.testing.assert_allclose(np.asarray(step.params[k]),
                                   np.asarray(ref.params[k]),
                                   rtol=2e-4, atol=2e-5, err_msg=k)


def test_bf16_truncation_exact_semantics():
    x = jnp.asarray(np.random.randn(100).astype(np.float32))
    t = np.asarray(bf16_truncate(x))
    bits = t.view(np.uint32)
    assert (bits & 0x0000FFFF).max() == 0  # low 16 bits cleared
    assert np.abs(t - np.asarray(x)).max() < 0.01 * np.abs(np.asarray(x)).max() + 1e-6


def test_bf16_compressed_training_still_converges():
    samples, _, _ = _make_data()
    m = _mlp(seed=5)
    o = optim.LocalOptimizer(m, samples, nn.ClassNLLCriterion(), batch_size=16,
                             end_trigger=Trigger.max_epoch(8))
    o.set_optim_method(optim.SGD(learning_rate=0.5))
    o.set_gradient_compression("bf16")
    o.optimize()
    res = optim.Evaluator(m).evaluate(samples, [optim.Top1Accuracy()])
    assert res[0][0].result()[0] > 0.9


def test_regularizer_and_freeze_in_train_step():
    model = nn.Sequential(
        nn.Linear(4, 8, w_regularizer=optim.L2Regularizer(0.1)), nn.Tanh(),
        nn.Linear(8, 2))
    model.get(2).freeze()
    frozen_before = np.asarray(model.get(2).weight).copy()
    step = TrainStep(model, nn.MSECriterion(), optim.SGD(learning_rate=0.1))
    x = np.random.randn(8, 4).astype(np.float32)
    y = np.random.randn(8, 2).astype(np.float32)
    for i in range(3):
        step.run(x, y, jax.random.key(i))
    step.sync_to_model()
    np.testing.assert_array_equal(np.asarray(model.get(2).weight), frozen_before)
    assert not np.allclose(np.asarray(model.get(0).weight), 0)


class ExceptionLayer(Module):
    """Fault injection (``utils/TestUtils.scala:103`` ExceptionTest): throws
    on the Nth forward."""

    count = 0

    def __init__(self, fail_at: int):
        super().__init__()
        self.fail_at = fail_at

    def update_output(self, input):
        ExceptionLayer.count += 1
        if ExceptionLayer.count == self.fail_at:
            raise RuntimeError("injected failure")
        return input


def test_retry_recovers_from_checkpoint(tmp_path):
    samples, _, _ = _make_data(n=32)
    ExceptionLayer.count = 0
    model = nn.Sequential(nn.Linear(4, 8), ExceptionLayer(fail_at=6), nn.Tanh(),
                          nn.Linear(8, 2), nn.LogSoftMax())
    o = optim.LocalOptimizer(model, samples, nn.ClassNLLCriterion(), batch_size=16,
                             end_trigger=Trigger.max_iteration(8))
    o.set_optim_method(optim.SGD(learning_rate=0.1))
    o.set_checkpoint(str(tmp_path), Trigger.several_iteration(2)).overwrite_checkpoint()
    trained = o.optimize()
    assert o.state["neval"] >= 8  # completed despite the injected failure
    assert os.path.exists(str(tmp_path))


def test_checkpoint_resume_roundtrip(tmp_path):
    samples, _, _ = _make_data()
    m = _mlp(seed=11)
    o = optim.LocalOptimizer(m, samples, nn.ClassNLLCriterion(), batch_size=16,
                             end_trigger=Trigger.max_iteration(4))
    o.set_optim_method(optim.Adam(learning_rate=0.01))
    o.set_checkpoint(str(tmp_path), Trigger.several_iteration(2)).overwrite_checkpoint()
    o.optimize()
    from bigdl_tpu.utils.serializer import load_module, load_optim_method

    mfile = optim.Optimizer.get_latest_file(str(tmp_path), "model")
    ofile = optim.Optimizer.get_latest_file(str(tmp_path), "optimMethod")
    assert mfile and mfile.endswith("model.4")
    m2 = load_module(mfile)
    om2 = load_optim_method(ofile)
    p1, p2 = state_dict(m), state_dict(m2)
    for k in p1:
        np.testing.assert_allclose(np.asarray(p1[k]), np.asarray(p2[k]), rtol=1e-6)
    assert om2.state["driver_state"]["neval"] == 4
    # resume continues the iteration count
    o2 = optim.LocalOptimizer(m2, samples, nn.ClassNLLCriterion(), batch_size=16,
                              end_trigger=Trigger.max_iteration(6))
    o2.set_optim_method(om2)
    o2.set_state(om2.state["driver_state"])
    o2.optimize()
    assert o2.state["neval"] == 6


def test_validation_and_summary_hooks():
    samples, _, _ = _make_data()
    m = _mlp(seed=13)

    class FakeSummary:
        def __init__(self):
            self.tags = []

        def add_scalar(self, tag, value, step):
            self.tags.append(tag)

    ts, vs = FakeSummary(), FakeSummary()
    o = optim.LocalOptimizer(m, samples, nn.ClassNLLCriterion(), batch_size=32,
                             end_trigger=Trigger.max_iteration(4))
    o.set_optim_method(optim.SGD(learning_rate=0.5))
    o.set_validation(Trigger.several_iteration(2), samples,
                     [optim.Top1Accuracy(), optim.Loss(nn.ClassNLLCriterion())], 32)
    o.set_train_summary(ts).set_validation_summary(vs)
    o.optimize()
    assert "Loss" in ts.tags and "Throughput" in ts.tags and "LearningRate" in ts.tags
    assert "Top1Accuracy" in vs.tags and "Loss" in vs.tags
    assert "score" in o.state


def test_predictor_and_evaluator():
    samples, x, y = _make_data()
    m = _mlp()
    optim.LocalOptimizer(m, samples, nn.ClassNLLCriterion(), batch_size=16,
                         end_trigger=Trigger.max_epoch(6)
                         ).set_optim_method(optim.SGD(learning_rate=0.5)).optimize()
    pred = optim.LocalPredictor(m).predict_class(samples)
    assert (pred == y).mean() > 0.9
    out = optim.LocalPredictor(m).predict(x)
    assert out.shape == (64, 2)


def test_per_stage_metrics_recorded():
    """The host loop must record every SPMD-observable stage
    (docs/straggler.md + Metrics.scala:31-130 re-scope)."""
    samples, _, _ = _make_data()
    o = optim.LocalOptimizer(_mlp(), samples, nn.ClassNLLCriterion(),
                             batch_size=16,
                             end_trigger=Trigger.max_iteration(6))
    o.set_optim_method(optim.SGD(learning_rate=0.1))
    o.set_validation(Trigger.several_iteration(3), samples,
                     [optim.Top1Accuracy()], batch_size=32)
    o.optimize()
    stages = o.metrics.stages()
    for want in ("data time", "host to device time (overlapped)",
                 "dispatch time", "computing time",
                 "compile + first iteration time", "validation time"):
        assert want in stages, (want, stages)
    assert o.metrics.count("compile + first iteration time") == 1
    assert o.metrics.count("computing time") == 5
    assert o.metrics.total("computing time") > 0
    assert "mean" in o.metrics.summary()


def test_straggler_watchdog_times_out_and_retry_budget_ends_run(monkeypatch):
    """A hung iteration triggers StragglerTimeout; with no checkpoint and
    an exhausted retry budget the run surfaces the failure
    (docs/straggler.md policy)."""
    import time as _time

    from bigdl_tpu.optim.optimizer import StragglerTimeout

    samples, _, _ = _make_data()
    o = optim.LocalOptimizer(_mlp(), samples, nn.ClassNLLCriterion(),
                             batch_size=16,
                             end_trigger=Trigger.max_iteration(3))
    o.set_optim_method(optim.SGD(learning_rate=0.1))
    calls = {"n": 0}

    def hang():
        calls["n"] += 1
        _time.sleep(5)

    monkeypatch.setenv("BIGDL_ITERATION_TIMEOUT", "0.5")
    monkeypatch.setenv("BIGDL_FAILURE_RETRY_TIMES", "1")
    # make the iteration hang without a device in the loop
    o._run_with_straggler_guard(lambda: None)  # guard path exercised
    with pytest.raises(StragglerTimeout):
        o._run_with_straggler_guard(hang)
    assert calls["n"] == 1


def test_straggler_auto_budget_arms_after_samples(monkeypatch):
    samples, _, _ = _make_data()
    o = optim.LocalOptimizer(_mlp(), samples, nn.ClassNLLCriterion(),
                             batch_size=16,
                             end_trigger=Trigger.max_iteration(1))
    monkeypatch.setenv("BIGDL_ITERATION_TIMEOUT", "auto")
    assert o._straggler_timeout() is None  # not armed yet
    for t in (0.1, 0.2, 0.1, 0.3, 0.2):
        o._iteration_times.append(t)
    assert o._straggler_timeout() == 60.0  # 10x median, floored at 60s
    o._iteration_times.extend([30.0] * 20)
    assert o._straggler_timeout() == pytest.approx(300.0)
    monkeypatch.setenv("BIGDL_ITERATION_TIMEOUT", "0")
    assert o._straggler_timeout() is None


def test_async_checkpoint_overlaps_and_lands(tmp_path, monkeypatch):
    """Checkpoint byte-writes overlap training (BIGDL_ASYNC_CHECKPOINT
    default); a slow writer must not lose or tear the file set — the run
    joins in-flight writes before restores and at the end."""
    import time as _time

    from bigdl_tpu.utils import file as File
    from bigdl_tpu.utils.serializer import load_module, load_optim_method

    real_save = File.save

    def slow_save(data, path, overwrite=False):
        _time.sleep(0.05)
        return real_save(data, path, overwrite)

    monkeypatch.setattr(File, "save", slow_save)
    # optimizer.py binds the module, not the function — patch its ref too
    import bigdl_tpu.optim.optimizer as opt_mod

    monkeypatch.setattr(opt_mod.File, "save", slow_save)

    samples, _, _ = _make_data()
    m = _mlp(seed=17)
    o = optim.LocalOptimizer(m, samples, nn.ClassNLLCriterion(),
                             batch_size=16,
                             end_trigger=Trigger.max_iteration(6))
    o.set_optim_method(optim.Adam(learning_rate=0.01))
    o.set_checkpoint(str(tmp_path), Trigger.several_iteration(1))
    o.overwrite_checkpoint()
    o.optimize()

    mfile = optim.Optimizer.get_latest_file(str(tmp_path), "model")
    ofile = optim.Optimizer.get_latest_file(str(tmp_path), "optimMethod")
    assert mfile and mfile.endswith("model.6")
    m2 = load_module(mfile)  # loads => the write fully landed
    om2 = load_optim_method(ofile)
    assert om2.state["driver_state"]["neval"] == 6
    p1, p2 = state_dict(m), state_dict(m2)
    for k in p1:
        np.testing.assert_allclose(np.asarray(p1[k]), np.asarray(p2[k]),
                                   rtol=1e-6)
    assert "checkpoint wait time" in o.metrics.stages()


def test_remat_trajectory_identical():
    """Rematerialization (jax.checkpoint) must change only the memory /
    recompute schedule, never the math: TrainStep(remat=True) and the
    nn.Remat block wrapper both reproduce the plain trajectory."""
    from bigdl_tpu.utils.rng import RNG

    rng = np.random.default_rng(4)
    batches = [(rng.normal(size=(16, 8)).astype(np.float32),
                rng.integers(0, 2, 16)) for _ in range(6)]

    def run(remat_flag, wrap):
        RNG.set_seed(77)
        block = nn.Sequential(nn.Linear(8, 32), nn.Tanh(),
                              nn.Linear(32, 8), nn.Tanh())
        m = nn.Sequential(nn.Remat(block) if wrap else block,
                          nn.Linear(8, 2), nn.LogSoftMax())
        step = TrainStep(m, nn.ClassNLLCriterion(),
                         optim.SGD(learning_rate=0.3, momentum=0.9),
                         remat=remat_flag)
        for i, (x, y) in enumerate(batches):
            step.run(x, y, jax.random.key(i))
        return {k: np.asarray(v) for k, v in step.params.items()}

    plain = run(False, False)
    step_remat = run(True, False)
    block_remat = run(False, True)
    for k in plain:
        np.testing.assert_allclose(step_remat[k], plain[k],
                                   rtol=1e-6, atol=1e-7, err_msg=k)
    # the wrapped model nests the block's params one level deeper; match
    # by sorted value shapes + norms instead of keys
    a = sorted((v.shape, round(float(np.linalg.norm(v)), 4))
               for v in plain.values())
    b = sorted((v.shape, round(float(np.linalg.norm(v)), 4))
               for v in block_remat.values())
    assert a == b


def test_remat_with_dropout_deterministic():
    """Dropout inside a Remat block: the recompute must reproduce the
    SAME mask (keys derive from the same fold_in chain), so grads equal
    the unwrapped module's."""
    from bigdl_tpu.nn.module import functional_call, state_dict
    from bigdl_tpu.utils.rng import RNG

    RNG.set_seed(5)
    inner = nn.Sequential(nn.Linear(8, 16), nn.Dropout(0.5), nn.Tanh())
    wrapped = nn.Remat(inner)  # SAME instance: same per-module rng ids
    x = jnp.asarray(np.random.default_rng(0)
                    .normal(size=(4, 8)).astype(np.float32))
    p1 = state_dict(inner, kind="param")
    p2 = state_dict(wrapped, kind="param")

    def loss(m, p, key):
        out, _ = functional_call(m, p, x, training=True, rng=key)
        return jnp.sum(out ** 2)

    key = jax.random.key(3)
    g1 = jax.grad(lambda p: loss(inner, p, key))(p1)
    g2 = jax.grad(lambda p: loss(wrapped, p, key))(p2)
    n1 = sorted(round(float(jnp.linalg.norm(v)), 5) for v in g1.values())
    n2 = sorted(round(float(jnp.linalg.norm(v)), 5) for v in g2.values())
    assert n1 == n2


def test_constructor_optim_method_kwarg():
    """Reference python-API parity: Optimizer(..., optim_method=...) in
    the constructor, equivalent to set_optim_method."""
    samples, _, _ = _make_data()
    o = optim.LocalOptimizer(_mlp(), samples, nn.ClassNLLCriterion(),
                             batch_size=16,
                             end_trigger=Trigger.max_iteration(2),
                             optim_method=optim.Adam(learning_rate=0.01))
    assert isinstance(o.optim_method, optim.Adam)
    o.optimize()
    assert o.state["neval"] >= 2


def test_fsdp_matches_allreduce_and_shards_params():
    """ZeRO-3 ('fsdp'): the parameters themselves live sharded over the
    data axis — trajectory identical to plain allreduce (pure GSPMD
    re-annotation, same math) AND the layout is verifiably sharded, so
    no device holds a whole replica."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    samples, _, _ = _make_data(n=64, dim=8)
    crit = nn.ClassNLLCriterion()
    mesh = make_mesh()
    results = {}
    for mode in ("allreduce", "fsdp"):
        m = _mlp(dim=8, seed=3)
        o = optim.DistriOptimizer(m, samples, crit, batch_size=32,
                                  end_trigger=Trigger.max_iteration(8),
                                  mesh=mesh)
        o.set_optim_method(optim.Adam(learning_rate=0.05))
        o.set_parameter_sync(mode)
        o.optimize()
        results[mode] = state_dict(m)
    for k in results["allreduce"]:
        np.testing.assert_allclose(np.asarray(results["allreduce"][k]),
                                   np.asarray(results["fsdp"][k]),
                                   rtol=1e-4, atol=1e-5, err_msg=k)
    # layout: every divisible leaf of an fsdp TrainStep is sharded over
    # data (the Optimizer run above used the same TrainStep config)
    step = TrainStep(_mlp(dim=8, seed=3), crit,
                     optim.Adam(learning_rate=0.05), mesh=mesh,
                     parameter_sync="fsdp")
    step.run(np.zeros((32, 8), np.float32), np.zeros(32, np.int64),
             jax.random.key(0))
    n = mesh.shape["data"]
    checked = 0
    for k, v in step.params.items():
        if v.ndim >= 1 and v.shape[0] % n == 0 and v.shape[0] >= n:
            want = NamedSharding(mesh, P(*(("data",) + (None,) * (v.ndim - 1))))
            assert v.sharding.is_equivalent_to(want, v.ndim), (k, v.sharding)
            checked += 1
    assert checked >= 2, "no parameter was actually fsdp-sharded"


def test_fsdp_composes_with_tensor_parallel():
    """fsdp + explicit TP rules on a data x model mesh: TP rules win on
    their leaves, everything else shards over data; trajectory equals
    the replicated run."""
    from jax.sharding import PartitionSpec as P

    from bigdl_tpu.utils.rng import RNG

    def build():
        RNG.set_seed(21)
        return nn.Sequential(
            nn.Linear(8, 32).set_name("tp_fc1"), nn.Tanh(),
            nn.Linear(32, 16).set_name("tp_fc2"), nn.Tanh(),
            nn.Linear(16, 2), nn.LogSoftMax())

    def tp_rules(path, arr):
        if path.startswith("0.weight"):
            return P("model", None)
        if path.startswith("0.bias"):
            return P("model")
        return None

    mesh = make_mesh((2, 4), ("data", "model"))
    rng = np.random.default_rng(9)
    batches = [(rng.normal(size=(16, 8)).astype(np.float32),
                rng.integers(0, 2, 16)) for _ in range(8)]
    final = {}
    for tag, sync, rules in (("fsdp_tp", "fsdp", tp_rules),
                             ("plain", "allreduce", None)):
        step = TrainStep(build(), nn.ClassNLLCriterion(),
                         optim.SGD(learning_rate=0.3, momentum=0.9),
                         mesh=mesh, parameter_sync=sync,
                         extra_sharding_rules=rules)
        for i, (x, y) in enumerate(batches):
            loss = step.run(x, y, jax.random.key(i))
        assert np.isfinite(float(loss))
        final[tag] = {k: np.asarray(v) for k, v in step.params.items()}
    for k in final["plain"]:
        np.testing.assert_allclose(final["fsdp_tp"][k], final["plain"][k],
                                   rtol=2e-4, atol=2e-5, err_msg=k)
