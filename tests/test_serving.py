"""Production inference serving (bigdl_tpu/serving/, docs/serving.md):
batcher coalescing/deadline invariants, bucket selection with ZERO
steady-state recompiles (retrace detector), AOT warmup, live HTTP e2e
against the batch Predictor's numerics, queue-full backpressure (429),
and graceful SIGTERM drain."""

import json
import os
import re
import signal
import subprocess
import sys
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from bigdl_tpu.serving.batcher import ContinuousBatcher, QueueFullError
from bigdl_tpu.serving.buckets import BucketPolicy, pow2_buckets
from bigdl_tpu.serving.executor import BucketedExecutor, executor_for

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# -- bucket policy -----------------------------------------------------------
def test_pow2_buckets_and_selection():
    assert pow2_buckets(8) == (1, 2, 4, 8)
    assert pow2_buckets(12) == (1, 2, 4, 8, 12)
    pol = BucketPolicy(max_batch=8)
    assert [pol.batch_bucket(n) for n in (1, 2, 3, 5, 8)] == [1, 2, 4, 8, 8]
    with pytest.raises(ValueError):
        pol.batch_bucket(9)
    with pytest.raises(ValueError):
        pol.batch_bucket(0)


def test_seq_bucket_selection_and_padding():
    pol = BucketPolicy(max_batch=4, seq_buckets=[16, 32])
    assert pol.seq_bucket(7) == 16
    assert pol.seq_bucket(17) == 32
    assert pol.seq_bucket(99) == 32  # clamps to the largest
    x = np.arange(2 * 10, dtype=np.int32).reshape(2, 10)
    padded = pol.pad(x, 4, 16)
    assert padded.shape == (4, 16)
    np.testing.assert_array_equal(padded[:2, :10], x)
    assert padded[2:].sum() == 0 and padded[:, 10:].sum() == 0
    # over-long sequences truncate onto the largest bucket
    long = np.ones((1, 40), np.int32)
    assert pol.pad(long, 1, pol.seq_bucket(40)).shape == (1, 32)


# -- continuous batcher (no jax needed: a fake runner) -----------------------
def test_batcher_coalesces_under_deadline():
    calls = []

    def runner(x):
        calls.append(np.asarray(x).shape[0])
        return np.asarray(x)

    b = ContinuousBatcher(runner, max_batch=8, max_wait_ms=250.0)
    try:
        reqs = [b.submit(np.full((1, 3), i, np.float32))
                for i in range(4)]
        for r in reqs:
            assert r.wait(5.0)
        # all four arrived well inside the first request's deadline ->
        # ONE dispatch carried them, each got its own rows back
        assert calls == [4]
        for i, r in enumerate(reqs):
            np.testing.assert_array_equal(
                r.output, np.full((1, 3), i, np.float32))
    finally:
        b.stop(drain=False)


def test_batcher_deadline_fires_without_full_batch():
    b = ContinuousBatcher(lambda x: np.asarray(x), max_batch=64,
                          max_wait_ms=50.0)
    try:
        t0 = time.perf_counter()
        r = b.submit(np.zeros((1, 2), np.float32))
        assert r.wait(5.0)
        # a lone request is dispatched at the deadline, not held for
        # max_batch rows
        assert time.perf_counter() - t0 < 2.0
        assert r.output.shape == (1, 2)
    finally:
        b.stop(drain=False)


def test_batcher_never_exceeds_max_batch_and_keeps_order():
    sizes = []

    def runner(x):
        sizes.append(np.asarray(x).shape[0])
        time.sleep(0.01)
        return np.asarray(x)

    b = ContinuousBatcher(runner, max_batch=4, max_wait_ms=20.0)
    try:
        reqs = [b.submit(np.full((1, 2), i, np.float32))
                for i in range(10)]
        for r in reqs:
            assert r.wait(10.0)
        assert max(sizes) <= 4
        for i, r in enumerate(reqs):  # slicing stayed aligned
            assert float(r.output[0, 0]) == i
    finally:
        b.stop(drain=False)


def test_batcher_queue_full_backpressure():
    release = threading.Event()

    def runner(x):
        release.wait(10.0)
        return np.asarray(x)

    b = ContinuousBatcher(runner, max_batch=1, max_wait_ms=0.0,
                          queue_limit=2)
    try:
        first = b.submit(np.zeros((1, 1), np.float32))
        time.sleep(0.2)  # worker now blocked inside the runner
        b.submit(np.zeros((1, 1), np.float32))
        b.submit(np.zeros((1, 1), np.float32))
        with pytest.raises(QueueFullError):
            b.submit(np.zeros((1, 1), np.float32))
        assert b.rejected == 1
        release.set()
        assert first.wait(5.0)
    finally:
        release.set()
        b.stop(drain=False)


def test_batcher_drain_finishes_queued_requests():
    slow = threading.Event()

    def runner(x):
        slow.wait(0.05)
        return np.asarray(x)

    b = ContinuousBatcher(runner, max_batch=2, max_wait_ms=1.0)
    reqs = [b.submit(np.full((1, 1), i, np.float32)) for i in range(6)]
    assert b.stop(drain=True, timeout=10.0)
    for i, r in enumerate(reqs):
        assert r.done.is_set() and r.error is None
        assert float(r.output[0, 0]) == i
    with pytest.raises(QueueFullError):  # admissions closed
        b.submit(np.zeros((1, 1), np.float32))


def test_batcher_relays_runner_errors():
    def runner(x):
        raise RuntimeError("boom")

    b = ContinuousBatcher(runner, max_batch=4, max_wait_ms=1.0)
    try:
        r = b.submit(np.zeros((1, 1), np.float32))
        assert r.wait(5.0)
        assert isinstance(r.error, RuntimeError)
    finally:
        b.stop(drain=False)


# -- bucketed executor -------------------------------------------------------
def _lenet():
    from bigdl_tpu.models import registry

    return registry.build_model("lenet")


def test_executor_warmup_compiles_every_bucket_then_zero_recompiles():
    from bigdl_tpu import telemetry
    from bigdl_tpu.analysis.retrace import trace_retraces

    model = _lenet()
    ex = BucketedExecutor(
        model, policy=BucketPolicy(max_batch=8, batch_buckets=[1, 2, 4, 8]))
    sink = telemetry.MemorySink()
    with telemetry.run(sinks=[sink]):
        ex.warmup((784,), np.float32)
        assert ex.compile_count == 4
        assert ex.warm_buckets() == [(1, None), (2, None), (4, None),
                                     (8, None)]
        x = np.random.RandomState(0).randn(8, 784).astype(np.float32)
        want = np.asarray(model.evaluate().forward(x))
        # steady state: every arrival size maps onto a warm bucket —
        # the retrace detector must stay CLEAN and the compile count
        # must not move
        with trace_retraces() as mon:
            for n in (1, 3, 2, 8, 5, 1, 4, 7):
                out = ex.run(x[:n])
                assert out.shape == (n, 10)
                np.testing.assert_allclose(out, want[:n], atol=1e-5)
        assert mon.report.diagnostics == []
        assert ex.compile_count == 4
    compiles = [e for e in sink.events if e.get("kind") == "compile"]
    assert len(compiles) == 4
    assert {e["name"] for e in compiles} == {"ServeExecutor.warmup"}


def test_executor_cold_bucket_compiles_in_path_and_is_named():
    from bigdl_tpu import telemetry

    ex = BucketedExecutor(
        _lenet(), policy=BucketPolicy(max_batch=4, batch_buckets=[2, 4]))
    sink = telemetry.MemorySink()
    with telemetry.run(sinks=[sink]):
        out = ex.run(np.zeros((2, 784), np.float32))  # no warmup: cold
        assert out.shape == (2, 10)
    names = [e["name"] for e in sink.events if e.get("kind") == "compile"]
    assert names == ["ServeExecutor.compile"]  # the in-request-path name


def test_executor_refresh_state_keeps_warm_executables():
    model = _lenet()
    ex = BucketedExecutor(model,
                          policy=BucketPolicy(max_batch=2,
                                              batch_buckets=[2]))
    ex.warmup((784,), np.float32)
    x = np.random.RandomState(1).randn(2, 784).astype(np.float32)
    before = ex.run(x)
    # same-shape weight update (training between predicts): executables
    # survive, outputs track the new params
    w = model.get(8).weight  # fc1
    model.get(8).weight = np.asarray(w) * 0.5
    ex.refresh_state()
    after = ex.run(x)
    assert ex.compile_count == 1
    assert not np.allclose(before, after)
    np.testing.assert_allclose(after,
                               np.asarray(model.evaluate().forward(x)),
                               atol=1e-5)


def test_predictor_shares_one_compile_cache_across_predicts():
    model = _lenet()
    from bigdl_tpu.optim.predictor import LocalPredictor

    x = np.random.RandomState(2).randn(10, 784).astype(np.float32)
    pred = LocalPredictor(model, batch_size=4)
    out1 = pred.predict(x)
    ex = executor_for(model, max_batch=4)
    count = ex.compile_count
    assert count >= 1
    # second predict — and a SECOND Predictor over the same model —
    # reuse the same executor: zero new compiles (the old code paid a
    # fresh EvalStep jit per call)
    out2 = pred.predict(x)
    out3 = LocalPredictor(model, batch_size=4).predict(x)
    assert ex.compile_count == count
    np.testing.assert_allclose(out1, out2, atol=1e-6)
    np.testing.assert_allclose(out1, out3, atol=1e-6)
    np.testing.assert_allclose(
        out1, np.asarray(model.evaluate().forward(x)), atol=1e-5)


def test_predictor_on_mesh_uses_mesh_aligned_buckets():
    """Review regression: the default pow2 bucket set starts at 1,
    which cannot shard over a multi-device data mesh — mesh executors
    must default to mesh-aligned buckets, and the mesh Predictor path
    must keep working."""
    from bigdl_tpu.optim.predictor import LocalPredictor
    from bigdl_tpu.parallel.mesh import make_mesh
    from bigdl_tpu.serving.executor import default_policy

    import jax

    mesh = make_mesh((2,), ("data",), devices=jax.devices()[:2])
    pol = default_policy(max_batch=8, mesh=mesh)
    assert all(b % 2 == 0 for b in pol.batch_buckets), pol.batch_buckets
    model = _lenet()
    x = np.random.RandomState(6).randn(7, 784).astype(np.float32)
    out = LocalPredictor(model, batch_size=4, mesh=mesh).predict(x)
    np.testing.assert_allclose(
        out, np.asarray(model.evaluate().forward(x)), atol=1e-5)


def test_executor_seq_buckets_token_model():
    """Token inputs snap onto (batch, seq) buckets; numerics match the
    model's own forward at the same padded shape."""
    import bigdl_tpu.nn as nn
    from bigdl_tpu.analysis.retrace import trace_retraces

    model = nn.Sequential(nn.LookupTable(50, 8), nn.TimeDistributed(
        nn.Linear(8, 4)))
    ex = BucketedExecutor(
        model, policy=BucketPolicy(max_batch=4, batch_buckets=[2, 4],
                                   seq_buckets=[8, 16]), seq_axis=1)
    ex.warmup((16,), np.int32)
    assert ex.compile_count == 4  # {2,4} x {8,16}
    rng = np.random.RandomState(3)
    with trace_retraces() as mon:
        for rows, t in ((1, 5), (2, 8), (3, 12), (4, 16), (2, 3)):
            x = rng.randint(1, 50, (rows, t)).astype(np.int32)
            out = ex.run(x)
            assert out.shape[:2] == (rows, t)
            padded = np.zeros((rows, ex.policy.seq_bucket(t)), np.int32)
            padded[:, :t] = x
            want = np.asarray(model.evaluate().forward(padded))
            np.testing.assert_allclose(out, want[:, :t], atol=1e-6)
    assert mon.report.diagnostics == []
    assert ex.compile_count == 4


def test_http_seq_bucketed_outputs_trim_to_request_length():
    """Review regression: the batcher pads ragged token requests to the
    common seq bucket BEFORE the executor, so the trim back to each
    request's own length must happen per request after slicing —
    a 5-token request gets 5 output steps, not the bucket's 8."""
    import jax

    import bigdl_tpu.nn as nn
    from bigdl_tpu.serving import serve_model

    model = nn.Sequential(nn.LookupTable(50, 8),
                          nn.TimeDistributed(nn.Linear(8, 4)))
    spec = jax.ShapeDtypeStruct((1, 16), np.int32)
    server = serve_model(model, spec, host="127.0.0.1", port=0,
                         max_batch=4, batch_buckets=[2, 4],
                         seq_buckets=[8, 16], max_wait_ms=20.0)
    try:
        rng = np.random.RandomState(9)
        xs = {5: rng.randint(1, 50, (1, 5)), 12: rng.randint(1, 50, (2, 12))}
        results = {}

        def client(t):
            code, resp = _post(server.port,
                               {"inputs": xs[t].astype(int).tolist()})
            results[t] = (code, np.asarray(resp["outputs"]))

        threads = [threading.Thread(target=client, args=(t,)) for t in xs]
        for th in threads:
            th.start()
        for th in threads:
            th.join(30.0)
        for t, (code, out) in results.items():
            assert code == 200
            assert out.shape[:2] == (xs[t].shape[0], t), (t, out.shape)
            # numerics: the model forward at this request's own bucket
            padded = np.zeros((xs[t].shape[0],
                               server.executor.policy.seq_bucket(t)),
                              np.int32)
            padded[:, :t] = xs[t]
            want = np.asarray(model.evaluate().forward(padded))
            np.testing.assert_allclose(out, want[:, :t], atol=1e-5)
    finally:
        server.stop(drain=False)


# -- live HTTP e2e -----------------------------------------------------------
@pytest.fixture
def lenet_server():
    from bigdl_tpu.models import registry
    from bigdl_tpu.serving import serve_model

    model = registry.build_model("lenet")
    server = serve_model(model, registry.input_spec("lenet", 1),
                         name="lenet", host="127.0.0.1", port=0,
                         max_batch=8, batch_buckets=[1, 2, 4, 8],
                         max_wait_ms=2.0)
    try:
        yield model, server
    finally:
        server.stop(drain=False)


def _post(port, payload, timeout=30.0):
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/v1/predict",
        data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"}, method="POST")
    with urllib.request.urlopen(req, timeout=timeout) as r:
        return r.status, json.loads(r.read())


def test_http_e2e_concurrent_mixed_sizes_match_predictor(lenet_server):
    from bigdl_tpu.optim.predictor import LocalPredictor

    model, server = lenet_server
    rng = np.random.RandomState(4)
    x = rng.randn(24, 784).astype(np.float32)
    want = LocalPredictor(model, batch_size=8).predict(x)

    results = {}
    errors = []
    slices = [(0, 1), (1, 4), (4, 6), (6, 11), (11, 19), (19, 24)]

    def client(lo, hi):
        try:
            code, resp = _post(server.port, {"inputs": x[lo:hi].tolist()})
            assert code == 200
            results[(lo, hi)] = np.asarray(resp["outputs"])
        except Exception as e:  # noqa: BLE001 - surfaced below
            errors.append(e)

    threads = [threading.Thread(target=client, args=s) for s in slices]
    for t in threads:
        t.start()
    for t in threads:
        t.join(30.0)
    assert errors == []
    for (lo, hi), out in results.items():
        assert out.shape == (hi - lo, 10)
        np.testing.assert_allclose(out, want[lo:hi], atol=1e-5)
    # a single bare sample gets a single bare output row back
    code, resp = _post(server.port, {"inputs": x[0].tolist()})
    assert code == 200
    np.testing.assert_allclose(np.asarray(resp["outputs"]), want[0],
                               atol=1e-5)
    assert resp["queue_ms"] >= 0.0


def test_http_status_healthz_metrics_and_bad_input(lenet_server):
    _, server = lenet_server
    _post(server.port, {"inputs": np.zeros(784).tolist()})
    st = json.load(urllib.request.urlopen(
        f"http://127.0.0.1:{server.port}/status", timeout=10))
    srv = st["serving"]
    assert srv["model"] == "lenet"
    assert srv["batch_buckets"] == [1, 2, 4, 8]
    assert srv["compiles"] == 4 and srv["warmup_s"] > 0
    assert srv["warm_buckets"][:2] == [[1], [2]]
    assert srv["requests"] >= 1 and srv["p50_ms"] > 0
    hz = urllib.request.urlopen(
        f"http://127.0.0.1:{server.port}/healthz", timeout=10)
    assert json.loads(hz.read())["ok"] is True
    body = urllib.request.urlopen(
        f"http://127.0.0.1:{server.port}/metrics", timeout=10
    ).read().decode()
    assert "bigdl_serve_qps" in body and body.rstrip().endswith("# EOF")
    # shape errors are a 400, not a 500 or a hang
    with pytest.raises(urllib.error.HTTPError) as ei:
        _post(server.port, {"inputs": [[1.0, 2.0]]})
    assert ei.value.code == 400
    with pytest.raises(urllib.error.HTTPError) as ei:
        _post(server.port, {"wrong": 1})
    assert ei.value.code == 400


def test_http_queue_full_returns_429(lenet_server):
    _, server = lenet_server
    release = threading.Event()
    inner = server.batcher.runner

    def slow(xx):
        release.wait(10.0)
        return inner(xx)

    server.batcher.runner = slow
    server.batcher.queue_limit = 2
    server.batcher._q.maxsize = 2
    codes = []
    lock = threading.Lock()

    def client():
        try:
            code, _ = _post(server.port,
                            {"inputs": np.zeros((1, 784)).tolist()})
        except urllib.error.HTTPError as e:
            code = e.code
        with lock:
            codes.append(code)

    threads = [threading.Thread(target=client) for _ in range(6)]
    for t in threads:
        t.start()
        time.sleep(0.05)  # let each submission land before the next
    release.set()
    for t in threads:
        t.join(30.0)
    assert 429 in codes, codes
    assert 200 in codes, codes  # accepted requests still completed


def test_serve_events_are_schema_valid():
    from bigdl_tpu import telemetry
    from bigdl_tpu.models import registry
    from bigdl_tpu.serving import serve_model
    from bigdl_tpu.telemetry import schema

    sink = telemetry.MemorySink()
    with telemetry.run(sinks=[sink]):
        model = registry.build_model("lenet")
        server = serve_model(model, registry.input_spec("lenet", 1),
                             host="127.0.0.1", port=0, max_batch=4,
                             batch_buckets=[4], max_wait_ms=1.0)
        try:
            _post(server.port, {"inputs": np.zeros((2, 784)).tolist()})
        finally:
            server.stop(drain=True)
    kinds = {e.get("kind") for e in sink.events}
    assert "serve" in kinds and "compile" in kinds
    names = {e.get("name") for e in sink.events}
    assert {"serve/started", "serve/drain", "serve/warmup",
            "serve/requests"} <= names
    assert schema.validate_events(sink.events) == []


def test_sigterm_drain_in_process():
    """SIGTERM flips /healthz to 503 and wait() returns; stop(drain)
    finishes queued work.  (The subprocess test below exercises the
    whole CLI path; this one pins the handler semantics.)"""
    from bigdl_tpu.models import registry
    from bigdl_tpu.serving import serve_model

    model = registry.build_model("lenet")
    server = serve_model(model, registry.input_spec("lenet", 1),
                         host="127.0.0.1", port=0, max_batch=4,
                         batch_buckets=[4], max_wait_ms=1.0)
    old_term = signal.getsignal(signal.SIGTERM)
    old_int = signal.getsignal(signal.SIGINT)
    try:
        server.install_signal_handlers()
        r = server.batcher.submit(np.zeros((1, 784), np.float32))
        os.kill(os.getpid(), signal.SIGTERM)
        server.wait()  # returns because the handler set the term event
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(
                f"http://127.0.0.1:{server.port}/healthz", timeout=10)
        assert ei.value.code == 503
        server.stop(drain=True)
        assert r.done.is_set() and r.error is None
    finally:
        signal.signal(signal.SIGTERM, old_term)
        signal.signal(signal.SIGINT, old_int)
        server.stop(drain=False)


@pytest.mark.deadline(240)
def test_cli_serve_live_e2e_with_sigterm_drain():
    """The acceptance path: `models/cli.py serve` on a registry model,
    real HTTP from another process, numerics equal to the in-process
    Predictor, graceful SIGTERM drain, exit 0."""
    from bigdl_tpu.models import registry
    from bigdl_tpu.optim.predictor import LocalPredictor
    from bigdl_tpu.utils.rng import RNG

    env = dict(os.environ, JAX_PLATFORMS="cpu")
    proc = subprocess.Popen(
        [sys.executable, "-m", "bigdl_tpu.models.cli", "serve",
         "--model", "lenet", "--port", "0", "-b", "8",
         "--buckets", "1,2,4,8", "--max-wait-ms", "2", "--seed", "42"],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        env=env, cwd=REPO)
    try:
        port = None
        deadline = time.time() + 180
        while time.time() < deadline:
            line = proc.stdout.readline()
            if not line:
                break
            m = re.search(r"serving lenet on port (\d+)", line)
            if m:
                port = int(m.group(1))
                break
        assert port, "no ready line from cli serve"
        RNG.set_seed(42)
        model = registry.build_model("lenet")
        x = np.random.RandomState(5).randn(6, 784).astype(np.float32)
        want = LocalPredictor(model, batch_size=8).predict(x)
        code, resp = _post(port, {"inputs": x.tolist()})
        assert code == 200
        np.testing.assert_allclose(np.asarray(resp["outputs"]), want,
                                   atol=1e-5)
        proc.send_signal(signal.SIGTERM)
        out, _ = proc.communicate(timeout=60)
        assert proc.returncode == 0, out
        assert "drained" in out
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.communicate(timeout=30)
