"""TF Session training path (VERDICT r1 item 3; reference
``utils/tf/Session.scala:48,150-263,435-461``): a REAL GraphDef built by
TensorFlow with a TFRecord queue input pipeline (string_input_producer ->
TFRecordReader -> parse_single_example -> batch queue) is interpreted
into a host DataSet, its compute subgraph becomes a trainable Graph, and
the Optimizer trains it to a loss target.

Fixture generation needs the real TensorFlow package (the reference's
oracle discipline: its tests shell out to real Lua Torch, gated on
availability — SURVEY §4); skipped when absent.
"""

import os

import numpy as np
import pytest

tf = pytest.importorskip("tensorflow")

import bigdl_tpu.nn as nn  # noqa: E402
import bigdl_tpu.optim as optim  # noqa: E402
from bigdl_tpu.utils.tf_session import TFTrainingSession  # noqa: E402


@pytest.fixture(scope="module")
def pipeline_graphdef(tmp_path_factory):
    """(graphdef bytes, tfrecord path): a learnable 3-class problem
    (label = argmax of the first 3 features) behind a TF queue pipeline."""
    tmp = tmp_path_factory.mktemp("tfsess")
    rec_path = str(tmp / "train.tfrecord")
    rng = np.random.RandomState(0)
    with tf.io.TFRecordWriter(rec_path) as w:
        for _ in range(96):
            x = rng.randn(6).astype(np.float32)
            y = int(np.argmax(x[:3]))
            ex = tf.train.Example(features=tf.train.Features(feature={
                "x": tf.train.Feature(
                    float_list=tf.train.FloatList(value=x)),
                "y": tf.train.Feature(
                    int64_list=tf.train.Int64List(value=[y])),
            }))
            w.write(ex.SerializeToString())

    tf1 = tf.compat.v1
    tf1.disable_eager_execution()
    g = tf1.Graph()
    with g.as_default():
        fq = tf1.train.string_input_producer([rec_path], shuffle=False)
        reader = tf1.TFRecordReader()
        _, serialized = reader.read(fq)
        feats = tf1.parse_single_example(serialized, features={
            "x": tf1.FixedLenFeature([6], tf.float32),
            "y": tf1.FixedLenFeature([], tf.int64)})
        bx, _by = tf1.train.batch([feats["x"], feats["y"]], batch_size=8)
        w1 = tf1.constant(
            (rng.randn(6, 3) * 0.1).astype(np.float32), name="W")
        b1 = tf1.constant(np.zeros(3, np.float32), name="b")
        logits = tf1.nn.bias_add(tf1.matmul(bx, w1, name="mm"), b1,
                                 name="logits")
        tf1.nn.log_softmax(logits, name="logprob")
    return g.as_graph_def().SerializeToString(), rec_path


def test_interpret_pipeline(pipeline_graphdef):
    gd, rec_path = pipeline_graphdef
    sess = TFTrainingSession(gd)
    model, records, graph_ports, label_ports = sess.build(["logprob"])
    assert graph_ports == [0] and label_ports == [1]
    assert len(records) == 96
    x0, y0 = records[0]
    assert x0.shape == (6,) and x0.dtype == np.float32
    assert y0.shape == () and y0.dtype == np.int64
    # the imported compute graph is trainable (W, b became Variables)
    from bigdl_tpu.nn.module import state_dict

    assert len(state_dict(model, kind="param")) == 2
    # forward works on a batch
    out = model.evaluate().forward(np.zeros((4, 6), np.float32))
    assert np.asarray(out).shape == (4, 3)


def test_train_imported_graph_reaches_loss_target(pipeline_graphdef):
    gd, _ = pipeline_graphdef
    sess = TFTrainingSession(gd)
    trained = sess.train(
        ["logprob"], criterion=nn.ClassNLLCriterion(),
        optim_method=optim.SGD(learning_rate=0.5),
        batch_size=16, end_trigger=optim.Trigger.max_epoch(6))
    # evaluate the trained graph on fresh samples of the same rule
    rng = np.random.RandomState(7)
    x = rng.randn(64, 6).astype(np.float32)
    y = np.argmax(x[:, :3], axis=1)
    logprob = np.asarray(trained.evaluate().forward(x))
    loss = -logprob[np.arange(64), y].mean()
    assert loss < 0.75, f"trained loss {loss} did not reach target"
    acc = (logprob.argmax(1) == y).mean()
    assert acc > 0.7, f"trained accuracy {acc} too low"


@pytest.fixture(scope="module")
def v1_parse_graphdef(tmp_path_factory, pipeline_graphdef):
    """The same learnable pipeline but through the LEGACY variadic-key
    ``ParseExample`` (v1) node — emitted via tf.raw_ops since TF2's
    public API always lowers to V2."""
    _, rec_path = pipeline_graphdef
    tf1 = tf.compat.v1
    tf1.disable_eager_execution()
    g = tf1.Graph()
    with g.as_default():
        fq = tf1.train.string_input_producer([rec_path], shuffle=False)
        reader = tf1.TFRecordReader()
        _, serialized = reader.read(fq)
        parsed = tf.raw_ops.ParseExample(
            serialized=tf1.reshape(serialized, [1]),
            names=tf1.constant([], tf.string),
            sparse_keys=[],
            sparse_types=[],
            dense_keys=[tf1.constant("x"), tf1.constant("y")],
            dense_defaults=[tf1.constant([], tf.float32),
                            tf1.constant([], tf.int64)],
            dense_shapes=[[6], []])
        px = tf1.reshape(parsed.dense_values[0], [6])
        py = tf1.reshape(parsed.dense_values[1], [])
        bx, _by = tf1.train.batch([px, py], batch_size=8)
        rng = np.random.RandomState(0)
        w1 = tf1.constant((rng.randn(6, 3) * 0.1).astype(np.float32), name="W")
        b1 = tf1.constant(np.zeros(3, np.float32), name="b")
        logits = tf1.nn.bias_add(tf1.matmul(bx, w1, name="mm"), b1,
                                 name="logits")
        tf1.nn.log_softmax(logits, name="logprob")
    return g.as_graph_def().SerializeToString()


def test_v1_parse_example_pipeline_trains(v1_parse_graphdef):
    """VERDICT r2 weak #9: the v1 parse op must train end-to-end."""
    sess = TFTrainingSession(v1_parse_graphdef)
    model, records, graph_ports, label_ports = sess.build(["logprob"])
    assert len(records) == 96
    x0, y0 = records[0]
    assert x0.shape == (6,) and y0.dtype == np.int64
    trained = sess.train(
        ["logprob"], criterion=nn.ClassNLLCriterion(),
        optim_method=optim.SGD(learning_rate=0.5),
        batch_size=16, end_trigger=optim.Trigger.max_epoch(6))
    rng = np.random.RandomState(7)
    x = rng.randn(64, 6).astype(np.float32)
    y = np.argmax(x[:, :3], axis=1)
    logprob = np.asarray(trained.evaluate().forward(x))
    acc = (logprob.argmax(1) == y).mean()
    assert acc > 0.7, f"trained accuracy {acc} too low"


@pytest.fixture(scope="module")
def image_pipeline_graphdef(tmp_path_factory):
    """An IMAGE pipeline (Session.scala:173-263): PNG bytes feature ->
    DecodePng -> Cast -> normalize -> Reshape, behind the same queue
    machinery.  Class = dominant color channel."""
    import io

    from PIL import Image

    tmp = tmp_path_factory.mktemp("tfimg")
    rec_path = str(tmp / "imgs.tfrecord")
    rng = np.random.RandomState(1)
    with tf.io.TFRecordWriter(rec_path) as w:
        for _ in range(48):
            y = int(rng.randint(0, 3))
            img = rng.randint(0, 100, (4, 4, 3)).astype(np.uint8)
            img[:, :, y] += 150
            buf = io.BytesIO()
            Image.fromarray(img).save(buf, format="PNG")
            ex = tf.train.Example(features=tf.train.Features(feature={
                "image": tf.train.Feature(bytes_list=tf.train.BytesList(
                    value=[buf.getvalue()])),
                "label": tf.train.Feature(int64_list=tf.train.Int64List(
                    value=[y]))}))
            w.write(ex.SerializeToString())

    tf1 = tf.compat.v1
    tf1.disable_eager_execution()
    g = tf1.Graph()
    with g.as_default():
        fq = tf1.train.string_input_producer([rec_path], shuffle=False)
        reader = tf1.TFRecordReader()
        _, serialized = reader.read(fq)
        feats = tf1.parse_single_example(serialized, features={
            "image": tf1.FixedLenFeature([], tf.string),
            "label": tf1.FixedLenFeature([], tf.int64)})
        img = tf1.image.decode_png(feats["image"], channels=3)
        img = tf1.cast(img, tf.float32) / 255.0
        img = tf1.reshape(img, [48])
        bx, _by = tf1.train.batch([img, feats["label"]], batch_size=8)
        w1 = tf1.constant((np.random.RandomState(2).randn(48, 3) * 0.1)
                          .astype(np.float32), name="W")
        logits = tf1.matmul(bx, w1, name="logits")
        tf1.nn.log_softmax(logits, name="logprob")
    return g.as_graph_def().SerializeToString(), rec_path


def test_image_pipeline_records_decoded(image_pipeline_graphdef):
    gd, _ = image_pipeline_graphdef
    sess = TFTrainingSession(gd)
    model, records, graph_ports, label_ports = sess.build(["logprob"])
    assert len(records) == 48
    x0, y0 = records[0]
    assert x0.shape == (48,) and x0.dtype == np.float32
    assert 0.0 <= float(x0.min()) and float(x0.max()) <= 1.0
    assert y0.dtype == np.int64


def test_image_pipeline_trains(image_pipeline_graphdef):
    gd, _ = image_pipeline_graphdef
    sess = TFTrainingSession(gd)
    trained = sess.train(
        ["logprob"], criterion=nn.ClassNLLCriterion(),
        optim_method=optim.SGD(learning_rate=0.5),
        batch_size=16, end_trigger=optim.Trigger.max_epoch(8))
    # fresh images of the same rule: dominant channel = class
    rng = np.random.RandomState(9)
    xs, ys = [], []
    for _ in range(32):
        y = int(rng.randint(0, 3))
        img = rng.randint(0, 100, (4, 4, 3)).astype(np.uint8)
        img[:, :, y] += 150
        xs.append(img.reshape(48).astype(np.float32) / 255.0)
        ys.append(y)
    logprob = np.asarray(trained.evaluate().forward(np.stack(xs)))
    acc = (logprob.argmax(1) == np.asarray(ys)).mean()
    assert acc > 0.8, f"trained accuracy {acc} too low"


# -- round-4 queue-breadth topologies (Session.scala:173-263 family) -------

def _write_records(path, n, seed, dim=6):
    """TFRecord file of (x[dim] float, y int64) with y = argmax(x[:3])."""
    rng = np.random.RandomState(seed)
    with tf.io.TFRecordWriter(path) as w:
        for _ in range(n):
            x = rng.randn(dim).astype(np.float32)
            y = int(np.argmax(x[:3]))
            ex = tf.train.Example(features=tf.train.Features(feature={
                "x": tf.train.Feature(
                    float_list=tf.train.FloatList(value=x)),
                "y": tf.train.Feature(
                    int64_list=tf.train.Int64List(value=[y])),
            }))
            w.write(ex.SerializeToString())
    return path


def test_random_shuffle_queue_shuffles(tmp_path):
    """shuffle_batch (RandomShuffleQueue) interprets with the queue's
    shuffle semantics: same record SET, different order than file order."""
    rec = _write_records(str(tmp_path / "r.tfrecord"), 64, seed=1)
    tf1 = tf.compat.v1
    tf1.disable_eager_execution()
    g = tf1.Graph()
    with g.as_default():
        fq = tf1.train.string_input_producer([rec], shuffle=False)
        _, serialized = tf1.TFRecordReader().read(fq)
        feats = tf1.parse_single_example(serialized, features={
            "x": tf1.FixedLenFeature([6], tf.float32),
            "y": tf1.FixedLenFeature([], tf.int64)})
        bx, _by = tf1.train.shuffle_batch(
            [feats["x"], feats["y"]], batch_size=8, capacity=64,
            min_after_dequeue=16)
        tf1.identity(bx, name="out")
    gd = g.as_graph_def().SerializeToString()

    from bigdl_tpu.utils.rng import RNG

    RNG.set_seed(12)
    sess = TFTrainingSession(gd)
    _, records, _, _ = sess.build(["out"])
    assert len(records) == 64
    # order differs from file order, content set identical
    raw = sorted(tuple(np.round(r[0], 5)) for r in records)
    # read the file directly for the reference order
    direct = TFTrainingSession(gd)
    deq = direct._walk_compute(["out"])[1][0]
    files, comps = direct.interpret_pipeline(deq)
    file_rows = direct._records(files, comps)
    assert sorted(tuple(np.round(r[0], 5)) for r in file_rows) == raw
    assert any(not np.allclose(a[0], b[0])
               for a, b in zip(records, file_rows))


def test_multi_enqueue_union(tmp_path):
    """Two enqueues into one queue union their record streams
    (handleDistriDequeue's RDD union)."""
    rec_a = _write_records(str(tmp_path / "a.tfrecord"), 24, seed=2)
    rec_b = _write_records(str(tmp_path / "b.tfrecord"), 40, seed=3)
    tf1 = tf.compat.v1
    tf1.disable_eager_execution()
    g = tf1.Graph()
    with g.as_default():
        q = tf1.queue.FIFOQueue(128, [tf.float32, tf.int64],
                                shapes=[[6], []], name="shared_q")
        for tag, rec in (("a", rec_a), ("b", rec_b)):
            with tf1.name_scope(tag):
                fq = tf1.train.string_input_producer([rec], shuffle=False)
                _, serialized = tf1.TFRecordReader().read(fq)
                feats = tf1.parse_single_example(serialized, features={
                    "x": tf1.FixedLenFeature([6], tf.float32),
                    "y": tf1.FixedLenFeature([], tf.int64)})
                q.enqueue([feats["x"], feats["y"]])
        bx, _by = q.dequeue_many(8)
        tf1.identity(bx, name="out")
    gd = g.as_graph_def().SerializeToString()

    sess = TFTrainingSession(gd)
    _, records, graph_ports, label_ports = sess.build(["out"])
    assert len(records) == 64  # 24 + 40 unioned
    assert graph_ports == [0] and label_ports == [1]


def test_multi_dequeue_same_queue_splits_stream(tmp_path):
    """Two dequeue nodes on ONE queue each get a disjoint round-robin
    slice (handleLocalDequeue's split), and the compute graph can consume
    both."""
    rec = _write_records(str(tmp_path / "r.tfrecord"), 32, seed=4)
    tf1 = tf.compat.v1
    tf1.disable_eager_execution()
    g = tf1.Graph()
    with g.as_default():
        fq = tf1.train.string_input_producer([rec], shuffle=False)
        _, serialized = tf1.TFRecordReader().read(fq)
        feats = tf1.parse_single_example(serialized, features={
            "x": tf1.FixedLenFeature([6], tf.float32)})
        q = tf1.queue.FIFOQueue(64, [tf.float32], shapes=[[6]],
                                name="tower_q")
        q.enqueue([feats["x"]])
        xa = q.dequeue(name="deq_a")
        xb = q.dequeue(name="deq_b")
        tf1.identity(tf1.add(xa, xb), name="out")
    gd = g.as_graph_def().SerializeToString()

    sess = TFTrainingSession(gd)
    _, records, graph_ports, label_ports = sess.build(["out"])
    # 32 records -> 16 zipped rows of (record 2i, record 2i+1)
    assert len(records) == 16
    assert len(records[0]) == 2
    assert graph_ports == [0, 1] and label_ports == []
    files, comps = sess.interpret_pipeline(
        sess._walk_compute(["out"])[1][0])
    file_rows = sess._records(files, comps)
    np.testing.assert_allclose(records[0][0], file_rows[0][0])
    np.testing.assert_allclose(records[0][1], file_rows[1][0])
    np.testing.assert_allclose(records[1][0], file_rows[2][0])


def test_direct_parse_feed_without_queue(tmp_path):
    """A graph whose compute consumes ParseExample outputs directly (no
    batching queue) trains through the host-interpreted path."""
    rec = _write_records(str(tmp_path / "r.tfrecord"), 48, seed=5)
    tf1 = tf.compat.v1
    tf1.disable_eager_execution()
    g = tf1.Graph()
    with g.as_default():
        fq = tf1.train.string_input_producer([rec], shuffle=False)
        _, serialized = tf1.TFRecordReader().read(fq)
        feats = tf1.parse_single_example(serialized, features={
            "x": tf1.FixedLenFeature([6], tf.float32),
            "y": tf1.FixedLenFeature([], tf.int64)})
        w = tf1.constant(np.zeros((6, 3), np.float32) + 0.1, name="W")
        xrow = tf1.reshape(feats["x"], [1, 6])
        tf1.nn.log_softmax(tf1.matmul(xrow, w), name="logprob")
    gd = g.as_graph_def().SerializeToString()

    sess = TFTrainingSession(gd)
    model, records, graph_ports, label_ports = sess.build(["logprob"])
    assert len(records) == 48
    assert len(graph_ports) == 1 and len(label_ports) == 1
    x0 = records[0][graph_ports[0]]
    assert x0.shape == (6,) and x0.dtype == np.float32


@pytest.fixture(scope="module")
def csv_pipeline_graphdef(tmp_path_factory):
    """(graphdef bytes): the classic TF 1.x CSV pipeline — filename
    queue -> TextLineReader (skipping a header) -> decode_csv -> batch
    queue — over a learnable 3-class rule (label = argmax of the first
    3 features).  Beyond the reference's reader set: its
    handleReaderNode matches only TFRecordReaderV2
    (Session.scala:128-131)."""
    tmp = tmp_path_factory.mktemp("tfcsv")
    csv_path = str(tmp / "train.csv")
    rng = np.random.RandomState(0)
    with open(csv_path, "w") as f:
        f.write("f0,f1,f2,f3,label\n")  # header, skipped by the reader
        for _ in range(96):
            x = rng.randn(4).astype(np.float32)
            y = int(np.argmax(x[:3]))
            f.write(",".join(f"{v:.6f}" for v in x) + f",{y}\n")

    tf1 = tf.compat.v1
    tf1.disable_eager_execution()
    g = tf1.Graph()
    with g.as_default():
        fq = tf1.train.string_input_producer([csv_path], shuffle=False)
        reader = tf1.TextLineReader(skip_header_lines=1)
        _, line = reader.read(fq)
        f0, f1, f2, f3, label = tf1.decode_csv(
            line, record_defaults=[[0.0], [0.0], [0.0], [0.0], [-1]])
        label64 = tf1.cast(label, tf.int64)
        b0, b1, b2, b3, _blab = tf1.train.batch(
            [f0, f1, f2, f3, label64], batch_size=8)
        bx = tf1.stack([b0, b1, b2, b3], axis=1)
        w1 = tf1.constant((rng.randn(4, 3) * 0.1).astype(np.float32),
                          name="W")
        b1 = tf1.constant(np.zeros(3, np.float32), name="b")
        logits = tf1.nn.bias_add(tf1.matmul(bx, w1, name="mm"), b1,
                                 name="logits")
        tf1.nn.log_softmax(logits, name="logprob")
    return g.as_graph_def().SerializeToString()


def test_textline_csv_pipeline_records(csv_pipeline_graphdef):
    """TextLineReader+DecodeCSV interprets into typed records: header
    skipped, floats and the int field parsed per record_defaults."""
    sess = TFTrainingSession(csv_pipeline_graphdef)
    model, records, graph_ports, label_ports = sess.build(["logprob"])
    assert len(records) == 96
    row = records[0]
    feats = [row[p] for p in graph_ports]
    labels = [row[p] for p in label_ports]
    x = np.concatenate([np.atleast_1d(f) for f in feats]).astype(np.float32)
    assert x.shape == (4,)
    assert len(labels) == 1 and labels[0].dtype == np.int64
    assert int(labels[0]) == int(np.argmax(x[:3]))


def test_textline_csv_pipeline_trains(csv_pipeline_graphdef):
    """End-to-end session training on a text-line pipeline (VERDICT r4
    next-step #7)."""
    sess = TFTrainingSession(csv_pipeline_graphdef)
    trained = sess.train(
        ["logprob"], criterion=nn.ClassNLLCriterion(),
        optim_method=optim.SGD(learning_rate=0.5),
        batch_size=16, end_trigger=optim.Trigger.max_epoch(6))
    rng = np.random.RandomState(7)
    x = rng.randn(64, 4).astype(np.float32)
    y = np.argmax(x[:, :3], axis=1)
    # the graph's inputs are the four dequeued CSV columns
    logprob = np.asarray(trained.evaluate().forward(
        [x[:, i] for i in range(4)]))
    acc = (logprob.argmax(1) == y).mean()
    assert acc > 0.7, f"trained accuracy {acc} too low"


@pytest.fixture(scope="module")
def fixedlen_pipeline_graphdef(tmp_path_factory):
    """(graphdef bytes): the classic CIFAR-10 binary pipeline — filename
    queue -> FixedLengthRecordReader (with a file header) -> decode_raw
    -> strided_slice label/image -> transpose -> scale -> batch queue.
    Record layout: 1 label byte + 3x8x8 image bytes.  Rule: label = 1
    iff the first pixel of channel 0 exceeds 127 (cleanly linearly
    separable from the pixels)."""
    tmp = tmp_path_factory.mktemp("tfbin")
    bin_path = str(tmp / "data.bin")
    rng = np.random.RandomState(3)
    with open(bin_path, "wb") as f:
        f.write(b"HDR!")  # header_bytes=4
        for _ in range(80):
            img = rng.randint(0, 256, (3, 8, 8)).astype(np.uint8)
            y = int(img[0, 0, 0] > 127)
            f.write(bytes([y]) + img.tobytes())

    tf1 = tf.compat.v1
    tf1.disable_eager_execution()
    g = tf1.Graph()
    with g.as_default():
        fq = tf1.train.string_input_producer([bin_path], shuffle=False)
        reader = tf1.FixedLengthRecordReader(record_bytes=1 + 192,
                                             header_bytes=4)
        _, value = reader.read(fq)
        record = tf1.decode_raw(value, tf.uint8)
        label = tf1.cast(tf1.reshape(
            tf1.strided_slice(record, [0], [1]), []), tf.int64)
        img = tf1.reshape(tf1.strided_slice(record, [1], [193]), [3, 8, 8])
        img = tf1.transpose(img, [1, 2, 0])  # CHW -> HWC
        imgf = tf1.cast(img, tf.float32) / 255.0
        bimg, _blab = tf1.train.batch([imgf, label], batch_size=8)
        flat = tf1.reshape(bimg, [-1, 192])
        rng2 = np.random.RandomState(0)
        w1 = tf1.constant((rng2.randn(192, 2) * 0.01).astype(np.float32),
                          name="W")
        logits = tf1.matmul(flat, w1, name="mm")
        tf1.nn.log_softmax(logits, name="logprob")
    return g.as_graph_def().SerializeToString()


def test_fixedlen_pipeline_records(fixedlen_pipeline_graphdef):
    """FixedLengthRecordReader records: header skipped, label byte and
    transposed/scaled image decoded per record."""
    sess = TFTrainingSession(fixedlen_pipeline_graphdef)
    model, records, graph_ports, label_ports = sess.build(["logprob"])
    assert len(records) == 80
    img, lab = records[0][graph_ports[0]], records[0][label_ports[0]]
    assert img.shape == (8, 8, 3) and img.dtype == np.float32
    assert float(img.max()) <= 1.0
    chw = np.transpose(img, (2, 0, 1)) * 255.0
    assert int(lab) == int(round(float(chw[0, 0, 0])) > 127)


def test_fixedlen_pipeline_trains(fixedlen_pipeline_graphdef):
    """End-to-end session training on the CIFAR-binary pipeline: the
    imported graph fits the pipeline's records (80 samples / 192 dims is
    a memorization regime, so the check is train-set accuracy — the
    pipeline-correctness signal, not generalization)."""
    sess = TFTrainingSession(fixedlen_pipeline_graphdef)
    model, records, graph_ports, label_ports = sess.build(["logprob"])
    trained = sess.train(
        ["logprob"], criterion=nn.ClassNLLCriterion(),
        optim_method=optim.SGD(learning_rate=1.0),
        batch_size=16, end_trigger=optim.Trigger.max_epoch(30))
    x = np.stack([r[graph_ports[0]] for r in records])
    y = np.asarray([int(r[label_ports[0]]) for r in records])
    logprob = np.asarray(trained.evaluate().forward(x))
    acc = (logprob.argmax(1) == y).mean()
    assert acc > 0.95, f"trained accuracy {acc} too low"



def test_shuffled_filename_queue(tmp_path):
    """string_input_producer(shuffle=True) inserts a RandomShuffle on
    the filename tensor — interpreted as a reproducible host-side file
    permutation (previously an honest NotImplementedError)."""
    import tensorflow as tf

    from bigdl_tpu.utils.rng import RNG

    paths = []
    rng = np.random.RandomState(5)
    for fi in range(4):
        p = str(tmp_path / f"f{fi}.tfrecord")
        with tf.io.TFRecordWriter(p) as w:
            ex = tf.train.Example(features=tf.train.Features(feature={
                "x": tf.train.Feature(float_list=tf.train.FloatList(
                    value=[float(fi)])),
                "y": tf.train.Feature(int64_list=tf.train.Int64List(
                    value=[fi % 2]))}))
            w.write(ex.SerializeToString())
        paths.append(p)

    tf1 = tf.compat.v1
    tf1.disable_eager_execution()
    g = tf1.Graph()
    with g.as_default():
        fq = tf1.train.string_input_producer(paths, shuffle=True)
        reader = tf1.TFRecordReader()
        _, serialized = reader.read(fq)
        feats = tf1.parse_single_example(serialized, features={
            "x": tf1.FixedLenFeature([1], tf.float32),
            "y": tf1.FixedLenFeature([], tf.int64)})
        bx, _by = tf1.train.batch([feats["x"], feats["y"]], batch_size=2)
        w1 = tf1.constant(np.ones((1, 2), np.float32), name="W")
        tf1.nn.log_softmax(tf1.matmul(bx, w1), name="logprob")
    gd = g.as_graph_def().SerializeToString()

    RNG.set_seed(7)
    sess = TFTrainingSession(gd)
    _, records, gp, lp = sess.build(["logprob"])
    got = [float(r[gp[0]][0]) for r in records]
    assert sorted(got) == [0.0, 1.0, 2.0, 3.0]  # all files, once each
    # reproducible permutation under the same seed
    RNG.set_seed(7)
    sess2 = TFTrainingSession(gd)
    _, records2, gp2, _ = sess2.build(["logprob"])
    assert got == [float(r[gp2[0]][0]) for r in records2]
    # and actually shuffled for SOME seed (not the identity for all)
    shuffled = False
    for seed in range(5):
        RNG.set_seed(seed)
        s3 = TFTrainingSession(gd)
        _, r3, g3, _ = s3.build(["logprob"])
        if [float(r[g3[0]][0]) for r in r3] != [0.0, 1.0, 2.0, 3.0]:
            shuffled = True
            break
    assert shuffled
