"""TF Session training path (VERDICT r1 item 3; reference
``utils/tf/Session.scala:48,150-263,435-461``): a REAL GraphDef built by
TensorFlow with a TFRecord queue input pipeline (string_input_producer ->
TFRecordReader -> parse_single_example -> batch queue) is interpreted
into a host DataSet, its compute subgraph becomes a trainable Graph, and
the Optimizer trains it to a loss target.

Fixture generation needs the real TensorFlow package (the reference's
oracle discipline: its tests shell out to real Lua Torch, gated on
availability — SURVEY §4); skipped when absent.
"""

import os

import numpy as np
import pytest

tf = pytest.importorskip("tensorflow")

import bigdl_tpu.nn as nn  # noqa: E402
import bigdl_tpu.optim as optim  # noqa: E402
from bigdl_tpu.utils.tf_session import TFTrainingSession  # noqa: E402


@pytest.fixture(scope="module")
def pipeline_graphdef(tmp_path_factory):
    """(graphdef bytes, tfrecord path): a learnable 3-class problem
    (label = argmax of the first 3 features) behind a TF queue pipeline."""
    tmp = tmp_path_factory.mktemp("tfsess")
    rec_path = str(tmp / "train.tfrecord")
    rng = np.random.RandomState(0)
    with tf.io.TFRecordWriter(rec_path) as w:
        for _ in range(96):
            x = rng.randn(6).astype(np.float32)
            y = int(np.argmax(x[:3]))
            ex = tf.train.Example(features=tf.train.Features(feature={
                "x": tf.train.Feature(
                    float_list=tf.train.FloatList(value=x)),
                "y": tf.train.Feature(
                    int64_list=tf.train.Int64List(value=[y])),
            }))
            w.write(ex.SerializeToString())

    tf1 = tf.compat.v1
    tf1.disable_eager_execution()
    g = tf1.Graph()
    with g.as_default():
        fq = tf1.train.string_input_producer([rec_path], shuffle=False)
        reader = tf1.TFRecordReader()
        _, serialized = reader.read(fq)
        feats = tf1.parse_single_example(serialized, features={
            "x": tf1.FixedLenFeature([6], tf.float32),
            "y": tf1.FixedLenFeature([], tf.int64)})
        bx, _by = tf1.train.batch([feats["x"], feats["y"]], batch_size=8)
        w1 = tf1.constant(
            (rng.randn(6, 3) * 0.1).astype(np.float32), name="W")
        b1 = tf1.constant(np.zeros(3, np.float32), name="b")
        logits = tf1.nn.bias_add(tf1.matmul(bx, w1, name="mm"), b1,
                                 name="logits")
        tf1.nn.log_softmax(logits, name="logprob")
    return g.as_graph_def().SerializeToString(), rec_path


def test_interpret_pipeline(pipeline_graphdef):
    gd, rec_path = pipeline_graphdef
    sess = TFTrainingSession(gd)
    model, records, graph_ports, label_ports = sess.build(["logprob"])
    assert graph_ports == [0] and label_ports == [1]
    assert len(records) == 96
    x0, y0 = records[0]
    assert x0.shape == (6,) and x0.dtype == np.float32
    assert y0.shape == () and y0.dtype == np.int64
    # the imported compute graph is trainable (W, b became Variables)
    from bigdl_tpu.nn.module import state_dict

    assert len(state_dict(model, kind="param")) == 2
    # forward works on a batch
    out = model.evaluate().forward(np.zeros((4, 6), np.float32))
    assert np.asarray(out).shape == (4, 3)


def test_train_imported_graph_reaches_loss_target(pipeline_graphdef):
    gd, _ = pipeline_graphdef
    sess = TFTrainingSession(gd)
    trained = sess.train(
        ["logprob"], criterion=nn.ClassNLLCriterion(),
        optim_method=optim.SGD(learning_rate=0.5),
        batch_size=16, end_trigger=optim.Trigger.max_epoch(6))
    # evaluate the trained graph on fresh samples of the same rule
    rng = np.random.RandomState(7)
    x = rng.randn(64, 6).astype(np.float32)
    y = np.argmax(x[:, :3], axis=1)
    logprob = np.asarray(trained.evaluate().forward(x))
    loss = -logprob[np.arange(64), y].mean()
    assert loss < 0.75, f"trained loss {loss} did not reach target"
    acc = (logprob.argmax(1) == y).mean()
    assert acc > 0.7, f"trained accuracy {acc} too low"


@pytest.fixture(scope="module")
def v1_parse_graphdef(tmp_path_factory, pipeline_graphdef):
    """The same learnable pipeline but through the LEGACY variadic-key
    ``ParseExample`` (v1) node — emitted via tf.raw_ops since TF2's
    public API always lowers to V2."""
    _, rec_path = pipeline_graphdef
    tf1 = tf.compat.v1
    tf1.disable_eager_execution()
    g = tf1.Graph()
    with g.as_default():
        fq = tf1.train.string_input_producer([rec_path], shuffle=False)
        reader = tf1.TFRecordReader()
        _, serialized = reader.read(fq)
        parsed = tf.raw_ops.ParseExample(
            serialized=tf1.reshape(serialized, [1]),
            names=tf1.constant([], tf.string),
            sparse_keys=[],
            sparse_types=[],
            dense_keys=[tf1.constant("x"), tf1.constant("y")],
            dense_defaults=[tf1.constant([], tf.float32),
                            tf1.constant([], tf.int64)],
            dense_shapes=[[6], []])
        px = tf1.reshape(parsed.dense_values[0], [6])
        py = tf1.reshape(parsed.dense_values[1], [])
        bx, _by = tf1.train.batch([px, py], batch_size=8)
        rng = np.random.RandomState(0)
        w1 = tf1.constant((rng.randn(6, 3) * 0.1).astype(np.float32), name="W")
        b1 = tf1.constant(np.zeros(3, np.float32), name="b")
        logits = tf1.nn.bias_add(tf1.matmul(bx, w1, name="mm"), b1,
                                 name="logits")
        tf1.nn.log_softmax(logits, name="logprob")
    return g.as_graph_def().SerializeToString()


def test_v1_parse_example_pipeline_trains(v1_parse_graphdef):
    """VERDICT r2 weak #9: the v1 parse op must train end-to-end."""
    sess = TFTrainingSession(v1_parse_graphdef)
    model, records, graph_ports, label_ports = sess.build(["logprob"])
    assert len(records) == 96
    x0, y0 = records[0]
    assert x0.shape == (6,) and y0.dtype == np.int64
    trained = sess.train(
        ["logprob"], criterion=nn.ClassNLLCriterion(),
        optim_method=optim.SGD(learning_rate=0.5),
        batch_size=16, end_trigger=optim.Trigger.max_epoch(6))
    rng = np.random.RandomState(7)
    x = rng.randn(64, 6).astype(np.float32)
    y = np.argmax(x[:, :3], axis=1)
    logprob = np.asarray(trained.evaluate().forward(x))
    acc = (logprob.argmax(1) == y).mean()
    assert acc > 0.7, f"trained accuracy {acc} too low"


@pytest.fixture(scope="module")
def image_pipeline_graphdef(tmp_path_factory):
    """An IMAGE pipeline (Session.scala:173-263): PNG bytes feature ->
    DecodePng -> Cast -> normalize -> Reshape, behind the same queue
    machinery.  Class = dominant color channel."""
    import io

    from PIL import Image

    tmp = tmp_path_factory.mktemp("tfimg")
    rec_path = str(tmp / "imgs.tfrecord")
    rng = np.random.RandomState(1)
    with tf.io.TFRecordWriter(rec_path) as w:
        for _ in range(48):
            y = int(rng.randint(0, 3))
            img = rng.randint(0, 100, (4, 4, 3)).astype(np.uint8)
            img[:, :, y] += 150
            buf = io.BytesIO()
            Image.fromarray(img).save(buf, format="PNG")
            ex = tf.train.Example(features=tf.train.Features(feature={
                "image": tf.train.Feature(bytes_list=tf.train.BytesList(
                    value=[buf.getvalue()])),
                "label": tf.train.Feature(int64_list=tf.train.Int64List(
                    value=[y]))}))
            w.write(ex.SerializeToString())

    tf1 = tf.compat.v1
    tf1.disable_eager_execution()
    g = tf1.Graph()
    with g.as_default():
        fq = tf1.train.string_input_producer([rec_path], shuffle=False)
        reader = tf1.TFRecordReader()
        _, serialized = reader.read(fq)
        feats = tf1.parse_single_example(serialized, features={
            "image": tf1.FixedLenFeature([], tf.string),
            "label": tf1.FixedLenFeature([], tf.int64)})
        img = tf1.image.decode_png(feats["image"], channels=3)
        img = tf1.cast(img, tf.float32) / 255.0
        img = tf1.reshape(img, [48])
        bx, _by = tf1.train.batch([img, feats["label"]], batch_size=8)
        w1 = tf1.constant((np.random.RandomState(2).randn(48, 3) * 0.1)
                          .astype(np.float32), name="W")
        logits = tf1.matmul(bx, w1, name="logits")
        tf1.nn.log_softmax(logits, name="logprob")
    return g.as_graph_def().SerializeToString(), rec_path


def test_image_pipeline_records_decoded(image_pipeline_graphdef):
    gd, _ = image_pipeline_graphdef
    sess = TFTrainingSession(gd)
    model, records, graph_ports, label_ports = sess.build(["logprob"])
    assert len(records) == 48
    x0, y0 = records[0]
    assert x0.shape == (48,) and x0.dtype == np.float32
    assert 0.0 <= float(x0.min()) and float(x0.max()) <= 1.0
    assert y0.dtype == np.int64


def test_image_pipeline_trains(image_pipeline_graphdef):
    gd, _ = image_pipeline_graphdef
    sess = TFTrainingSession(gd)
    trained = sess.train(
        ["logprob"], criterion=nn.ClassNLLCriterion(),
        optim_method=optim.SGD(learning_rate=0.5),
        batch_size=16, end_trigger=optim.Trigger.max_epoch(8))
    # fresh images of the same rule: dominant channel = class
    rng = np.random.RandomState(9)
    xs, ys = [], []
    for _ in range(32):
        y = int(rng.randint(0, 3))
        img = rng.randint(0, 100, (4, 4, 3)).astype(np.uint8)
        img[:, :, y] += 150
        xs.append(img.reshape(48).astype(np.float32) / 255.0)
        ys.append(y)
    logprob = np.asarray(trained.evaluate().forward(np.stack(xs)))
    acc = (logprob.argmax(1) == np.asarray(ys)).mean()
    assert acc > 0.8, f"trained accuracy {acc} too low"
