"""Scan-over-layers (nn/layers/scan.py, docs/compile.md): a ScanLayers
stack must be an exact, cheaper-to-compile replacement for the unrolled
Sequential it came from — same outputs, same grads, same buffer
advance, state-dict/BTPU round trips both directions, zero retraces,
and ONE block body in the lowered HLO instead of N."""

import copy

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import bigdl_tpu.nn as nn
import bigdl_tpu.optim as optim
from bigdl_tpu.analysis.retrace import trace_retraces
from bigdl_tpu.nn.layers.scan import ScanLayers, auto_scan, layer_signature
from bigdl_tpu.nn.module import (functional_call, state_dict,
                                 stamp_scope_names)
from bigdl_tpu.parallel.train_step import TrainStep, _jit_cache_size
from bigdl_tpu.utils.rng import RNG


def _mlp_blocks(n=4, dim=8, seed=3):
    RNG.set_seed(seed)
    return [nn.Sequential(nn.Linear(dim, dim), nn.Tanh())
            for _ in range(n)]


def _pair(n=4, dim=8):
    """(unrolled, scanned) models over the SAME parameter values."""
    blocks = _mlp_blocks(n, dim)
    unrolled = nn.Sequential(*[copy.deepcopy(b) for b in blocks])
    scanned = nn.Sequential(ScanLayers(blocks))
    return unrolled, scanned


def _grad_map(unrolled_grads, scanned_grads, prefix="0.body."):
    """Compare unrolled '<i>.<rest>' grads against scanned stacked
    '<prefix><rest>'[i]."""
    for k, g in unrolled_grads.items():
        i, rest = k.split(".", 1)
        got = np.asarray(scanned_grads[prefix + rest][int(i)])
        np.testing.assert_allclose(np.asarray(g), got,
                                   rtol=1e-6, atol=1e-7, err_msg=k)


# -- numerics parity ---------------------------------------------------------
def test_forward_and_grad_parity_vs_unrolled():
    unrolled, scanned = _pair()
    x = jnp.asarray(np.random.RandomState(0).randn(5, 8).astype(np.float32))
    np.testing.assert_allclose(np.asarray(unrolled.forward(x)),
                               np.asarray(scanned.forward(x)),
                               rtol=1e-6, atol=1e-7)

    su, ss = state_dict(unrolled), state_dict(scanned)

    def loss(model, p):
        return jnp.sum(functional_call(model, p, x)[0] ** 2)

    gu = jax.grad(lambda p: loss(unrolled, p))(su)
    gs = jax.grad(lambda p: loss(scanned, p))(ss)
    _grad_map(gu, gs)


def test_grads_match_finite_differences():
    """The numeric-grad harness contract on the scanned path: central
    differences through the full scan confirm the analytic cotangents."""
    _, scanned = _pair(n=3, dim=4)
    x = jnp.asarray(np.random.RandomState(1).randn(3, 4).astype(np.float32))
    state = state_dict(scanned)

    def loss(p):
        return jnp.sum(functional_call(scanned, p, x)[0] ** 2)

    grads = jax.grad(loss)(state)
    key = "0.body.0.weight"
    g = np.asarray(grads[key])
    eps = 1e-3
    for idx in ((0, 0, 0), (1, 2, 1), (2, 3, 3)):
        bumped = dict(state)
        delta = np.zeros(state[key].shape, np.float32)
        delta[idx] = eps
        bumped[key] = state[key] + delta
        hi = float(loss(bumped))
        bumped[key] = state[key] - delta
        lo = float(loss(bumped))
        fd = (hi - lo) / (2 * eps)
        assert abs(fd - g[idx]) < 1e-2 * max(1.0, abs(fd)), \
            f"finite-diff {fd} vs analytic {g[idx]} at {idx}"


def test_buffer_advance_matches_unrolled():
    """Training-mode BN running stats advance per scanned layer exactly
    as the unrolled chain advances them."""
    RNG.set_seed(1)
    blocks = [nn.Sequential(nn.SpatialConvolution(4, 4, 3, 3, 1, 1, 1, 1),
                            nn.SpatialBatchNormalization(4), nn.ReLU(True))
              for _ in range(3)]
    unrolled = nn.Sequential(*[copy.deepcopy(b) for b in blocks]).train()
    scanned = nn.Sequential(ScanLayers(blocks)).train()
    x = jnp.asarray(np.random.RandomState(0).randn(2, 4, 8, 8)
                    .astype(np.float32))
    su, ss = state_dict(unrolled), state_dict(scanned)
    yu, nu = functional_call(unrolled, su, x, training=True)
    ys, ns = functional_call(scanned, ss, x, training=True)
    np.testing.assert_allclose(np.asarray(yu), np.asarray(ys),
                               rtol=1e-5, atol=1e-6)
    for k, v in nu.items():
        if "running" not in k:
            continue
        i, rest = k.split(".", 1)
        np.testing.assert_allclose(
            np.asarray(v), np.asarray(ns[f"0.body.{rest}"][int(i)]),
            rtol=1e-5, atol=1e-6, err_msg=k)


# -- the converted registry models -------------------------------------------
def _model_pair(build, *args, **kwargs):
    """Build the same model twice at one seed: unrolled and scanned."""
    RNG.set_seed(11)
    unrolled = build(*args, **kwargs, scan=False)
    RNG.set_seed(11)
    scanned = build(*args, **kwargs, scan=True)
    return unrolled, scanned


def test_resnet_cifar_scanned_matches_unrolled():
    from bigdl_tpu.models import build_resnet_cifar

    unrolled, scanned = _model_pair(build_resnet_cifar, 20, 10)
    assert any(isinstance(c, ScanLayers) for c in scanned.layers), \
        "scan=True resnet must contain ScanLayers stage groups"
    x = jnp.asarray(np.random.RandomState(0).randn(2, 3, 32, 32)
                    .astype(np.float32))
    np.testing.assert_allclose(
        np.asarray(unrolled.evaluate().forward(x)),
        np.asarray(scanned.evaluate().forward(x)), rtol=1e-6, atol=1e-6)


def test_transformer_scanned_matches_unrolled_through_train_step():
    """One full compiled train step (fwd + bwd + SGD update) on the
    scanned transformer matches the unrolled one: equal loss AND equal
    post-update predictions — gradients agreed everywhere."""
    from bigdl_tpu.models import build_transformer_lm

    unrolled, scanned = _model_pair(
        build_transformer_lm, 50, num_layers=3, embed_dim=32, num_heads=4,
        max_len=16)
    assert any(isinstance(c, ScanLayers) for c in scanned.layers)
    rng = np.random.RandomState(2)
    x = jnp.asarray(rng.randint(0, 50, (2, 16), dtype=np.int32))
    y = jnp.asarray(rng.randint(0, 50, (2, 16), dtype=np.int32))
    crit = nn.TimeDistributedCriterion(nn.ClassNLLCriterion(),
                                       size_average=True)
    losses, outs = [], []
    for model in (unrolled, scanned):
        step = TrainStep(model, copy.deepcopy(crit),
                         optim.SGD(learning_rate=0.1))
        losses.append(float(step.run(x, y, jax.random.key(0))))
        step.sync_to_model()
        outs.append(np.asarray(model.evaluate().forward(x)))
    assert abs(losses[0] - losses[1]) < 1e-5, losses
    np.testing.assert_allclose(outs[0], outs[1], rtol=1e-5, atol=1e-5)


def test_lstm_scanned_matches_unrolled():
    from bigdl_tpu.models import build_lstm_classifier

    unrolled, scanned = _model_pair(
        build_lstm_classifier, 80, embed_dim=16, hidden_size=16,
        num_layers=3, class_num=4)
    assert any(isinstance(c, ScanLayers) for c in scanned.layers), \
        "equal-width LSTM stack must collapse into ScanLayers"
    x = jnp.asarray(np.random.RandomState(3).randint(0, 80, (2, 12),
                                                     dtype=np.int32))
    np.testing.assert_allclose(
        np.asarray(unrolled.evaluate().forward(x)),
        np.asarray(scanned.evaluate().forward(x)), rtol=1e-5, atol=1e-6)


def test_registry_flag_converts_models():
    from bigdl_tpu.models.registry import build_model
    from bigdl_tpu.utils.config import BigDLConfig, set_config

    try:
        set_config(BigDLConfig(scan_layers=True))
        model = build_model("resnet")
        assert any(isinstance(m, ScanLayers) for m in model.modules())
        set_config(BigDLConfig(scan_layers=False))
        model = build_model("resnet")
        assert not any(isinstance(m, ScanLayers) for m in model.modules())
    finally:
        set_config(None)


# -- ONE compiled body -------------------------------------------------------
def test_scanned_stack_lowers_to_single_body():
    """The tentpole claim: the lowered HLO contains the block body ONCE
    (inside the scan region) where the unrolled chain repeats it N
    times."""
    unrolled, scanned = _pair(n=4)
    x = jnp.ones((5, 8))

    def hlo(model):
        st = state_dict(model)
        return jax.jit(
            lambda s, a: functional_call(model, s, a, training=False)[0]
        ).lower(st, x).as_text()

    assert hlo(unrolled).count("tanh") == 4
    assert hlo(scanned).count("tanh") == 1


def test_zero_retraces_and_one_compile_under_train_step():
    _, scanned = _pair()
    step = TrainStep(scanned, nn.MSECriterion(),
                     optim.SGD(learning_rate=0.1))
    x = jnp.ones((4, 8))
    y = jnp.zeros((4, 8))
    with trace_retraces() as mon:
        for i in range(3):
            step.run(x, y, jax.random.key(i))
    assert mon.report.rules_fired() == []
    assert _jit_cache_size(step._compiled) == 1


def test_scanned_body_attribution_scopes():
    """PR-4 attribution works for the scanned body: rows under
    ...ScanLayers.body carry the block's flops (counted once, matching
    how often XLA compiles it)."""
    from bigdl_tpu.telemetry.attribution import attribute_lowered

    _, scanned = _pair()
    stamp_scope_names(scanned)
    st = state_dict(scanned)
    lowered = jax.jit(
        lambda s, a: functional_call(scanned, s, a, training=False)[0]
    ).lower(st, jnp.ones((5, 8)))
    rows = {r["path"]: r for r in attribute_lowered(lowered, scanned)["rows"]}
    assert rows["0.body.0"]["flops"] > 0, rows.keys()
    assert rows["0.body.0"]["class"] == "Linear"


# -- state mapping, both directions ------------------------------------------
def test_layer_state_dict_round_trip_against_unrolled():
    unrolled, scanned = _pair()
    sl = scanned.get(0)
    x = jnp.asarray(np.random.RandomState(4).randn(3, 8).astype(np.float32))

    # export: scanned per-layer keys == the unrolled Sequential's keys
    per = sl.layer_state_dict()
    assert set(per) == set(state_dict(unrolled))
    fresh = nn.Sequential(*[copy.deepcopy(b) for b in _mlp_blocks(4, 8, 9)])
    from bigdl_tpu.nn.module import load_state_dict

    load_state_dict(fresh, per)
    np.testing.assert_allclose(np.asarray(fresh.forward(x)),
                               np.asarray(scanned.forward(x)),
                               rtol=1e-6, atol=1e-7)

    # import: an unrolled checkpoint loads onto the stacked axis
    donor = nn.Sequential(*_mlp_blocks(4, 8, 21))
    sl.load_layer_state_dict(state_dict(donor))
    np.testing.assert_allclose(np.asarray(donor.forward(x)),
                               np.asarray(scanned.forward(x)),
                               rtol=1e-6, atol=1e-7)


def test_load_layer_state_dict_strict_errors():
    _, scanned = _pair(n=2)
    sl = scanned.get(0)
    good = sl.layer_state_dict()
    with pytest.raises(KeyError, match="missing"):
        sl.load_layer_state_dict({k: v for k, v in good.items()
                                  if not k.startswith("1.")})
    with pytest.raises(KeyError, match="unexpected"):
        sl.load_layer_state_dict({**good, "9.nope": np.zeros(2)})


def test_btpu_round_trip():
    from bigdl_tpu.utils import module_format as mf

    _, scanned = _pair()
    x = jnp.asarray(np.random.RandomState(5).randn(2, 8).astype(np.float32))
    want = np.asarray(scanned.forward(x))
    clone = mf.loads(mf.dumps(scanned))
    assert isinstance(clone.get(0), ScanLayers)
    np.testing.assert_allclose(np.asarray(clone.forward(x)), want,
                               rtol=1e-6, atol=1e-7)


def test_to_layers_reconstructs_blocks():
    unrolled, scanned = _pair()
    rebuilt = nn.Sequential(*scanned.get(0).to_layers())
    x = jnp.asarray(np.random.RandomState(6).randn(2, 8).astype(np.float32))
    np.testing.assert_allclose(np.asarray(rebuilt.forward(x)),
                               np.asarray(unrolled.forward(x)),
                               rtol=1e-6, atol=1e-7)


# -- guardrails --------------------------------------------------------------
def test_structural_mismatch_rejected():
    RNG.set_seed(0)
    with pytest.raises(ValueError, match="identical"):
        ScanLayers(nn.Linear(4, 4), nn.Linear(5, 5))
    # equal shapes, different scalar hyperparameter: still rejected
    with pytest.raises(ValueError, match="identical"):
        ScanLayers(nn.Dropout(0.1), nn.Dropout(0.5))
    with pytest.raises(ValueError):
        ScanLayers()


def test_auto_scan_groups_only_identical_runs():
    RNG.set_seed(0)
    seq = nn.Sequential(nn.Linear(4, 8), nn.Tanh())  # distinct head
    for _ in range(3):
        seq.add(nn.Sequential(nn.Linear(8, 8), nn.Tanh()))
    seq.add(nn.Linear(8, 2))
    auto_scan(seq)
    kinds = [type(c).__name__ for c in seq.layers]
    assert kinds == ["Linear", "Tanh", "ScanLayers", "Linear"], kinds
    assert seq.get(2).n_layers == 3


def test_dropout_streams_differ_per_scanned_layer():
    """Stochastic blocks must not share one mask across scanned layers:
    the layer index is folded into the step key (the scanned analogue
    of per-clone _rng_ids).  Two composed p=0.5 dropouts keep ~25% of
    cells with independent masks, but exactly the first mask's ~50%
    when the layers replay one mask (a kept cell is kept twice)."""
    from bigdl_tpu.utils.rng import rng_context

    RNG.set_seed(0)
    blocks = [nn.Sequential(nn.Dropout(0.5)) for _ in range(2)]
    sl = nn.Sequential(ScanLayers(blocks)).train()
    x = jnp.ones((1, 1024))
    with rng_context(jax.random.key(0)):
        composed = np.asarray(sl.forward(x))
    kept = float((composed != 0).mean())
    assert 0.1 < kept < 0.4, \
        f"kept fraction {kept}: layers appear to share one dropout mask"
    # and the realization is deterministic under one key
    with rng_context(jax.random.key(0)):
        again = np.asarray(sl.forward(x))
    np.testing.assert_array_equal(composed, again)


def test_tuple_hyperparameters_distinguish_blocks():
    """Shape-spec hypers are tuples (Transpose.permutations,
    View.sizes): same-class layers differing only there compute
    different functions and must NOT stack (review finding: the scalar
    filter used to drop them, silently corrupting auto_scan'd models)."""
    assert layer_signature(nn.Transpose(((1, 2),))) \
        != layer_signature(nn.Transpose(((2, 3),)))
    with pytest.raises(ValueError, match="identical"):
        ScanLayers(nn.Transpose(((1, 2),)), nn.Transpose(((2, 3),)))
    seq = nn.Sequential(nn.Transpose(((1, 2),)), nn.Transpose(((2, 3),)))
    x = jnp.asarray(np.random.RandomState(0).randn(2, 3, 4, 5)
                    .astype(np.float32))
    want = np.asarray(seq.forward(x))
    auto_scan(seq)
    assert not any(isinstance(c, ScanLayers) for c in seq.layers)
    np.testing.assert_array_equal(np.asarray(seq.forward(x)), want)


def test_signature_is_order_stable():
    RNG.set_seed(0)
    a = nn.Sequential(nn.Linear(4, 4), nn.ReLU())
    RNG.set_seed(1)
    b = nn.Sequential(nn.Linear(4, 4), nn.ReLU())
    assert layer_signature(a) == layer_signature(b)
