"""Numerical-gradient validation for layers WITHOUT a PyTorch oracle
(SURVEY §4's gradient-check discipline — the reference cross-checks every
layer's backward against either Torch or a numeric differentiator).
``jax.test_util.check_grads`` compares each layer's VJP against finite
differences, so custom-VJP layers and composite normalizations get a
backward check even where no framework oracle exists.

Every case runs under BOTH kernel-dispatch legs (``BIGDL_KERNELS=xla``
and ``=pallas``): each of these layers routes through a
``bigdl_tpu.ops`` custom-VJP op whose hand-derived exact cotangent must
hold whether the backend is the XLA reference or the Pallas kernel (in
interpret mode on the CPU suite — the identical code path that Mosaic
compiles on TPU)."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest
from jax.test_util import check_grads

try:  # this jaxlib keeps the scoped x64 switch in jax.experimental
    from jax.experimental import enable_x64 as _enable_x64
except ImportError:  # newer jax promoted it to the public namespace
    _enable_x64 = jax.enable_x64

import bigdl_tpu.nn as nn
from bigdl_tpu.utils.rng import RNG


def _layer_fn(layer):
    layer.evaluate()  # freeze any stochastic/stat behavior

    def fn(x):
        return layer.update_output(x)

    return fn


CASES = [
    # composite normalizations (no torch counterpart)
    ("within_channel_lrn", lambda: nn.SpatialWithinChannelLRN(3, 0.01, 0.75),
     (2, 4, 6, 6)),
    ("subtractive_norm", lambda: nn.SpatialSubtractiveNormalization(4),
     (2, 4, 7, 7)),
    ("divisive_norm", lambda: nn.SpatialDivisiveNormalization(4),
     (2, 4, 7, 7)),
    ("contrastive_norm", lambda: nn.SpatialContrastiveNormalization(4),
     (2, 4, 7, 7)),
    # custom-VJP paths
    ("maxpool_tie_split", lambda: nn.SpatialMaxPooling(3, 3, 2, 2, 1, 1)
     .split_ties(), (2, 3, 9, 9)),
    ("lrn_banded_conv", lambda: nn.SpatialCrossMapLRN(5, 0.0001, 0.75),
     (2, 7, 5, 5)),
    # ceil-mode average pooling (asymmetric declared-vs-overflow padding
    # divisors — the subtle Torch semantics in _PoolBase._avg)
    ("ceil_avg_pool", lambda: nn.SpatialAveragePooling(3, 3, 2, 2, 1, 1,
                                                       ceil_mode=True),
     (2, 3, 9, 9)),
]


@pytest.mark.parametrize("kernels", ["xla", "pallas"])
@pytest.mark.parametrize("case", CASES, ids=lambda c: c[0])
def test_vjp_matches_finite_differences(case, kernels, monkeypatch):
    name, build, shape = case
    monkeypatch.setenv("BIGDL_KERNELS", kernels)
    RNG.set_seed(0)
    # finite differences need f64 — scoped, so the rest of the suite
    # keeps the default f32 world
    with _enable_x64():
        layer = build()
        x = jnp.asarray(
            np.random.RandomState(0).randn(*shape).astype(np.float64))
        # order=1 reverse mode: forward value + VJP vs central differences
        check_grads(_layer_fn(layer), (x,), order=1, modes=("rev",),
                    atol=1e-3, rtol=1e-3)
