"""int8 post-training quantization (the reference's bigquant capability,
``spark/dl/pom.xml:85-90``): QuantizedLinear / QuantizedSpatialConvolution
numeric closeness to their float twins, quantize() tree walk, BTPU
round-trip, and int8 dtype discipline."""

import numpy as np
import pytest

import bigdl_tpu.nn as nn
from bigdl_tpu.nn.module import state_dict
from bigdl_tpu.nn.quantized import (QuantizedLinear,
                                    QuantizedSpatialConvolution, quantize)
from bigdl_tpu.utils.rng import RNG


def _rel_err(a, b):
    a, b = np.asarray(a, np.float64), np.asarray(b, np.float64)
    return np.abs(a - b).max() / (np.abs(b).max() + 1e-12)


def test_quantized_linear_close_to_float():
    RNG.set_seed(40)
    m = nn.Linear(64, 32)
    x = np.random.RandomState(0).randn(16, 64).astype(np.float32)
    want = np.asarray(m.evaluate().forward(x))
    q = QuantizedLinear.from_float(m)
    got = np.asarray(q.forward(x))
    # int8 symmetric quantization: ~1% relative error at these shapes
    assert _rel_err(got, want) < 0.02, _rel_err(got, want)
    assert np.asarray(q.weight_q).dtype == np.int8
    assert state_dict(q, kind="param") == {}  # inference-only


def test_quantized_conv_close_to_float():
    RNG.set_seed(41)
    m = nn.SpatialConvolution(8, 16, 3, 3, 2, 2, 1, 1)
    x = np.random.RandomState(1).randn(4, 8, 14, 14).astype(np.float32)
    want = np.asarray(m.evaluate().forward(x))
    q = QuantizedSpatialConvolution.from_float(m)
    got = np.asarray(q.forward(x))
    assert got.shape == want.shape
    assert _rel_err(got, want) < 0.03, _rel_err(got, want)


def test_quantized_grouped_and_same_pad_conv():
    RNG.set_seed(42)
    m = nn.SpatialConvolution(8, 16, 3, 3, 1, 1, -1, -1, n_group=4)
    x = np.random.RandomState(2).randn(2, 8, 10, 10).astype(np.float32)
    want = np.asarray(m.evaluate().forward(x))
    got = np.asarray(QuantizedSpatialConvolution.from_float(m).forward(x))
    assert got.shape == want.shape
    assert _rel_err(got, want) < 0.03


def test_quantize_walk_preserves_model_accuracy():
    """quantize(model) on a trained classifier: predictions match the
    float model on nearly every sample (the bigquant acceptance bar)."""
    import bigdl_tpu.optim as optim
    from bigdl_tpu.dataset.sample import Sample

    RNG.set_seed(43)
    rng = np.random.RandomState(3)
    x = rng.randn(128, 8).astype(np.float32)
    y = (x[:, 0] + x[:, 1] > 0).astype(np.int64)
    samples = [Sample(x[i], y[i]) for i in range(128)]
    model = nn.Sequential(nn.Linear(8, 32), nn.ReLU(),
                          nn.Linear(32, 2), nn.LogSoftMax())
    o = optim.LocalOptimizer(model, samples, nn.ClassNLLCriterion(),
                             batch_size=32,
                             end_trigger=optim.Trigger.max_epoch(10))
    o.set_optim_method(optim.SGD(learning_rate=0.5))
    o.optimize()
    float_pred = np.asarray(model.evaluate().forward(x)).argmax(1)

    qmodel = quantize(model)
    assert isinstance(qmodel.get(0), QuantizedLinear)
    assert isinstance(qmodel.get(2), QuantizedLinear)
    q_pred = np.asarray(qmodel.forward(x)).argmax(1)
    assert (q_pred == float_pred).mean() >= 0.98


def test_quantized_btpu_roundtrip(tmp_path):
    from bigdl_tpu.utils.serializer import load_module, save_module

    RNG.set_seed(44)
    model = quantize(nn.Sequential(
        nn.SpatialConvolution(3, 4, 3, 3, 1, 1, 1, 1),
        nn.ReLU(), nn.Reshape([4 * 6 * 6]), nn.Linear(4 * 6 * 6, 5)))
    x = np.random.RandomState(4).randn(2, 3, 6, 6).astype(np.float32)
    want = np.asarray(model.forward(x))
    path = str(tmp_path / "q.btpu")
    save_module(model, path)
    back = load_module(path)
    assert np.asarray(back.get(0).weight_q).dtype == np.int8
    np.testing.assert_allclose(np.asarray(back.evaluate().forward(x)),
                               want, rtol=1e-6, atol=1e-6)


def test_quantized_weight_memory_shrinks():
    RNG.set_seed(45)
    m = nn.Linear(256, 256)
    q = QuantizedLinear.from_float(m)
    fbytes = np.asarray(m.weight).nbytes
    qbytes = np.asarray(q.weight_q).nbytes + np.asarray(q.w_scale).nbytes
    assert qbytes < fbytes / 3.5  # ~4x smaller


def test_calibrated_scales_drop_the_amax_reduce():
    """BASELINE.md round-6 fix: after calibrate() the activation scale
    is a trace CONSTANT — the per-call global amax reduce (a full extra
    activation read and a fusion barrier) is gone from the program."""
    import jax

    from bigdl_tpu.nn.module import functional_call, state_dict
    from bigdl_tpu.nn.quantized import calibrate

    RNG.set_seed(50)
    x = np.random.RandomState(5).randn(4, 3, 12, 12).astype(np.float32)
    q = quantize(nn.Sequential(
        nn.SpatialConvolution(3, 8, 3, 3, 1, 1, 1, 1), nn.ReLU(),
        nn.SpatialConvolution(8, 16, 3, 3, 2, 2, 1, 1)))

    def jaxpr_of(model):
        state = state_dict(model)
        return str(jax.make_jaxpr(
            lambda s, xx: functional_call(model, s, xx,
                                          training=False)[0])(state, x))

    assert "reduce_max" in jaxpr_of(q)  # dynamic path: the barrier
    calibrate(q, [x])
    assert "reduce_max" not in jaxpr_of(q)
    for m in q.modules():
        if hasattr(m, "act_scale"):
            assert m.act_scale is not None and m.act_scale > 0


def test_calibrated_numerics_close_to_float_and_match_dynamic():
    from bigdl_tpu.nn.quantized import calibrate

    RNG.set_seed(51)
    m = nn.Sequential(nn.SpatialConvolution(3, 8, 3, 3, 1, 1, 1, 1),
                      nn.ReLU(), nn.Reshape([8 * 10 * 10]),
                      nn.Linear(8 * 10 * 10, 5))
    x = np.random.RandomState(6).randn(4, 3, 10, 10).astype(np.float32)
    want = np.asarray(m.evaluate().forward(x))
    q = quantize(m)
    dyn = np.asarray(q.forward(x))
    calibrate(q, [x])
    stat = np.asarray(q.forward(x))
    # calibrated on this very batch the scales agree exactly, so the
    # static path must reproduce the dynamic path bit-for-bit
    np.testing.assert_array_equal(stat, dyn)
    assert _rel_err(stat, want) < 0.03
    # traffic hotter than the calibration set clips instead of blowing
    # up (the documented saturation semantics)
    hot = np.asarray(q.forward(x * 10.0))
    assert np.isfinite(hot).all()


def test_calibrate_rejects_unquantized_and_empty():
    from bigdl_tpu.nn.quantized import calibrate

    RNG.set_seed(52)
    with pytest.raises(ValueError, match="no quantized"):
        calibrate(nn.Sequential(nn.Linear(4, 2)), [np.zeros((1, 4))])
    q = quantize(nn.Sequential(nn.Linear(4, 2)))
    with pytest.raises(ValueError, match="empty"):
        calibrate(q, [])


def test_calibrated_scale_survives_btpu_roundtrip(tmp_path):
    from bigdl_tpu.nn.quantized import calibrate
    from bigdl_tpu.utils.serializer import load_module, save_module

    RNG.set_seed(53)
    x = np.random.RandomState(7).randn(2, 8).astype(np.float32)
    q = calibrate(quantize(nn.Sequential(nn.Linear(8, 4))), [x])
    scale = q.get(0).act_scale
    path = str(tmp_path / "qc.btpu")
    save_module(q, path)
    back = load_module(path)
    assert back.get(0).act_scale == scale
    np.testing.assert_allclose(np.asarray(back.evaluate().forward(x)),
                               np.asarray(q.forward(x)), rtol=1e-6)


def test_int8_calibrated_inception_bytes_not_worse_than_bf16():
    """The serving-PR acceptance on the round-6 regression, verified by
    the attribution byte counts (XLA cost analysis of the lowered
    forward — CPU works, no TPU needed): calibrated int8 inception must
    move NO MORE bytes than the bf16 forward at equal flops.  The old
    dynamic path moved ~1.15x bf16 (measured: the per-conv amax reduce
    + quantize/dequant extra passes), which is exactly why int8 ran
    0.62x bf16 end-to-end on v5e."""
    import jax
    import jax.numpy as jnp

    from bigdl_tpu.models import registry
    from bigdl_tpu.nn.module import functional_call, state_dict
    from bigdl_tpu.nn.quantized import calibrate
    from bigdl_tpu.telemetry.device import normalize_cost_analysis

    x = np.random.RandomState(8).randn(2, 3, 224, 224).astype(np.float32)

    def fwd_bytes(model, cdt=None):
        state = state_dict(model)

        def fwd(s, xx):
            if cdt is not None:
                s = {k: (v.astype(cdt)
                         if jnp.issubdtype(v.dtype, jnp.floating) else v)
                     for k, v in s.items()}
                xx = xx.astype(cdt)
            return functional_call(model, s, xx, training=False)[0]

        compiled = jax.jit(fwd).lower(state, jnp.asarray(x)).compile()
        cost = normalize_cost_analysis(compiled.cost_analysis())
        return float(cost.get("bytes accessed") or 0)

    RNG.set_seed(54)
    bf16_bytes = fwd_bytes(registry.build_model("inception_v1").evaluate(),
                           jnp.bfloat16)
    RNG.set_seed(54)
    q = quantize(registry.build_model("inception_v1").evaluate())
    calibrate(q, [x])
    int8_bytes = fwd_bytes(q)
    assert bf16_bytes > 0 and int8_bytes > 0
    assert int8_bytes <= bf16_bytes, (
        f"calibrated int8 moves {int8_bytes / bf16_bytes:.3f}x the "
        f"bf16 bytes — the round-6 regression is back")


def test_quantize_subclass_dispatch(caplog):
    """isinstance-style dispatch (ADVICE r4): a math-identical subclass
    (SpatialShareConvolution) quantizes as its base; a subclass that
    overrides the forward math (the space-to-depth masked conv) is left
    float WITH a warning, never silently skipped or mis-converted."""
    import logging

    from bigdl_tpu.nn.fuse import _MaskedStride1Conv

    RNG.set_seed(0)
    share = nn.SpatialShareConvolution(3, 8, 3, 3)
    assert isinstance(quantize(share), QuantizedSpatialConvolution)

    RNG.set_seed(0)
    masked = _MaskedStride1Conv(3, 8, 3, 3)
    with caplog.at_level(logging.WARNING, logger="bigdl_tpu"):
        out = quantize(masked)
    assert out is masked  # unchanged
    assert any("overrides its forward math" in r.message
               for r in caplog.records)
