"""int8 post-training quantization (the reference's bigquant capability,
``spark/dl/pom.xml:85-90``): QuantizedLinear / QuantizedSpatialConvolution
numeric closeness to their float twins, quantize() tree walk, BTPU
round-trip, and int8 dtype discipline."""

import numpy as np
import pytest

import bigdl_tpu.nn as nn
from bigdl_tpu.nn.module import state_dict
from bigdl_tpu.nn.quantized import (QuantizedLinear,
                                    QuantizedSpatialConvolution, quantize)
from bigdl_tpu.utils.rng import RNG


def _rel_err(a, b):
    a, b = np.asarray(a, np.float64), np.asarray(b, np.float64)
    return np.abs(a - b).max() / (np.abs(b).max() + 1e-12)


def test_quantized_linear_close_to_float():
    RNG.set_seed(40)
    m = nn.Linear(64, 32)
    x = np.random.RandomState(0).randn(16, 64).astype(np.float32)
    want = np.asarray(m.evaluate().forward(x))
    q = QuantizedLinear.from_float(m)
    got = np.asarray(q.forward(x))
    # int8 symmetric quantization: ~1% relative error at these shapes
    assert _rel_err(got, want) < 0.02, _rel_err(got, want)
    assert np.asarray(q.weight_q).dtype == np.int8
    assert state_dict(q, kind="param") == {}  # inference-only


def test_quantized_conv_close_to_float():
    RNG.set_seed(41)
    m = nn.SpatialConvolution(8, 16, 3, 3, 2, 2, 1, 1)
    x = np.random.RandomState(1).randn(4, 8, 14, 14).astype(np.float32)
    want = np.asarray(m.evaluate().forward(x))
    q = QuantizedSpatialConvolution.from_float(m)
    got = np.asarray(q.forward(x))
    assert got.shape == want.shape
    assert _rel_err(got, want) < 0.03, _rel_err(got, want)


def test_quantized_grouped_and_same_pad_conv():
    RNG.set_seed(42)
    m = nn.SpatialConvolution(8, 16, 3, 3, 1, 1, -1, -1, n_group=4)
    x = np.random.RandomState(2).randn(2, 8, 10, 10).astype(np.float32)
    want = np.asarray(m.evaluate().forward(x))
    got = np.asarray(QuantizedSpatialConvolution.from_float(m).forward(x))
    assert got.shape == want.shape
    assert _rel_err(got, want) < 0.03


def test_quantize_walk_preserves_model_accuracy():
    """quantize(model) on a trained classifier: predictions match the
    float model on nearly every sample (the bigquant acceptance bar)."""
    import bigdl_tpu.optim as optim
    from bigdl_tpu.dataset.sample import Sample

    RNG.set_seed(43)
    rng = np.random.RandomState(3)
    x = rng.randn(128, 8).astype(np.float32)
    y = (x[:, 0] + x[:, 1] > 0).astype(np.int64)
    samples = [Sample(x[i], y[i]) for i in range(128)]
    model = nn.Sequential(nn.Linear(8, 32), nn.ReLU(),
                          nn.Linear(32, 2), nn.LogSoftMax())
    o = optim.LocalOptimizer(model, samples, nn.ClassNLLCriterion(),
                             batch_size=32,
                             end_trigger=optim.Trigger.max_epoch(10))
    o.set_optim_method(optim.SGD(learning_rate=0.5))
    o.optimize()
    float_pred = np.asarray(model.evaluate().forward(x)).argmax(1)

    qmodel = quantize(model)
    assert isinstance(qmodel.get(0), QuantizedLinear)
    assert isinstance(qmodel.get(2), QuantizedLinear)
    q_pred = np.asarray(qmodel.forward(x)).argmax(1)
    assert (q_pred == float_pred).mean() >= 0.98


def test_quantized_btpu_roundtrip(tmp_path):
    from bigdl_tpu.utils.serializer import load_module, save_module

    RNG.set_seed(44)
    model = quantize(nn.Sequential(
        nn.SpatialConvolution(3, 4, 3, 3, 1, 1, 1, 1),
        nn.ReLU(), nn.Reshape([4 * 6 * 6]), nn.Linear(4 * 6 * 6, 5)))
    x = np.random.RandomState(4).randn(2, 3, 6, 6).astype(np.float32)
    want = np.asarray(model.forward(x))
    path = str(tmp_path / "q.btpu")
    save_module(model, path)
    back = load_module(path)
    assert np.asarray(back.get(0).weight_q).dtype == np.int8
    np.testing.assert_allclose(np.asarray(back.evaluate().forward(x)),
                               want, rtol=1e-6, atol=1e-6)


def test_quantized_weight_memory_shrinks():
    RNG.set_seed(45)
    m = nn.Linear(256, 256)
    q = QuantizedLinear.from_float(m)
    fbytes = np.asarray(m.weight).nbytes
    qbytes = np.asarray(q.weight_q).nbytes + np.asarray(q.w_scale).nbytes
    assert qbytes < fbytes / 3.5  # ~4x smaller


def test_quantize_subclass_dispatch(caplog):
    """isinstance-style dispatch (ADVICE r4): a math-identical subclass
    (SpatialShareConvolution) quantizes as its base; a subclass that
    overrides the forward math (the space-to-depth masked conv) is left
    float WITH a warning, never silently skipped or mis-converted."""
    import logging

    from bigdl_tpu.nn.fuse import _MaskedStride1Conv

    RNG.set_seed(0)
    share = nn.SpatialShareConvolution(3, 8, 3, 3)
    assert isinstance(quantize(share), QuantizedSpatialConvolution)

    RNG.set_seed(0)
    masked = _MaskedStride1Conv(3, 8, 3, 3)
    with caplog.at_level(logging.WARNING, logger="bigdl_tpu"):
        out = quantize(masked)
    assert out is masked  # unchanged
    assert any("overrides its forward math" in r.message
               for r in caplog.records)
