"""Fleet aggregation + step-skew blame (telemetry/fleet.py, ISSUE 10).

Synthetic multi-log unit tests: the blame verdict must name the right
cause for crafted data-wait / comms / checkpoint / compute gaps (and
prefer attributable causes over the compute inflation every OTHER host
shows as collective wait); the live watcher must tail incrementally,
publish gauges + cluster/skew instants, and surface on /status and
/metrics; the multi-log --chrome export must produce one per-process
trace.  The live 2-process end-to-end rides tests/test_multihost.py."""

import json
import time
import urllib.request

import pytest

from bigdl_tpu import telemetry
from bigdl_tpu.telemetry import schema
from bigdl_tpu.telemetry.fleet import (FleetWatcher, HostState, blame,
                                       fleet_view, format_fleet_view)
from bigdl_tpu.utils.config import BigDLConfig, set_config


@pytest.fixture(autouse=True)
def _fresh_config():
    set_config(None)
    yield
    set_config(None)


def _write_host(path, pidx, steps=10, dur=0.1, data_wait=0.0,
                checkpoint=0.0, comms_s=None, t0=None, run_ts=None,
                pid_override=None):
    """Craft one host's run log: per-iteration spans shaped like the
    Optimizer's (iteration > data_wait [+ checkpoint]), step events with
    ``dur``, optional comms events with measured_s."""
    t0 = time.time() if t0 is None else t0
    with telemetry.run(str(path), meta={"process_index": pidx}):
        tr = telemetry.get()
        if comms_s is not None:
            tr.emit("comms", count=2, bytes=1 << 20,
                    payload_bytes=1 << 19, measured_s=comms_s)
        for i in range(1, steps + 1):
            it = tr.begin("train/iteration", step=i)
            dw = tr.begin("data_wait")
            tr.end(dw)
            # overwrite the measured span dur with the crafted value
            tr.emit("step", step=i, dur=dur, records=16,
                    throughput=16.0 / dur)
            if checkpoint:
                sid = tr.begin("checkpoint")
                tr.end(sid)
            tr.end(it)
    # post-process: JSONL is append-only text — rewrite the crafted
    # component durations directly (simpler than faking wall time)
    lines = []
    with open(path, "r", encoding="utf-8") as fh:
        for line in fh:
            ev = json.loads(line)
            if ev.get("kind") == "span_end":
                if ev["name"] == "data_wait":
                    ev["dur"] = data_wait
                elif ev["name"] == "checkpoint":
                    ev["dur"] = checkpoint
                elif ev["name"] == "train/iteration":
                    ev["dur"] = dur
            if run_ts is not None and ev.get("kind") == "run_start":
                ev["ts"] = run_ts
            if pid_override is not None:
                # crafted fleet logs come from ONE pytest process: give
                # each synthetic host its own OS-pid lane
                ev["pid"] = pid_override
            lines.append(json.dumps(ev))
    with open(path, "w", encoding="utf-8") as fh:
        fh.write("\n".join(lines) + "\n")


def _states(tmp_path, specs):
    """specs: {pidx: kwargs for _write_host}; returns HostStates."""
    states = []
    for pidx, kw in specs.items():
        path = tmp_path / f"run-x-p{pidx}-1.jsonl"
        _write_host(path, pidx, **kw)
        st = HostState(str(path))
        st.fold(schema.read_events(str(path))[0])
        states.append(st)
    return states


# -- blame picks the right cause ---------------------------------------------
def test_blame_data_wait_gap(tmp_path):
    """The injected-slow-input shape: the laggard's own data_wait is
    high, every OTHER host's step time is equally inflated (collective
    wait inside compute) — blame must land on the data-wait host, not
    on the hosts whose compute merely mirrors it."""
    states = _states(tmp_path, {
        0: dict(dur=0.30, data_wait=0.01),   # compute residual 0.29
        1: dict(dur=0.30, data_wait=0.25),   # the actual straggler
        2: dict(dur=0.30, data_wait=0.01),
    })
    v = blame(states)
    assert v is not None
    assert v["laggard"] == 1 and v["cause"] == "data_wait"
    assert v["excess_s"] == pytest.approx(0.24, abs=0.02)


def test_blame_comms_gap(tmp_path):
    states = _states(tmp_path, {
        0: dict(dur=0.10, comms_s=0.005),
        1: dict(dur=0.10, comms_s=0.06),
        2: dict(dur=0.10, comms_s=0.005),
    })
    v = blame(states)
    assert v["laggard"] == 1 and v["cause"] == "comms"


def test_blame_checkpoint_gap(tmp_path):
    states = _states(tmp_path, {
        0: dict(dur=0.40, checkpoint=0.3),
        1: dict(dur=0.40, checkpoint=0.01),
    })
    v = blame(states)
    assert v["laggard"] == 0 and v["cause"] == "checkpoint"


def test_blame_compute_fallback(tmp_path):
    """Nothing attributable: the genuinely-slow-compute host (thermal
    throttle shape) is named via the residual."""
    states = _states(tmp_path, {
        0: dict(dur=0.10, data_wait=0.01),
        1: dict(dur=0.35, data_wait=0.01),
    })
    v = blame(states)
    assert v["laggard"] == 1 and v["cause"] == "compute"


def test_blame_healthy_fleet_and_single_host(tmp_path):
    states = _states(tmp_path, {
        0: dict(dur=0.10, data_wait=0.01),
        1: dict(dur=0.10, data_wait=0.012),
    })
    assert blame(states) is None
    assert blame(states[:1]) is None


def test_blame_stalled_host(tmp_path):
    """A host that stopped stepping (crash/wedge) lags in completed
    steps with no per-step component gap — blamed as 'stalled'."""
    states = _states(tmp_path, {
        0: dict(dur=0.10, steps=12),
        1: dict(dur=0.10, steps=4),
    })
    v = blame(states)
    assert v["laggard"] == 1 and v["cause"] == "stalled"
    assert v["lag_steps"] == 8


# -- the one-shot view --------------------------------------------------------
def test_fleet_view_rows_and_format(tmp_path):
    states_dir = tmp_path
    for pidx, kw in {0: dict(dur=0.1, data_wait=0.08),
                     1: dict(dur=0.1, data_wait=0.01)}.items():
        _write_host(states_dir / f"run-a-p{pidx}-1.jsonl", pidx, **kw)
    loaded = [(str(p), schema.read_events(str(p))[0])
              for p in sorted(states_dir.glob("run-*.jsonl"))]
    view = fleet_view(loaded)
    assert set(view["hosts"]) == {"p0", "p1"}
    assert view["hosts"]["p0"]["data_wait_share"] > 0.5
    assert view["blame"]["laggard"] == 0
    text = format_fleet_view(view)
    assert "skew blame: p0 — data_wait" in text
    assert "data" in text and "comms" in text


def test_fleet_cli_one_shot_dir_and_json(tmp_path, capsys):
    from bigdl_tpu.telemetry import __main__ as cli

    for pidx in (0, 1):
        _write_host(tmp_path / f"run-b-p{pidx}-1.jsonl", pidx, dur=0.05)
    rc = cli.main(["fleet", str(tmp_path)])
    out = capsys.readouterr().out
    assert rc == 0 and "fleet view (2 processes)" in out
    rc = cli.main(["fleet", str(tmp_path), "--json"])
    doc = json.loads(capsys.readouterr().out)
    assert rc == 0 and set(doc["hosts"]) == {"p0", "p1"}
    # an empty dir is an error, not an empty table
    empty = tmp_path / "nothing"
    empty.mkdir()
    assert cli.main(["fleet", str(empty)]) == 2


# -- the live watcher ---------------------------------------------------------
def test_watcher_tails_incrementally_and_emits_skew(tmp_path):
    p0 = tmp_path / "run-c-p0-1.jsonl"
    p1 = tmp_path / "run-c-p1-1.jsonl"
    _write_host(p0, 0, steps=6, dur=0.2, data_wait=0.01)
    watcher = FleetWatcher(str(tmp_path), interval=60)  # manual polls
    sink = telemetry.MemorySink()
    with telemetry.run(sinks=[sink]):
        watcher.poll_once()
        snap = watcher.snapshot()
        assert set(snap["hosts"]) == {"p0"}
        assert snap["blame"] is None  # one host: nothing to compare
    # the second host appears AFTER the first poll — the tail picks
    # it up and the verdict fires on the next one
    _write_host(p1, 1, steps=6, dur=0.2, data_wait=0.15)
    with telemetry.run(sinks=[sink]):
        watcher.poll_once()
        snap = watcher.snapshot()
        assert set(snap["hosts"]) == {"p0", "p1"}
        assert snap["blame"]["laggard"] == 1
        assert snap["blame"]["cause"] == "data_wait"
    watcher.stop()
    skews = [e for e in sink.events
             if e.get("kind") == "event" and e.get("name") == "cluster/skew"]
    assert skews and skews[-1]["laggard"] == 1
    assert skews[-1]["cause"] == "data_wait"
    assert schema.validate_events(sink.events) == []
    gauges = {e["name"] for e in sink.events if e.get("kind") == "gauge"}
    assert "fleet/lag_steps" in gauges and "fleet/skew_s" in gauges
    # same verdict inside the cooldown: no instant spam
    with telemetry.run(sinks=[sink]):
        n = len(skews)
        watcher2 = FleetWatcher(str(tmp_path), interval=60)
        watcher2.poll_once()
        watcher2.poll_once()
        watcher2.stop()
    skews2 = [e for e in sink.events
              if e.get("kind") == "event"
              and e.get("name") == "cluster/skew"]
    assert len(skews2) == n + 1  # one per fresh watcher verdict


def test_watcher_starts_on_coordinator_of_multiprocess_run(tmp_path):
    """start_run wires the watcher only for process 0 of a multi-process
    run, and /status + /metrics carry the fleet block while it lives."""
    set_config(BigDLConfig(metrics_port=0, fleet_interval=0.2))
    # a peer's log already in the dir
    _write_host(tmp_path / "run-d-p1-9.jsonl", 1, steps=4, dur=0.05)
    telemetry.start_run(str(tmp_path),
                        meta={"process_index": 0, "process_count": 2})
    try:
        assert telemetry.fleet_watcher() is not None
        tr = telemetry.get()
        for i in range(1, 5):
            tr.emit("step", step=i, dur=0.05, records=8)
        telemetry.fleet_watcher().poll_once()
        port = telemetry.metrics_server().port
        st = json.load(urllib.request.urlopen(
            f"http://127.0.0.1:{port}/status", timeout=5))
        fleet = st.get("fleet") or {}
        assert fleet.get("dir") == str(tmp_path)
        assert "p1" in (fleet.get("hosts") or {})
        body = urllib.request.urlopen(
            f"http://127.0.0.1:{port}/metrics", timeout=5).read().decode()
        assert "bigdl_fleet_hosts" in body
        assert 'bigdl_fleet_last_step{process_index="1"}' in body
        assert body.rstrip().endswith("# EOF")
    finally:
        telemetry.end_run()
    assert telemetry.fleet_watcher() is None


def test_watcher_not_started_for_single_process_or_non_coordinator(
        tmp_path):
    set_config(BigDLConfig(fleet_interval=0.2))
    with telemetry.run(str(tmp_path), meta={"process_index": 0,
                                            "process_count": 1}):
        assert telemetry.fleet_watcher() is None
    with telemetry.run(str(tmp_path), meta={"process_index": 1,
                                            "process_count": 2}):
        assert telemetry.fleet_watcher() is None
    set_config(BigDLConfig(fleet_interval=0.0))
    with telemetry.run(str(tmp_path), meta={"process_index": 0,
                                            "process_count": 2}):
        assert telemetry.fleet_watcher() is None


def test_watcher_dedupes_reincarnation_logs(tmp_path):
    """Two logs for one rank (supervisor restart): the snapshot keeps
    the newest incarnation only."""
    _write_host(tmp_path / "run-old-p0-1.jsonl", 0, steps=3, dur=0.05,
                run_ts=1000.0)
    _write_host(tmp_path / "run-new-p0-2.jsonl", 0, steps=7, dur=0.05,
                run_ts=2000.0)
    watcher = FleetWatcher(str(tmp_path), interval=60)
    watcher.poll_once()
    snap = watcher.snapshot()
    assert len(snap["hosts"]) == 1
    assert snap["hosts"]["p0"]["last_step"] == 7
    assert snap["hosts"]["p0"]["path"].endswith("run-new-p0-2.jsonl")
    watcher.stop()


# -- multi-log chrome export --------------------------------------------------
def test_multi_log_chrome_export_has_process_lanes(tmp_path, capsys):
    from bigdl_tpu.telemetry import __main__ as cli

    paths = []
    for pidx in (0, 1):
        p = tmp_path / f"run-e-p{pidx}-1.jsonl"
        _write_host(p, pidx, steps=3, dur=0.02,
                    pid_override=1000 + pidx)
        paths.append(str(p))
    out_path = tmp_path / "fleet_trace.json"
    rc = cli.main(paths + ["--chrome", str(out_path)])
    out = capsys.readouterr().out
    assert rc == 0
    assert "fleet view (2 processes)" in out
    assert "2 process lanes" in out
    doc = json.loads(out_path.read_text())
    metas = [e for e in doc["traceEvents"]
             if e.get("ph") == "M" and e.get("name") == "process_name"]
    labels = {e["args"]["name"] for e in metas}
    assert any(lbl.startswith("p0 ") for lbl in labels), labels
    assert any(lbl.startswith("p1 ") for lbl in labels), labels
    # both processes' step events landed in one trace
    steps = [e for e in doc["traceEvents"] if e.get("cat") == "step"]
    assert len({e["pid"] for e in steps}) <= 2 and steps


def test_cluster_watchdog_flight_dump_carries_fleet_snapshot(tmp_path):
    """The PR-7 watchdog's peer-lost dump includes the live fleet table
    when a watcher is running — the 'who was dragging before the loss'
    evidence."""
    from bigdl_tpu.parallel.cluster import ClusterMonitor

    set_config(BigDLConfig(metrics_port=None, fleet_interval=0.2,
                           telemetry_dir=str(tmp_path)))
    _write_host(tmp_path / "run-f-p1-3.jsonl", 1, steps=3, dur=0.05)
    telemetry.start_run(str(tmp_path),
                        meta={"process_index": 0, "process_count": 2})
    try:
        telemetry.fleet_watcher().poll_once()
        mon = ClusterMonitor(str(tmp_path / "hb"), 0, 2, deadline=1.0,
                             abort=False)
        mon._lost[1] = "test: peer gone"
        mon._fire()
        recorder = telemetry.flight_recorder()
        assert recorder is not None
        dump_path = recorder.last_dump_path
        assert dump_path, "no flight dump written"
        doc = json.loads(open(dump_path).read())
        assert "fleet" in doc.get("evidence", {}), doc.get("evidence")
        assert "p1" in doc["evidence"]["fleet"]["hosts"]
    finally:
        telemetry.end_run()


# -- elastic resharding: departed hosts are not "stalled" ---------------------
def _append_reshard(path, to_n, declared_n, ts):
    """Hand-append a cluster/reshard instant (the workers of the new,
    smaller incarnation emit it from the restore path)."""
    ev = {"v": 1, "ts": ts, "pid": 1, "tid": 0, "kind": "event",
          "name": "cluster/reshard", "source": "restore",
          "from_processes": declared_n, "to_processes": to_n,
          "declared_n": declared_n}
    with open(path, "a", encoding="utf-8") as fh:
        fh.write(json.dumps(ev) + "\n")


def test_departed_hosts_not_blamed_after_reshard(tmp_path):
    """ISSUE 12 satellite: hosts absent because the cluster
    LEGITIMATELY shrank (a cluster/reshard instant says so) must not be
    blamed ``stalled`` forever — they fold into the table as departed,
    drop out of lag/blame, and the view carries current/declared
    width."""
    now = time.time()
    # p2/p3 stopped at step 4 (the old width-4 incarnation); p0/p1
    # continued to step 12 after the reshard to width 2
    for pidx in (2, 3):
        _write_host(tmp_path / f"run-a-p{pidx}-1.jsonl", pidx, steps=4,
                    dur=0.1, pid_override=10 + pidx)
    for pidx in (0, 1):
        _write_host(tmp_path / f"run-b-p{pidx}-1.jsonl", pidx, steps=12,
                    dur=0.1, pid_override=10 + pidx)
    loaded = [(str(p), schema.read_events(str(p))[0])
              for p in sorted(tmp_path.glob("run-*.jsonl"))]
    # control: WITHOUT the reshard instant the shrink looks like a
    # stall and p2/p3 take the blame
    view = fleet_view(loaded)
    assert view["blame"] is not None
    assert view["blame"]["cause"] == "stalled"
    assert view["blame"]["laggard"] in (2, 3)
    assert view["width"] is None

    _append_reshard(tmp_path / "run-b-p0-1.jsonl", to_n=2, declared_n=4,
                    ts=now + 3600)
    loaded = [(str(p), schema.read_events(str(p))[0])
              for p in sorted(tmp_path.glob("run-*.jsonl"))]
    view = fleet_view(loaded)
    assert view["width"] == {"current": 2, "declared": 4,
                             "ts": now + 3600, "source": "restore"}
    assert view["hosts"]["p2"]["departed"] and view["hosts"]["p3"]["departed"]
    assert not view["hosts"]["p0"]["departed"]
    # the survivors are in lock-step: no verdict, no residual lag
    assert view["blame"] is None
    assert view["step_lag"] == 0
    assert any("departed legitimately" in n for n in view["notes"])
    text = format_fleet_view(view)
    assert "DEPARTED" in text
    assert "width: 2/4 declared  (DEGRADED — cluster resharded)" in text
    # a host OUTSIDE the width that keeps stepping is alive, not hidden:
    # blame can still see it
    late = tmp_path / "run-c-p2-1.jsonl"
    _write_host(late, 2, steps=20, dur=0.1, pid_override=99)
    lines = []
    for line in late.read_text().splitlines():
        ev = json.loads(line)
        ev["ts"] = float(ev.get("ts", now)) + 7200  # after the reshard
        lines.append(json.dumps(ev))
    late.write_text("\n".join(lines) + "\n")
    loaded = [(str(p), schema.read_events(str(p))[0])
              for p in sorted(tmp_path.glob("run-*.jsonl"))]
    view = fleet_view(loaded)
    assert not view["hosts"]["p2"]["departed"]


def test_watcher_snapshot_carries_width_and_departed(tmp_path):
    now = time.time()
    for pidx in (2, 3):
        _write_host(tmp_path / f"run-a-p{pidx}-1.jsonl", pidx, steps=4,
                    dur=0.1, pid_override=10 + pidx)
    for pidx in (0, 1):
        _write_host(tmp_path / f"run-b-p{pidx}-1.jsonl", pidx, steps=12,
                    dur=0.1, pid_override=10 + pidx)
    _append_reshard(tmp_path / "run-b-p0-1.jsonl", to_n=2, declared_n=4,
                    ts=now + 3600)
    watcher = FleetWatcher(str(tmp_path), interval=60)
    watcher.poll_once()
    snap = watcher.snapshot()
    assert snap["width"]["current"] == 2 and snap["width"]["declared"] == 4
    assert snap["hosts"]["p3"]["departed"]
    assert snap["lag_steps"] == 0
    assert snap["blame"] is None
