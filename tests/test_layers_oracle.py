"""Per-layer oracle tests against torch (CPU) — the analogue of the
reference's Lua-Torch subprocess oracle suite (``torch/TH.scala``,
SURVEY §4): same inputs, compare outputs and input-gradients."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
import torch
import torch.nn.functional as F

import bigdl_tpu.nn as nn


def _cmp(ours, theirs, rtol=1e-4, atol=1e-5):
    np.testing.assert_allclose(np.asarray(ours), np.asarray(theirs), rtol=rtol, atol=atol)


def _grad_cmp(layer, x_np, torch_fn, rtol=1e-4, atol=1e-5):
    """Compare d(sum(out))/d(input)."""
    x = jnp.asarray(x_np)
    gi = layer.backward(x, jnp.ones_like(layer.forward(x)))
    tx = torch.tensor(x_np, requires_grad=True)
    torch_fn(tx).sum().backward()
    _cmp(gi, tx.grad.numpy(), rtol, atol)


# ----------------------------- activations -------------------------------

ACT_CASES = [
    (nn.ReLU(), torch.relu),
    (nn.ReLU6(), lambda x: F.relu6(x)),
    (nn.Tanh(), torch.tanh),
    (nn.Sigmoid(), torch.sigmoid),
    (nn.ELU(), F.elu),
    (nn.LeakyReLU(0.02), lambda x: F.leaky_relu(x, 0.02)),
    (nn.SoftPlus(), F.softplus),
    (nn.SoftPlus(2.0), lambda x: F.softplus(x, beta=2.0)),
    (nn.LogSigmoid(), F.logsigmoid),
    (nn.TanhShrink(), F.tanhshrink),
    (nn.SoftSign(), F.softsign),
    (nn.SoftShrink(0.4), lambda x: F.softshrink(x, 0.4)),
    (nn.HardShrink(0.4), lambda x: F.hardshrink(x, 0.4)),
    (nn.HardTanh(-2.0, 2.0), lambda x: F.hardtanh(x, -2.0, 2.0)),
    (nn.SoftMax(), lambda x: F.softmax(x, dim=1)),
    (nn.LogSoftMax(), lambda x: F.log_softmax(x, dim=1)),
    (nn.SoftMin(), lambda x: F.softmin(x, dim=1)),
]


@pytest.mark.parametrize("case", ACT_CASES, ids=lambda c: type(c[0]).__name__ + str(id(c))[-3:])
def test_activation_forward_backward(case):
    layer, ref = case
    x = np.random.randn(4, 7).astype(np.float32)
    _cmp(layer.forward(jnp.asarray(x)), ref(torch.tensor(x)).numpy())
    _grad_cmp(layer, x, ref)


def test_prelu():
    layer = nn.PReLU(5)
    x = np.random.randn(3, 5, 4).astype(np.float32)
    ref = F.prelu(torch.tensor(x), torch.tensor(np.asarray(layer.weight)))
    _cmp(layer.forward(jnp.asarray(x)), ref.numpy())


# ----------------------------- convolutions ------------------------------

def test_spatial_convolution_matches_torch():
    layer = nn.SpatialConvolution(3, 8, 3, 3, 2, 2, 1, 1)
    x = np.random.randn(2, 3, 9, 9).astype(np.float32)
    ref = F.conv2d(torch.tensor(x), torch.tensor(np.asarray(layer.weight)),
                   torch.tensor(np.asarray(layer.bias)), stride=2, padding=1)
    _cmp(layer.forward(jnp.asarray(x)), ref.numpy(), rtol=1e-3, atol=1e-4)


def test_spatial_convolution_groups_nhwc():
    layer = nn.SpatialConvolution(4, 8, 3, 3, 1, 1, 0, 0, n_group=2, format="NHWC")
    x = np.random.randn(2, 7, 7, 4).astype(np.float32)
    ref = F.conv2d(torch.tensor(x.transpose(0, 3, 1, 2)),
                   torch.tensor(np.asarray(layer.weight)),
                   torch.tensor(np.asarray(layer.bias)), groups=2)
    out = layer.forward(jnp.asarray(x))
    _cmp(np.asarray(out).transpose(0, 3, 1, 2), ref.numpy(), rtol=1e-3, atol=1e-4)


def test_conv_grads_match_torch():
    layer = nn.SpatialConvolution(2, 4, 3, 3)
    x = np.random.randn(1, 2, 6, 6).astype(np.float32)
    layer.zero_grad_parameters()
    out = layer.forward(jnp.asarray(x))
    layer.backward(jnp.asarray(x), jnp.ones_like(out))
    tw = torch.tensor(np.asarray(layer.weight), requires_grad=True)
    tb = torch.tensor(np.asarray(layer.bias), requires_grad=True)
    tx = torch.tensor(x, requires_grad=True)
    F.conv2d(tx, tw, tb).sum().backward()
    _cmp(layer._grads["weight"], tw.grad.numpy(), rtol=1e-3, atol=1e-4)
    _cmp(layer._grads["bias"], tb.grad.numpy(), rtol=1e-3, atol=1e-4)
    _cmp(layer.grad_input, tx.grad.numpy(), rtol=1e-3, atol=1e-4)


def test_full_convolution_matches_torch():
    layer = nn.SpatialFullConvolution(4, 6, 3, 3, 2, 2, 1, 1, 1, 1)
    x = np.random.randn(2, 4, 5, 5).astype(np.float32)
    ref = F.conv_transpose2d(torch.tensor(x), torch.tensor(np.asarray(layer.weight)),
                             torch.tensor(np.asarray(layer.bias)),
                             stride=2, padding=1, output_padding=1)
    _cmp(layer.forward(jnp.asarray(x)), ref.numpy(), rtol=1e-3, atol=1e-4)


def test_dilated_convolution_matches_torch():
    layer = nn.SpatialDilatedConvolution(3, 5, 3, 3, 1, 1, 2, 2, 2, 2)
    x = np.random.randn(1, 3, 9, 9).astype(np.float32)
    ref = F.conv2d(torch.tensor(x), torch.tensor(np.asarray(layer.weight)),
                   torch.tensor(np.asarray(layer.bias)), padding=2, dilation=2)
    _cmp(layer.forward(jnp.asarray(x)), ref.numpy(), rtol=1e-3, atol=1e-4)


def test_temporal_convolution_matches_torch():
    layer = nn.TemporalConvolution(6, 4, 3, 2)
    x = np.random.randn(2, 11, 6).astype(np.float32)
    # torch conv1d is NCW with weight (out, in, k)
    ref = F.conv1d(torch.tensor(x.transpose(0, 2, 1)),
                   torch.tensor(np.asarray(layer.weight)),
                   torch.tensor(np.asarray(layer.bias)), stride=2)
    _cmp(np.asarray(layer.forward(jnp.asarray(x))).transpose(0, 2, 1),
         ref.numpy(), rtol=1e-3, atol=1e-4)


def test_volumetric_convolution_matches_torch():
    layer = nn.VolumetricConvolution(2, 4, 3, 3, 3, 1, 1, 1, 1, 1, 1)
    x = np.random.randn(1, 2, 5, 5, 5).astype(np.float32)
    ref = F.conv3d(torch.tensor(x), torch.tensor(np.asarray(layer.weight)),
                   torch.tensor(np.asarray(layer.bias)), padding=1)
    _cmp(layer.forward(jnp.asarray(x)), ref.numpy(), rtol=1e-3, atol=1e-4)


# ----------------------------- pooling -----------------------------------

def test_max_pooling_matches_torch():
    layer = nn.SpatialMaxPooling(3, 3, 2, 2, 1, 1)
    x = np.random.randn(2, 3, 9, 9).astype(np.float32)
    ref = F.max_pool2d(torch.tensor(x), 3, 2, 1)
    _cmp(layer.forward(jnp.asarray(x)), ref.numpy())


def test_max_pooling_ceil_mode():
    layer = nn.SpatialMaxPooling(3, 3, 2, 2).ceil()
    x = np.random.randn(1, 2, 8, 8).astype(np.float32)
    ref = F.max_pool2d(torch.tensor(x), 3, 2, 0, ceil_mode=True)
    _cmp(layer.forward(jnp.asarray(x)), ref.numpy())


def test_avg_pooling_matches_torch():
    layer = nn.SpatialAveragePooling(2, 2, 2, 2)
    x = np.random.randn(2, 3, 8, 8).astype(np.float32)
    ref = F.avg_pool2d(torch.tensor(x), 2, 2)
    _cmp(layer.forward(jnp.asarray(x)), ref.numpy())


def test_avg_pooling_pad_count_exclude():
    layer = nn.SpatialAveragePooling(3, 3, 2, 2, 1, 1, count_include_pad=False)
    x = np.random.randn(1, 1, 7, 7).astype(np.float32)
    ref = F.avg_pool2d(torch.tensor(x), 3, 2, 1, count_include_pad=False)
    _cmp(layer.forward(jnp.asarray(x)), ref.numpy())


def test_volumetric_max_pooling():
    layer = nn.VolumetricMaxPooling(2, 2, 2)
    x = np.random.randn(1, 2, 4, 4, 4).astype(np.float32)
    ref = F.max_pool3d(torch.tensor(x), 2, 2)
    _cmp(layer.forward(jnp.asarray(x)), ref.numpy())


# ----------------------------- normalization ------------------------------

def test_batchnorm_train_and_eval_match_torch():
    layer = nn.BatchNormalization(5, eps=1e-5, momentum=0.1)
    tbn = torch.nn.BatchNorm1d(5, eps=1e-5, momentum=0.1)
    x = np.random.randn(8, 5).astype(np.float32)
    out = layer.forward(jnp.asarray(x))
    ref = tbn(torch.tensor(x))
    _cmp(out, ref.detach().numpy(), rtol=1e-3, atol=1e-4)
    _cmp(layer.running_mean, tbn.running_mean.numpy(), rtol=1e-3, atol=1e-5)
    _cmp(layer.running_var, tbn.running_var.numpy(), rtol=1e-3, atol=1e-5)
    layer.evaluate(); tbn.eval()
    x2 = np.random.randn(4, 5).astype(np.float32)
    _cmp(layer.forward(jnp.asarray(x2)), tbn(torch.tensor(x2)).detach().numpy(),
         rtol=1e-3, atol=1e-4)


def test_spatial_batchnorm_matches_torch():
    layer = nn.SpatialBatchNormalization(3)
    tbn = torch.nn.BatchNorm2d(3)
    x = np.random.randn(4, 3, 5, 5).astype(np.float32)
    _cmp(layer.forward(jnp.asarray(x)), tbn(torch.tensor(x)).detach().numpy(),
         rtol=1e-3, atol=1e-4)


def test_cross_map_lrn_matches_torch():
    layer = nn.SpatialCrossMapLRN(5, 0.0001, 0.75, 1.0)
    x = np.random.randn(2, 7, 4, 4).astype(np.float32)
    ref = torch.nn.LocalResponseNorm(5, 0.0001, 0.75, 1.0)(torch.tensor(x))
    _cmp(layer.forward(jnp.asarray(x)), ref.numpy(), rtol=1e-4, atol=1e-5)


def test_max_pooling_backward_matches_torch():
    """The opt-in tie-split VJP (residue-class gather backward) must agree
    with the torch oracle on continuous inputs (ties have measure zero)."""
    for kw, kh, dw, dh, pw, ph, ceil in [(3, 3, 2, 2, 1, 1, False),
                                         (3, 3, 1, 1, 1, 1, False),
                                         (3, 3, 2, 2, 0, 0, True),
                                         (2, 2, 2, 2, 0, 0, False)]:
        layer = nn.SpatialMaxPooling(kw, kh, dw, dh, pw, ph).split_ties()
        if ceil:
            layer.ceil()
        assert layer.tie_split
        x_np = np.random.randn(2, 3, 9, 9).astype(np.float32)
        _grad_cmp(layer, x_np,
                  lambda t: F.max_pool2d(t, (kh, kw), (dh, dw), (ph, pw),
                                         ceil_mode=ceil))


def test_max_pooling_tie_split_conserves_gradient():
    """With ties, split_ties() divides the cotangent equally among maxima
    — total gradient mass equals the torch first-argmax convention."""
    layer = nn.SpatialMaxPooling(2, 2, 2, 2).split_ties()
    x = jnp.ones((1, 1, 4, 4), jnp.float32)  # every window fully tied
    g = layer.backward(x, jnp.ones((1, 1, 2, 2), jnp.float32))
    assert float(jnp.sum(g)) == pytest.approx(4.0)
    np.testing.assert_allclose(np.asarray(g), 0.25 * np.ones((1, 1, 4, 4)))


def test_max_pooling_torch_ties_path():
    layer = nn.SpatialMaxPooling(3, 3, 2, 2, 1, 1).torch_ties()
    x_np = np.random.randn(2, 3, 9, 9).astype(np.float32)
    _grad_cmp(layer, x_np, lambda t: F.max_pool2d(t, 3, 2, 1))


def test_cross_map_lrn_backward_and_variants():
    """The banded-matmul LRN (MXU path): backward vs torch, NHWC layout,
    and the generic-beta fallback."""
    x_np = np.random.randn(2, 7, 4, 4).astype(np.float32)
    _grad_cmp(nn.SpatialCrossMapLRN(5, 0.0001, 0.75, 1.0), x_np,
              lambda t: torch.nn.LocalResponseNorm(5, 0.0001, 0.75, 1.0)(t))
    # beta != 0.75 exercises the jnp.power fallback
    _grad_cmp(nn.SpatialCrossMapLRN(3, 0.001, 0.5, 2.0), x_np,
              lambda t: torch.nn.LocalResponseNorm(3, 0.001, 0.5, 2.0)(t))
    # NHWC agrees with NCHW
    lrn_c = nn.SpatialCrossMapLRN(5, 0.0001, 0.75, 1.0)
    lrn_l = nn.SpatialCrossMapLRN(5, 0.0001, 0.75, 1.0, format="NHWC")
    out_c = lrn_c.forward(jnp.asarray(x_np))
    out_l = lrn_l.forward(jnp.asarray(x_np.transpose(0, 2, 3, 1)))
    _cmp(jnp.transpose(out_l, (0, 3, 1, 2)), out_c)


def test_dropout_keeps_expectation():
    layer = nn.Dropout(0.4)
    x = jnp.ones((1000, 20))
    out = layer.forward(x)
    kept = np.asarray(out) != 0
    assert abs(kept.mean() - 0.6) < 0.05
    np.testing.assert_allclose(np.asarray(out)[kept], 1.0 / 0.6, rtol=1e-5)
    layer.evaluate()
    np.testing.assert_array_equal(np.asarray(layer.forward(x)), np.asarray(x))


def test_normalize_matches_torch():
    layer = nn.Normalize(2.0)
    x = np.random.randn(4, 6).astype(np.float32)
    _cmp(layer.forward(jnp.asarray(x)), F.normalize(torch.tensor(x), 2.0).numpy())


# ----------------------------- rnn ----------------------------------------

def sync_lstm_to_torch(cell, tl):
    """Copy our packed (i,f,g,o) LSTM cell weights into a torch LSTM —
    the ONE copy of the gate-packing contract shared by the fixed
    oracles and the shape fuzz."""
    with torch.no_grad():
        tl.weight_ih_l0.copy_(torch.tensor(np.asarray(cell.i2g.weight)))
        tl.bias_ih_l0.copy_(torch.tensor(np.asarray(cell.i2g.bias)))
        tl.weight_hh_l0.copy_(torch.tensor(np.asarray(cell.h2g.weight)))
        tl.bias_hh_l0.zero_()


def sync_gru_to_torch(cell, tg):
    """Copy our (r,z | n) GRU cell weights into a torch GRU."""
    with torch.no_grad():
        tg.weight_ih_l0.copy_(torch.tensor(np.asarray(cell.i2g.weight)))
        tg.bias_ih_l0.copy_(torch.tensor(np.asarray(cell.i2g.bias)))
        w_hh = np.concatenate([np.asarray(cell.h2rz.weight),
                               np.asarray(cell.h2n.weight)])
        tg.weight_hh_l0.copy_(torch.tensor(w_hh))
        tg.bias_hh_l0.zero_()



def test_lstm_matches_torch():
    hidden, inp = 7, 5
    cell = nn.LSTM(inp, hidden)
    rec = nn.Recurrent(cell)
    x = np.random.randn(3, 6, inp).astype(np.float32)

    tl = torch.nn.LSTM(inp, hidden, batch_first=True)
    sync_lstm_to_torch(cell, tl)
    out = rec.forward(jnp.asarray(x))
    ref, _ = tl(torch.tensor(x))
    _cmp(out, ref.detach().numpy(), rtol=1e-3, atol=1e-4)


def test_lstm_backward_matches_torch():
    """Input gradients THROUGH the lax.scan time loop vs torch's
    unrolled backward."""
    hidden, inp = 7, 5
    cell = nn.LSTM(inp, hidden)
    rec = nn.Recurrent(cell)
    x_np = np.random.randn(3, 6, inp).astype(np.float32)
    gy = np.random.randn(3, 6, hidden).astype(np.float32)

    tl = torch.nn.LSTM(inp, hidden, batch_first=True)
    sync_lstm_to_torch(cell, tl)
    gx = rec.backward(jnp.asarray(x_np), jnp.asarray(gy))
    tx = torch.tensor(x_np, requires_grad=True)
    out, _ = tl(tx)
    out.backward(torch.tensor(gy))
    _cmp(gx, tx.grad.numpy(), rtol=1e-3, atol=1e-4)


def test_gru_matches_torch():
    hidden, inp = 4, 3
    cell = nn.GRU(inp, hidden)
    rec = nn.Recurrent(cell)
    x = np.random.randn(2, 5, inp).astype(np.float32)
    tg = torch.nn.GRU(inp, hidden, batch_first=True)
    sync_gru_to_torch(cell, tg)
    out = rec.forward(jnp.asarray(x))
    ref, _ = tg(torch.tensor(x))
    _cmp(out, ref.detach().numpy(), rtol=1e-3, atol=1e-4)


def test_gru_backward_matches_torch():
    hidden, inp = 4, 3
    cell = nn.GRU(inp, hidden)
    rec = nn.Recurrent(cell)
    x_np = np.random.randn(2, 5, inp).astype(np.float32)
    gy = np.random.randn(2, 5, hidden).astype(np.float32)
    tg = torch.nn.GRU(inp, hidden, batch_first=True)
    sync_gru_to_torch(cell, tg)
    gx = rec.backward(jnp.asarray(x_np), jnp.asarray(gy))
    tx = torch.tensor(x_np, requires_grad=True)
    out, _ = tg(tx)
    out.backward(torch.tensor(gy))
    _cmp(gx, tx.grad.numpy(), rtol=1e-3, atol=1e-4)


def test_rnn_cell_and_birecurrent_shapes():
    rec = nn.Recurrent(nn.RnnCell(4, 6))
    x = jnp.asarray(np.random.randn(2, 5, 4).astype(np.float32))
    assert rec.forward(x).shape == (2, 5, 6)
    bi = nn.BiRecurrent().with_cell(nn.LSTM(4, 6))
    assert bi.forward(x).shape == (2, 5, 12)


def test_recurrent_decoder_shape():
    dec = nn.RecurrentDecoder(4, nn.LSTM(5, 5))
    x = jnp.asarray(np.random.randn(2, 5).astype(np.float32))
    assert dec.forward(x).shape == (2, 4, 5)


def test_recurrent_under_jit_and_grad():
    from bigdl_tpu.nn.module import functional_call, state_dict

    rec = nn.Recurrent(nn.LSTM(3, 4))
    x = jnp.asarray(np.random.randn(2, 5, 3).astype(np.float32))
    p = state_dict(rec)

    @jax.jit
    def loss(p):
        out, _ = functional_call(rec, p, x)
        return jnp.sum(out ** 2)

    g = jax.grad(loss)(p)
    assert g["0.i2g.weight"].shape == (16, 3)
    assert float(loss(p)) > 0


# ----------------------------- graph / containers -------------------------

def test_graph_dag_forward_backward():
    inp = nn.Input()
    fc1 = nn.Linear(4, 8).set_name("fc1").inputs(inp)
    act = nn.ReLU().inputs(fc1)
    fc2 = nn.Linear(8, 2).set_name("fc2").inputs(act)
    model = nn.Graph(inp, fc2)
    x = jnp.ones((3, 4))
    out = model.forward(x)
    assert out.shape == (3, 2)
    seq = nn.Sequential(model["fc1"], nn.ReLU(), model["fc2"])
    _cmp(out, seq.forward(x))
    model.zero_grad_parameters()
    model.backward(x, jnp.ones((3, 2)))
    assert "weight" in model["fc1"]._grads


def test_graph_multi_input_output():
    a, b = nn.Input(), nn.Input()
    s = nn.CAddTable().inputs(a, b)
    m = nn.CMulTable().inputs(a, b)
    model = nn.Graph([a, b], [s, m])
    x, y = jnp.ones((2, 3)), jnp.full((2, 3), 2.0)
    out_s, out_m = model.forward([x, y])
    _cmp(out_s, np.full((2, 3), 3.0))
    _cmp(out_m, np.full((2, 3), 2.0))


def test_graph_stop_gradient():
    inp = nn.Input()
    fc1 = nn.Linear(3, 3).set_name("fc1").inputs(inp)
    fc2 = nn.Linear(3, 2).set_name("fc2").inputs(fc1)
    model = nn.Graph(inp, fc2).stop_gradient(["fc1"])
    x = jnp.ones((2, 3))
    model.zero_grad_parameters()
    model.forward(x)
    model.backward(x, jnp.ones((2, 2)))
    assert "weight" not in model["fc1"]._grads or \
        np.allclose(np.asarray(model["fc1"]._grads["weight"]), 0.0)
    assert "weight" in model["fc2"]._grads


def test_concat_and_table_containers():
    c = nn.Concat(1).add(nn.Linear(4, 3)).add(nn.Linear(4, 5))
    x = jnp.ones((2, 4))
    assert c.forward(x).shape == (2, 8)
    ct = nn.ConcatTable().add(nn.Identity()).add(nn.MulConstant(2.0))
    out = ct.forward(x)
    _cmp(out[1], 2 * np.asarray(out[0]))
    pt = nn.ParallelTable().add(nn.MulConstant(2.0)).add(nn.MulConstant(3.0))
    out = pt.forward([x, x])
    _cmp(out[0] * 1.5, out[1])


def test_shape_layers():
    x = jnp.asarray(np.arange(24, dtype=np.float32).reshape(2, 3, 4))
    assert nn.Reshape((12,)).forward(x).shape == (2, 12)
    assert nn.Transpose([(1, 2)]).forward(x).shape == (2, 4, 3)
    assert nn.Select(1, 0).forward(x).shape == (2, 4)
    assert nn.Narrow(2, 1, 2).forward(x).shape == (2, 3, 2)
    assert nn.Squeeze().forward(jnp.ones((2, 1, 3))).shape == (2, 3)
    assert nn.Unsqueeze(1).forward(x).shape == (2, 1, 3, 4)
    parts = nn.SplitTable(1).forward(x)
    assert len(parts) == 3 and parts[0].shape == (2, 4)
    joined = nn.JoinTable(1).forward(parts)
    assert joined.shape == (2, 12)
    infer = nn.InferReshape((0, -1), batch_mode=False).forward(x)
    assert infer.shape == (2, 12)


def test_global_max_pooling_uses_fallback_and_matches():
    """Window taps above the gate (global pooling) must route to the
    reduce_window autodiff path, with identical forward results."""
    layer = nn.SpatialMaxPooling(1, 1, global_pooling=True)
    x_np = np.random.randn(2, 3, 16, 16).astype(np.float32)
    out = layer.forward(jnp.asarray(x_np))
    np.testing.assert_allclose(np.asarray(out).reshape(2, 3),
                               x_np.max(axis=(2, 3)))
    g = layer.backward(jnp.asarray(x_np), jnp.ones_like(out))
    assert float(jnp.sum(g)) == pytest.approx(6.0)
