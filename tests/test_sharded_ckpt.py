"""Sharded (orbax-backed, per-host) checkpointing — the pod-scale layout
where no single host ever materializes the full model
(``utils/sharded_ckpt.py``; the default BTPU path is the reference's
gather-and-write ``Optimizer.scala:284-322``)."""

import os

import jax
import numpy as np
import pytest

import bigdl_tpu.nn as nn
import bigdl_tpu.optim as optim
from bigdl_tpu.dataset.sample import Sample
from bigdl_tpu.optim.trigger import Trigger
from bigdl_tpu.parallel.mesh import make_mesh
from bigdl_tpu.parallel.train_step import TrainStep
from bigdl_tpu.utils.sharded_ckpt import (latest_step_dir,
                                          restore_train_step,
                                          save_train_step)


def _mlp(seed):
    from bigdl_tpu.utils.rng import RNG

    RNG.set_seed(seed)
    return nn.Sequential(nn.Linear(8, 16), nn.Tanh(),
                         nn.Linear(16, 2), nn.LogSoftMax())


def _data(n=64, seed=0):
    rng = np.random.default_rng(seed)
    x = rng.normal(size=(n, 8)).astype(np.float32)
    y = (x[:, 0] > 0).astype(np.int64)
    return [Sample(x[i], np.int64(y[i])) for i in range(n)], x, y


def test_save_restore_preserves_sharded_layout(tmp_path):
    """Arrays restore under the LIVE mesh placement — incl. the ZeRO-1
    sharded optimizer state (the layout whose point is that no host
    holds it whole)."""
    samples, x, y = _data()
    mesh = make_mesh()
    step = TrainStep(_mlp(3), nn.ClassNLLCriterion(),
                     optim.Adam(learning_rate=0.05), mesh=mesh,
                     parameter_sync="sharded")
    for i in range(3):
        step.run(x[:32], y[:32], jax.random.key(i))
    want = {k: np.asarray(v) for k, v in step.params.items()}
    opt_shardings = jax.tree.map(lambda a: a.sharding, step.opt_state)

    d = str(tmp_path / "sharded.3")
    save_train_step(step, d, extra={"neval": 3})

    step2 = TrainStep(_mlp(99), nn.ClassNLLCriterion(),
                      optim.Adam(learning_rate=0.05), mesh=mesh,
                      parameter_sync="sharded")
    extra = restore_train_step(step2, d)
    assert extra == {"neval": 3}
    for k in want:
        np.testing.assert_array_equal(np.asarray(step2.params[k]), want[k])
    got_shardings = jax.tree.map(lambda a: a.sharding, step2.opt_state)
    assert jax.tree.all(jax.tree.map(
        lambda a, b: a.is_equivalent_to(b, 2) if hasattr(a, "spec") else True,
        got_shardings, opt_shardings))
    # resumed training continues identically
    l1 = float(step.run(x[:32], y[:32], jax.random.key(9)))
    l2 = float(step2.run(x[:32], y[:32], jax.random.key(9)))
    assert abs(l1 - l2) < 1e-6


def test_optimizer_sharded_backend_retry_and_resume(tmp_path):
    """End-to-end through the Optimizer: sharded checkpoints fire on the
    trigger, an injected failure restores from the newest one, and the
    run completes."""
    from tests.test_training_loop import ExceptionLayer

    samples, _, _ = _data(n=32)
    ExceptionLayer.count = 0
    model = nn.Sequential(nn.Linear(8, 16), ExceptionLayer(fail_at=6),
                          nn.Tanh(), nn.Linear(16, 2), nn.LogSoftMax())
    o = optim.DistriOptimizer(model, samples, nn.ClassNLLCriterion(),
                              batch_size=16,
                              end_trigger=Trigger.max_iteration(8),
                              mesh=make_mesh())
    o.set_optim_method(optim.SGD(learning_rate=0.1))
    o.set_checkpoint(str(tmp_path), Trigger.several_iteration(2),
                     backend="sharded")
    o.overwrite_checkpoint()
    o.optimize()
    assert o.state["neval"] >= 8
    latest = latest_step_dir(str(tmp_path))
    assert latest is not None and os.path.basename(latest) == "sharded.8"


def test_async_sharded_save_overlaps_training(tmp_path):
    """``wait=False`` returns the blocking tail: training steps proceed
    while orbax's write is in flight, ``finish()`` commits the meta
    marker, and the checkpoint only becomes discoverable (complete) after
    the commit — VERDICT r4 Weak #5 (sharded didn't compose with
    async)."""
    _, x, y = _data()
    step = TrainStep(_mlp(3), nn.ClassNLLCriterion(),
                     optim.SGD(learning_rate=0.05), mesh=make_mesh())
    step.run(x[:32], y[:32], jax.random.key(0))
    want = {k: np.asarray(v) for k, v in step.params.items()}

    d = str(tmp_path / "sharded.1")
    finish = save_train_step(step, d, extra={"neval": 1}, wait=False)
    assert callable(finish)
    # overlap: keep training while the write is in flight — the snapshot
    # must reflect the state AT save time, not the mutated one
    for i in range(3):
        step.run(x[:32], y[:32], jax.random.key(10 + i))
    assert latest_step_dir(str(tmp_path)) is None  # not yet committed
    finish()
    assert latest_step_dir(str(tmp_path)) == d

    step2 = TrainStep(_mlp(99), nn.ClassNLLCriterion(),
                      optim.SGD(learning_rate=0.05), mesh=make_mesh())
    extra = restore_train_step(step2, d)
    assert extra == {"neval": 1}
    for k in want:
        np.testing.assert_array_equal(np.asarray(step2.params[k]), want[k])


def test_optimizer_async_sharded_with_retention(tmp_path, monkeypatch):
    """End-to-end: BIGDL_ASYNC_CHECKPOINT + backend='sharded' + keep=2 —
    saves overlap iterations behind the _join_checkpoint_write barrier
    and only the newest two checkpoint dirs survive."""
    monkeypatch.setenv("BIGDL_ASYNC_CHECKPOINT", "1")
    from bigdl_tpu.utils.config import set_config
    set_config(None)  # re-read env
    try:
        samples, _, _ = _data(n=32)
        o = optim.DistriOptimizer(_mlp(5), samples, nn.ClassNLLCriterion(),
                                  batch_size=16,
                                  end_trigger=Trigger.max_iteration(8),
                                  mesh=make_mesh())
        o.set_optim_method(optim.SGD(learning_rate=0.1))
        o.set_checkpoint(str(tmp_path), Trigger.several_iteration(2),
                         backend="sharded", keep=2)
        o.overwrite_checkpoint()
        o.optimize()
    finally:
        monkeypatch.delenv("BIGDL_ASYNC_CHECKPOINT")
        set_config(None)
    names = sorted(n for n in os.listdir(tmp_path)
                   if n.startswith("sharded."))
    assert names == ["sharded.6", "sharded.8"], names


def test_btpu_retention(tmp_path):
    """keep=N prunes old model./optimMethod. pairs on the default
    backend too."""
    samples, _, _ = _data(n=32)
    o = optim.LocalOptimizer(_mlp(7), samples, nn.ClassNLLCriterion(),
                             batch_size=16,
                             end_trigger=Trigger.max_iteration(6))
    o.set_optim_method(optim.SGD(learning_rate=0.1))
    o.set_checkpoint(str(tmp_path), Trigger.several_iteration(1), keep=3)
    o.overwrite_checkpoint()
    o.optimize()
    files = sorted(os.listdir(tmp_path))
    models = [f for f in files if f.startswith("model.")]
    optims = [f for f in files if f.startswith("optimMethod.")]
    assert models == ["model.4", "model.5", "model.6"], files
    assert optims == ["optimMethod.4", "optimMethod.5", "optimMethod.6"]


def test_remote_discovery_and_prune():
    """latest_step_dir/prune_old work on remote (fsspec) roots — the
    ADVICE r4 medium finding: abspath mangled gs:// paths and
    os.path.isdir made resume blind to remote checkpoints.  Drive the
    discovery + retention halves on memory:// with fabricated complete
    checkpoints (the orbax shard write itself is Tensorstore's scheme
    support, exercised at real deployments)."""
    pytest.importorskip("fsspec")
    from bigdl_tpu.utils import file as File
    from bigdl_tpu.utils.sharded_ckpt import prune_old

    root = "memory://ckpt_disc"
    for n in (2, 4, 6):
        File.save(b"{}", f"{root}/sharded.{n}/bigdl_meta.json",
                  overwrite=True)
    File.save(b"x", f"{root}/sharded.9/state/notmeta", overwrite=True)
    assert latest_step_dir(root) == f"{root}/sharded.6"  # 9 is incomplete
    pruned = prune_old(root, keep=1)
    assert pruned == [f"{root}/sharded.2", f"{root}/sharded.4"]
    assert latest_step_dir(root) == f"{root}/sharded.6"
    assert not File.exists(f"{root}/sharded.2/bigdl_meta.json")


def test_sharded_backend_rejects_unknown():
    o = optim.LocalOptimizer(_mlp(1), _data()[0], nn.ClassNLLCriterion(),
                             batch_size=16,
                             end_trigger=Trigger.max_iteration(1))
    with pytest.raises(ValueError, match="unknown checkpoint backend"):
        o.set_checkpoint("/tmp/x", Trigger.every_epoch(), backend="zip")
