"""Sparse embedding fast path (nn/layers/embedding.py + the TrainStep
sparse-sync leg + lazy row-wise optimizer applies; ISSUE 15,
docs/sparse.md).

The contract under test, in order of importance:

1. **Numerics-exact**: N training steps under the sparse (indices,
   rows) sync equal the dense-all-reduce path (rtol 1e-6; Adagrad/Adam
   bit-equal) — duplicate indices and the padding index included, on a
   single device AND on multi-device meshes across every
   ``parameter_sync`` layout (the 2-process gloo leg lives in
   ``tests/test_multihost.py``).
2. **The measured win**: the PR-10 comms walker shows the table's
   per-step sync bytes collapsing >= 10x on a 2-device mesh.
3. The row-sparse cotangent itself (``test_numeric_grads.py``
   discipline): finite differences + scatter-equivalence against the
   dense cotangent.
"""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

try:  # this jaxlib keeps the scoped x64 switch in jax.experimental
    from jax.experimental import enable_x64 as _enable_x64
except ImportError:
    _enable_x64 = jax.enable_x64

import bigdl_tpu.nn as nn
import bigdl_tpu.optim as optim
from bigdl_tpu.nn.layers import embedding as embed
from bigdl_tpu.parallel.mesh import DATA_AXIS, make_mesh
from bigdl_tpu.parallel.train_step import TrainStep
from bigdl_tpu.utils.config import BigDLConfig, set_config
from bigdl_tpu.utils.rng import RNG

V, D, CLASSES = 128, 8, 4


@pytest.fixture(autouse=True)
def _fresh_config():
    set_config(None)
    yield
    set_config(None)


def _cfg(sparse_mode: str):
    set_config(BigDLConfig.from_env({"BIGDL_SPARSE": sparse_mode}))


def _classifier(vocab=V, dim=D, padding_idx=None, sparse=None,
                max_norm=float("inf"), w_regularizer=None):
    RNG.set_seed(0)
    return nn.Sequential(
        nn.LookupTable(vocab, dim, padding_idx=padding_idx, sparse=sparse,
                       max_norm=max_norm, w_regularizer=w_regularizer),
        nn.Select(1, -1), nn.Linear(dim, CLASSES), nn.LogSoftMax())


def _batch(vocab=V, batch=16, seq=4, seed=0):
    rng = np.random.RandomState(seed)
    x = rng.randint(0, vocab, (batch, seq)).astype(np.int32)
    x[0, :2] = 5   # duplicate indices inside one batch
    x[1, 0] = 0    # the padding index (when configured)
    y = rng.randint(0, CLASSES, batch)
    return x, y


def _train(model_fn, mode, steps=5, mesh_size=0, sync="allreduce",
           method=None, rules=None, batch=None, **step_kw):
    _cfg(mode)
    x, y = batch if batch is not None else _batch()
    mesh = (make_mesh((mesh_size,), (DATA_AXIS,),
                      devices=jax.devices()[:mesh_size])
            if mesh_size else None)
    st = TrainStep(model_fn(), nn.ClassNLLCriterion(),
                   method() if method else optim.SGD(0.1, momentum=0.9),
                   mesh=mesh, parameter_sync=sync,
                   extra_sharding_rules=rules, **step_kw)
    loss = None
    for _ in range(steps):
        loss = st.run(x, y, jax.random.key(3))
    params = {k: np.asarray(v)
              for k, v in st.gather_replicated(st.params).items()}
    return params, float(loss), st


def _assert_params_close(a, b, rtol=1e-6, atol=1e-7):
    assert set(a) == set(b)
    for k in a:
        np.testing.assert_allclose(a[k], b[k], rtol=rtol, atol=atol,
                                   err_msg=k)


# -- 1. numerics-exact sparse vs dense ---------------------------------------
def test_multistep_sparse_matches_dense_sgd_momentum():
    fn = lambda: _classifier(padding_idx=0)  # noqa: E731
    dense, ld, _ = _train(fn, "off")
    sparse, ls, st = _train(fn, "on")
    assert st._sparse_stats, "sparse path did not engage"
    assert ld == pytest.approx(ls, rel=1e-6)
    _assert_params_close(dense, sparse)


@pytest.mark.parametrize("method,bitexact", [
    (lambda: optim.Adagrad(0.1), True),     # lazy row-wise apply
    (lambda: optim.SGD(0.1), False),        # row-wise p[u] -= lr*g
    (lambda: optim.Adam(0.01), True),       # densify-locally fallback
    (lambda: optim.SGD(0.1, momentum=0.9, nesterov=True), False),
])
def test_multistep_sparse_matches_dense_per_method(method, bitexact):
    fn = lambda: _classifier(padding_idx=0)  # noqa: E731
    dense, _, _ = _train(fn, "off", method=method)
    sparse, _, _ = _train(fn, "on", method=method)
    _assert_params_close(dense, sparse)
    if bitexact:
        for k in dense:
            assert np.array_equal(dense[k], sparse[k]), (
                f"{k}: lazy apply must reproduce the dense update "
                f"bit-for-bit for this method")


@pytest.mark.parametrize("kw,tol", [
    ({"remat": True}, 1e-6),              # capture under jax.checkpoint
    ({"compute_dtype": jnp.bfloat16}, 1e-2),  # the bench recipe's dtype
], ids=["remat", "bf16"])
def test_multistep_sparse_matches_dense_composed(kw, tol):
    fn = _classifier
    dense, _, _ = _train(fn, "off", **kw)
    sparse, _, st = _train(fn, "on", **kw)
    assert st._sparse_stats
    _assert_params_close(dense, sparse, rtol=tol, atol=tol)


@pytest.mark.parametrize("mesh_size,sync", [
    (2, "allreduce"), (4, "sharded"), (2, "fsdp")])
def test_multistep_sparse_matches_dense_on_mesh(mesh_size, sync):
    fn = _classifier
    dense, _, _ = _train(fn, "off", mesh_size=mesh_size, sync=sync)
    sparse, _, _ = _train(fn, "on", mesh_size=mesh_size, sync=sync)
    _assert_params_close(dense, sparse)


def test_row_sharded_table_matches_replicated_dense():
    """The table row-sharded over the data axis (PartitionSpec via
    row_sharding_rules) + sparse sync == the replicated dense run."""
    fn = _classifier
    dense, _, _ = _train(fn, "off", mesh_size=2)
    model = fn()
    rules = embed.row_sharding_rules(model, axis=DATA_AXIS)
    _cfg("on")
    x, y = _batch()
    mesh = make_mesh((2,), (DATA_AXIS,), devices=jax.devices()[:2])
    st = TrainStep(model, nn.ClassNLLCriterion(),
                   optim.SGD(0.1, momentum=0.9), mesh=mesh,
                   extra_sharding_rules=rules)
    for _ in range(5):
        st.run(x, y, jax.random.key(3))
    sparse = {k: np.asarray(v)
              for k, v in st.gather_replicated(st.params).items()}
    _assert_params_close(dense, sparse)
    # the table really is sharded: each leaf's committed sharding
    # splits dim 0 (vocab) over the data axis
    spec = st._param_sharding("0.weight", st.params["0.weight"]).spec
    assert tuple(spec)[0] == DATA_AXIS


def test_pure_embedding_model_all_params_sparse():
    """Every parameter sparse: update_mixed's dense leg runs over an
    empty tree and counters still advance exactly once."""
    def fn():
        RNG.set_seed(0)
        return nn.Sequential(nn.EmbeddingBag(V, CLASSES, mode="mean"),
                             nn.LogSoftMax())
    dense, _, _ = _train(fn, "off", steps=3)
    sparse, _, st = _train(fn, "on", steps=3)
    _assert_params_close(dense, sparse)
    assert int(st.opt_state["neval"]) == 3


# -- 2. the measured win (PR-10 comms walker) --------------------------------
def test_comms_bytes_drop_at_least_10x_on_mesh():
    from bigdl_tpu.telemetry import comms

    def facts(mode):
        _cfg(mode)
        RNG.set_seed(0)
        model = nn.Sequential(nn.LookupTable(4096, 32), nn.Select(1, -1),
                              nn.Linear(32, CLASSES), nn.LogSoftMax())
        mesh = make_mesh((2,), (DATA_AXIS,), devices=jax.devices()[:2])
        st = TrainStep(model, nn.ClassNLLCriterion(),
                       optim.SGD(0.1, momentum=0.9), mesh=mesh)
        x, y = _batch(vocab=4096, batch=16, seq=8)
        compiled = st._build().lower(
            st.params, st.opt_state, st.buffers, *st._shard_batch(x, y),
            jax.random.key(0)).compile()
        return comms.comms_facts(compiled, mesh=mesh, model=st.model)

    dense, sparse = facts("off"), facts("auto")
    assert dense["bytes"] >= 10 * sparse["bytes"], (
        f"sparse sync must cut step comms >= 10x here: "
        f"dense={dense['bytes']} sparse={sparse['bytes']}")
    # no collective in the sparse program moves table-scale payload
    table_payload = 4096 * 32 * 4
    assert all(r["payload_bytes"] < table_payload
               for r in sparse["rows"]), sparse["rows"]


def test_attribute_comms_model_sparse_ab_on_dlrm():
    """The CLI-backing A/B: dlrm's registry-scale tables at mesh 2 —
    the sparse leg moves <10% of the dense leg's bytes and restores
    the prior config afterwards."""
    from bigdl_tpu.telemetry import comms
    from bigdl_tpu.utils.config import get_config

    before = get_config().sparse_sync
    dense = comms.attribute_comms_model("dlrm", batch=32, devices=2,
                                        sparse="off")
    sparse = comms.attribute_comms_model("dlrm", batch=32, devices=2,
                                         sparse="auto")
    assert get_config().sparse_sync == before
    assert dense["bytes"] >= 10 * sparse["bytes"]
    assert sparse["sparse"] == "auto"
    # the embedding tables own the surviving (small) sync rows
    assert any(r["path"].startswith("embed_") for r in sparse["rows"])


# -- 3. the row-sparse cotangent itself --------------------------------------
def _capture_rows_fn(layer, idx):
    """f(proxy) -> scalar loss through the layer's sparse path, plus the
    recorded unique indices — the differentiable view of the row-sparse
    cotangent."""
    paths = {id(layer): "weight"}
    shapes, _ = embed.discover_proxies(
        lambda: layer.update_output(idx), paths)
    (key, sds), = shapes.items()

    def f(proxy):
        with embed.SparseCapture(paths, {key: proxy}) as cap:
            out = layer.update_output(idx)
            u = cap.aux[key]["u"]
        return jnp.sum(jnp.sin(out)), u

    return f, sds


@pytest.mark.parametrize("build", [
    lambda: nn.LookupTable(11, 3, sparse=True, padding_idx=2),
    lambda: nn.EmbeddingBag(11, 3, mode="sum", sparse=True,
                            padding_idx=2),
    lambda: nn.EmbeddingBag(11, 3, mode="mean", sparse=True,
                            padding_idx=2),
], ids=["lookup", "bag_sum", "bag_mean"])
def test_sparse_vjp_matches_finite_differences_and_dense(build):
    from jax.test_util import check_grads

    RNG.set_seed(0)
    with _enable_x64():
        layer = build().evaluate()
        # duplicates (7 twice in row 0) AND the padding index (2)
        idx = jnp.asarray(np.array([[7, 7, 2, 1], [3, 4, 4, 2]],
                                   dtype=np.int32))
        f, sds = _capture_rows_fn(layer, idx)
        proxy0 = jnp.zeros(sds.shape, jnp.float64)
        check_grads(lambda p: f(p)[0], (proxy0,), order=1,
                    modes=("rev",), atol=1e-3, rtol=1e-3)
        g_rows, u = jax.grad(f, has_aux=True)(proxy0)
        # padding row's cotangent is zeroed INSIDE the VJP
        pad_slots = np.asarray(u) == 2
        assert pad_slots.any()
        assert np.all(np.asarray(g_rows)[pad_slots] == 0.0)
        # scatter-equivalence: rows scattered onto their indices ==
        # the DENSE path's table cotangent (duplicates pre-summed)
        dense_tab = layer.weight

        def dense_loss(w):
            layer.weight = w
            try:
                return jnp.sum(jnp.sin(layer.update_output(idx)))
            finally:
                layer.weight = dense_tab
        g_dense = jax.grad(dense_loss)(dense_tab)
        scattered = jnp.zeros_like(dense_tab).at[u].add(
            g_rows.astype(dense_tab.dtype), mode="drop")
        # f32 table: the bag reduction orders its sums differently on
        # the two paths, so equivalence is to f32 round-off
        np.testing.assert_allclose(np.asarray(scattered),
                                   np.asarray(g_dense), rtol=1e-5,
                                   atol=1e-7)


def test_embedding_bag_forward_reference():
    """sum/mean vs a numpy reference, padding entries excluded from the
    value AND the mean denominator."""
    RNG.set_seed(0)
    w = np.random.RandomState(1).randn(9, 4).astype(np.float32)
    idx = np.array([[1, 2, 0, 2], [0, 0, 0, 3]], dtype=np.int32)
    for mode in ("sum", "mean"):
        bag = nn.EmbeddingBag(9, 4, mode=mode, padding_idx=0)
        bag.weight = jnp.asarray(w)
        out = np.asarray(bag.update_output(jnp.asarray(idx)))
        ref = np.zeros((2, 4), np.float32)
        for r in range(2):
            rows = [w[i] for i in idx[r] if i != 0]
            if rows:
                ref[r] = np.sum(rows, axis=0)
                if mode == "mean":
                    ref[r] /= len(rows)
        np.testing.assert_allclose(out, ref, rtol=1e-6)
    # 1-D input treated as bag size 1
    bag = nn.EmbeddingBag(9, 4, mode="sum")
    bag.weight = jnp.asarray(w)
    out = np.asarray(bag.update_output(jnp.asarray(idx[:, 0])))
    np.testing.assert_allclose(out, w[idx[:, 0]], rtol=1e-6)


# -- guardrails and knobs ----------------------------------------------------
def test_auto_density_rule_keeps_long_sequences_dense():
    """lstm_text's regime: lookups >> vocab -> the auto rule stays
    dense (docs/sparse.md 'when dense wins'); sparse=True forces."""
    lt = nn.LookupTable(100, 4)
    assert lt._sparse_active(49, 100)          # 2*49 <= 100
    assert not lt._sparse_active(51, 100)      # past half the table
    assert nn.LookupTable(100, 4, sparse=True)._sparse_active(5000, 100)
    # and end to end: a batch touching most of the vocab never engages
    fn = lambda: _classifier(vocab=16)  # noqa: E731
    _, _, st = _train(fn, "auto", steps=1,
                      batch=_batch(vocab=16, batch=16, seq=4))
    assert st._sparse_stats is None


def test_off_knob_and_guardrails_force_dense():
    fn = lambda: _classifier()  # noqa: E731
    _, _, st = _train(fn, "off", steps=1)
    assert st._sparse_stats is None
    # max_norm renorm is differentiated through on the dense path only
    assert not nn.LookupTable(V, D, max_norm=1.0)._sparse_active(4, V)
    # a regularized table's reg gradient is dense by definition
    from bigdl_tpu.optim.regularizer import L2Regularizer

    reg_fn = lambda: _classifier(w_regularizer=L2Regularizer(1e-3))  # noqa: E731
    dense, _, _ = _train(reg_fn, "off", steps=3)
    sparse, _, st = _train(reg_fn, "on", steps=3)
    assert st._sparse_stats is None  # table excluded -> no sparse leg
    _assert_params_close(dense, sparse)


def test_value_clipping_outside_zero_disables_sparse():
    fn = lambda: _classifier()  # noqa: E731
    _cfg("on")
    x, y = _batch()
    st = TrainStep(fn(), nn.ClassNLLCriterion(), optim.SGD(0.1),
                   gradient_clipping=(0.01, 1.0))
    assert st._sparse_tables == {}
    st2 = TrainStep(fn(), nn.ClassNLLCriterion(), optim.SGD(0.1),
                    gradient_clipping=(-1.0, 1.0))
    assert st2._sparse_tables  # zero-preserving bounds keep the path
    dense, _, _ = _train(fn, "off", gradient_clipping=(-0.02, 0.02))
    sparse, _, _ = _train(fn, "on", gradient_clipping=(-0.02, 0.02))
    _assert_params_close(dense, sparse)


def test_multi_call_table_densifies_before_nonlinear_legs():
    """A table used twice per forward (overlapping index sets) must see
    value clipping / compression applied to the cross-call SUM, exactly
    like the dense path — the per-call-then-sum ordering diverges by up
    to the whole clip budget on overlapping rows (review finding)."""
    from bigdl_tpu.nn.module import Module

    class DoubleLookup(Module):
        def __init__(self):
            super().__init__()
            self.emb = nn.LookupTable(64, 8, sparse=True)
            self.head = nn.Linear(8, CLASSES)
            self.out = nn.LogSoftMax()

        def update_output(self, x):
            a = jnp.sum(self.emb(x), axis=1)
            b = jnp.sum(self.emb(x[:, ::2]), axis=1)  # overlapping rows
            return self.out(self.head(a + b))

    rng = np.random.RandomState(0)
    x = rng.randint(0, 64, (16, 4)).astype(np.int32)
    y = rng.randint(0, CLASSES, 16)

    def run(mode):
        _cfg(mode)
        RNG.set_seed(0)
        st = TrainStep(DoubleLookup(), nn.ClassNLLCriterion(),
                       optim.SGD(0.5),
                       gradient_clipping=(-1e-4, 1e-4))
        for _ in range(3):
            st.run(x, y, jax.random.key(1))
        return {k: np.asarray(v) for k, v in st.params.items()}

    _assert_params_close(run("off"), run("on"))


def test_duck_typed_optimizer_without_update_mixed_still_trains():
    """The pre-sparse contract: a method implementing only
    init_state()/update() must keep training a sparse-capable model —
    the step densifies the rows locally for it (review finding)."""
    class PlainSGD:
        def init_state(self, params):
            return {"neval": jnp.zeros((), jnp.int32),
                    "epoch": jnp.ones((), jnp.int32)}

        def update(self, grads, params, state):
            new_p = {k: p - 0.1 * grads[k] for k, p in params.items()}
            return new_p, {**state, "neval": state["neval"] + 1}

    _cfg("on")
    x, y = _batch()
    st = TrainStep(_classifier(), nn.ClassNLLCriterion(), PlainSGD())
    before = np.asarray(st.params["0.weight"])
    loss = st.run(x, y, jax.random.key(0))
    assert np.isfinite(loss)
    assert st._sparse_stats  # the capture still engaged
    assert not np.array_equal(before, np.asarray(st.params["0.weight"]))


def test_compression_and_max_norm_ride_the_sparse_rows():
    fn = lambda: _classifier()  # noqa: E731
    for kw in ({"gradient_compression": "bf16"}, {"max_norm": 0.05}):
        dense, _, _ = _train(fn, "off", **kw)
        sparse, _, st = _train(fn, "on", **kw)
        assert st._sparse_stats
        _assert_params_close(dense, sparse)


def test_health_probe_and_grad_fault_see_sparse_grads():
    fn = lambda: _classifier()  # noqa: E731
    _, _, std = _train(fn, "off", steps=1, health_probe=True)
    _, _, sts = _train(fn, "on", steps=1, health_probe=True)
    assert sts._sparse_stats
    np.testing.assert_allclose(np.asarray(sts.last_health),
                               np.asarray(std.last_health), rtol=1e-5)
    # a nan_grads fault poisons the table through the sparse leg too,
    # and skip_nonfinite keeps the previous table wholesale
    _cfg("on")
    x, y = _batch()
    st = TrainStep(fn(), nn.ClassNLLCriterion(), optim.SGD(0.1),
                   grad_fault=True, skip_nonfinite=True)
    before = np.asarray(st.params["0.weight"])
    st.run(x, y, jax.random.key(0), grad_scale=float("nan"))
    after = np.asarray(st.params["0.weight"])
    assert np.isfinite(after).all()
    np.testing.assert_array_equal(before, after)


def test_train_sparse_instant_emitted_and_schema_valid(tmp_path):
    from bigdl_tpu import telemetry
    from bigdl_tpu.telemetry import schema

    _cfg("on")
    x, y = _batch()
    telemetry.start_run(str(tmp_path))
    try:
        st = TrainStep(_classifier(), nn.ClassNLLCriterion(),
                       optim.SGD(0.1))
        st.run(x, y, jax.random.key(0))
    finally:
        telemetry.end_run()
    logs = sorted(tmp_path.glob("*.jsonl"))
    assert logs, list(tmp_path.iterdir())
    events, errors = schema.read_events(str(logs[-1]))
    assert not errors
    assert not schema.validate_events(events)
    inst = [e for e in events
            if e.get("kind") == "event" and e.get("name") == "train/sparse"]
    assert len(inst) == 1
    row = inst[0]
    assert row["tables"] == 1
    assert row["saved_bytes"] > 0
    assert row["dense_bytes"] == V * D * 4
    assert row["rows"][0]["path"] == "0.weight"
    # the stats fold onto /status for tpu_watch's sparse= block
    from bigdl_tpu.telemetry.metrics_http import MetricsSink

    sink = MetricsSink()
    for e in events:
        sink.emit(e)
    assert sink.status()["sparse"]["saved_bytes"] == row["saved_bytes"]


def test_scan_path_carries_sparse_sync():
    """aot_scan (the bench protocol) engages the same sparse leg inside
    the scanned body and matches the dense scan's losses."""
    def run(mode):
        _cfg(mode)
        x, y = _batch()
        st = TrainStep(_classifier(), nn.ClassNLLCriterion(),
                       optim.SGD(0.1, momentum=0.9))
        st.aot_scan(x, y, jax.random.key(0), 4)
        losses = st.run_scan(x, y, jax.random.key(1), 4)
        return np.asarray(losses), st
    ld, _ = run("off")
    ls, st = run("on")
    assert st._sparse_stats
    np.testing.assert_allclose(ls, ld, rtol=1e-6)


# -- the recsys scenario -----------------------------------------------------
def test_dlrm_registry_model_trains_and_serves_shapes():
    from bigdl_tpu.models import registry

    RNG.set_seed(0)
    model = registry.build_model("dlrm")
    spec = registry.input_spec("dlrm", 4)
    assert tuple(spec.shape) == (4, 21)  # 13 count + 8 categorical
    criterion, tgt = registry.train_pieces("dlrm", 4)
    rng = np.random.RandomState(0)
    x = np.concatenate([rng.randint(0, 100, (4, 13)),
                        rng.randint(0, 50000, (4, 8))],
                       axis=1).astype(np.int32)
    out = model.forward(jnp.asarray(x))
    assert out.shape == (4, 2)
    np.testing.assert_allclose(np.asarray(jnp.exp(out)).sum(axis=1),
                               1.0, rtol=1e-5)
    _cfg("auto")
    st = TrainStep(model, criterion, optim.Adagrad(0.05))
    y = rng.randint(0, 2, 4)
    l0 = st.run(jnp.asarray(x), jnp.asarray(y), jax.random.key(0))
    l1 = st.run(jnp.asarray(x), jnp.asarray(y), jax.random.key(1))
    assert np.isfinite([l0, l1]).all()
    # every table went sparse: 8 bags, 512-row cap each at batch 4*1
    assert st._sparse_stats and st._sparse_stats["tables"] == 8


def test_dlrm_sparse_matches_dense():
    from bigdl_tpu import models

    rng = np.random.RandomState(0)
    x = np.concatenate([rng.randint(0, 100, (8, 13)),
                        rng.randint(0, 300, (8, 8))],
                       axis=1).astype(np.int32)
    y = rng.randint(0, 2, 8)

    def run(mode):
        _cfg(mode)
        RNG.set_seed(0)
        m = models.build_dlrm(vocab_size=300)
        st = TrainStep(m, nn.ClassNLLCriterion(),
                       optim.SGD(0.05, momentum=0.9))
        for i in range(4):
            loss = st.run(jnp.asarray(x), jnp.asarray(y),
                          jax.random.key(5))
        return ({k: np.asarray(v) for k, v in st.params.items()},
                float(loss))

    dense, ld = run("off")
    sparse, ls = run("on")
    assert ld == pytest.approx(ls, rel=1e-6)
    _assert_params_close(dense, sparse)


# -- bench honesty -----------------------------------------------------------
def test_zipf_indices_skew_and_bounds():
    import bench

    rng = np.random.default_rng(0)
    ids = bench.zipf_indices(rng, (4000,), 1000, 1.05)
    assert ids.dtype == np.int32
    assert ids.min() >= 0 and ids.max() < 1000
    counts = np.bincount(ids, minlength=1000)
    # hot head: rank-0 id is much warmer than the tail median
    assert counts[0] > 20 * max(1, np.median(counts[500:]))


@pytest.mark.slow
def test_bucketed_lstm_leg_accounts_pad_positions():
    """The bucketed bench protocol: per-bucket sub-legs ride the
    dataset/text.py bucket set and MFU credits only valid tokens."""
    import bench

    row = bench._run_config_bucketed("lstm_text", 8, 2, (16, 32))
    assert set(row["buckets"]) <= {"16", "32"}
    assert 0 < row["valid_token_frac"] < 1
    shares = sum(b["share"] for b in row["buckets"].values())
    assert shares == pytest.approx(1.0, abs=0.01)
    assert row["images_per_sec"] > 0
