"""Test configuration: run everything on a virtual 8-device CPU mesh so
multi-chip sharding paths are exercised without TPU hardware (the analogue
of the reference's `local[N]` + Engine-override distributed tests,
``optim/DistriOptimizerSpec.scala:40-41``)."""

import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = flags + " --xla_force_host_platform_device_count=8"

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax  # noqa: E402

# The axon TPU plugin registers itself from sitecustomize and overrides the
# platform selection; force the virtual 8-device CPU backend for tests.
jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402


def pytest_configure(config):
    config.addinivalue_line(
        "markers",
        "deadline(seconds): hard per-test wall-clock cap enforced with "
        "SIGALRM — every multihost/cluster test carries one so a "
        "deadlocked collective can never eat the tier-1 time budget")
    config.addinivalue_line(
        "markers", "slow: excluded from the tier-1 `-m 'not slow'` run")


@pytest.fixture(autouse=True)
def _seed_rng():
    from bigdl_tpu.utils.rng import RNG

    RNG.set_seed(42)
    yield


@pytest.fixture(autouse=True)
def _hard_deadline(request):
    """Enforce ``@pytest.mark.deadline(seconds)``: SIGALRM interrupts
    whatever the test is blocked in (including a subprocess wait on a
    hung cluster) and fails it with TimeoutError instead of letting it
    run to the suite-level timeout.  Main-thread only by construction
    (pytest runs tests on the main thread)."""
    import signal as _signal

    marker = request.node.get_closest_marker("deadline")
    if marker is None:
        yield
        return
    limit = float(marker.args[0])

    def _on_alarm(signum, frame):
        raise TimeoutError(
            f"{request.node.nodeid} exceeded its {limit:.0f}s deadline "
            f"(deadlocked collective / hung subprocess?)")

    old = _signal.signal(_signal.SIGALRM, _on_alarm)
    _signal.setitimer(_signal.ITIMER_REAL, limit)
    try:
        yield
    finally:
        _signal.setitimer(_signal.ITIMER_REAL, 0.0)
        _signal.signal(_signal.SIGALRM, old)
