"""Test configuration: run everything on a virtual 8-device CPU mesh so
multi-chip sharding paths are exercised without TPU hardware (the analogue
of the reference's `local[N]` + Engine-override distributed tests,
``optim/DistriOptimizerSpec.scala:40-41``)."""

import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = flags + " --xla_force_host_platform_device_count=8"

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax  # noqa: E402

# The axon TPU plugin registers itself from sitecustomize and overrides the
# platform selection; force the virtual 8-device CPU backend for tests.
jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402


@pytest.fixture(autouse=True)
def _seed_rng():
    from bigdl_tpu.utils.rng import RNG

    RNG.set_seed(42)
    yield
