"""CI wiring for the tracer-leak lint: the whole repo's Python sources
must stay clean (``tools/lint_graft.py`` is the standalone entry point;
this pytest makes a fresh leak fail tier-1)."""

import os

from bigdl_tpu.analysis.ast_lint import DEFAULT_LINT_DIRS, lint_paths

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_repo_sources_lint_clean():
    paths = [os.path.join(REPO, d) for d in DEFAULT_LINT_DIRS]
    report = lint_paths(paths)
    assert not report.errors, "\n" + report.format()


def test_lint_covers_telemetry_package():
    # bigdl_tpu/telemetry/ is inside the default lint roots; pin that
    # explicitly (and that it is clean on its own) so a future root
    # reshuffle can't silently drop the subsystem from CI
    tele = os.path.join(REPO, "bigdl_tpu", "telemetry")
    assert os.path.isdir(tele)
    # the ISSUE-3 observability modules must exist AND be covered — a
    # rename/move that orphans one of them from the lint roots fails here
    for mod in ("health.py", "metrics_http.py", "diff.py"):
        assert os.path.isfile(os.path.join(tele, mod)), mod
    report = lint_paths([tele])
    assert not report.errors and not report.warnings, "\n" + report.format()


def test_lint_actually_scans_regions():
    # guard against the lint silently matching nothing: the repo has
    # known jitted regions (train_step, ops/control, rnn scan bodies)
    import ast

    from bigdl_tpu.analysis.ast_lint import _find_regions

    found = 0
    for rel in ("bigdl_tpu/parallel/train_step.py",
                "bigdl_tpu/ops/control.py",
                "bigdl_tpu/nn/layers/rnn.py"):
        path = os.path.join(REPO, rel)
        with open(path, "r", encoding="utf-8") as fh:
            found += len(_find_regions(ast.parse(fh.read())))
    assert found >= 5, "region detection went blind"
