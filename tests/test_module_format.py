"""BTPU versioned persistence tests (VERDICT r1 item 5; reference:
``utils/serializer/ModuleSerializer.scala:34`` + ``bigdl.proto``).

Round-trips, forward-equality after reload, shared-weight preservation,
checkpoint integration, and the negative paths: corrupted files, future
format versions, unknown classes, and non-BTPU (legacy pickle) blobs all
fail with a clean SerializationError — never arbitrary code execution.
"""

import numpy as np
import pytest

import bigdl_tpu.nn as nn
from bigdl_tpu.nn.module import state_dict
from bigdl_tpu.utils import module_format as mf
from bigdl_tpu.utils import serializer
from bigdl_tpu.utils.rng import RNG


def _roundtrip_forward(model, x):
    m2 = mf.loads(mf.dumps(model))
    np.testing.assert_allclose(np.asarray(model.evaluate().forward(x)),
                               np.asarray(m2.evaluate().forward(x)),
                               rtol=1e-6)
    return m2


def test_mlp_roundtrip_forward_equality():
    RNG.set_seed(0)
    model = nn.Sequential(nn.Linear(6, 16), nn.Tanh(), nn.Dropout(0.2),
                          nn.Linear(16, 3), nn.LogSoftMax())
    x = np.random.RandomState(0).randn(4, 6).astype(np.float32)
    _roundtrip_forward(model, x)


def test_conv_bn_roundtrip_keeps_buffers():
    RNG.set_seed(0)
    model = nn.Sequential(nn.SpatialConvolution(3, 4, 3, 3, 1, 1, 1, 1),
                          nn.SpatialBatchNormalization(4), nn.ReLU())
    # populate BN running stats
    x = np.random.RandomState(1).randn(2, 3, 5, 5).astype(np.float32)
    model.training_mode()
    model.forward(x)
    m2 = _roundtrip_forward(model, x)
    sd1, sd2 = state_dict(model), state_dict(m2)
    assert set(sd1) == set(sd2)
    for k in sd1:
        np.testing.assert_array_equal(np.asarray(sd1[k]), np.asarray(sd2[k]))


def test_graph_roundtrip():
    from bigdl_tpu.nn.graph import node_from_module

    RNG.set_seed(0)
    inp = nn.Input(name="x")
    h = node_from_module(nn.Linear(8, 8).set_name("fc1"), [inp])
    r = node_from_module(nn.ReLU().set_name("act"), [h])
    out = node_from_module(nn.Linear(8, 4).set_name("fc2"), [r])
    g = nn.Graph([inp], [out])
    x = np.random.RandomState(0).randn(2, 8).astype(np.float32)
    _roundtrip_forward(g, x)


def test_model_zoo_roundtrip():
    """EVERY zoo family serializes and reloads byte-exact — incl. the
    graph-heavy (Inception), residual (ResNet-50), recurrent (LSTM) and
    remat-wrapped (transformer) structures; LeNet additionally proves
    forward equality through the reloaded module."""
    import jax.numpy as jnp

    from bigdl_tpu import models

    RNG.set_seed(0)
    for build in (lambda: models.build_lenet5(10),
                  lambda: models.build_resnet_cifar(8, 10),
                  lambda: models.build_resnet(50, 10),
                  lambda: models.build_lstm_classifier(50, 8, 8, 3),
                  lambda: models.build_vgg_for_cifar10(10),
                  lambda: models.build_inception_v1(100),
                  lambda: models.build_inception_v2(100),
                  lambda: models.build_autoencoder(32),
                  lambda: models.build_transformer_lm(
                      64, num_layers=1, embed_dim=16, num_heads=2,
                      max_len=32, remat=True)):
        m = build()
        m2 = mf.loads(mf.dumps(m))
        sd1, sd2 = state_dict(m), state_dict(m2)
        assert set(sd1) == set(sd2)
        for k in sd1:
            np.testing.assert_array_equal(np.asarray(sd1[k]),
                                          np.asarray(sd2[k]))

    RNG.set_seed(0)
    lenet = models.build_lenet5(10)
    reloaded = mf.loads(mf.dumps(lenet))
    x = jnp.asarray(np.random.RandomState(0)
                    .randn(2, 1, 28, 28).astype(np.float32))
    np.testing.assert_allclose(
        np.asarray(lenet.evaluate().forward(x)),
        np.asarray(reloaded.evaluate().forward(x)), rtol=1e-6)


def test_optim_method_roundtrip():
    import bigdl_tpu.optim as optim

    om = optim.Adam(learning_rate=3e-4)
    om.state["driver_state"] = {"epoch": 3, "neval": 11}
    om.state["func_state"] = {"step": np.asarray(11),
                              "m": {"w": np.ones((4, 2), np.float32)}}
    o2 = mf.loads(mf.dumps(om, kind="optim"), kind="optim")
    assert type(o2) is optim.Adam
    assert o2.state["driver_state"] == {"epoch": 3, "neval": 11}
    np.testing.assert_array_equal(o2.state["func_state"]["m"]["w"],
                                  np.ones((4, 2), np.float32))


def test_shared_weights_stay_shared():
    RNG.set_seed(0)
    shared = nn.Linear(5, 5)
    model = nn.Sequential(shared, nn.ReLU(), shared)
    m2 = mf.loads(mf.dumps(model))
    mods = list(m2.modules())
    layers = [m for m in mods if isinstance(m, nn.Linear)]
    assert layers[0] is layers[1], "shared module duplicated on reload"


def test_serializer_file_roundtrip(tmp_path):
    RNG.set_seed(0)
    model = nn.Sequential(nn.Linear(4, 2), nn.LogSoftMax())
    p = str(tmp_path / "m.btpu")
    serializer.save_module(model, p)
    m2 = serializer.load_module(p)
    x = np.random.RandomState(0).randn(3, 4).astype(np.float32)
    np.testing.assert_allclose(np.asarray(model.forward(x)),
                               np.asarray(m2.forward(x)), rtol=1e-6)


def test_rejects_bad_magic_and_legacy_pickle(tmp_path):
    import pickle

    blob = pickle.dumps({"x": 1})
    with pytest.raises(mf.SerializationError, match="magic"):
        mf.loads(blob)
    p = tmp_path / "legacy"
    p.write_bytes(blob)
    with pytest.raises(mf.SerializationError):
        serializer.load_module(str(p))


def test_rejects_future_version():
    from bigdl_tpu.utils import protowire

    blob = mf.MAGIC + protowire.write_varint(mf.FORMAT_VERSION + 1)
    with pytest.raises(mf.SerializationError, match="version"):
        mf.loads(blob)


def test_rejects_corrupted_payload():
    RNG.set_seed(0)
    blob = bytearray(mf.dumps(nn.Linear(4, 4)))
    blob = blob[: len(blob) // 2]  # truncate mid-tensor
    with pytest.raises(mf.SerializationError):
        mf.loads(bytes(blob))


def test_rejects_unknown_class():
    import json

    from bigdl_tpu.utils import protowire

    structure = {"__t__": "obj", "c": "TotallyUnknownLayer", "id": 0, "a": {}}
    header = {"format": "bigdl_tpu", "kind": "module", "tensors": 0}
    blob = (mf.MAGIC + protowire.write_varint(mf.FORMAT_VERSION)
            + protowire.emit_bytes(1, json.dumps(header).encode())
            + protowire.emit_bytes(2, json.dumps(structure).encode()))
    with pytest.raises(mf.SerializationError, match="unknown class"):
        mf.loads(blob)


def test_rejects_wrong_kind():
    RNG.set_seed(0)
    blob = mf.dumps(nn.Linear(2, 2), kind="module")
    with pytest.raises(mf.SerializationError, match="kind|expected"):
        mf.loads(blob, kind="optim")


def test_no_code_execution_on_load():
    """A malicious structure naming arbitrary modules/functions must not
    import or call anything outside bigdl_tpu."""
    import json

    from bigdl_tpu.utils import protowire

    structure = {"__t__": "fn", "m": "os", "q": "system"}
    header = {"format": "bigdl_tpu", "kind": "module", "tensors": 0}
    blob = (mf.MAGIC + protowire.write_varint(mf.FORMAT_VERSION)
            + protowire.emit_bytes(1, json.dumps(header).encode())
            + protowire.emit_bytes(2, json.dumps(structure).encode()))
    with pytest.raises(mf.SerializationError, match="refusing"):
        mf.loads(blob)


def test_register_extension_class():
    from bigdl_tpu.nn.module import Module, Parameter

    @mf.register
    class _MyScale(Module):
        def __init__(self, n):
            super().__init__()
            self.weight = Parameter(np.full((n,), 2.0, np.float32))

        def update_output(self, input):
            return input * self._params["weight"]

    m = _MyScale(3)
    m2 = mf.loads(mf.dumps(m))
    np.testing.assert_array_equal(np.asarray(m2._params["weight"]),
                                  np.full((3,), 2.0, np.float32))


def test_file_layer_contract(tmp_path):
    """utils.file moves opaque bytes only (VERDICT r1 weak #6: the remote
    path's contract); object encoding lives in module_format."""
    from bigdl_tpu.utils import file as File

    p = str(tmp_path / "blob.bin")
    File.save(b"abc", p)
    assert File.load(p) == b"abc"
    with pytest.raises(FileExistsError):
        File.save(b"xyz", p)
    File.save(b"xyz", p, overwrite=True)
    assert File.load(p) == b"xyz"
    with pytest.raises(TypeError, match="bytes"):
        File.save({"not": "bytes"}, str(tmp_path / "o.bin"))
    assert File.is_remote("gs://bucket/k") and not File.is_remote(p)
    # memory:// exercises the fsspec remote branch end-to-end
    try:
        import fsspec  # noqa: F401

        File.save(b"remote", "memory://ckpt/blob.bin", overwrite=True)
        assert File.load("memory://ckpt/blob.bin") == b"remote"
    except ImportError:
        with pytest.raises(RuntimeError, match="fsspec"):
            File.load("gs://bucket/k")


def test_file_remote_gs_branch(monkeypatch):
    """The gs:// branch drives fsspec correctly (mocked in-memory fs —
    the environment has no egress; ``utils/File.scala:111-155`` parity)."""
    import io
    import sys
    import types

    from bigdl_tpu.utils import file as file_util

    store = {}

    class _FakeOpenFile:
        def __init__(self, path, mode):
            self.path, self.mode = path, mode

        def open(self):
            if "r" in self.mode:
                if self.path not in store:
                    raise FileNotFoundError(self.path)
                return io.BytesIO(store[self.path])
            buf = io.BytesIO()
            close = buf.close

            def flush_close():
                store[self.path] = buf.getvalue()
                close()

            buf.close = flush_close
            return buf

    fake = types.ModuleType("fsspec")
    fake.open = _FakeOpenFile
    monkeypatch.setitem(sys.modules, "fsspec", fake)

    file_util.save(b"payload", "gs://bucket/dir/obj.bin", overwrite=True)
    assert store["gs://bucket/dir/obj.bin"] == b"payload"
    assert file_util.load("gs://bucket/dir/obj.bin") == b"payload"
    with pytest.raises(FileNotFoundError):
        file_util.load("gs://bucket/missing")


def test_file_remote_without_fsspec(monkeypatch):
    import builtins
    import sys

    from bigdl_tpu.utils import file as file_util

    monkeypatch.setitem(sys.modules, "fsspec", None)
    real_import = builtins.__import__

    def no_fsspec(name, *a, **k):
        if name == "fsspec":
            raise ImportError("no fsspec")
        return real_import(name, *a, **k)

    monkeypatch.setattr(builtins, "__import__", no_fsspec)
    with pytest.raises(RuntimeError, match="fsspec"):
        file_util.load("gs://bucket/x")
