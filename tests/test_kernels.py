"""Kernel-library parity + dispatch tests (bigdl_tpu/ops/).

Every fused op keeps two legs under one ``jax.custom_vjp`` — the Pallas
kernel (interpret mode on this CPU suite: the IDENTICAL code path that
Mosaic compiles on TPU) and the XLA reference.  Parity must hold on
forward values AND the hand-derived VJP cotangents, across odd shapes,
dtypes, and ceil/asymmetric-padding edges; ``tests/test_numeric_grads.py``
separately pins both legs against finite differences.

The dispatch layer's contract is pinned here too: ``BIGDL_KERNELS=xla``
bypasses Pallas EVERYWHERE (the process-wide kill switch), ``pallas``
forces the kernels, a typo'd value raises instead of silently
defaulting, and every decision lands in the decision ring + the
``kernel/dispatch`` telemetry stream.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from bigdl_tpu.ops import dispatch
from bigdl_tpu.ops.lrn_pallas import cross_map_lrn, within_channel_lrn
from bigdl_tpu.ops.norm_pallas import (contrastive_norm, divisive_norm,
                                       subtractive_norm)
from bigdl_tpu.ops.pool_pallas import avg_pool, maxpool_tie_split


def _rng(seed=0):
    return np.random.RandomState(seed)


def _both_legs(fn, x, seed=1, rtol=1e-5, atol=1e-6, monkeypatch=None):
    """Run fn's value+VJP on both dispatch legs and assert parity."""
    outs = {}
    for mode in ("xla", "pallas"):
        monkeypatch.setenv("BIGDL_KERNELS", mode)
        y, vjp = jax.vjp(fn, x)
        outs[mode] = (y, vjp)
    y1, vjp1 = outs["xla"]
    y2, vjp2 = outs["pallas"]
    np.testing.assert_allclose(np.asarray(y1, np.float32),
                               np.asarray(y2, np.float32),
                               rtol=rtol, atol=atol)
    gy = jnp.asarray(_rng(seed).randn(*y1.shape).astype(np.float32),
                     y1.dtype)
    np.testing.assert_allclose(np.asarray(vjp1(gy)[0], np.float32),
                               np.asarray(vjp2(gy)[0], np.float32),
                               rtol=rtol, atol=atol)
    return y1


# ---------------------------------------------------------------------------
# parity: LRN family
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("shape,size", [
    ((2, 7, 5, 5), 5),      # band wider than half the channels
    ((1, 3, 4, 4), 3),      # tiny channel count
    ((2, 16, 7, 9), 5),     # non-square odd spatial
])
def test_cross_map_lrn_parity(shape, size, monkeypatch):
    x = jnp.asarray(_rng().randn(*shape).astype(np.float32))
    _both_legs(lambda a: cross_map_lrn(a, size, 1e-4, 0.75, 1.0), x,
               monkeypatch=monkeypatch)


def test_cross_map_lrn_general_beta_and_k(monkeypatch):
    x = jnp.asarray(_rng(3).randn(1, 5, 6, 6).astype(np.float32))
    _both_legs(lambda a: cross_map_lrn(a, 3, 0.001, 0.5, 2.0), x,
               monkeypatch=monkeypatch)


@pytest.mark.parametrize("shape,size", [
    ((2, 4, 6, 6), 3),
    ((1, 2, 7, 5), 4),      # EVEN window: asymmetric (lo, hi) pads
    ((2, 3, 9, 9), 5),
])
def test_within_channel_lrn_parity(shape, size, monkeypatch):
    x = jnp.asarray(_rng(1).randn(*shape).astype(np.float32))
    _both_legs(lambda a: within_channel_lrn(a, size, 0.01, 0.75), x,
               monkeypatch=monkeypatch)


def test_lrn_bf16_parity(monkeypatch):
    """The bench dtype: both legs agree within bf16 slack."""
    x = jnp.asarray(_rng(2).randn(2, 8, 8, 8).astype(np.float32),
                    jnp.bfloat16)
    _both_legs(lambda a: cross_map_lrn(a, 5, 1e-4, 0.75, 1.0), x,
               rtol=2e-2, atol=2e-2, monkeypatch=monkeypatch)


# ---------------------------------------------------------------------------
# parity: subtractive / divisive / contrastive
# ---------------------------------------------------------------------------

def _gauss(k):
    from bigdl_tpu.nn.layers.normalization import _gaussian_kernel

    return jnp.asarray(_gaussian_kernel(k))


@pytest.mark.parametrize("shape,ksize", [
    ((2, 4, 7, 7), 9),      # default 9x9 gaussian, kernel > image half
    ((1, 3, 12, 10), 5),
    ((2, 1, 6, 6), 4),      # EVEN kernel: asymmetric SAME pads
])
def test_subtractive_norm_parity(shape, ksize, monkeypatch):
    x = jnp.asarray(_rng(4).randn(*shape).astype(np.float32))
    _both_legs(lambda a: subtractive_norm(a, _gauss(ksize)), x,
               monkeypatch=monkeypatch)


@pytest.mark.parametrize("shape,ksize", [
    ((2, 4, 7, 7), 9),
    ((1, 2, 9, 11), 5),
])
def test_divisive_norm_parity(shape, ksize, monkeypatch):
    x = jnp.asarray(_rng(5).randn(*shape).astype(np.float32))
    _both_legs(lambda a: divisive_norm(a, _gauss(ksize)), x,
               monkeypatch=monkeypatch)


def test_contrastive_norm_parity(monkeypatch):
    x = jnp.asarray(_rng(6).randn(2, 4, 7, 7).astype(np.float32))
    _both_legs(lambda a: contrastive_norm(a, _gauss(9)), x,
               monkeypatch=monkeypatch)


def test_smoothing_kernel_gets_zero_cotangent(monkeypatch):
    """The smoothing kernel is a BUFFER (never trained): its cotangent
    is zero by contract on both legs."""
    x = jnp.asarray(_rng(7).randn(1, 2, 5, 5).astype(np.float32))
    k = _gauss(3)
    for mode in ("xla", "pallas"):
        monkeypatch.setenv("BIGDL_KERNELS", mode)
        _, vjp = jax.vjp(lambda a, w: subtractive_norm(a, w), x, k)
        _, dk = vjp(jnp.ones((1, 2, 5, 5), jnp.float32))
        assert float(jnp.max(jnp.abs(dk))) == 0.0


# ---------------------------------------------------------------------------
# parity: pooling (tie-split + Torch-divisor average)
# ---------------------------------------------------------------------------

def _full(k, s, p):
    return ((1, 1) + k, (1, 1) + s, ((0, 0), (0, 0)) + p)


POOL_CASES = [
    # (shape, k, s, pads) — incl. ceil-overflow + anisotropic edges
    ((2, 3, 9, 9), (3, 3), (2, 2), ((1, 1), (1, 1))),
    ((2, 3, 9, 9), (3, 3), (2, 2), ((1, 2), (1, 2))),   # ceil overflow
    ((1, 2, 7, 8), (3, 2), (2, 3), ((1, 0), (0, 1))),   # anisotropic
    ((1, 1, 6, 6), (3, 3), (1, 1), ((0, 0), (0, 0))),   # stride-1 overlap
    ((1, 2, 11, 11), (2, 2), (2, 2), ((0, 1), (0, 1))),  # residue shortfall
]


@pytest.mark.parametrize("shape,k,s,p", POOL_CASES)
@pytest.mark.parametrize("tie_heavy", [False, True])
def test_maxpool_tie_split_parity(shape, k, s, p, tie_heavy, monkeypatch):
    x = _rng(8).randn(*shape).astype(np.float32)
    if tie_heavy:  # quantize to force equal maxima inside windows
        x = np.round(x * 2.0) / 2.0
    dims, strides, pads = _full(k, s, p)
    _both_legs(lambda a: maxpool_tie_split(a, dims, strides, pads),
               jnp.asarray(x), monkeypatch=monkeypatch)


@pytest.mark.parametrize("shape,k,s,p", POOL_CASES)
@pytest.mark.parametrize("count_include_pad", [True, False])
def test_avg_pool_parity(shape, k, s, p, count_include_pad, monkeypatch):
    x = jnp.asarray(_rng(9).randn(*shape).astype(np.float32))
    dims, strides, pads = _full(k, s, p)
    # declared padding below the ceil-overflow hi — the Torch divisor
    # subtlety the op must reproduce on both legs
    declared = ((0, 0), (0, 0)) \
        + tuple((lo, min(hi, lo)) for lo, hi in p)
    _both_legs(lambda a: avg_pool(a, dims, strides, pads, declared,
                                  count_include_pad, True), x,
               monkeypatch=monkeypatch)


def test_tie_split_conserves_gradient_mass(monkeypatch):
    """Equal-split semantics: summed input gradient == summed output
    gradient regardless of ties (mass conservation), on both legs."""
    x = jnp.asarray(np.ones((1, 1, 4, 4), np.float32))  # ALL ties
    dims, strides, pads = _full((2, 2), (2, 2), ((0, 0), (0, 0)))
    for mode in ("xla", "pallas"):
        monkeypatch.setenv("BIGDL_KERNELS", mode)
        _, vjp = jax.vjp(
            lambda a: maxpool_tie_split(a, dims, strides, pads), x)
        gy = jnp.asarray(_rng(10).randn(1, 1, 2, 2).astype(np.float32))
        (dx,) = vjp(gy)
        np.testing.assert_allclose(float(jnp.sum(dx)),
                                   float(jnp.sum(gy)), rtol=1e-6)
        # each of the 4 tied positions gets exactly a quarter
        np.testing.assert_allclose(np.asarray(dx)[0, 0, :2, :2],
                                   np.asarray(gy)[0, 0, 0, 0] / 4.0,
                                   rtol=1e-6)


def test_cross_map_lrn_rank5_and_nhwc(monkeypatch):
    """Rank-5 inputs keep the generic reduce_window reference (review
    r6 finding: the op-routing rewrite briefly dropped it) and NHWC
    matches NCHW through the native-layout reference leg — with the
    exact VJP, no relayout transposes."""
    import bigdl_tpu.nn as nn

    layer = nn.SpatialCrossMapLRN(3, 0.001, 0.75)
    x5 = jnp.asarray(_rng(20).randn(2, 3, 4, 5, 5).astype(np.float32))
    y5 = layer.update_output(x5)
    assert y5.shape == x5.shape

    x = jnp.asarray(_rng(21).randn(2, 6, 5, 5).astype(np.float32))
    nchw = nn.SpatialCrossMapLRN(5, 1e-4, 0.75)
    nhwc = nn.SpatialCrossMapLRN(5, 1e-4, 0.75, format="NHWC")
    y_c, vjp_c = jax.vjp(nchw.update_output, x)
    y_l, vjp_l = jax.vjp(nhwc.update_output, jnp.transpose(x, (0, 2, 3, 1)))
    np.testing.assert_allclose(np.asarray(y_c),
                               np.asarray(jnp.transpose(y_l, (0, 3, 1, 2))),
                               rtol=1e-5, atol=1e-6)
    gy = jnp.asarray(_rng(22).randn(*y_c.shape).astype(np.float32))
    np.testing.assert_allclose(
        np.asarray(vjp_c(gy)[0]),
        np.asarray(jnp.transpose(
            vjp_l(jnp.transpose(gy, (0, 2, 3, 1)))[0], (0, 3, 1, 2))),
        rtol=1e-4, atol=1e-5)
    # and no transpose ops in the NHWC forward HLO (native layout)
    hlo = jax.jit(nhwc.update_output).lower(
        jnp.transpose(x, (0, 2, 3, 1))).as_text()
    assert "transpose" not in hlo


def test_pool_nonstandard_rank_uses_xla_leg(monkeypatch):
    """5-D volumetric windows have no Pallas kernel — the op must fall
    back (and record it) rather than fail."""
    monkeypatch.setenv("BIGDL_KERNELS", "pallas")
    dispatch.clear_decisions()
    x = jnp.asarray(_rng(11).randn(1, 2, 4, 6, 6).astype(np.float32))
    d5, s5, p5 = (1, 1, 2, 2, 2), (1, 1, 2, 2, 2), ((0, 0),) * 5
    y, vjp = jax.vjp(lambda a: maxpool_tie_split(a, d5, s5, p5), x)
    vjp(jnp.ones_like(y))
    recs = [r for r in dispatch.decisions()
            if r[0].startswith("pool_tie_split")]
    assert recs and all(b == "xla" and reason == "unsupported-shape"
                        for _, b, reason in recs)


# ---------------------------------------------------------------------------
# dispatch contract
# ---------------------------------------------------------------------------

def test_bad_kernel_mode_raises(monkeypatch):
    monkeypatch.setenv("BIGDL_KERNELS", "palas")
    with pytest.raises(ValueError, match="BIGDL_KERNELS"):
        dispatch.kernel_mode()


def test_xla_mode_bypasses_pallas_everywhere(monkeypatch):
    """BIGDL_KERNELS=xla is the process-wide kill switch: drive every
    kernel-library layer fwd+bwd and assert not one Pallas decision."""
    import bigdl_tpu.nn as nn
    from bigdl_tpu.ops.pooling_pallas import pallas_pool_supported
    from bigdl_tpu.utils.rng import RNG

    monkeypatch.setenv("BIGDL_KERNELS", "xla")
    dispatch.clear_decisions()
    RNG.set_seed(0)
    layers = [
        nn.SpatialCrossMapLRN(5, 1e-4, 0.75),
        nn.SpatialWithinChannelLRN(3, 0.01, 0.75),
        nn.SpatialSubtractiveNormalization(4),
        nn.SpatialDivisiveNormalization(4),
        nn.SpatialContrastiveNormalization(4),
        nn.SpatialMaxPooling(3, 3, 2, 2, 1, 1).split_ties(),
        nn.SpatialAveragePooling(3, 3, 2, 2, 1, 1, ceil_mode=True),
    ]
    x = jnp.asarray(_rng(12).randn(2, 4, 9, 9).astype(np.float32))
    for layer in layers:
        layer.evaluate()
        y, vjp = jax.vjp(layer.update_output, x)
        vjp(jnp.ones_like(y))
    recs = dispatch.decisions()
    assert recs, "kernel-library layers must record dispatch decisions"
    assert all(b == "xla" for _, b, _ in recs), \
        [r for r in recs if r[1] != "xla"]
    # the argmax-pool support gate honors the same switch: supported
    # under its own opt-in, vetoed the moment BIGDL_KERNELS=xla
    xb = jnp.zeros((2, 4, 8, 8), jnp.bfloat16)
    dims, strides, pads = _full((2, 2), (2, 2), ((0, 0), (0, 0)))
    monkeypatch.setenv("BIGDL_POOL_KERNEL", "interpret")
    monkeypatch.setenv("BIGDL_KERNELS", "auto")
    assert pallas_pool_supported(xb, dims, strides, pads)
    monkeypatch.setenv("BIGDL_KERNELS", "xla")
    assert not pallas_pool_supported(xb, dims, strides, pads)


def test_pallas_mode_forces_kernels(monkeypatch):
    monkeypatch.setenv("BIGDL_KERNELS", "pallas")
    dispatch.clear_decisions()
    x = jnp.asarray(_rng(13).randn(1, 4, 5, 5).astype(np.float32))
    y, vjp = jax.vjp(lambda a: cross_map_lrn(a, 3, 1e-4, 0.75, 1.0), x)
    vjp(jnp.ones_like(y))
    recs = [r for r in dispatch.decisions()
            if r[0].startswith("lrn_cross_map")]
    assert {op for op, _, _ in recs} \
        == {"lrn_cross_map.fwd", "lrn_cross_map.bwd"}
    assert all(b == "pallas" for _, b, _ in recs)


def test_auto_mode_off_tpu_prefers_xla(monkeypatch):
    """auto on the CPU suite = fused XLA (never the slow interpreter);
    the Pallas leg is still reachable via the explicit knob above."""
    monkeypatch.setenv("BIGDL_KERNELS", "auto")
    dispatch.clear_decisions()
    x = jnp.asarray(_rng(14).randn(1, 4, 5, 5).astype(np.float32))
    within_channel_lrn(x, 3, 0.01, 0.75)
    recs = [r for r in dispatch.decisions()
            if r[0] == "lrn_within_channel.fwd"]
    assert recs and recs[-1][1] == "xla" \
        and recs[-1][2] == "auto:off-tpu"


def test_dispatch_emits_telemetry_instant(tmp_path, monkeypatch):
    """Decisions are observable: a run log carries schema-valid
    kernel/dispatch instants naming op + backend."""
    from bigdl_tpu import telemetry
    from bigdl_tpu.telemetry import schema

    monkeypatch.setenv("BIGDL_KERNELS", "xla")
    telemetry.start_run(str(tmp_path))
    try:
        x = jnp.asarray(_rng(15).randn(1, 3, 5, 5).astype(np.float32))
        cross_map_lrn(x, 3, 1e-4, 0.75, 1.0)
    finally:
        telemetry.end_run()
    logs = list(tmp_path.glob("*.jsonl"))
    assert len(logs) == 1
    events, errors = schema.read_events(str(logs[0]))
    assert not errors
    inst = [e for e in events if e.get("name") == "kernel/dispatch"]
    assert inst and inst[0]["op"] == "lrn_cross_map.fwd" \
        and inst[0]["backend"] == "xla"
    assert not schema.validate_events(events)


def test_attention_routing_shares_predicate(monkeypatch):
    """BIGDL_KERNELS routes the attention auto-backend too, and
    bench.py's MFU correction reads the SAME predicate."""
    from bigdl_tpu.ops.attention import flash_auto, select_attention_backend

    monkeypatch.setenv("BIGDL_KERNELS", "xla")
    assert select_attention_backend(4096, 4096) \
        == ("dense", "forced:BIGDL_KERNELS=xla")
    assert not flash_auto(4096, 4096)
    monkeypatch.setenv("BIGDL_KERNELS", "pallas")
    assert select_attention_backend(64, 64)[0] == "flash"
    assert select_attention_backend(64, 64, masked=True)[0] == "dense"
    monkeypatch.setenv("BIGDL_KERNELS", "auto")
    # off-TPU auto is always dense (this suite runs on CPU)
    assert select_attention_backend(4096, 4096)[0] == "dense"


def test_mha_auto_backend_records_dispatch(monkeypatch):
    import bigdl_tpu.nn as nn
    from bigdl_tpu.utils.rng import RNG

    monkeypatch.setenv("BIGDL_KERNELS", "pallas")
    dispatch.clear_decisions()
    RNG.set_seed(0)
    mha = nn.MultiHeadAttention(16, 2, causal=True)
    mha.evaluate()
    x = jnp.asarray(_rng(16).randn(2, 8, 16).astype(np.float32))
    y = mha.forward(x)
    assert y.shape == (2, 8, 16)
    recs = [r for r in dispatch.decisions() if r[0] == "attention"]
    assert recs and recs[-1][1] == "pallas" \
        and recs[-1][2] == "forced:BIGDL_KERNELS=pallas"
