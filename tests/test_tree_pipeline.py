"""BinaryTreeLSTM, Nms, and the DLEstimator/DLClassifier pipeline
adapters."""

import jax
import jax.numpy as jnp
import numpy as np

import bigdl_tpu.nn as nn


def _tiny_tree():
    """5-node tree: nodes 1,2 leaves (emb 1,2); node 3 = (1,2);
    node 4 leaf (emb 3); node 5 = root (3,4)."""
    tree = np.zeros((5, 3), np.int32)
    tree[0] = (0, 0, 1)
    tree[1] = (0, 0, 2)
    tree[2] = (1, 2, 0)
    tree[3] = (0, 0, 3)
    tree[4] = (3, 4, 0)
    return tree


def _reference_forward(m, emb, tree):
    """Host-side recursion oracle (the reference's recursiveForward)."""
    def rec(i):
        left, right, leaf = tree[i - 1]
        if left == 0 and right == 0:
            return m._leaf(emb[leaf - 1])
        lc, lh = rec(left)
        rc, rh = rec(right)
        return m._compose(lc, lh, rc, rh)

    states = {}
    for i in range(1, tree.shape[0] + 1):
        if np.any(tree[i - 1] != 0):
            states[i] = rec(i)
    return states


def test_binary_tree_lstm_matches_recursion_oracle():
    from bigdl_tpu.utils.rng import RNG

    RNG.set_seed(0)
    m = nn.BinaryTreeLSTM(4, 6)
    rng = np.random.RandomState(0)
    emb = jnp.asarray(rng.randn(1, 3, 4), jnp.float32)
    tree = _tiny_tree()[None]
    out = m.forward((emb, jnp.asarray(tree)))
    assert out.shape == (1, 5, 6)
    oracle = _reference_forward(m, emb[0], tree[0])
    for i, (c, h) in oracle.items():
        np.testing.assert_allclose(np.asarray(out[0, i - 1]),
                                   np.asarray(h), rtol=1e-5, atol=1e-5)


def test_binary_tree_lstm_trains_under_jit():
    from bigdl_tpu.nn.module import functional_call, state_dict
    from bigdl_tpu.utils.rng import RNG

    RNG.set_seed(1)
    m = nn.BinaryTreeLSTM(4, 6)
    rng = np.random.RandomState(1)
    emb = jnp.asarray(rng.randn(2, 3, 4), jnp.float32)
    trees = jnp.asarray(np.stack([_tiny_tree(), _tiny_tree()]))
    params = state_dict(m, kind="param")

    @jax.jit
    def loss(p):
        out, _ = functional_call(m, p, (emb, trees))
        return jnp.sum(out[:, -1, :] ** 2)  # root hidden state

    grads = jax.grad(loss)(params)
    assert set(grads) == set(params)
    nz = [k for k, g in grads.items() if float(jnp.max(jnp.abs(g))) > 0]
    assert any("comp_" in k for k in nz) and any("leaf_" in k for k in nz)


def test_nms():
    boxes = jnp.asarray([
        [0, 0, 10, 10],
        [1, 1, 10.5, 10.5],   # heavy overlap with box 0
        [20, 20, 30, 30],
        [100, 100, 110, 110],
    ], jnp.float32)
    scores = jnp.asarray([0.9, 0.95, 0.8, 0.1])
    keep, count = nn.Nms(threshold=0.5, max_output=4).forward((boxes, scores))
    assert int(count) == 3
    kept = sorted(int(i) for i in np.asarray(keep)[:int(count)])
    assert kept == [1, 2, 3]  # box 0 suppressed by higher-scoring box 1


def test_dl_classifier_pipeline():
    import bigdl_tpu.optim as optim
    from bigdl_tpu.pipeline import DLClassifier
    from bigdl_tpu.utils.rng import RNG

    RNG.set_seed(2)
    rng = np.random.RandomState(2)
    X = rng.randn(128, 4).astype(np.float32)
    y = (X[:, 0] + X[:, 1] > 0).astype(np.int64)
    model = nn.Sequential(nn.Linear(4, 16), nn.Tanh(), nn.Linear(16, 2),
                          nn.LogSoftMax())
    est = DLClassifier(model, nn.ClassNLLCriterion(), [4]) \
        .set_batch_size(32).set_max_epoch(30) \
        .set_optim_method(optim.SGD(learning_rate=0.5))
    fitted = est.fit(X, y)
    pred = fitted.transform(X)
    assert pred.shape == (128,)
    assert (pred == y).mean() > 0.9


def test_dl_estimator_passthrough_options():
    """The estimator must forward mesh / end-trigger / validation /
    summary / optim-method choices to the Optimizer instead of hardcoding
    defaults (``DLEstimator.scala`` param surface)."""
    import bigdl_tpu.optim as optim
    from bigdl_tpu.parallel.mesh import make_mesh
    from bigdl_tpu.pipeline import DLClassifier
    from bigdl_tpu.utils.rng import RNG

    class FakeSummary:
        def __init__(self):
            self.tags = []

        def add_scalar(self, tag, value, step):
            self.tags.append(tag)

    RNG.set_seed(4)
    rng = np.random.RandomState(4)
    X = rng.randn(128, 4).astype(np.float32)
    y = (X[:, 0] - X[:, 2] > 0).astype(np.int64)
    model = nn.Sequential(nn.Linear(4, 16), nn.Tanh(), nn.Linear(16, 2),
                          nn.LogSoftMax())
    ts, vs = FakeSummary(), FakeSummary()
    est = DLClassifier(model, nn.ClassNLLCriterion(), [4]) \
        .set_batch_size(32) \
        .set_optim_method(optim.SGD(learning_rate=0.5)) \
        .set_mesh(make_mesh()) \
        .set_end_trigger(optim.Trigger.max_iteration(40)) \
        .set_validation(optim.Trigger.several_iteration(10), X, y,
                        [optim.Top1Accuracy()]) \
        .set_train_summary(ts).set_validation_summary(vs)
    fitted = est.fit(X, y)
    pred = fitted.transform(X)
    assert (pred == y).mean() > 0.9
    assert "Loss" in ts.tags
    assert "Top1Accuracy" in vs.tags


def test_dl_estimator_regression():
    import bigdl_tpu.optim as optim
    from bigdl_tpu.pipeline import DLEstimator
    from bigdl_tpu.utils.rng import RNG

    RNG.set_seed(3)
    rng = np.random.RandomState(3)
    X = rng.randn(128, 3).astype(np.float32)
    y = (X @ np.array([1.0, -2.0, 0.5], np.float32))[:, None]
    est = DLEstimator(nn.Sequential(nn.Linear(3, 1)), nn.MSECriterion(),
                      [3], [1]).set_batch_size(32).set_max_epoch(40) \
        .set_optim_method(optim.SGD(learning_rate=0.1))
    fitted = est.fit(X, y)
    pred = fitted.transform(X)
    assert float(np.mean((pred - y) ** 2)) < 0.05
