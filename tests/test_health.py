"""Training-health monitor + live metrics export + run-regression diff
(ISSUE 3): seeded-divergence runs must produce ``health/*`` events, trip
the policy (warn / skip-step / halt with ``HealthError``), and leave a
schema-valid run log; the OpenMetrics endpoint must serve parseable text
during a live run; ``telemetry diff`` must flag a slowed run and exit
nonzero."""

import glob
import json
import os
import re
import urllib.request

import numpy as np
import pytest

import bigdl_tpu.nn as nn
import bigdl_tpu.optim as optim
from bigdl_tpu import telemetry
from bigdl_tpu.dataset.minibatch import MiniBatch
from bigdl_tpu.dataset.sample import Sample
from bigdl_tpu.dataset.transformer import Transformer
from bigdl_tpu.optim.trigger import Trigger
from bigdl_tpu.telemetry import schema
from bigdl_tpu.telemetry.health import (HealthError, HealthPolicy,
                                        LossEwma, probe_stats)
from bigdl_tpu.utils.config import BigDLConfig, set_config


def teardown_function(_fn):
    telemetry.end_run()
    set_config(None)


def _samples(n=64, dim=4, seed=0):
    rng = np.random.default_rng(seed)
    return [Sample(rng.normal(size=dim).astype(np.float32),
                   np.int64(i % 2)) for i in range(n)]


def _mlp(dim=4):
    from bigdl_tpu.utils.rng import RNG

    RNG.set_seed(7)
    return nn.Sequential(nn.Linear(dim, 8), nn.Tanh(), nn.Linear(8, 2),
                         nn.LogSoftMax())


class PoisonAt(Transformer):
    """Replace every batch input with NaN from batch index ``at`` on —
    the seeded divergence (a corrupt shard, a bad augmentation)."""

    def __init__(self, at: int):
        self.at = at

    def apply(self, it):
        for i, batch in enumerate(it):
            if i >= self.at:
                batch = MiniBatch(
                    [np.full_like(a, np.nan) for a in batch.inputs],
                    list(batch.targets) or None)
            yield batch


def _poisoned_optimizer(at=2, iters=20, **policy_kw):
    from bigdl_tpu.dataset.dataset import DataSet
    from bigdl_tpu.dataset.transformer import SampleToMiniBatch

    ds = DataSet.array(_samples()).transform(
        SampleToMiniBatch(16)).transform(PoisonAt(at))
    o = optim.LocalOptimizer(_mlp(), ds, nn.ClassNLLCriterion(),
                             batch_size=16,
                             end_trigger=Trigger.max_iteration(iters))
    o.set_optim_method(optim.SGD(learning_rate=0.1))
    if policy_kw:
        o.set_health_policy(HealthPolicy(**policy_kw))
    return o


# -- probe + policy units ----------------------------------------------------
def test_probe_stats_decodes_vector():
    stats = probe_stats([3.0, 4.0, 2.0, 0.0, 0.0], 0.5)
    assert stats["grad_norm"] == 3.0
    assert stats["update_ratio"] == pytest.approx(0.5)
    assert stats["nonfinite_grads"] == 0 and stats["loss"] == 0.5
    bad = probe_stats([float("nan"), 1.0, 0.0, 5.0, 2.0], float("nan"))
    assert bad["nonfinite_grads"] == 5 and bad["nonfinite_params"] == 2


def test_loss_ewma_detects_spike_not_noise():
    det = LossEwma(alpha=0.1, spike_factor=4.0, warmup=5)
    rng = np.random.default_rng(0)
    for i in range(30):  # gentle noise: no findings
        assert det.update(i, 1.0 + 0.01 * rng.normal()) == []
    findings = det.update(30, 50.0)
    assert [n for n, _ in findings] == ["health/loss_spike"]
    assert findings[0][1]["step"] == 30
    # nonfinite losses bypass the EWMA entirely
    assert det.update(31, float("nan")) == []


def test_loss_ewma_detects_plateau_once():
    det = LossEwma(alpha=0.5, warmup=2, plateau_patience=4,
                   plateau_rtol=1e-3)
    names = []
    for i in range(20):
        names += [n for n, _ in det.update(i, 1.0)]
    assert names.count("health/plateau") == 1  # once per plateau


def test_policy_escalation_and_halt_trigger():
    pol = HealthPolicy(on_nonfinite="halt", halt_after=2)
    finite = probe_stats([1.0, 1.0, 0.1, 0, 0], 0.5)
    nonfinite = probe_stats([float("inf"), 1.0, 0.1, 3, 0], float("nan"))
    assert pol.observe(1, finite)[0] == "ok"
    action, findings = pol.observe(2, nonfinite)
    assert action == "warn"
    assert any(n == "health/nonfinite" for n, _ in findings)
    action, findings = pol.observe(3, nonfinite)
    assert action == "halt"
    assert any(n == "health/halt" for n, _ in findings)
    # a finite step resets the consecutive counter
    pol2 = HealthPolicy(on_nonfinite="halt", halt_after=2)
    pol2.observe(1, nonfinite)
    pol2.observe(2, finite)
    assert pol2.observe(3, nonfinite)[0] == "warn"
    # custom Trigger-style predicate: halt on TOTAL nonfinite steps
    pol3 = HealthPolicy(
        on_nonfinite="warn",
        halt_when=Trigger(lambda s: s["nonfinite_steps"] >= 2))
    pol3.observe(1, nonfinite)
    assert pol3.observe(5, nonfinite)[0] == "halt"


def test_policy_rejects_bad_config():
    with pytest.raises(ValueError, match="on_nonfinite"):
        HealthPolicy(on_nonfinite="explode")
    with pytest.raises(ValueError, match="halt_after"):
        HealthPolicy(halt_after=0)


def test_user_policy_state_is_fresh_per_run_attempt():
    """A user-installed policy is config; its running counters/EWMA must
    start pristine on every run attempt (checkpoint-restore retries,
    repeated optimize() calls) — and the user's object is never
    mutated."""
    pol = HealthPolicy(on_nonfinite="halt", halt_after=2)
    for _ in range(2):  # second optimize() halts at the same step
        o = _poisoned_optimizer(at=0, iters=10)
        o.set_health_policy(pol)
        with pytest.raises(HealthError) as exc:
            o.optimize()
        assert exc.value.step == 2
    assert pol.state["consecutive_nonfinite"] == 0
    assert pol.state["nonfinite_steps"] == 0


def test_invalid_health_env_fails_fast_not_retried():
    """A BIGDL_HEALTH typo is a config error: it must raise before the
    checkpoint-restore retry loop, not burn the retry budget on it."""
    import time as _time

    set_config(BigDLConfig(health_action="hal",  # typo
                           failure_retry_times=5,
                           failure_retry_interval=60.0))
    o = _poisoned_optimizer(at=100, iters=1)
    t0 = _time.perf_counter()
    with pytest.raises(ValueError, match="on_nonfinite"):
        o.optimize()
    assert _time.perf_counter() - t0 < 5.0  # no retries, no training


# -- seeded divergence end-to-end --------------------------------------------
def test_nan_run_halts_with_health_error_and_valid_log(tmp_path):
    """The acceptance path: a run that NaNs at a known step must emit
    ``health/nonfinite`` events, halt with HealthError carrying the
    evidence, never burn the retry budget, and leave a schema-valid
    run log."""
    tele_dir = str(tmp_path / "tele")
    set_config(BigDLConfig(telemetry_dir=tele_dir, health_action="halt",
                           health_halt_after=2, failure_retry_times=3,
                           failure_retry_interval=60.0))
    o = _poisoned_optimizer(at=2)  # first NaN batch -> step 3
    with pytest.raises(HealthError) as exc:
        o.optimize()
    err = exc.value
    assert err.step == 4  # halt_after=2 consecutive nonfinite steps
    assert err.evidence["nonfinite_grads"] > 0
    assert err.evidence["consecutive_nonfinite"] == 2
    assert not telemetry.enabled(), "owned run must end on halt"

    runs = glob.glob(os.path.join(tele_dir, "run-*.jsonl"))
    assert len(runs) == 1, "halt must not be retried (one run, one log)"
    n, errors = schema.validate_run(runs[0])
    assert errors == [] and n > 10
    events, _ = schema.read_events(runs[0])
    probes = [e for e in events if e["kind"] == "health"]
    assert len(probes) == 4 and probes[0]["step"] == 1
    assert all(k in probes[0] for k in
               ("grad_norm", "update_ratio", "nonfinite_grads"))
    names = [e["name"] for e in events if e["kind"] == "event"]
    assert names.count("health/nonfinite") == 2
    assert names.count("health/halt") == 1
    assert "run/retry" not in names, "HealthError must bypass the retry loop"


def test_skip_policy_keeps_params_finite_and_completes():
    from bigdl_tpu.nn.module import state_dict

    sink = telemetry.MemorySink()
    o = _poisoned_optimizer(at=3, iters=8, on_nonfinite="skip",
                            halt_after=100)
    with telemetry.run(sinks=[sink]):
        model = o.optimize()  # completes: poisoned updates never land
    for k, v in state_dict(model).items():
        assert np.isfinite(np.asarray(v)).all(), k
    names = [e["name"] for e in sink.events if e["kind"] == "event"]
    assert names.count("health/skip") == 5  # steps 4..8 all skipped
    assert schema.validate_events(sink.events) == []


def test_warn_policy_never_halts():
    o = _poisoned_optimizer(at=2, iters=6, on_nonfinite="warn")
    o.optimize()  # diverged, warned, completed


def test_health_off_disables_probes():
    set_config(BigDLConfig(health_action="off"))
    o = _poisoned_optimizer(at=2, iters=4)
    sink = telemetry.MemorySink()
    with telemetry.run(sinks=[sink]):
        o.optimize()
    assert not [e for e in sink.events if e["kind"] == "health"]


def test_health_scalars_reach_train_summary(tmp_path):
    from bigdl_tpu.visualization import TrainSummary

    ts = TrainSummary(str(tmp_path), "app")
    o = _poisoned_optimizer(at=100, iters=4, on_nonfinite="warn")
    o.set_train_summary(ts)
    o.optimize()
    rows = ts.read_scalar("health/grad_norm")
    assert [int(r[0]) for r in rows] == [1, 2, 3, 4]
    ts.close()


# -- live metrics endpoint ---------------------------------------------------
_SAMPLE_RE = re.compile(
    r"^[a-zA-Z_:][a-zA-Z0-9_:]*(\{[^}]*\})? (NaN|[+-]Inf|[-+0-9.eE]+)$")


def test_metrics_endpoint_serves_openmetrics_during_run():
    set_config(BigDLConfig(metrics_port=0))
    sink = telemetry.MemorySink()
    with telemetry.run(sinks=[sink]):
        server = telemetry.metrics_server()
        assert server is not None and server.port > 0
        telemetry.emit("step", step=3, dur=0.01, loss=0.5, records=16,
                       throughput=1600.0, epoch=1)
        telemetry.emit("health", step=3, grad_norm=1.5, param_norm=2.0,
                       update_norm=0.1, update_ratio=0.05,
                       nonfinite_grads=0, nonfinite_params=0, loss=0.5)
        telemetry.counter("records", 16)
        telemetry.gauge("prefetch/queue_depth", 2)
        base = f"http://127.0.0.1:{server.port}"
        text = urllib.request.urlopen(base + "/metrics",
                                      timeout=5).read().decode()
        lines = [ln for ln in text.splitlines() if ln]
        assert lines[-1] == "# EOF"
        samples = [ln for ln in lines if not ln.startswith("#")]
        assert samples, text
        for ln in samples:  # every sample line is exposition-parseable
            assert _SAMPLE_RE.match(ln), ln
        by_name = {ln.split("{")[0]: ln for ln in samples}
        assert 'process_index="0"' in by_name["bigdl_step"]
        assert by_name["bigdl_step"].endswith(" 3")
        assert by_name["bigdl_loss"].endswith(" 0.5")
        assert "bigdl_health_grad_norm" in by_name
        assert "bigdl_prefetch_queue_depth" in by_name
        assert by_name["bigdl_records_total"].endswith(" 16")

        status = json.loads(urllib.request.urlopen(
            base + "/status", timeout=5).read())
        assert status["step"]["step"] == 3
        assert status["health"]["grad_norm"] == 1.5
        ok = json.loads(urllib.request.urlopen(
            base + "/healthz", timeout=5).read())
        assert ok == {"ok": True}
        assert urllib.request.urlopen(base + "/metrics",
                                      timeout=5).status == 200
    # run ended -> endpoint torn down
    assert telemetry.metrics_server() is None
    with pytest.raises(Exception):
        urllib.request.urlopen(f"http://127.0.0.1:{server.port}/healthz",
                               timeout=1)


def test_metrics_endpoint_off_by_default():
    with telemetry.run(sinks=[telemetry.MemorySink()]):
        assert telemetry.metrics_server() is None


# -- cli end-to-end (acceptance shape) ---------------------------------------
def test_cli_train_divergence_halts_with_metrics_port(tmp_path,
                                                      monkeypatch,
                                                      capsys):
    """``cli train lenet --telemetry <dir> --metrics-port 0`` on a
    diverging run (lr so large the first update overflows float32)
    halts with HealthError and leaves a schema-valid log containing
    health events."""
    from bigdl_tpu.models import cli as models_cli

    tele_dir = str(tmp_path / "tele")
    monkeypatch.setenv("BIGDL_HEALTH_HALT_AFTER", "2")
    # the cli writes --telemetry/--metrics-port into os.environ; seed
    # them via monkeypatch so the mutation is UNDONE after this test
    monkeypatch.setenv("BIGDL_TELEMETRY", tele_dir)
    monkeypatch.setenv("BIGDL_METRICS_PORT", "0")
    with pytest.raises(HealthError) as exc:
        models_cli.main(["train", "--model", "lenet", "-b", "256",
                         "--max-epoch", "1", "--learning-rate", "1e40",
                         "--telemetry", tele_dir, "--metrics-port", "0"])
    capsys.readouterr()
    assert exc.value.evidence["nonfinite_params"] > 0
    runs = glob.glob(os.path.join(tele_dir, "run-*.jsonl"))
    assert len(runs) == 1
    n, errors = schema.validate_run(runs[0])
    assert errors == [], errors[:5]
    events, _ = schema.read_events(runs[0])
    names = [e["name"] for e in events if e["kind"] == "event"]
    assert "health/halt" in names
    assert any(e["kind"] == "health" for e in events)
    # the endpoint came up on an ephemeral port and announced itself
    serving = [e for e in events if e.get("name") == "metrics/serving"]
    assert serving and serving[0]["port"] > 0


# -- regression diff ---------------------------------------------------------
def _write_run(path, dur, steps=10, pidx=0, health_events=0):
    with telemetry.run(str(path), meta={"process_index": pidx}):
        tr = telemetry.get()
        for i in range(1, steps + 1):
            sid = tr.begin("train/iteration", step=i)
            tr.emit("step", step=i, dur=dur, loss=1.0 / i, records=16,
                    throughput=16.0 / dur)
            tr.end(sid)
        for _ in range(health_events):
            telemetry.instant("health/nonfinite", step=1)


def test_diff_flags_slowed_run_nonzero(tmp_path, capsys):
    from bigdl_tpu.telemetry import __main__ as cli

    fast, slow = tmp_path / "fast.jsonl", tmp_path / "slow.jsonl"
    _write_run(fast, 0.010)
    _write_run(slow, 0.016)
    rc = cli.main(["diff", str(fast), str(slow)])
    out = capsys.readouterr().out
    assert rc == 1, out
    assert "REGRESSED" in out and "step_p50_s" in out
    # same run against itself: clean
    assert cli.main(["diff", str(fast), str(fast)]) == 0
    # improvements never flag
    assert cli.main(["diff", str(slow), str(fast)]) == 0
    # fresh health events are a regression regardless of speed
    sick = tmp_path / "sick.jsonl"
    _write_run(sick, 0.010, health_events=2)
    assert cli.main(["diff", str(fast), str(sick)]) == 1
    out = capsys.readouterr().out
    assert "health_events" in out


def test_diff_threshold_and_json(tmp_path, capsys):
    from bigdl_tpu.telemetry import __main__ as cli

    a, b = tmp_path / "a.jsonl", tmp_path / "b.jsonl"
    _write_run(a, 0.010)
    _write_run(b, 0.011)  # +10%: inside a 25% threshold
    assert cli.main(["diff", str(a), str(b),
                     "--threshold-pct", "25"]) == 0
    capsys.readouterr()  # drop the table view
    rc = cli.main(["diff", str(a), str(b), "--json"])
    doc = json.loads(capsys.readouterr().out)
    assert {"a", "b", "rows"} <= set(doc)
    assert any(r["name"] == "step_p50_s" for r in doc["rows"])
    assert rc in (0, 1)
    # machine-readable verdict (CI consumes the payload, not the table):
    # the verdict/exit code travel IN the JSON and agree with the rc
    assert doc["verdict"] == ("regressed" if rc == 1 else "ok")
    assert doc["exit_code"] == rc
    assert doc["regressions"] == sum(r["regressed"] for r in doc["rows"])
    assert doc["compared"] == len(doc["rows"])
    assert doc["threshold_pct"] == 10.0 and doc["count_slack"] == 0


def test_diff_json_verdict_covers_all_exit_codes(tmp_path, capsys):
    from bigdl_tpu.telemetry import __main__ as cli

    fast, slow = tmp_path / "fast.jsonl", tmp_path / "slow.jsonl"
    _write_run(fast, 0.010)
    _write_run(slow, 0.016)
    assert cli.main(["diff", str(fast), str(slow), "--json"]) == 1
    assert json.loads(capsys.readouterr().out)["verdict"] == "regressed"
    assert cli.main(["diff", str(fast), str(fast), "--json"]) == 0
    assert json.loads(capsys.readouterr().out)["verdict"] == "ok"
    bare = tmp_path / "bare.json"  # bench doc with no metrics at all
    bare.write_text("{}")
    assert cli.main(["diff", str(fast), str(bare), "--json"]) == 2
    out = capsys.readouterr().out
    assert json.loads(out)["verdict"] == "not_comparable"


def test_diff_zero_baseline_still_regresses():
    """0 -> worse is an infinite pct change: it must flag, not slip
    through the pct threshold as 'no delta_pct computable'."""
    from bigdl_tpu.telemetry.diff import diff_metrics

    rows = diff_metrics({"data_wait_share": 0.0},
                        {"data_wait_share": 0.5})
    assert rows[0]["regressed"]
    rows = diff_metrics({"data_wait_share": 0.0},
                        {"data_wait_share": 0.0})
    assert not rows[0]["regressed"]


def test_diff_bench_json_and_missing_file(tmp_path, capsys):
    from bigdl_tpu.telemetry import __main__ as cli

    base = tmp_path / "base.json"
    cand = tmp_path / "cand.json"
    base.write_text(json.dumps({"metric": "m", "configs": {
        "lenet_mnist": {"images_per_sec": 1000.0, "mfu": 0.5}}}))
    cand.write_text(json.dumps({"metric": "m", "configs": {
        "lenet_mnist": {"images_per_sec": 850.0, "mfu": 0.42},
        "broken": {"error": "X"}}}))
    assert cli.main(["diff", str(base), str(cand)]) == 1
    assert "lenet_mnist.images_per_sec" in capsys.readouterr().out
    assert cli.main(["diff", str(base), str(tmp_path / "nope.json")]) == 2


def test_bench_diff_against_flag(tmp_path, monkeypatch, capsys):
    """bench.py --diff-against delegates to the diff engine and exits 4
    on a regression (CI contract)."""
    import bench

    baseline = tmp_path / "baseline.json"
    baseline.write_text(json.dumps({"metric": "m", "configs": {
        "lenet_mnist": {"images_per_sec": 10.0**9}}}))  # unbeatable
    monkeypatch.setenv("BENCH_CONFIGS", "lenet_mnist")
    monkeypatch.setenv("BENCH_ITERS", "2")
    monkeypatch.setenv("BENCH_INFER", "0")
    monkeypatch.setenv("BENCH_WEDGE_TIMEOUT", "0")
    with pytest.raises(SystemExit) as exc:
        bench.main(["--diff-against", str(baseline)])
    assert exc.value.code == 4
    err = capsys.readouterr().err
    assert "REGRESSED" in err


# -- fleet view --------------------------------------------------------------
def test_fleet_view_reports_skew_and_lag(tmp_path, capsys):
    from bigdl_tpu.telemetry import __main__ as cli
    from bigdl_tpu.telemetry.report import fleet_summarize

    p0, p1 = tmp_path / "p0.jsonl", tmp_path / "p1.jsonl"
    _write_run(p0, 0.010, steps=10, pidx=0)
    _write_run(p1, 0.010, steps=8, pidx=1)
    loaded = [(str(p), schema.read_events(str(p))[0]) for p in (p0, p1)]
    fleet = fleet_summarize(loaded)
    assert fleet["step_lag"] == 2
    assert {p["process_index"] for p in fleet["processes"]} == {0, 1}
    assert fleet["skew"]["at_step"] is not None
    rc = cli.main([str(p0), str(p1)])
    out = capsys.readouterr().out
    assert rc == 0
    assert "fleet view (2 processes)" in out
    assert "step lag" in out and "step skew" in out
    # --validate accepts multiple logs too
    assert cli.main([str(p0), str(p1), "--validate"]) == 0


def test_fleet_duplicate_process_index_merges_latest_incarnation(
        tmp_path, capsys):
    """Two logs claiming one process_index are what a supervisor
    restart produces (two incarnations of the same rank): the fleet
    view must keep the LATEST run per rank instead of double-counting
    skew across incarnations — the superseded log is reported, never
    silently dropped."""
    import time as _time

    from bigdl_tpu.telemetry import __main__ as cli
    from bigdl_tpu.telemetry.report import fleet_summarize

    paths = [tmp_path / n for n in ("old_p0.jsonl", "new_p0.jsonl",
                                    "p1.jsonl")]
    _write_run(paths[0], 0.010, steps=3, pidx=0)  # dead incarnation
    _time.sleep(0.05)  # run_start ts orders the incarnations
    _write_run(paths[1], 0.010, steps=5, pidx=0)
    _write_run(paths[2], 0.010, steps=5, pidx=1)
    loaded = [(str(p), schema.read_events(str(p))[0]) for p in paths]
    fleet = fleet_summarize(loaded)
    # one row per RANK, and rank 0's row is the newest incarnation
    assert len(fleet["processes"]) == 2
    by_pidx = {p["process_index"]: p for p in fleet["processes"]}
    assert by_pidx[0]["path"].endswith("new_p0.jsonl")
    assert by_pidx[0]["last_step"] == 5
    assert fleet["step_lag"] == 0  # the dead incarnation's 3 steps
    # don't fake a lag
    assert fleet["superseded"] == [str(paths[0])]
    assert fleet["notes"] and "kept latest" in fleet["notes"][0]
    assert cli.main([str(p) for p in paths]) == 0
    out = capsys.readouterr().out
    assert "note:" in out and "superseded" in out
    assert "WARNING" not in out


def test_schema_accepts_health_kind():
    base = {"v": 1, "ts": 1.0, "pid": 1, "tid": 1}
    assert not schema.validate_event(
        {**base, "kind": "health", "step": 3, "grad_norm": 1.0})
    assert schema.validate_event({**base, "kind": "health"})  # no step
