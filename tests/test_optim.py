"""Optim-method oracle tests vs torch.optim, schedule math, triggers,
validation methods."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
import torch

import bigdl_tpu.optim as optim
from bigdl_tpu.optim.optim_method import (
    Adam, Adadelta, Adagrad, Adamax, Default, Exponential, LBFGS, MultiStep,
    Plateau, Poly, RMSprop, SequentialSchedule, SGD, Step, Warmup,
)


def _run_method(method, torch_cls, torch_kwargs, steps=5, shape=(7,),
                rng=None):
    """Run ours and torch's on the same quadratic problem; compare params."""
    rng = rng or np.random
    w0 = rng.randn(*shape).astype(np.float32)
    target = rng.randn(*shape).astype(np.float32)

    params = {"w": jnp.asarray(w0)}
    state = method.init_state(params)

    tw = torch.tensor(w0.copy(), requires_grad=True)
    topt = torch_cls([tw], **torch_kwargs)
    tt = torch.tensor(target)

    for _ in range(steps):
        grads = {"w": 2.0 * (params["w"] - jnp.asarray(target))}
        params, state = method.update(grads, params, state)
        topt.zero_grad()
        ((tw - tt) ** 2).sum().backward()
        topt.step()
    np.testing.assert_allclose(np.asarray(params["w"]), tw.detach().numpy(),
                               rtol=1e-4, atol=1e-5)


def test_sgd_plain_matches_torch():
    _run_method(SGD(learning_rate=0.1), torch.optim.SGD, {"lr": 0.1})


def test_sgd_momentum_nesterov_weightdecay():
    _run_method(SGD(learning_rate=0.05, momentum=0.9, nesterov=True, weight_decay=0.01),
                torch.optim.SGD, {"lr": 0.05, "momentum": 0.9, "nesterov": True,
                                  "weight_decay": 0.01})


def test_adam_matches_torch():
    _run_method(Adam(learning_rate=0.01), torch.optim.Adam, {"lr": 0.01}, steps=10)


def test_adamax_matches_torch():
    _run_method(Adamax(learning_rate=0.02), torch.optim.Adamax, {"lr": 0.02}, steps=10)


def test_adagrad_matches_torch():
    _run_method(Adagrad(learning_rate=0.05), torch.optim.Adagrad, {"lr": 0.05}, steps=10)


def test_adadelta_matches_torch():
    _run_method(Adadelta(decay_rate=0.9, epsilon=1e-6), torch.optim.Adadelta,
                {"rho": 0.9, "eps": 1e-6, "lr": 1.0}, steps=10)


def test_rmsprop_matches_torch():
    _run_method(RMSprop(learning_rate=0.01, decay_rate=0.99), torch.optim.RMSprop,
                {"lr": 0.01, "alpha": 0.99}, steps=10)


def test_lbfgs_converges_quadratic():
    target = jnp.asarray(np.random.randn(10).astype(np.float32))

    def feval(x):
        return jnp.sum((x - target) ** 2), 2.0 * (x - target)

    x, losses = LBFGS(max_iter=30).optimize(feval, jnp.zeros(10))
    assert losses[-1] < 1e-6
    np.testing.assert_allclose(np.asarray(x), np.asarray(target), atol=1e-3)


# ------------------------------ schedules ---------------------------------

def _st(neval, epoch=1):
    return {"neval": jnp.asarray(neval, jnp.int32), "epoch": jnp.asarray(epoch, jnp.int32)}


def test_schedules_math():
    assert float(Default(0.1).rate(1.0, _st(10))) == pytest.approx(1.0 / 2.0)
    assert float(Poly(0.5, 100).rate(1.0, _st(75))) == pytest.approx(0.5)
    assert float(Step(10, 0.5).rate(1.0, _st(25))) == pytest.approx(0.25)
    assert float(MultiStep([10, 20], 0.1).rate(1.0, _st(15))) == pytest.approx(0.1)
    assert float(Exponential(10, 0.5, staircase=True).rate(1.0, _st(25))) == pytest.approx(0.25)
    w = Warmup(0.01, 10, Step(1000, 1.0))
    assert float(w.rate(0.1, _st(5))) == pytest.approx(0.15)
    assert float(w.rate(0.1, _st(50))) == pytest.approx(0.2)
    seq = SequentialSchedule().add(Poly(1.0, 10), 10).add(Step(1000, 1.0), 10**9)
    assert float(seq.rate(1.0, _st(5))) == pytest.approx(0.5)
    assert float(seq.rate(1.0, _st(15))) == pytest.approx(1.0)


def test_plateau_host_side():
    p = Plateau(factor=0.5, patience=2, mode="min")
    for v in [1.0, 0.9, 0.91, 0.92, 0.93]:
        p.on_metric(v)
    assert p.current_factor == pytest.approx(0.5)


def test_schedule_in_jitted_sgd():
    sgd = SGD(learning_rate=1.0, learning_rate_schedule=Step(2, 0.5))
    params = {"w": jnp.ones(3)}
    state = sgd.init_state(params)

    @jax.jit
    def step(p, s):
        return sgd.update({"w": jnp.ones(3)}, p, s)

    lrs = []
    for _ in range(5):
        before = params["w"]
        params, state = step(params, state)
        lrs.append(float(before[0] - params["w"][0]))
    assert lrs == pytest.approx([1.0, 1.0, 0.5, 0.5, 0.25])


# ------------------------------ triggers ----------------------------------

def test_triggers():
    from bigdl_tpu.optim.trigger import Trigger

    t = Trigger.several_iteration(3)
    fires = [t({"neval": i}) for i in range(1, 10)]
    assert fires == [False, False, True, False, False, True, False, False, True]
    assert Trigger.max_epoch(5)({"epoch": 6})
    assert not Trigger.max_epoch(5)({"epoch": 5})
    assert Trigger.min_loss(0.1)({"loss": 0.05})
    assert Trigger.max_score(0.9)({"score": 0.95})
    e = Trigger.every_epoch()
    assert not e({"epoch": 1, "_epoch_boundary": False})
    assert e({"epoch": 2, "_epoch_boundary": True})
    assert not e({"epoch": 2, "_epoch_boundary": True})  # once per epoch


# ------------------------------ validation --------------------------------

def test_validation_methods():
    out = np.array([[0.1, 0.9, 0.0], [0.8, 0.1, 0.1], [0.2, 0.3, 0.5], [0.9, 0.05, 0.05]])
    target = np.array([1, 0, 0, 0])
    r = optim.Top1Accuracy()(out, target)
    assert r.result()[0] == pytest.approx(0.75)
    r5 = optim.Top5Accuracy()(out, target)
    assert r5.result()[0] == pytest.approx(1.0)
    merged = r + r
    assert merged.result() == (0.75, 8)
    mae = optim.MAE()(out, np.array([1.0, 0.0, 2.0, 0.0]))
    assert mae.result()[0] == pytest.approx(0.0)


def test_regularizers():
    from bigdl_tpu.optim.regularizer import L1L2Regularizer, L1Regularizer, L2Regularizer

    p = jnp.asarray([1.0, -2.0, 3.0])
    np.testing.assert_allclose(np.asarray(L2Regularizer(0.1).grad(p)), 0.1 * np.asarray(p))
    np.testing.assert_allclose(np.asarray(L1Regularizer(0.1).grad(p)),
                               0.1 * np.sign(np.asarray(p)))
    assert float(L1L2Regularizer(0.1, 0.2).loss(p)) == pytest.approx(
        0.1 * 6.0 + 0.5 * 0.2 * 14.0)


@pytest.mark.parametrize("seed", [0, 1, 2])
def test_optim_hyperparameter_fuzz_vs_torch(seed):
    """Randomized-hyperparameter trajectory equivalence vs torch.optim —
    the fixed-config oracles above pin one point each; this sweep walks
    the (lr, momentum, nesterov, dampening, weight-decay, betas, rho...)
    space where update-rule algebra quietly diverges between
    implementations."""
    rng = np.random.RandomState(600 + seed)

    def u(lo, hi):
        return float(rng.uniform(lo, hi))

    cases = []
    for _ in range(4):
        mom = u(0.0, 0.95)
        nesterov = bool(rng.randint(0, 2)) and mom > 0
        damp = 0.0 if nesterov else u(0.0, 0.5)
        wd = u(0.0, 0.05)
        cases.append((SGD(learning_rate=u(0.005, 0.2), momentum=mom,
                          nesterov=nesterov, dampening=damp,
                          weight_decay=wd),
                      torch.optim.SGD,
                      {"lr": None, "momentum": mom, "nesterov": nesterov,
                       "dampening": damp, "weight_decay": wd}))
    for _ in range(3):
        b1, b2 = u(0.8, 0.95), u(0.99, 0.9999)
        eps = 10.0 ** u(-9, -6)
        cases.append((Adam(learning_rate=u(0.001, 0.05), beta1=b1,
                           beta2=b2, epsilon=eps),
                      torch.optim.Adam,
                      {"lr": None, "betas": (b1, b2), "eps": eps}))
    for _ in range(2):
        rho = u(0.85, 0.99)
        eps = 10.0 ** u(-8, -5)
        cases.append((Adadelta(decay_rate=rho, epsilon=eps),
                      torch.optim.Adadelta,
                      {"lr": 1.0, "rho": rho, "eps": eps}))
    for _ in range(2):
        dr = u(0.9, 0.999)
        eps = 10.0 ** u(-9, -7)
        cases.append((RMSprop(learning_rate=u(0.001, 0.02), decay_rate=dr,
                              epsilon=eps),
                      torch.optim.RMSprop,
                      {"lr": None, "alpha": dr, "eps": eps}))

    for method, tcls, kwargs in cases:
        if kwargs.get("lr") is None:
            kwargs["lr"] = method.learning_rate
        _run_method(method, tcls, kwargs, steps=8,
                    rng=np.random.RandomState(700 + seed))
