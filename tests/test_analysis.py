"""Static-analyzer suite (``bigdl_tpu/analysis``): one intentionally
broken model per rule class asserting the EXACT rule id fires, plus a
clean run over every model in the zoo registry asserting zero errors —
so no pass can degrade into a stub that always returns clean."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

import bigdl_tpu.nn as nn
import bigdl_tpu.optim as optim
from bigdl_tpu.analysis import (check_model, check_partition_specs,
                                check_shapes, trace_retraces)
from bigdl_tpu.analysis.ast_lint import lint_source
from bigdl_tpu.analysis.shape_pass import infer_input_spec, output_spec
from bigdl_tpu.analysis.sharding_pass import check_train_step
from bigdl_tpu.models import registry
from bigdl_tpu.nn.graph import Graph, GraphBuildError, Input, Node
from bigdl_tpu.nn.module import load_state_dict, state_dict
from bigdl_tpu.parallel.mesh import make_mesh
from bigdl_tpu.parallel.train_step import EvalStep, TrainStep


# --------------------------------------------------------------------------
# seeded defects: every rule class must fire with its exact rule id
# --------------------------------------------------------------------------

def test_seeded_shape_mismatch():
    # 8-dim output feeds a 4-dim input: dot contraction mismatch
    broken = nn.Sequential(nn.Linear(4, 8), nn.Linear(4, 2))
    res = check_shapes(broken, jax.ShapeDtypeStruct((2, 4), jnp.float32))
    assert "shape/mismatch" in res.report.rules_fired()
    assert res.out is None
    # the finding is pinned to the offending layer, not the whole model
    assert res.report.errors[0].where == "1"


def test_seeded_f64_promotion():
    class PromoteF64(nn.Module):
        def update_output(self, input):
            return jnp.asarray(input, jnp.float64)

    from jax.experimental import enable_x64

    m = nn.Sequential(nn.Linear(4, 4), PromoteF64(), nn.Linear(4, 2))
    with enable_x64():
        res = check_shapes(m, jax.ShapeDtypeStruct((2, 4), jnp.float32))
    assert "shape/f64" in res.report.rules_fired()
    # only the promoting layer is flagged, not every downstream consumer
    assert [d.where for d in res.report
            if d.rule == "shape/f64"] == ["1"]


def test_seeded_dead_node():
    inp = Input()
    live = nn.Linear(4, 4).set_name("live").inputs(inp)
    nn.Linear(4, 4).set_name("deadbranch").inputs(inp)  # feeds nothing
    g = Graph(inp, live)
    res = check_shapes(g, jax.ShapeDtypeStruct((2, 4), jnp.float32))
    assert "shape/dead-node" in res.report.rules_fired()
    assert any("deadbranch" in d.message for d in res.report)
    assert not res.report.errors  # dead node is a warning, model still runs


def test_seeded_bad_partition_spec_axis():
    mesh = make_mesh((jax.device_count(),), ("data",))
    report = check_partition_specs(
        mesh,
        {"w": P("model"), "v": P("data")},
        {"w": np.zeros((8, 8)), "v": np.zeros((6, 2))})
    rules = report.rules_fired()
    assert "shard/unknown-axis" in rules        # 'model' not on this mesh
    if jax.device_count() > 1 and 6 % jax.device_count():
        assert "shard/indivisible" in rules     # 6 rows over 8 devices


def test_seeded_bad_train_step_sharding_rule():
    # a bad axis in extra_sharding_rules would explode inside
    # TrainStep.__init__'s device_put — the pre-flight check names the
    # parameter and the bad axis BEFORE construction
    from bigdl_tpu.analysis.sharding_pass import check_sharding_rules

    mesh = make_mesh((jax.device_count(),), ("data",))
    m = nn.Sequential(nn.Linear(4, 4), nn.LogSoftMax())
    report = check_sharding_rules(
        mesh, state_dict(m, kind="param"),
        lambda path, arr: P("model") if path.endswith("weight") else None)
    assert "shard/unknown-axis" in report.rules_fired()
    assert any("0.weight" in d.where for d in report)


def test_seeded_duplicate_axis_and_rule_error():
    mesh = make_mesh((jax.device_count(),), ("data",))
    report = check_partition_specs(
        mesh, {"w": P("data", "data")}, {"w": np.zeros((8, 8))})
    assert "shard/duplicate-axis" in report.rules_fired()

    from bigdl_tpu.analysis.sharding_pass import check_sharding_rules

    def crashing_rules(path, arr):
        raise RuntimeError("boom")

    report = check_sharding_rules(
        mesh, {"0.weight": np.zeros((4, 4))}, crashing_rules)
    assert report.rules_fired() == ["shard/rule-error"]


def test_retrace_run_scan_static_n_change():
    m = nn.Sequential(nn.Linear(4, 3), nn.LogSoftMax())
    step = TrainStep(m, nn.ClassNLLCriterion(),
                     optim.SGD(learning_rate=0.1))
    x = jnp.ones((2, 4, 4))  # [n, batch, dim] stacked iterations
    y = jnp.zeros((2, 4), jnp.int32)
    with trace_retraces() as mon:
        step.run_scan(x, y, jax.random.key(0), n=2, stacked=True)
        step.run_scan(x[:1], y[:1], jax.random.key(1), n=1,
                      stacked=True)  # n change: rebuild
    # the x/y leading-dim change is ALSO reported; the static:n finding
    # is the one naming the real compile-key cause
    findings = [d for d in mon.report if "static:n" in d.where]
    assert findings and findings[0].rule == "retrace/shape-change"
    assert "2 -> 1" in findings[0].message


def test_hooks_never_kill_the_step():
    class Exploding:
        def on_dispatch(self, *a):
            raise RuntimeError("observer bug")

        def on_cache(self, *a):
            raise RuntimeError("observer bug")

    from bigdl_tpu.analysis import hooks as hooks_mod

    m = nn.Sequential(nn.Linear(4, 3), nn.LogSoftMax())
    step = TrainStep(m, nn.ClassNLLCriterion(),
                     optim.SGD(learning_rate=0.1))
    bad = Exploding()
    hooks_mod.register(bad)
    try:
        loss = step.run(jnp.ones((4, 4)), jnp.zeros((4,), jnp.int32),
                        jax.random.key(0))
    finally:
        hooks_mod.unregister(bad)
    assert np.isfinite(float(loss))


def test_replicated_large_param_warning():
    if jax.device_count() < 2:
        pytest.skip("needs a multi-device mesh")
    mesh = make_mesh((jax.device_count(),), ("data",))
    m = nn.Sequential(nn.Linear(512, 2048))  # 1M+ elements, replicated
    step = TrainStep(m, nn.MSECriterion(), optim.SGD(learning_rate=0.1),
                     mesh=mesh)
    report = check_train_step(step)
    assert "shard/replicated-large" in report.rules_fired()
    assert not report.errors  # advisory, not an error


def test_seeded_tracer_leak_fixture():
    fixture = """
import time
import numpy as np
import jax

@jax.jit
def step(x):
    if x > 0:              # lint/tracer-branch
        x = -x
    t = time.time()        # lint/host-call
    return np.abs(x) + t   # lint/tracer-numpy
"""
    report = lint_source(fixture, "fixture.py")
    rules = report.rules_fired()
    assert "lint/tracer-branch" in rules
    assert "lint/host-call" in rules
    assert "lint/tracer-numpy" in rules


def test_lint_static_idioms_stay_clean():
    clean = """
import jax
import jax.numpy as jnp

@jax.jit
def f(x, y):
    if x.ndim == 3:        # static: fine
        x = x[None]
    n = x.shape[0]
    if n > 2:              # static-derived: fine
        y = y + 1
    if y is None:          # identity: fine
        return x
    return jnp.where(x > 0, x, -x)   # traced select: fine
"""
    assert not lint_source(clean, "clean.py").rules_fired()


def test_lint_name_resolution_respects_scope():
    # a module-level host helper sharing its name with a locally-jitted
    # def must NOT be linted as traced code (Python scoping: the local
    # def wins at the jit(...) reference)
    src = """
import jax


def fwd(x, t):
    if x > t:          # host-side: fine
        return x
    return t


def build():
    def fwd(y):
        return -y
    return jax.jit(fwd)
"""
    assert not lint_source(src, "scoped.py").rules_fired()


def test_lint_match_statement_bodies_scanned():
    src = """
import jax
import numpy as np

@jax.jit
def f(x, mode):
    match mode:
        case "neg":
            if x > 0:           # leak inside a case body
                x = -x
        case _:
            x = np.abs(x)       # np on tracer inside a case body
    return x
"""
    rules = lint_source(src, "m.py").rules_fired()
    assert "lint/tracer-branch" in rules
    assert "lint/tracer-numpy" in rules


def test_lint_paths_accepts_extensionless_file(tmp_path):
    from bigdl_tpu.analysis.ast_lint import lint_paths

    script = tmp_path / "train"  # explicit target, no .py suffix
    script.write_text("import jax\n\n@jax.jit\ndef f(x):\n"
                      "    if x > 0:\n        return -x\n    return x\n")
    assert "lint/tracer-branch" in \
        lint_paths([str(script)]).rules_fired()


def test_lint_noqa_suppression():
    src = """
import jax

@jax.jit
def f(x):
    if x > 0:  # noqa: lint/tracer-branch
        return -x
    return x
"""
    assert not lint_source(src, "x.py").rules_fired()


def test_seeded_graph_duplicate_name():
    inp = Input()
    a = nn.Linear(4, 4).set_name("fc").inputs(inp)
    b = nn.Linear(4, 4).set_name("fc").inputs(a)  # distinct module, same name
    with pytest.raises(GraphBuildError) as exc:
        Graph(inp, b)
    assert exc.value.rule == "graph/duplicate-name"
    assert "fc" in str(exc.value)


def test_graph_weight_sharing_names_ok():
    # the SAME module object on two nodes (Siamese) is not a collision
    shared = nn.Linear(4, 4).set_name("tied")
    inp = Input()
    a = shared.inputs(inp)
    b = shared.inputs(a)
    g = Graph(inp, b)
    out = g.forward(jnp.ones((2, 4)))
    assert out.shape == (2, 4)


def test_seeded_graph_cycle():
    n1 = Node(nn.Linear(4, 4).set_name("a"))
    n2 = Node(nn.Linear(4, 4).set_name("b"))
    n1.add_prev(n2)
    n2.add_prev(n1)
    with pytest.raises(GraphBuildError) as exc:
        Graph([], n1)
    assert exc.value.rule == "graph/cycle"
    # the message names the actual cycle members
    assert "a" in str(exc.value) and "b" in str(exc.value)


def test_seeded_retrace_shape_change():
    m = nn.Sequential(nn.Linear(4, 3), nn.LogSoftMax())
    step = TrainStep(m, nn.ClassNLLCriterion(),
                     optim.SGD(learning_rate=0.1))
    y4 = jnp.zeros((4,), jnp.int32)
    y6 = jnp.zeros((6,), jnp.int32)
    with trace_retraces() as mon:
        step.run(jnp.ones((4, 4)), y4, jax.random.key(0))
        step.run(jnp.ones((4, 4)), y4, jax.random.key(1))  # steady: no diag
        step.run(jnp.ones((6, 4)), y6, jax.random.key(2))  # retrace
    rules = mon.report.rules_fired()
    assert rules.count("retrace/shape-change") == 2  # x and y both changed
    assert any("x" in d.where for d in mon.report)


def test_retrace_sees_direct_run_sharded():
    # the Optimizer's hot loop calls run_sharded directly (its h2d vs
    # dispatch Metrics split) — the detector must still attribute the
    # retrace to the argument instead of a false retrace/recompile
    m = nn.Sequential(nn.Linear(4, 3), nn.LogSoftMax())
    step = TrainStep(m, nn.ClassNLLCriterion(),
                     optim.SGD(learning_rate=0.1))
    with trace_retraces() as mon:
        for n in (4, 4, 6):  # last batch shrinks: legitimate retrace
            x, y = step._shard_batch(jnp.ones((n, 4)),
                                     jnp.zeros((n,), jnp.int32))
            step.run_sharded(x, y, jax.random.key(n))
    rules = mon.report.rules_fired()
    assert mon.dispatches == 3
    assert "retrace/shape-change" in rules
    assert "retrace/recompile" not in rules


def test_cli_json_output_is_pure_json(capsys):
    import json

    from bigdl_tpu.analysis.__main__ import main

    assert main(["lenet", "--json"]) == 0
    out = capsys.readouterr().out
    assert json.loads(out) == []  # clean model, no findings, valid JSON


def test_seeded_retrace_python_scalar():
    es = EvalStep(nn.Sequential(nn.Identity()))
    with trace_retraces() as mon:
        es.run(jnp.float32(1.0))   # strong f32 scalar
        es.run(2.0)                # Python float: weak — flip recompiles
    assert "retrace/python-scalar" in mon.report.rules_fired()


# --------------------------------------------------------------------------
# satellite: load_state_dict aggregates ALL key problems in one error
# --------------------------------------------------------------------------

def test_load_state_dict_reports_all_keys_at_once():
    m = nn.Sequential(nn.Linear(2, 2), nn.Linear(2, 2))
    st = state_dict(m)
    bad = dict(st)
    del bad["0.weight"], bad["1.bias"]          # two missing
    bad["ghost.weight"] = jnp.zeros((2, 2))     # two unexpected
    bad["phantom.bias"] = jnp.zeros((2,))
    with pytest.raises(KeyError) as exc:
        load_state_dict(m, bad, strict=True)
    msg = str(exc.value)
    for key in ("0.weight", "1.bias", "ghost.weight", "phantom.bias"):
        assert key in msg, f"{key} not reported in: {msg}"


def test_load_state_dict_nonstrict_ignores_unknown():
    m = nn.Sequential(nn.Linear(2, 2))
    load_state_dict(m, {"nope.weight": jnp.zeros((2, 2))}, strict=False)


# --------------------------------------------------------------------------
# clean runs: every zoo model must pass every static check with 0 errors
# --------------------------------------------------------------------------

@pytest.mark.parametrize("name", registry.model_names())
def test_zoo_model_checks_clean(name):
    model = registry.build_model(name)
    spec = registry.input_spec(name)
    res = check_model(model, spec)
    assert not res.report.errors, res.report.format()
    assert res.out is not None
    assert res.layers, "per-layer walk produced no rows"


def test_infer_input_spec_matches_registry():
    # optimize_for_tpu's fallback inference agrees with the canonical
    # spec for the conv models it exists for
    for name in ("resnet", "vgg_cifar", "lenet"):
        model = registry.build_model(name)
        inferred = infer_input_spec(model)
        assert inferred is not None, name
        assert output_spec(model, inferred) is not None, name


# --------------------------------------------------------------------------
# CLI plumbing
# --------------------------------------------------------------------------

def test_cli_model_check_exit_codes(capsys):
    from bigdl_tpu.analysis.__main__ import main

    assert main(["lenet", "resnet"]) == 0
    out = capsys.readouterr().out
    assert "0 error(s)" in out


def test_cli_list_rules(capsys):
    from bigdl_tpu.analysis.__main__ import main

    assert main(["--list-rules"]) == 0
    out = capsys.readouterr().out
    for rule in ("shape/mismatch", "shard/unknown-axis",
                 "retrace/shape-change", "lint/tracer-branch"):
        assert rule in out


def test_cli_lint_path_fails_on_leak(tmp_path, capsys):
    from bigdl_tpu.analysis.__main__ import main

    bad = tmp_path / "leaky.py"
    bad.write_text("import jax\n\n@jax.jit\ndef f(x):\n"
                   "    if x > 0:\n        return -x\n    return x\n")
    assert main([str(bad)]) == 1
    assert main([str(bad), "--suppress", "lint/tracer-branch"]) == 0


def test_lint_graft_tool_exit_codes(tmp_path):
    # the wrapper's argparse/exit plumbing on explicit targets; the
    # repo-wide clean run is tests/test_lint_clean.py (no need to lint
    # the whole tree twice per tier-1 run)
    import os
    import sys

    sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..",
                                    "tools"))
    try:
        import lint_graft
    finally:
        sys.path.pop(0)
    clean = tmp_path / "clean.py"
    clean.write_text("import jax\n\n@jax.jit\ndef f(x):\n    return -x\n")
    leaky = tmp_path / "leaky.py"
    leaky.write_text("import jax\n\n@jax.jit\ndef f(x):\n"
                     "    if x > 0:\n        return -x\n    return x\n")
    assert lint_graft.main([str(clean)]) == 0
    assert lint_graft.main([str(tmp_path)]) == 1
    assert lint_graft.main([str(leaky),
                            "--suppress", "lint/tracer-branch"]) == 0
