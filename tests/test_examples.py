"""Smoke tests for the example applications (SURVEY §2.13 example
families; VERDICT r3 item 8): each example must run end-to-end at toy
scale and produce a sane result."""

import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def test_mlpipeline_lenet_runs_and_learns(tmp_path, monkeypatch):
    monkeypatch.chdir(tmp_path)  # bigdl.log from the redirect goes here
    from examples.mlpipeline_lenet import main

    acc = main(["--limit", "256", "-e", "6", "-b", "32"])
    # synthetic MNIST is 10-class; the CNN must beat chance decisively
    assert acc > 0.3, acc


def test_image_predictor_runs(tmp_path, monkeypatch):
    monkeypatch.chdir(tmp_path)
    import bigdl_tpu.nn as nn
    from bigdl_tpu.utils.rng import RNG
    from bigdl_tpu.utils.serializer import save_module
    from examples.image_predictor import main

    RNG.set_seed(3)
    model = nn.Sequential(
        nn.SpatialConvolution(3, 4, 3, 3, 1, 1, 1, 1),
        nn.ReLU(), nn.SpatialAveragePooling(8, 8, 8, 8),
        nn.Reshape([4]), nn.Linear(4, 5), nn.SoftMax())
    mpath = str(tmp_path / "m.btpu")
    save_module(model, mpath)

    imgdir = tmp_path / "images"
    imgdir.mkdir()
    rng = np.random.RandomState(0)
    for i in range(6):
        np.save(imgdir / f"img_{i}.npy",
                rng.randn(3, 8, 8).astype(np.float32))

    results = main(["-f", str(imgdir), "-t", "bigdl", "--modelPath", mpath,
                    "--imageSize", "8"])
    assert len(results) == 6
    names = [n for n, _ in results]
    assert names == sorted(names)
    assert all(0 <= c < 5 for _, c in results)


def test_treelstm_sentiment_learns(tmp_path, monkeypatch):
    monkeypatch.chdir(tmp_path)
    from examples.treelstm_sentiment import main

    before, after = main(["-e", "4", "-b", "16"])
    # word-polarity majority voting is learnable: training must help and
    # end clearly above chance (2 classes)
    assert after > 0.7, (before, after)
    assert after > before - 0.05


def test_tensorflow_interop_roundtrip_and_finetune(tmp_path, monkeypatch):
    monkeypatch.chdir(tmp_path)
    from examples.tensorflow_interop import main

    acc = main(["--modelPath", str(tmp_path / "m.pb")])
    assert acc > 0.8, acc


def test_tta_bench_protocol(tmp_path, monkeypatch):
    """Time-to-accuracy harness (BASELINE third leg): reaches the target
    on synthetic data and reports the protocol fields."""
    monkeypatch.chdir(tmp_path)
    sys.path.insert(0, "/root/repo/tools")
    from tools.tta_bench import main

    r = main(["--model", "lenet", "--target", "0.9", "-b", "64",
              "--max-epoch", "6"])
    assert r["reached"] and r["final_top1"] >= 0.9
    assert r["value"] > 0 and r["iterations"] > 0
