"""Randomized-config criterion fuzz vs torch — forward LOSS and
backward GRADINPUT across sampled shapes, weights, and size_average
settings (the reduction/weighting algebra is where criterion
implementations quietly diverge; the optimizer fuzz caught exactly such
a divergence in SGD dampening)."""

import numpy as np
import pytest
import torch

import bigdl_tpu.nn as nn


def _cmp(ours_loss, ours_grad, t_loss, t_grad, tag, rtol=1e-4, atol=1e-5):
    np.testing.assert_allclose(float(ours_loss), float(t_loss.detach()),
                               rtol=rtol, atol=atol, err_msg=f"{tag} loss")
    np.testing.assert_allclose(np.asarray(ours_grad), t_grad.numpy(),
                               rtol=rtol, atol=atol, err_msg=f"{tag} grad")


@pytest.mark.parametrize("seed", [0, 1, 2])
def test_classnll_fuzz(seed):
    rng = np.random.RandomState(800 + seed)
    for _ in range(6):
        n, c = int(rng.randint(2, 9)), int(rng.randint(2, 7))
        size_avg = bool(rng.randint(0, 2))
        use_w = bool(rng.randint(0, 2))
        w = (rng.rand(c).astype(np.float32) + 0.2) if use_w else None
        logits = rng.randn(n, c).astype(np.float32)
        logp = logits - np.log(np.exp(logits).sum(1, keepdims=True))
        y = rng.randint(0, c, n)

        crit = nn.ClassNLLCriterion(weights=w, size_average=size_avg)
        loss = crit.forward(logp, y)
        grad = crit.backward(logp, y)

        tx = torch.tensor(logp, requires_grad=True)
        tcrit = torch.nn.NLLLoss(
            weight=None if w is None else torch.tensor(w),
            reduction="mean" if size_avg else "sum")
        tl = tcrit(tx, torch.tensor(y))
        tl.backward()
        _cmp(loss, grad, tl, tx.grad, f"nll avg={size_avg} w={use_w}")


@pytest.mark.parametrize("seed", [0, 1])
def test_elementwise_criterion_fuzz(seed):
    """MSE / Abs(L1) / SmoothL1 / BCE / KLDiv over random shapes and
    size_average."""
    rng = np.random.RandomState(900 + seed)
    for _ in range(8):
        shape = tuple(int(rng.randint(2, 6))
                      for _ in range(int(rng.randint(1, 4))))
        size_avg = bool(rng.randint(0, 2))
        red = "mean" if size_avg else "sum"
        x = rng.randn(*shape).astype(np.float32)
        t = rng.randn(*shape).astype(np.float32)

        cases = [
            (nn.MSECriterion(size_average=size_avg),
             torch.nn.MSELoss(reduction=red), x, t),
            (nn.AbsCriterion(size_average=size_avg),
             torch.nn.L1Loss(reduction=red), x, t),
            (nn.SmoothL1Criterion(size_average=size_avg),
             torch.nn.SmoothL1Loss(reduction=red), x, t),
        ]
        # BCE needs inputs in (0,1); KLDiv wants log-probs vs probs
        p = 1.0 / (1.0 + np.exp(-x))
        tgt01 = (t > 0).astype(np.float32)
        cases.append((nn.BCECriterion(size_average=size_avg),
                      torch.nn.BCELoss(reduction=red), p, tgt01))
        logq = np.log(np.abs(x) / np.abs(x).sum() + 1e-8).astype(np.float32)
        pr = (np.abs(t) / np.abs(t).sum()).astype(np.float32)
        cases.append((nn.DistKLDivCriterion(size_average=size_avg),
                      torch.nn.KLDivLoss(reduction=red), logq, pr))

        for crit, tcrit, xi, ti in cases:
            loss = crit.forward(xi, ti)
            grad = crit.backward(xi, ti)
            tx = torch.tensor(xi, requires_grad=True)
            tl = tcrit(tx, torch.tensor(ti))
            tl.backward()
            _cmp(loss, grad, tl, tx.grad,
                 f"{type(crit).__name__} avg={size_avg} shape={shape}",
                 rtol=2e-4, atol=2e-5)
