"""Schema-drift guard: every event kind and stream name the sources
emit must be registered in ``telemetry/schema.py`` — a new event can't
silently bypass ``--validate`` and the readers (report, diff,
metrics_http) that key off names.

The scan is purely lexical (literal first arguments of the emit
helpers), so adding an event stream means adding its name to
``schema.KINDS`` / ``schema.STREAM_NAMES`` in the same change — which
is exactly the point."""

import glob
import os
import re

from bigdl_tpu.telemetry import schema

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

#: reliability-critical modules the registry pins alongside the CI lint
#: (tools/lint_graft.py PINNED_MODULES) — a rename/removal must fail
#: tests, not silently drop the subsystem from the lexical scan
PINNED = ["bigdl_tpu/faults.py", "bigdl_tpu/utils/ckpt_digest.py",
          "bigdl_tpu/utils/sharded_ckpt.py",
          # elastic resharding (ISSUE 12): the topology record both
          # checkpoint backends write and the pre-load reshard
          # validation — a silent drop reverts checkpoints to
          # same-shape-only restore
          "bigdl_tpu/utils/ckpt_topology.py",
          "bigdl_tpu/parallel/cluster.py",
          # the serving layer (ISSUE 8): the bucketed compile cache the
          # batch Predictor ALSO routes through — a silent drop reverts
          # every predict() to a fresh-EvalStep compile
          "bigdl_tpu/serving/buckets.py",
          "bigdl_tpu/serving/executor.py",
          "bigdl_tpu/serving/batcher.py",
          "bigdl_tpu/serving/server.py",
          # the LLM decode subsystem (ISSUE 13): KV cache + prefill/
          # decode executables + generation batching — a silent drop
          # reverts generation to one full-context forward per token
          # and loses the /v1/generate streaming surface
          "bigdl_tpu/serving/generate/kv_cache.py",
          "bigdl_tpu/serving/generate/decode.py",
          "bigdl_tpu/serving/generate/batcher.py",
          # compile-time war (ISSUE 9): scan-over-layers + the managed
          # persistent compile cache — a silent drop reverts models to
          # N-times-unrolled lowering and unmeasured cache traffic
          "bigdl_tpu/nn/layers/scan.py",
          "bigdl_tpu/utils/compile_cache.py",
          # fleet-wide comms observability (ISSUE 10): the collective
          # walker the bytes-moved diff gate reads, and the live
          # cross-host aggregator behind /status.fleet + skew blame
          "bigdl_tpu/telemetry/comms.py",
          "bigdl_tpu/telemetry/fleet.py",
          # request-level serving traces (ISSUE 14): the span-timeline
          # store behind /v1/trace/<id>, the per-request blame verdict,
          # and the SLO burn gates — a silent drop reverts serving
          # observability to aggregate percentiles with no evidence
          "bigdl_tpu/telemetry/request_trace.py",
          # memory observability (ISSUE 11): the HBM walker behind the
          # peak_hbm_bytes diff gate, the fit estimator, and the
          # OOM-forensics evidence — a silent drop reverts device OOMs
          # to a bare RESOURCE_EXHAUSTED
          "bigdl_tpu/telemetry/memory.py",
          # sparse embedding fast path (ISSUE 15): the row-sparse
          # cotangent capture + the recsys scenario — a silent drop
          # reverts every embedding gradient to the dense table
          # all-reduce and loses the dlrm bench/serving tenant
          "bigdl_tpu/nn/layers/embedding.py",
          "bigdl_tpu/models/dlrm.py",
          # goodput ledger (ISSUE 18): a silent drop loses the
          # wall-time conservation contract and every goodput surface
          # (end-of-run event, CLI fold, diff/bench gates)
          "bigdl_tpu/telemetry/ledger.py",
          # straggler-tolerant local SGD (ISSUE 20): the bounded-
          # staleness barrier + shed protocol — a silent drop leaves
          # parameter_sync=local with no cross-process exchange and no
          # way to stop waiting for a slow host
          "bigdl_tpu/parallel/local_sync.py"]


def test_pinned_fault_tolerance_modules_present():
    missing = [m for m in PINNED
               if not os.path.isfile(os.path.join(REPO, m))]
    assert missing == [], (
        f"pinned modules missing: {missing} — fault injection and "
        f"crash-consistent restore are load-bearing (ISSUE 5); update "
        f"the pins if these moved")
    from tools.lint_graft import check_pins

    assert check_pins(REPO) == []

#: literal emit kinds: tracer.emit("<kind>", ...)
_KIND_RE = re.compile(r'\.emit\(\s*"(\w+)"')
#: literal stream names through the typed helpers
_NAME_RE = re.compile(
    r'\.(?:instant|gauge|counter|stage|span|begin)\(\s*"([^"]+)"')
#: instants spelled as emit("event", name="...")
_EVENT_NAME_RE = re.compile(r'\.emit\(\s*"event",\s*name="([^"]+)"')
#: compile events carry a literal dispatch-kind name
_COMPILE_NAME_RE = re.compile(r'\.emit\(\s*"compile",\s*name="([^"]+)"')
#: Metrics pipeline stages (forwarded into stage events by the bridge)
_STAGE_RE = re.compile(r'(?:metrics\.add|self\.metrics\.add|\.timer)'
                       r'\(\s*"([^"]+)"')
#: health findings are built as ("health/<x>", attrs) tuples
_FINDING_RE = re.compile(r'\(\s*"(health/[\w]+)"')


def _sources():
    paths = glob.glob(os.path.join(REPO, "bigdl_tpu", "**", "*.py"),
                      recursive=True)
    paths += glob.glob(os.path.join(REPO, "tools", "*.py"))
    paths += [os.path.join(REPO, "bench.py"),
              os.path.join(REPO, "bench_serving.py")]
    # the registry itself and this test don't count as emitters
    skip = os.path.join("telemetry", "schema.py")
    return [p for p in paths if os.path.exists(p) and skip not in p]


def _scan():
    kinds, names = set(), set()
    for path in _sources():
        with open(path, "r", encoding="utf-8") as fh:
            src = fh.read()
        kinds.update(_KIND_RE.findall(src))
        names.update(_NAME_RE.findall(src))
        names.update(_EVENT_NAME_RE.findall(src))
        names.update(_COMPILE_NAME_RE.findall(src))
        names.update(_STAGE_RE.findall(src))
        if path.endswith(os.path.join("telemetry", "health.py")):
            names.update(_FINDING_RE.findall(src))
    return kinds, names


def test_every_emitted_kind_is_registered():
    kinds, _ = _scan()
    # pattern-rot tripwire: the scan must keep seeing the core kinds
    assert {"step", "compile", "device_facts", "health",
            "attribution"} <= kinds
    unregistered = sorted(kinds - set(schema.KINDS))
    assert unregistered == [], (
        f"event kinds emitted but not in schema.KINDS: {unregistered} — "
        f"register them (with their required fields) in "
        f"telemetry/schema.py")


def test_every_emitted_stream_name_is_registered():
    _, names = _scan()
    assert {"train/iteration", "data_wait", "straggler/timeout",
            "prefetch/queue_depth", "profile/armed", "flight/dump",
            "fault/injected", "checkpoint/quarantined",
            "run/preempted", "run/resumed"} <= names, \
        "name scan lost its anchors"
    unregistered = sorted(names - set(schema.STREAM_NAMES))
    assert unregistered == [], (
        f"stream names emitted but not in schema.STREAM_NAMES: "
        f"{unregistered} — register them in telemetry/schema.py so "
        f"--validate and the readers know about them")


def test_registry_names_are_not_stale():
    """The reverse direction, advisory-strength: names in the registry
    should still have an emitter somewhere (catches renames that forget
    the registry).  'computing time' is emitted via a ternary the
    lexical scan can't see; dispatch kinds are built dynamically."""
    _, names = _scan()
    allowed_unseen = {"computing time", "TrainStep.run",
                      "TrainStep.run_sharded", "TrainStep.run_scan",
                      "EvalStep.run",
                      # serving compile events carry their name through
                      # a variable (warmup vs in-request-path), so the
                      # lexical scan can't see the literals
                      "ServeExecutor.warmup", "ServeExecutor.compile",
                      "GenerateExecutor.warmup",
                      "GenerateExecutor.compile"}
    stale = sorted(set(schema.STREAM_NAMES) - names - allowed_unseen)
    assert stale == [], (
        f"STREAM_NAMES entries with no emitter found: {stale} — "
        f"remove them or fix the rename")
