"""Memory observability (telemetry/memory.py, ISSUE 11).

Covers the scheduled-HLO liveness walker (category totals cross-checked
against ``Compiled.memory_analysis()`` within 10% on 2-device lenet AND
transformer steps — the acceptance criterion), the ZeRO-1 per-device
optimizer-state drop, the remat activations-at-peak drop, the per-step
``memory`` event and its knob, the fit-estimator CLI, OOM forensics
(flight dump + ``MemoryExhaustedError`` evidence), the serving
executor's per-bucket memory accounting, the fleet memory-pressure
note, and the diff/bench ``peak_hbm_bytes`` gates."""

import glob
import json
import os
import subprocess
import sys

import jax
import numpy as np
import pytest

import bigdl_tpu.nn as nn
import bigdl_tpu.optim as optim
from bigdl_tpu import telemetry
from bigdl_tpu.parallel.mesh import make_mesh
from bigdl_tpu.parallel.train_step import TrainStep
from bigdl_tpu.telemetry import memory as tmem, schema
from bigdl_tpu.utils.config import BigDLConfig, set_config

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _fresh_config():
    set_config(None)
    yield
    set_config(None)


def _registry_step(name, batch, sync="allreduce", devices=2):
    from bigdl_tpu.models import registry

    mesh = make_mesh((devices,), ("data",),
                     devices=jax.devices()[:devices]) \
        if devices > 1 else None
    model = registry.build_model(name)
    spec = registry.input_spec(name, batch)
    criterion, tspec = registry.train_pieces(name, batch)
    step = TrainStep(model, criterion,
                     optim.SGD(learning_rate=0.01, momentum=0.9),
                     mesh=mesh, parameter_sync=sync)
    return step, spec, tspec


# -- acceptance: walker vs XLA's own memory_analysis -------------------------
@pytest.mark.parametrize("name,batch", [("lenet", 8), ("transformer", 2)])
def test_walker_categories_match_memory_analysis(name, batch):
    """The acceptance criterion: on the 2-device sharded lenet and
    transformer train steps, the walker's per-device argument total
    must MATCH XLA's (the ENTRY parameter shapes are post-SPMD), its
    liveness temp peak must land within 10% of XLA's buffer-assignment
    temp, and the donation detection must equal the alias bytes."""
    step, spec, tspec = _registry_step(name, batch)
    out = tmem.attribute_memory_train_step(step, spec, tspec)
    ma = out.get("memory_analysis")
    assert ma, "CPU backend stopped reporting memory_analysis"
    assert out["args_bytes"] == ma["argument_bytes"]
    assert abs(out["temp_peak_bytes"] - ma["temp_bytes"]) \
        / ma["temp_bytes"] < 0.10, (out["temp_peak_bytes"],
                                    ma["temp_bytes"])
    assert out["donated_bytes"] == ma["alias_bytes"]
    # the categories tile the argument total exactly
    cats = out["categories"]
    assert cats["params"] + cats["opt_state"] + cats["buffers"] \
        + cats["batch"] + cats["other"] == out["args_bytes"]
    # activations + workspace tile the live-at-peak temp
    assert cats["activations_at_peak"] + cats["workspace_at_peak"] \
        == out["temp_peak_bytes"]
    # named modules own real bytes and the table renders
    named = [r for r in out["rows"] if r["path"] != "(unattributed)"]
    assert named and sum(r["total_bytes"] for r in named) > 0
    text = tmem.format_memory(out)
    assert "per-device peak" in text and "by module" in text


def test_zero1_drops_per_device_optimizer_state():
    """ZeRO-1 ('sharded') shards the optimizer state over the data
    axis: the walker must show strictly lower PER-DEVICE opt-state
    bytes than the dense replicated layout — the arXiv 2004.13336
    claim made CI-checkable (exactly 1/2 on a 2-device mesh for the
    shardable leaves)."""
    outs = {}
    for sync in ("allreduce", "sharded"):
        step, spec, tspec = _registry_step("lenet", 8, sync=sync)
        outs[sync] = tmem.attribute_memory_train_step(step, spec, tspec)
    dense, zero = outs["allreduce"], outs["sharded"]
    assert zero["categories"]["opt_state"] \
        < dense["categories"]["opt_state"]
    # params stay replicated under ZeRO-1 — only the moments shrink
    assert zero["categories"]["params"] == dense["categories"]["params"]
    # the drop is visible per module too, not just in the totals
    zrows = {r["path"]: r for r in zero["rows"]}
    shrunk = [r for r in dense["rows"]
              if r["path"] in zrows and r["opt_bytes"]
              and zrows[r["path"]]["opt_bytes"] < r["opt_bytes"]]
    assert shrunk, "no module shows the per-device opt-state drop"


def test_remat_lowers_activations_at_peak():
    """A Remat-wrapped transformer block recomputes its forward in the
    backward instead of saving activations: the walker's
    activations-at-peak must drop (the recomputed ops carry the
    transpose() frame, so they read as backward workspace, and the
    saved residuals shrink to the block inputs)."""
    from bigdl_tpu import models

    def peak_acts(remat):
        model = models.build_transformer_lm(
            256, num_layers=2, embed_dim=128, num_heads=4, max_len=256,
            remat=remat)
        crit = nn.TimeDistributedCriterion(nn.ClassNLLCriterion(),
                                           size_average=True)
        step = TrainStep(model, crit,
                         optim.SGD(learning_rate=0.01, momentum=0.9))
        x = jax.ShapeDtypeStruct((2, 256), np.int32)
        y = jax.ShapeDtypeStruct((2, 256), np.int32)
        out = tmem.attribute_memory_train_step(step, x, y)
        return out["categories"]["activations_at_peak"], out

    acts_plain, _ = peak_acts(False)
    acts_remat, out_remat = peak_acts(True)
    assert acts_remat < 0.5 * acts_plain, (acts_remat, acts_plain)
    # and the whole peak shrinks too — remat trades HBM for FLOPs
    assert out_remat["peak_bytes"] > 0


def test_scope_of_drops_bare_remat_frames():
    """jax.checkpoint inserts BARE checkpoint/rematted_computation
    frames; they are transform structure, not module scopes — a
    Remat-wrapped block's ops must fold onto the block's tree path."""
    from bigdl_tpu.telemetry.attribution import scope_of

    path, direction = scope_of(
        "jit(step)/jit(main)/transpose(jvp(2))/checkpoint/"
        "rematted_computation/0/fc1/dot_general")
    assert path == "2.0.fc1" and direction == "bwd"
    path, direction = scope_of(
        "jit(step)/jit(main)/jvp(3)/checkpoint/0/attn/dot_general")
    assert path == "3.0.attn" and direction == "fwd"


# -- the memory event + knob --------------------------------------------------
def _sharded_step_run(sink):
    mesh = make_mesh((2,), ("data",), devices=jax.devices()[:2])
    model = nn.Sequential(nn.Linear(6, 8), nn.Tanh(), nn.Linear(8, 4),
                          nn.LogSoftMax())
    step = TrainStep(model, nn.ClassNLLCriterion(),
                     optim.SGD(learning_rate=0.1), mesh=mesh)
    x = np.ones((8, 6), np.float32)
    y = np.zeros((8,), np.int64)
    with telemetry.run(sinks=[sink]):
        step.run(x, y, jax.random.key(0))


def test_memory_event_auto_on_for_sharded_step():
    sink = telemetry.MemorySink()
    _sharded_step_run(sink)
    events = [e for e in sink.events if e.get("kind") == "memory"]
    assert len(events) == 1
    ev = events[0]
    assert schema.validate_event(ev) == []
    assert ev["peak_bytes"] > 0
    assert ev["program"] == "train_step"
    assert ev["categories"]["params"] > 0
    assert ev["rows"]  # per-module rows travel with the event


def test_memory_event_default_off_single_device_and_off_knob():
    # auto + no mesh: nothing emitted
    sink = telemetry.MemorySink()
    model = nn.Sequential(nn.Linear(6, 4), nn.LogSoftMax())
    step = TrainStep(model, nn.ClassNLLCriterion(),
                     optim.SGD(learning_rate=0.1))
    with telemetry.run(sinks=[sink]):
        step.run(np.ones((4, 6), np.float32), np.zeros((4,), np.int64),
                 jax.random.key(0))
    assert not [e for e in sink.events if e.get("kind") == "memory"]
    # off knob mutes even the sharded step
    set_config(BigDLConfig(telemetry_memory="off"))
    sink2 = telemetry.MemorySink()
    _sharded_step_run(sink2)
    assert not [e for e in sink2.events if e.get("kind") == "memory"]


def test_memory_on_knob_forces_single_device_and_survives_device_off():
    """BIGDL_MEMORY=on must emit on a single-device step and even with
    BIGDL_TELEMETRY_DEVICE=off — the knobs are independent (the comms
    contract, extended)."""
    set_config(BigDLConfig(telemetry_device="off",
                           telemetry_memory="on"))
    sink = telemetry.MemorySink()
    model = nn.Sequential(nn.Linear(6, 4), nn.LogSoftMax())
    step = TrainStep(model, nn.ClassNLLCriterion(),
                     optim.SGD(learning_rate=0.1))
    with telemetry.run(sinks=[sink]):
        step.run(np.ones((4, 6), np.float32), np.zeros((4,), np.int64),
                 jax.random.key(0))
    kinds = [e.get("kind") for e in sink.events]
    assert "memory" in kinds
    assert "device_facts" not in kinds  # the device level still holds


def test_memory_event_rides_aot_scan_and_sees_the_loop_body():
    """aot_scan has the executable in hand — the memory event is a text
    parse, and the walker's while-body recursion must report the peak
    INSIDE the scanned step (far above the tuple shuffle around it)."""
    mesh = make_mesh((2,), ("data",), devices=jax.devices()[:2])
    model = nn.Sequential(nn.Linear(64, 128), nn.Tanh(),
                          nn.Linear(128, 4), nn.LogSoftMax())
    step = TrainStep(model, nn.ClassNLLCriterion(),
                     optim.SGD(learning_rate=0.1), mesh=mesh)
    x = np.ones((8, 64), np.float32)
    y = np.zeros((8,), np.int64)
    sink = telemetry.MemorySink()
    with telemetry.run(sinks=[sink]):
        step.aot_scan(x, y, jax.random.key(0), 3)
    events = [e for e in sink.events if e.get("kind") == "memory"]
    assert len(events) == 1
    ev = events[0]
    assert ev["program"] == "aot_scan"
    # the body's live temp dominates: peak must exceed the args alone
    assert ev["peak_bytes"] > ev["args_bytes"]


# -- OOM forensics ------------------------------------------------------------
def test_oom_forensics_flight_dump_carries_buffer_table(tmp_path,
                                                        monkeypatch):
    monkeypatch.setenv("BIGDL_TELEMETRY", str(tmp_path))
    model = nn.Sequential(nn.Linear(6, 4), nn.LogSoftMax())
    step = TrainStep(model, nn.ClassNLLCriterion(),
                     optim.SGD(learning_rate=0.1, momentum=0.9))
    x = np.ones((4, 6), np.float32)
    y = np.zeros((4,), np.int64)

    def boom(*args):
        raise RuntimeError("RESOURCE_EXHAUSTED: Out of memory "
                           "allocating 123456789 bytes")

    step._compiled = boom
    with telemetry.run(str(tmp_path)):
        with pytest.raises(tmem.MemoryExhaustedError) as ei:
            step.run_sharded(x, y, jax.random.key(0))
    err = ei.value
    assert err.evidence["categories"]["params"] > 0
    assert err.evidence["largest_buffers"][0]["bytes"] > 0
    assert "RESOURCE_EXHAUSTED" in err.evidence["error"]
    assert isinstance(err.__cause__, RuntimeError)
    dumps = glob.glob(str(tmp_path / "flight-*.json"))
    assert dumps, "OOM must flight-dump before re-raising"
    doc = json.load(open(dumps[0]))
    assert doc["reason"] == "oom"
    assert doc["evidence"]["largest_buffers"]
    assert doc["evidence"]["categories"]["params"] > 0


def test_non_oom_errors_pass_through_unwrapped():
    model = nn.Sequential(nn.Linear(6, 4), nn.LogSoftMax())
    step = TrainStep(model, nn.ClassNLLCriterion(),
                     optim.SGD(learning_rate=0.1))

    def boom(*args):
        raise RuntimeError("something else entirely")

    step._compiled = boom
    with pytest.raises(RuntimeError, match="something else"):
        step.run_sharded(np.ones((4, 6), np.float32),
                         np.zeros((4,), np.int64), jax.random.key(0))


def test_is_oom_spellings():
    assert tmem.is_oom(RuntimeError("RESOURCE_EXHAUSTED: ..."))
    assert tmem.is_oom(RuntimeError("Out of memory while trying to "
                                    "allocate 1 bytes"))
    assert not tmem.is_oom(ValueError("shape mismatch"))


# -- serving executor: per-bucket executable memory ---------------------------
def test_executor_warmup_records_bucket_memory():
    from bigdl_tpu.serving.executor import BucketedExecutor
    from bigdl_tpu.serving.buckets import BucketPolicy

    model = nn.Sequential(nn.Linear(12, 8), nn.Tanh(), nn.Linear(8, 4),
                          nn.LogSoftMax())
    ex = BucketedExecutor(model,
                          policy=BucketPolicy(max_batch=8,
                                              batch_buckets=[4, 8]))
    ex.warmup((12,), np.float32)
    assert set(ex.bucket_memory) == {(4, None), (8, None)}
    summary = ex.memory_summary()
    assert summary["state_bytes"] > 0
    assert summary["resident_bytes"] >= summary["state_bytes"]
    assert set(summary["buckets"]) == {"b4", "b8"}
    # the server's /status carries it (ROADMAP item 2's KV-cache budget
    # subtracts this from the device)
    from bigdl_tpu.serving.server import ModelServer

    server = ModelServer(model, jax.ShapeDtypeStruct((1, 12),
                                                     np.float32),
                         host="127.0.0.1", port=0)
    try:
        server.warmup()
        st = server.status()
        assert st["memory"]["state_bytes"] > 0
        assert st["memory"]["resident_bytes"] \
            >= st["memory"]["state_bytes"]
    finally:
        server.stop(drain=False)


# -- fleet: memory fold + pressure note ---------------------------------------
def _host_events(pidx, data_wait_s, live, limit):
    evs = [{"kind": "run_start", "ts": 0.0,
            "meta": {"process_index": pidx}}]
    t = 1.0
    for i in range(1, 9):
        evs.append({"kind": "span_end", "name": "data_wait",
                    "span": i, "dur": data_wait_s, "ts": t})
        evs.append({"kind": "step", "step": i, "dur": 0.1, "ts": t})
        t += 0.1
    evs.append({"kind": "memory", "ts": t, "peak_bytes": 1 << 30,
                "hbm_limit_bytes": limit,
                "live": [{"device": 0, "peak_bytes_in_use": live,
                          "bytes_limit": limit}]})
    return evs


def test_fleet_folds_memory_and_blame_notes_pressure():
    from bigdl_tpu.telemetry.fleet import fleet_view

    limit = 16 * (1 << 30)
    view = fleet_view([
        ("run-a-p0-1.jsonl", _host_events(0, 0.001, live=limit // 2,
                                          limit=limit)),
        ("run-b-p1-2.jsonl", _host_events(1, 0.06,
                                          live=int(limit * 0.97),
                                          limit=limit)),
    ])
    row = view["hosts"]["p1"]
    assert row["hbm_peak_bytes"] == 1 << 30
    assert row["hbm_live_bytes"] == int(limit * 0.97)
    assert row["memory_pressure"] is True
    assert view["hosts"]["p0"]["memory_pressure"] is False
    verdict = view["blame"]
    assert verdict and verdict["laggard"] == 1
    assert verdict["cause"] == "data_wait"
    assert verdict["memory_pressure"] == ["p1"]
    from bigdl_tpu.telemetry.fleet import format_fleet_view

    text = format_fleet_view(view)
    assert "memory pressure" in text and "hbm" in text


def test_metrics_sink_folds_memory_event():
    from bigdl_tpu.telemetry.metrics_http import MetricsSink

    sink = MetricsSink()
    sink.emit({"kind": "memory", "peak_bytes": 123456,
               "args_bytes": 100000, "temp_peak_bytes": 23456,
               "hbm_limit_bytes": 1 << 30,
               "live": [{"device": 0, "peak_bytes_in_use": 777,
                         "bytes_limit": 1 << 30}]})
    st = sink.status()
    assert st["memory"]["peak_bytes"] == 123456
    assert st["memory"]["live_bytes"] == 777
    assert st["memory"]["limit_bytes"] == 1 << 30
    text = sink.openmetrics()
    assert "bigdl_hbm_peak_bytes" in text
    assert "bigdl_hbm_live_bytes" in text


# -- CLI ----------------------------------------------------------------------
def test_cli_attribute_memory_model_and_json(capsys):
    from bigdl_tpu.telemetry import __main__ as cli

    rc = cli.main(["attribute", "--memory", "--model", "lenet",
                   "--mesh", "2"])
    out = capsys.readouterr().out
    assert rc == 0
    assert "HBM attribution" in out and "by module" in out
    rc = cli.main(["attribute", "--memory", "--model", "lenet",
                   "--mesh", "2", "--json"])
    doc = json.loads(capsys.readouterr().out)
    assert rc == 0 and doc["peak_bytes"] > 0
    assert doc["categories"]["opt_state"] > 0


def test_cli_attribute_memory_from_run_log(tmp_path, capsys):
    from bigdl_tpu.telemetry import __main__ as cli

    log = tmp_path / "run.jsonl"
    _sharded_step_run(telemetry.JsonlSink(str(log)))
    rc = cli.main(["attribute", "--memory", str(log)])
    out = capsys.readouterr().out
    assert rc == 0 and "per-device peak" in out
    # a log without memory events exits 2 with a hint
    empty = tmp_path / "empty.jsonl"
    with telemetry.run(str(empty)):
        telemetry.instant("epoch", epoch=1)
    assert cli.main(["attribute", "--memory", str(empty)]) == 2


def test_cli_fit_estimator_json_exit_codes(capsys, monkeypatch):
    from bigdl_tpu.telemetry import __main__ as cli

    monkeypatch.setenv("BIGDL_HBM_GB", "1.0")
    rc = cli.main(["memory", "--model", "lenet", "--mesh", "2",
                   "--zero1", "--json"])
    doc = json.loads(capsys.readouterr().out)
    assert rc == 0
    assert doc["fits"] is True and doc["headroom_pct"] > 0
    assert doc["mesh"] == {"devices": 2, "sync": "sharded"}
    assert doc["remat_advice"], "advisor rows expected"
    # an absurdly small budget flips the verdict and the exit code
    monkeypatch.setenv("BIGDL_HBM_GB", "0.0001")
    rc = cli.main(["memory", "--model", "lenet", "--no-advice"])
    out = capsys.readouterr().out
    assert rc == 1 and "DOES NOT FIT" in out
    # nothing to estimate exits 2
    assert cli.main(["memory", "--model", "nosuchmodel"]) == 2


def test_fit_estimator_rejects_oversized_mesh():
    with pytest.raises(ValueError, match="xla_force_host_platform"):
        tmem.attribute_memory_model("lenet", devices=99)


def test_remat_advice_ranks_blocks():
    out = tmem.fit_estimate("transformer", batch=2, devices=1)
    advice = out["remat_advice"]
    assert advice
    blocks = [a for a in advice if a["class"] == "TransformerBlock"]
    assert blocks, advice
    assert all(a["act_bytes"] > 0 for a in advice)
    # sorted by payoff: bytes saved per recompute-FLOP, descending
    ratios = [a["bytes_per_mflop"] for a in advice]
    assert ratios == sorted(ratios, reverse=True)


# -- diff / bench gates -------------------------------------------------------
def _memory_log(path, peak):
    with telemetry.run(str(path)):
        tr = telemetry.get()
        for i in range(1, 4):
            tr.emit("step", step=i, dur=0.01, records=8)
        tr.emit("memory", peak_bytes=peak, args_bytes=peak // 2,
                temp_peak_bytes=peak // 2)


def test_diff_flags_peak_hbm_regression(tmp_path, capsys):
    from bigdl_tpu.telemetry import __main__ as cli

    lean, fat = tmp_path / "lean.jsonl", tmp_path / "fat.jsonl"
    _memory_log(lean, 1_000_000)
    _memory_log(fat, 1_500_000)
    rc = cli.main(["diff", str(lean), str(fat)])
    out = capsys.readouterr().out
    assert rc == 1
    assert "peak_hbm_bytes" in out and "REGRESSED" in out
    # less memory is an improvement, not a regression
    assert cli.main(["diff", str(fat), str(lean)]) == 0
    capsys.readouterr()
    # the dedicated threshold: 60% growth passes a 100% budget
    rc = cli.main(["diff", str(lean), str(fat),
                   "--memory-threshold-pct", "100"])
    assert rc == 0
    capsys.readouterr()
    # --json carries the memory threshold for CI archiving
    rc = cli.main(["diff", str(lean), str(fat), "--json"])
    doc = json.loads(capsys.readouterr().out)
    assert doc["memory_threshold_pct"] == 10.0
    assert rc == 1


def test_bench_row_peak_hbm_diffs_by_suffix():
    from bigdl_tpu.telemetry.diff import bench_metrics, diff_metrics

    a = bench_metrics({"configs": {"x": {"images_per_sec": 10.0,
                                         "peak_hbm_bytes": 100.0}}})
    b = bench_metrics({"configs": {"x": {"images_per_sec": 10.0,
                                         "peak_hbm_bytes": 200.0}}})
    rows = {r["name"]: r for r in diff_metrics(a, b)}
    assert rows["x.peak_hbm_bytes"]["regressed"]
    rows = {r["name"]: r
            for r in diff_metrics(a, b, memory_threshold_pct=200.0)}
    assert not rows["x.peak_hbm_bytes"]["regressed"]


@pytest.mark.deadline(150)
def test_bench_memory_budget_exits_4_on_injected_regression(tmp_path):
    """The acceptance gate: bench.py --memory-budget flags a config
    whose peak_hbm_bytes grew past the budget with exit 4 — the same
    contract as --compile-budget."""
    baseline = tmp_path / "base.json"
    baseline.write_text(json.dumps(
        {"configs": {"lenet_mnist": {"peak_hbm_bytes": 1.0}}}))
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               BENCH_CONFIGS="lenet_mnist", BENCH_ITERS="2",
               BENCH_INFER="0", BIGDL_SINGLETON_WAIT="1")
    env.pop("XLA_FLAGS", None)
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench.py"),
         "--diff-against", str(baseline), "--memory-budget", "10"],
        capture_output=True, text=True, timeout=140, env=env, cwd=REPO)
    assert proc.returncode == 4, proc.stderr[-2000:]
    assert "peak_hbm_bytes" in proc.stderr
    line = json.loads(proc.stdout.strip().splitlines()[-1])
    row = line["configs"]["lenet_mnist"]
    assert row["peak_hbm_bytes"] > 1000
    assert row["hbm_categories"]["params"] > 0


def test_cli_rejects_comms_plus_memory():
    """The two views must not silently shadow each other — and the two
    front-ends must agree (review finding: they resolved the flag pair
    in opposite orders)."""
    from bigdl_tpu.telemetry import __main__ as cli

    with pytest.raises(SystemExit):
        cli.main(["attribute", "--comms", "--memory", "--model",
                  "lenet"])
    from bigdl_tpu.models import cli as mcli

    with pytest.raises(SystemExit):
        mcli.main(["attribute", "--model", "lenet", "--comms",
                   "--memory"])


def test_pressure_judged_against_rows_own_allocator_limit():
    """The allocator's reservation-adjusted bytes_limit is the binding
    constraint — a device at 97% of ITS limit is pressured even when
    the spec-sheet budget says otherwise (review finding: the budget
    used to win and the warning under-fired right before a real OOM)."""
    limit = 10 * (1 << 30)
    live = [{"device": 0, "peak_bytes_in_use": int(limit * 0.97),
             "bytes_limit": limit}]
    # a LARGER configured budget must not mask the allocator ceiling
    hit = tmem.pressured_device(live, budget=16 * (1 << 30))
    assert hit and hit["limit_bytes"] == limit
    # no per-row limit: the budget is the fallback
    bare = [{"device": 0, "peak_bytes_in_use": int(limit * 0.97)}]
    assert tmem.pressured_device(bare, budget=limit)
    assert tmem.pressured_device(bare, budget=None) is None
    # display helper prefers the rows' own limit too
    peak, shown = tmem.live_peak_and_limit(live, 16 * (1 << 30))
    assert peak == int(limit * 0.97) and shown == limit


# -- device table -------------------------------------------------------------
def test_hbm_limit_override_and_table(monkeypatch):
    from bigdl_tpu.telemetry.device import hbm_per_device

    assert hbm_per_device("TPU v4 chip") == 32 * (1 << 30)
    assert hbm_per_device("TPU v5p pod") == 95 * (1 << 30)
    assert hbm_per_device("TPU v5 litepod") == 16 * (1 << 30)
    assert hbm_per_device("cpu") is None
    monkeypatch.setenv("BIGDL_HBM_GB", "2.5")
    assert tmem.hbm_limit_bytes() == int(2.5 * (1 << 30))
    monkeypatch.delenv("BIGDL_HBM_GB")
    # CPU: no table entry, no allocator limit -> None
    assert tmem.hbm_limit_bytes() is None
