"""Engine.check_singleton — the reference's two-drivers-on-one-device
guard (``Engine.scala:165``, ``DistriOptimizer.scala:543-554``), rebuilt
as an advisory per-platform flock because the TPU failure mode (two host
processes contending for one chip's PJRT client) presents as an
indefinite claim hang.  The guard must never touch jax itself."""

import os
import subprocess
import sys
import textwrap

import pytest

from bigdl_tpu.utils.config import BigDLConfig, set_config
from bigdl_tpu.utils.engine import Engine


HOLDER = textwrap.dedent("""
    import os, sys, fcntl, time
    fd = os.open(sys.argv[1], os.O_CREAT | os.O_RDWR, 0o600)
    fcntl.flock(fd, fcntl.LOCK_EX | fcntl.LOCK_NB)
    print("held", flush=True)
    time.sleep(30)
""")


@pytest.fixture
def fresh_lock():
    if Engine._singleton_fd is not None:
        os.close(Engine._singleton_fd)
        Engine._singleton_fd = None
    yield
    if Engine._singleton_fd is not None:
        os.close(Engine._singleton_fd)
        Engine._singleton_fd = None


def test_first_process_acquires(fresh_lock):
    assert Engine.check_singleton(force=True) is True
    assert Engine.check_singleton(force=True) is True  # idempotent while held
    # pid recorded for conflict diagnosis
    with open(Engine._singleton_lock_path()) as f:
        assert f.read().strip() == str(os.getpid())


def test_path_derivation_touches_no_jax(fresh_lock, monkeypatch):
    """The lock identity must come from env/config only — initializing a
    backend IS the claim the guard protects against."""
    monkeypatch.delenv("TPU_VISIBLE_DEVICES", raising=False)
    path = Engine._singleton_lock_path()
    assert "bigdl_tpu_" in path
    monkeypatch.setenv("TPU_VISIBLE_DEVICES", "sentinel-0,1")
    assert Engine._singleton_lock_path() != path  # visibility splits the lock


def test_conflict_warns_and_raises(fresh_lock):
    holder = subprocess.Popen(
        [sys.executable, "-c", HOLDER, Engine._singleton_lock_path()],
        stdout=subprocess.PIPE, text=True)
    try:
        assert holder.stdout.readline().strip() == "held"
        assert Engine.check_singleton(force=True) is False  # default: warn
        with pytest.raises(RuntimeError, match="another process"):
            Engine.check_singleton(raise_on_conflict=True, force=True)
        try:
            set_config(BigDLConfig(check_singleton_strict=True))
            with pytest.raises(RuntimeError):
                Engine.check_singleton(force=True)
        finally:
            set_config(None)
    finally:
        holder.kill()
        holder.wait()


TIMED_HOLDER = textwrap.dedent("""
    import os, sys, fcntl, time
    fd = os.open(sys.argv[1], os.O_CREAT | os.O_RDWR, 0o600)
    fcntl.flock(fd, fcntl.LOCK_EX | fcntl.LOCK_NB)
    print("held", flush=True)
    time.sleep(float(sys.argv[2]))
    os.close(fd)
    time.sleep(30)
""")


def test_wait_rides_out_bounded_claim(fresh_lock):
    """A bench-side claim with ``wait_s`` above the holder's bound must
    acquire after the holder releases (the round-4 watcher/bench
    collision: fail-fast lost the measurement even though the watcher's
    probe claim was bounded)."""
    holder = subprocess.Popen(
        [sys.executable, "-c", TIMED_HOLDER,
         Engine._singleton_lock_path(), "3"],
        stdout=subprocess.PIPE, text=True)
    try:
        assert holder.stdout.readline().strip() == "held"
        # no wait: conflict
        assert Engine.check_singleton(force=True) is False
        # wait past the holder's bound: acquired
        assert Engine.check_singleton(force=True, wait_s=20) is True
    finally:
        holder.kill()
        holder.wait()


def test_wait_deadline_still_conflicts(fresh_lock):
    """An UNbounded holder must still produce a conflict after the
    deadline — the wait is a handoff grace, not an infinite block."""
    holder = subprocess.Popen(
        [sys.executable, "-c", HOLDER, Engine._singleton_lock_path()],
        stdout=subprocess.PIPE, text=True)
    try:
        assert holder.stdout.readline().strip() == "held"
        with pytest.raises(RuntimeError, match="waited"):
            Engine.check_singleton(raise_on_conflict=True, force=True,
                                   wait_s=0.5)
    finally:
        holder.kill()
        holder.wait()


def test_unusable_lockfile_is_advisory(fresh_lock, monkeypatch):
    monkeypatch.setattr(Engine, "_singleton_lock_path",
                        lambda: "/nonexistent-dir/x.lock")
    assert Engine.check_singleton(force=True) is True  # skipped, not a failure


def test_lock_released_on_reset(fresh_lock):
    assert Engine.check_singleton(force=True) is True
    Engine.reset()
    assert Engine._singleton_fd is None
    assert Engine.check_singleton(force=True) is True  # reacquirable


def test_cpu_platform_short_circuits(fresh_lock, monkeypatch):
    """Concurrent CPU-only processes are legitimate — no lock taken."""
    monkeypatch.setenv("JAX_PLATFORMS", "cpu")
    assert Engine.check_singleton() is True
    assert Engine._singleton_fd is None


def test_probe_backend_paths(fresh_lock, monkeypatch):
    import time

    import jax

    # normal path returns the device list
    devs = Engine.probe_backend(timeout_s=60)
    assert len(devs) >= 1

    # a hanging backend raises within the bound instead of blocking
    monkeypatch.setattr(jax, "devices", lambda *a: time.sleep(30))
    with pytest.raises(RuntimeError, match="exceeded"):
        Engine.probe_backend(timeout_s=0.2)

    # a failing backend surfaces its error
    def boom(*a):
        raise ValueError("no backend")

    monkeypatch.setattr(jax, "devices", boom)
    with pytest.raises(RuntimeError, match="no backend"):
        Engine.probe_backend(timeout_s=5)

    # second-driver conflict diagnosed as such, not as a timeout
    monkeypatch.setenv("JAX_PLATFORMS", "faketpu")  # defeat cpu carve-out
    holder = subprocess.Popen(
        [sys.executable, "-c", HOLDER, Engine._singleton_lock_path()],
        stdout=subprocess.PIPE, text=True)
    try:
        assert holder.stdout.readline().strip() == "held"
        with pytest.raises(RuntimeError, match="another process"):
            Engine.probe_backend(timeout_s=5)
    finally:
        holder.kill()
        holder.wait()
