"""Unified runtime telemetry (docs/observability.md): span
nesting/ordering guarantees, schema + Chrome-trace invariants, Metrics
concurrency + event forwarding, straggler/prefetch/retrace visibility,
and the tier-1 end-to-end check — a registry-model CLI training run with
telemetry on must yield a schema-valid JSONL log from which the
inspection CLI reconstructs the stage table, step percentiles,
compile/retrace timeline, and an MFU estimate."""

import glob
import json
import os
import threading

import numpy as np
import pytest

import bigdl_tpu.nn as nn
import bigdl_tpu.optim as optim
from bigdl_tpu import telemetry
from bigdl_tpu.dataset.sample import Sample
from bigdl_tpu.optim.metrics import Metrics
from bigdl_tpu.optim.trigger import Trigger
from bigdl_tpu.telemetry import schema
from bigdl_tpu.telemetry.chrome_trace import chrome_trace
from bigdl_tpu.telemetry.report import format_summary, summarize
from bigdl_tpu.utils.config import set_config


def teardown_function(_fn):
    telemetry.end_run()  # no run leaks across tests
    set_config(None)


def _events(sink, kind):
    return [e for e in sink.events if e["kind"] == kind]


# -- tracer core -------------------------------------------------------------
def test_span_nesting_and_pairing():
    sink = telemetry.MemorySink()
    with telemetry.run(sinks=[sink]):
        with telemetry.span("outer", tag="a"):
            with telemetry.span("inner1"):
                pass
            with telemetry.span("inner2"):
                pass
    assert schema.validate_events(sink.events) == []
    begins = _events(sink, "span_begin")
    ends = _events(sink, "span_end")
    assert [b["name"] for b in begins] == ["outer", "inner1", "inner2"]
    outer, inner1, inner2 = begins
    assert outer["depth"] == 0 and outer["parent"] == 0
    assert inner1["parent"] == outer["span"] and inner1["depth"] == 1
    assert inner2["parent"] == outer["span"] and inner2["depth"] == 1
    # LIFO close order: children end before the parent
    assert [e["name"] for e in ends] == ["inner1", "inner2", "outer"]
    assert all(e["dur"] >= 0 for e in ends)
    assert outer["tag"] == "a"  # attrs travel with the event


def test_span_unwind_closes_abandoned_spans():
    sink = telemetry.MemorySink()
    tracer = telemetry.Tracer(sinks=[sink])
    a = tracer.begin("a")
    tracer.begin("b")  # never explicitly ended
    tracer.end(a)  # must close b first, marked abandoned
    assert schema.validate_events(sink.events) == []
    ends = _events(sink, "span_end")
    assert [e["name"] for e in ends] == ["b", "a"]
    assert ends[0].get("abandoned") is True
    assert "abandoned" not in ends[1]
    tracer.end(12345)  # unknown id: no-op, still balanced
    assert schema.validate_events(sink.events) == []


def test_span_stacks_are_per_thread():
    sink = telemetry.MemorySink()
    tracer = telemetry.Tracer(sinks=[sink])
    barrier = threading.Barrier(2)

    def worker(name):
        barrier.wait()
        with tracer.span(name):
            with tracer.span(name + "/child"):
                pass

    threads = [threading.Thread(target=worker, args=(f"t{i}",))
               for i in range(2)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert schema.validate_events(sink.events) == []
    for b in _events(sink, "span_begin"):
        # each thread's root span parents to 0, never to the other thread
        if not b["name"].endswith("/child"):
            assert b["parent"] == 0 and b["depth"] == 0


def test_module_helpers_are_noops_when_disabled():
    assert not telemetry.enabled()
    telemetry.stage("x", 0.1)
    telemetry.counter("x", 1)
    telemetry.gauge("x", 1)
    telemetry.instant("x")
    with telemetry.span("x"):
        pass  # nullcontext


def test_close_unwinds_spans_left_open_on_other_threads():
    sink = telemetry.MemorySink()
    tracer = telemetry.Tracer(sinks=[sink])
    opened = threading.Event()

    def worker():
        tracer.begin("worker/stuck")  # thread exits without ending it
        opened.set()

    t = threading.Thread(target=worker)
    t.start()
    t.join()
    assert opened.wait(5)
    tracer.close()
    assert schema.validate_events(sink.events) == []
    end = next(e for e in _events(sink, "span_end")
               if e["name"] == "worker/stuck")
    assert end.get("abandoned") is True
    begin = next(e for e in _events(sink, "span_begin")
                 if e["name"] == "worker/stuck")
    assert end["tid"] == begin["tid"] != threading.get_ident()


def test_maybe_run_ownership(tmp_path, monkeypatch):
    # telemetry off: no run started, yields None
    with telemetry.maybe_run() as owned:
        assert owned is None and not telemetry.enabled()
    # configured + no active run: owns it, ends it even on exceptions
    monkeypatch.setenv("BIGDL_TELEMETRY", str(tmp_path))
    with pytest.raises(RuntimeError, match="boom"):
        with telemetry.maybe_run(meta={"cmd": "t"}) as owned:
            assert owned and telemetry.enabled()
            raise RuntimeError("boom")
    assert not telemetry.enabled(), "owned run must end on exception"
    n, errors = schema.validate_run(owned)
    assert errors == [] and n >= 2  # run_start + run_end flushed
    # an OUTER run is never ended (and never re-pointed at a new file)
    sink = telemetry.MemorySink()
    with telemetry.run(sinks=[sink]) as outer:
        with telemetry.maybe_run() as owned:
            assert owned is None
            assert telemetry.get() is outer
        assert telemetry.enabled(), "outer run must survive maybe_run"
        telemetry.instant("after")  # still recorded by the outer run
    assert any(e["name"] == "after" for e in _events(sink, "event"))


def test_nested_start_run_rejected(tmp_path):
    telemetry.start_run(str(tmp_path))
    with pytest.raises(RuntimeError, match="already active"):
        telemetry.start_run(str(tmp_path))
    telemetry.end_run()
    telemetry.end_run()  # idempotent


# -- schema ------------------------------------------------------------------
def test_schema_rejects_malformed_events():
    base = {"v": 1, "ts": 1.0, "pid": 1, "tid": 1}
    assert schema.validate_event({**base, "kind": "nope"})
    assert schema.validate_event({**base, "kind": "stage", "name": "x"})
    assert schema.validate_event(
        {**base, "kind": "stage", "name": 3, "dur": 0.1})
    assert not schema.validate_event(
        {**base, "kind": "stage", "name": "x", "dur": 0.1})
    # structural: unclosed + out-of-order spans
    ev = [dict(base, kind="span_begin", name="a", span=1, parent=0,
               depth=0),
          dict(base, kind="span_begin", name="b", span=2, parent=1,
               depth=1),
          dict(base, kind="span_end", name="a", span=1, dur=0.1)]
    problems = schema.validate_events(ev)
    assert any("out of order" in p for p in problems)
    ev = [dict(base, kind="span_begin", name="a", span=1, parent=0,
               depth=0)]
    assert any("never closed" in p for p in schema.validate_events(ev))


def test_jsonl_roundtrip_and_validate_run(tmp_path):
    path = str(tmp_path / "run.jsonl")
    with telemetry.run(path):
        telemetry.counter("records", 32)
        with telemetry.span("stage_a"):
            telemetry.instant("marker", detail="hello")
    n, errors = schema.validate_run(path)
    assert errors == []
    assert n == 7  # run_start, counter, begin, event, end, goodput, run_end
    events, parse_errors = schema.read_events(path)
    assert parse_errors == []
    assert events[0]["kind"] == "run_start"
    assert events[-1]["kind"] == "run_end"


# -- chrome export -----------------------------------------------------------
def _assert_chrome_nesting(trace):
    stacks = {}
    for ev in trace["traceEvents"]:
        key = (ev.get("pid"), ev.get("tid"))
        if ev["ph"] == "B":
            stacks.setdefault(key, []).append(ev["name"])
        elif ev["ph"] == "E":
            stack = stacks.setdefault(key, [])
            assert stack, f"E without B on lane {key}: {ev['name']}"
            assert stack.pop() == ev["name"], "unbalanced span nesting"
    for key, stack in stacks.items():
        assert not stack, f"unclosed chrome spans on lane {key}: {stack}"


def test_chrome_trace_export_nests_and_types():
    sink = telemetry.MemorySink()
    with telemetry.run(sinks=[sink]):
        with telemetry.span("outer"):
            with telemetry.span("inner"):
                telemetry.gauge("depth", 2)
        telemetry.stage("h2d", 0.01)
        telemetry.instant("fired")
        telemetry.emit("step", step=1, dur=0.5, loss=1.0)
    trace = chrome_trace(sink.events)
    _assert_chrome_nesting(trace)
    phases = {e["ph"] for e in trace["traceEvents"]}
    assert {"B", "E", "X", "C", "i"} <= phases
    xs = [e for e in trace["traceEvents"] if e["ph"] == "X"]
    assert any(e["name"] == "step 1" and e["dur"] == 0.5e6 for e in xs)
    # X events start dur before their emission timestamp
    h2d = next(e for e in xs if e["name"] == "h2d")
    assert h2d["dur"] == pytest.approx(0.01e6)


# -- Metrics: concurrency + forwarding (satellite) ---------------------------
def test_metrics_concurrent_writers_lose_nothing():
    m = Metrics()
    n_threads, n_adds = 8, 400
    barrier = threading.Barrier(n_threads + 1)
    stop = threading.Event()

    def writer(i):
        barrier.wait()
        for _ in range(n_adds):
            m.add("shared stage", 1.0)
            m.add(f"own {i}", 2.0)

    def reader():
        barrier.wait()
        while not stop.is_set():
            m.summary()
            m.get("shared stage")
            m.stages()

    threads = [threading.Thread(target=writer, args=(i,))
               for i in range(n_threads)]
    rt = threading.Thread(target=reader)
    for t in threads + [rt]:
        t.start()
    for t in threads:
        t.join()
    stop.set()
    rt.join()
    assert m.count("shared stage") == n_threads * n_adds
    assert m.total("shared stage") == pytest.approx(n_threads * n_adds)
    for i in range(n_threads):
        assert m.count(f"own {i}") == n_adds
        assert m.get(f"own {i}") == 2.0


def test_metrics_stages_report_in_stable_pipeline_order():
    """ISSUE-3 satellite: summaries of the same run must be comparable
    line-by-line — canonical pipeline stages first (execution order, not
    alphabetical), unknown stages in first-recorded order."""
    m = Metrics()
    for name in ("computing time", "zeta custom", "data time",
                 "alpha custom", "dispatch time"):
        m.add(name, 1.0)
    assert m.stages() == ["data time", "dispatch time", "computing time",
                          "zeta custom", "alpha custom"]
    lines = m.summary().splitlines()[1:-1]
    assert [ln.split(" : ")[0] for ln in lines] == m.stages()


def test_metrics_forward_into_event_log_under_concurrency():
    sink = telemetry.MemorySink()
    with telemetry.run(sinks=[sink]):
        m = Metrics()
        threads = [threading.Thread(
            target=lambda: [m.add("stage", 0.5) for _ in range(100)])
            for _ in range(4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        with m.timer("timed stage"):
            pass
    stages = _events(sink, "stage")
    assert len([e for e in stages if e["name"] == "stage"]) == 400
    assert any(e["name"] == "timed stage" for e in stages)
    assert schema.validate_events(sink.events) == []


# -- runtime visibility: straggler, prefetch, retrace ------------------------
def _make_samples(n=64, dim=4):
    rng = np.random.default_rng(0)
    return [Sample(rng.normal(size=dim).astype(np.float32),
                   np.int64(rng.integers(0, 2))) for _ in range(n)]


def test_straggler_firing_lands_in_event_log(monkeypatch):
    import time as _time

    from bigdl_tpu.optim.optimizer import StragglerTimeout

    sink = telemetry.MemorySink()
    monkeypatch.setenv("BIGDL_ITERATION_TIMEOUT", "0.3")
    o = optim.LocalOptimizer(
        nn.Sequential(nn.Linear(4, 2), nn.LogSoftMax()), _make_samples(),
        nn.ClassNLLCriterion(), batch_size=16,
        end_trigger=Trigger.max_iteration(1))
    with telemetry.run(sinks=[sink]):
        with pytest.raises(StragglerTimeout):
            o._run_with_straggler_guard(lambda: _time.sleep(5))
    fired = [e for e in _events(sink, "event")
             if e["name"] == "straggler/timeout"]
    assert fired and fired[0]["budget_s"] == pytest.approx(0.3)


def test_training_emits_steps_prefetch_depth_and_compiles():
    sink = telemetry.MemorySink()
    model = nn.Sequential(nn.Linear(4, 2), nn.LogSoftMax())
    o = optim.LocalOptimizer(model, _make_samples(),
                             nn.ClassNLLCriterion(), batch_size=16,
                             end_trigger=Trigger.max_iteration(5))
    o.set_optim_method(optim.SGD(learning_rate=0.1))
    with telemetry.run(sinks=[sink]):
        o.optimize()
    assert schema.validate_events(sink.events) == []
    steps = _events(sink, "step")
    assert [e["step"] for e in steps] == [1, 2, 3, 4, 5]
    assert all(e["records"] == 16 and e["dur"] > 0 for e in steps)
    # prefetch (default depth 2) samples its queue fill level
    depths = [e for e in sink.events
              if e["kind"] == "gauge" and e["name"] == "prefetch/queue_depth"]
    assert depths
    # the first dispatch compiled (the Optimizer dispatches via
    # run_sharded), and the facts explain it
    compiles = _events(sink, "compile")
    assert any(c["name"] == "TrainStep.run_sharded" for c in compiles)
    facts = _events(sink, "device_facts")
    assert facts and facts[0]["facts"].get("flops_per_step", 0) > 0
    # iteration spans wrap data_wait spans (nesting in the live log)
    begins = _events(sink, "span_begin")
    it_ids = {b["span"] for b in begins if b["name"] == "train/iteration"}
    dw = [b for b in begins if b["name"] == "data_wait"]
    assert dw and all(b["parent"] in it_ids for b in dw)


def test_unwritable_telemetry_dir_never_kills_training(tmp_path,
                                                       monkeypatch):
    """Telemetry is an observer: a misconfigured BIGDL_TELEMETRY (here a
    plain file where a directory is needed) must log a warning and train
    anyway, not raise out of optimize()."""
    blocker = tmp_path / "not_a_dir"
    blocker.write_text("occupied")
    monkeypatch.setenv("BIGDL_TELEMETRY", str(blocker / "sub"))
    model = nn.Sequential(nn.Linear(4, 2), nn.LogSoftMax())
    o = optim.LocalOptimizer(model, _make_samples(),
                             nn.ClassNLLCriterion(), batch_size=16,
                             end_trigger=Trigger.max_iteration(1))
    o.set_optim_method(optim.SGD(learning_rate=0.1))
    o.optimize()  # must complete
    assert not telemetry.enabled(), "no half-started run may leak"


def test_optimize_preserves_caller_spans():
    """The documented embedding pattern: a span the CALLER opened around
    optimize() must survive it — the loop's exception unwind stops at
    its own scope's depth."""
    sink = telemetry.MemorySink()
    model = nn.Sequential(nn.Linear(4, 2), nn.LogSoftMax())
    o = optim.LocalOptimizer(model, _make_samples(),
                             nn.ClassNLLCriterion(), batch_size=16,
                             end_trigger=Trigger.max_iteration(2))
    o.set_optim_method(optim.SGD(learning_rate=0.1))
    with telemetry.run(sinks=[sink]):
        with telemetry.span("job"):
            o.optimize()
            telemetry.instant("still_inside_job")
    assert schema.validate_events(sink.events) == []
    job_ends = [e for e in _events(sink, "span_end")
                if e["name"] == "job"]
    assert len(job_ends) == 1 and "abandoned" not in job_ends[0]


def test_retrace_bridge_attributes_shape_change():
    import jax

    from bigdl_tpu.parallel.train_step import TrainStep
    from bigdl_tpu.telemetry.bridge import RetraceBridge

    sink = telemetry.MemorySink()
    rng = np.random.default_rng(0)
    with telemetry.run(sinks=[sink]):
        bridge = RetraceBridge(telemetry.get()).install()
        try:
            step = TrainStep(nn.Sequential(nn.Linear(4, 2)),
                             nn.MSECriterion(),
                             optim.SGD(learning_rate=0.1))
            for n in (8, 16):  # batch shape change => retrace
                x = rng.normal(size=(n, 4)).astype(np.float32)
                y = rng.normal(size=(n, 2)).astype(np.float32)
                step.run(x, y, jax.random.key(0))
        finally:
            bridge.remove()
    retraces = _events(sink, "retrace")
    assert any(e["rule"] == "retrace/shape-change" for e in retraces)
    assert len(_events(sink, "compile")) >= 2  # both shapes compiled


def test_aot_scan_respects_device_facts_off(monkeypatch):
    import jax

    from bigdl_tpu.parallel.train_step import TrainStep

    monkeypatch.setenv("BIGDL_TELEMETRY_DEVICE", "off")
    sink = telemetry.MemorySink()
    rng = np.random.default_rng(0)
    x = rng.normal(size=(8, 4)).astype(np.float32)
    y = rng.normal(size=(8, 2)).astype(np.float32)
    with telemetry.run(sinks=[sink]):
        step = TrainStep(nn.Sequential(nn.Linear(4, 2)),
                         nn.MSECriterion(), optim.SGD(learning_rate=0.1))
        step.aot_scan(x, y, jax.random.key(0), 2)
        step.run(x, y, jax.random.key(1))
    # "off" silences BOTH device-facts emitters; compiles still land
    assert not _events(sink, "device_facts")
    assert any(c["name"] == "TrainStep.aot_scan"
               for c in _events(sink, "compile"))


def test_summary_bridge_feeds_tensorboard(tmp_path):
    from bigdl_tpu.visualization import TrainSummary

    ts = TrainSummary(str(tmp_path), "app")
    sink = telemetry.MemorySink()
    model = nn.Sequential(nn.Linear(4, 2), nn.LogSoftMax())
    o = optim.LocalOptimizer(model, _make_samples(),
                             nn.ClassNLLCriterion(), batch_size=16,
                             end_trigger=Trigger.max_iteration(4))
    o.set_optim_method(optim.SGD(learning_rate=0.1))
    o.set_train_summary(ts)
    with telemetry.run(sinks=[sink]):
        o.optimize()
    rows = ts.read_scalar("telemetry/prefetch/queue_depth")
    assert rows, "telemetry gauges bridged into the TrainSummary writer"
    assert ts.read_scalar("Loss")  # the existing scalars still flow
    ts.close()


# -- device facts / MFU ------------------------------------------------------
def test_peak_flops_table_and_override(monkeypatch):
    from bigdl_tpu.telemetry import device

    assert device.peak_flops_per_device("TPU v4") == 275e12
    assert device.peak_flops_per_device("TPU v5 lite") == 197e12
    assert device.peak_flops_per_device("TPU v5p") == 459e12
    assert device.peak_flops_per_device("cpu") is None
    monkeypatch.setenv("BIGDL_PEAK_FLOPS", "2e12")
    assert device.peak_flops_per_device("cpu") == 2e12


def test_mfu_estimate():
    from bigdl_tpu.telemetry.device import mfu_estimate

    assert mfu_estimate(1e12, 0.01, 275e12, 1) == \
        pytest.approx(1e14 / 275e12)
    assert mfu_estimate(1e12, 0.01, 275e12, 4) == \
        pytest.approx(1e14 / (4 * 275e12))
    assert mfu_estimate(0, 0.01, 275e12) is None
    assert mfu_estimate(1e12, 0.01, None) is None


# -- the tier-1 end-to-end acceptance ----------------------------------------
def test_cli_train_with_telemetry_end_to_end(tmp_path, monkeypatch,
                                             capsys):
    """models/cli train (registry model, synthetic data) with telemetry
    on -> schema-valid JSONL -> the inspection CLI reconstructs the
    per-stage table, step p50/p95, compile timeline, and an MFU
    estimate; the Chrome export nests correctly."""
    from bigdl_tpu.models import cli as models_cli
    from bigdl_tpu.telemetry import __main__ as tele_cli

    tele_dir = str(tmp_path / "tele")
    monkeypatch.setenv("BIGDL_TELEMETRY", tele_dir)
    # CPU has no peak-FLOPs table entry; pin one so MFU is computable
    monkeypatch.setenv("BIGDL_PEAK_FLOPS", "1e12")
    models_cli.main(["train", "--model", "lenet", "-b", "256",
                     "--max-epoch", "1", "--telemetry", tele_dir])
    capsys.readouterr()  # drop the training output
    runs = glob.glob(os.path.join(tele_dir, "run-*.jsonl"))
    assert len(runs) == 1
    n, errors = schema.validate_run(runs[0])
    assert errors == [], errors[:5]
    assert n > 20

    events, _ = schema.read_events(runs[0])
    summary = summarize(events)
    # 1024 synthetic records / batch 256 = 4 steps
    assert summary["steps"]["count"] == 4
    assert summary["steps"]["records"] == 1024
    assert summary["steps"]["p95_s"] >= summary["steps"]["p50_s"] > 0
    for stage_name in ("data time", "dispatch time", "validation time",
                       "train/iteration", "data_wait"):
        assert stage_name in summary["stages"], stage_name
    assert any(c["name"] == "TrainStep.run_sharded"
               for c in summary["compiles"])
    facts = summary["device_facts"]
    assert facts["flops_per_step"] > 0
    assert facts["peak_flops_per_device"] == 1e12
    assert summary["mfu"] is not None and summary["mfu"] > 0

    chrome_path = str(tmp_path / "trace.json")
    rc = tele_cli.main([runs[0], "--chrome", chrome_path])
    assert rc == 0
    out = capsys.readouterr().out
    assert "-- stage time --" in out
    assert "p50" in out and "p95" in out
    assert "compile" in out
    assert "MFU" in out
    with open(chrome_path) as fh:
        trace = json.load(fh)
    assert trace["traceEvents"]
    _assert_chrome_nesting(trace)
    rc = tele_cli.main([runs[0], "--validate"])
    assert rc == 0


def test_cli_json_summary_roundtrips(tmp_path, capsys):
    from bigdl_tpu.telemetry import __main__ as tele_cli

    path = str(tmp_path / "run.jsonl")
    with telemetry.run(path):
        telemetry.emit("step", step=1, dur=0.01, records=8,
                       throughput=800.0)
        telemetry.stage("data time", 0.002)
    assert tele_cli.main([path, "--json"]) == 0
    summary = json.loads(capsys.readouterr().out)
    assert summary["steps"]["count"] == 1
    assert summary["stages"]["data time"]["n"] == 1
