"""Multi-host runtime tests (VERDICT r1 item 1).

The reference coordinates N executors through Spark
(``utils/Engine.scala:93-106,344-418``); the TPU build joins processes via
``jax.distributed`` and feeds per-process shards of the global batch.
These tests spin up a REAL 2-process CPU cluster (each process with 2
virtual devices -> a 4-device global mesh) and assert it trains to the
same weights as a single process — the reference's RefDistriOptimizer
equivalence discipline (SURVEY §4) applied across a process boundary.
"""

import os
import subprocess
import sys

import numpy as np
import pytest

# the flock-serialized allocator with the recent-port ledger: two tests
# (or two pytest workers) grabbing ports back-to-back can otherwise race
# the same ephemeral port into both clusters (deflake, ISSUE 20)
from bigdl_tpu.parallel.cluster import _free_port

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
WORKER = os.path.join(REPO, "tests", "multihost_worker.py")


def _worker_env(**extra) -> dict:
    # the worker sets its own XLA_FLAGS/platform before importing jax
    env = {k: v for k, v in os.environ.items()
           if k not in ("XLA_FLAGS", "JAX_PLATFORMS")}
    env["BIGDL_REPO"] = REPO
    env.update({k: str(v) for k, v in extra.items()})
    return env


def _run_cluster(tmp_path, tag: str, nproc: int = 2, expect_out: bool = True,
                 timeout: int = 420, codes=None, **extra) -> str:
    """Run the worker on an ``nproc``-process cluster; return the
    coordinator's saved-params path.  ``expect_out=False`` for runs that
    legitimately end without publishing params (graceful preemption).
    ``codes`` maps process index -> expected returncode for runs where a
    nonzero exit IS the asserted behavior (a shed straggler exits 43).
    The generous default ``timeout`` is deliberate: these tests spin
    real jax.distributed clusters and must stay green on loaded CI
    machines (deflake budget, ISSUE 5)."""
    port = _free_port()
    out = str(tmp_path / f"{tag}.npz")
    procs = [
        subprocess.Popen(
            [sys.executable, WORKER],
            env=_worker_env(BIGDL_COORDINATOR_ADDRESS=f"127.0.0.1:{port}",
                            BIGDL_NUM_PROCESSES=nproc, BIGDL_PROCESS_ID=pid,
                            BIGDL_TEST_OUT=out, **extra),
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT)
        for pid in range(nproc)
    ]
    outputs = []
    try:
        for p in procs:
            stdout, _ = p.communicate(timeout=timeout)
            outputs.append(stdout.decode(errors="replace"))
    finally:
        for p in procs:  # a hung collective must not leak live workers
            if p.poll() is None:
                p.kill()
                p.wait()
    for pid, (p, text) in enumerate(zip(procs, outputs)):
        want = (codes or {}).get(pid, 0)
        assert p.returncode == want, (
            f"cluster worker p{pid} exited {p.returncode} "
            f"(expected {want}):\n{text[-4000:]}")
    if expect_out:
        assert os.path.exists(out), "coordinator did not write params"
    return out


def _run_single(tmp_path, tag: str, **extra) -> str:
    out = str(tmp_path / f"{tag}.npz")
    r = subprocess.run([sys.executable, WORKER],
                       env=_worker_env(BIGDL_TEST_OUT=out, **extra),
                       capture_output=True, timeout=420)
    text = r.stdout.decode(errors="replace") + r.stderr.decode(errors="replace")
    assert r.returncode == 0, f"single-process worker failed:\n{text[-4000:]}"
    return out


def _assert_same_params(path_a: str, path_b: str):
    a, b = np.load(path_a), np.load(path_b)
    assert set(a.files) == set(b.files) and len(a.files) > 0
    for k in a.files:
        np.testing.assert_allclose(a[k], b[k], rtol=2e-4, atol=1e-5,
                                   err_msg=f"param {k} diverged")


@pytest.mark.deadline(240)
def test_two_process_training_matches_single_process(tmp_path):
    mp = _run_cluster(tmp_path, "mp")
    sp = _run_single(tmp_path, "sp")
    _assert_same_params(mp, sp)


@pytest.mark.deadline(300)
def test_four_process_training_matches_single_process(tmp_path):
    """Scale the control-plane test to 4 processes (4 x 2 virtual devices
    = an 8-device global mesh): the trajectory must still match the
    single process — the multi-host path's behavior is process-count
    invariant, the property pod-scale training rests on."""
    mp = _run_cluster(tmp_path, "mp4", nproc=4)
    sp = _run_single(tmp_path, "sp4")
    _assert_same_params(mp, sp)


@pytest.mark.deadline(240)
def test_two_process_sparse_sync_matches_dense_single_process(tmp_path):
    """The sparse-sync acceptance (ISSUE 15, docs/sparse.md) on the
    REAL 2-process gloo cluster: the embedding classifier trained under
    the row-sparse (indices, rows) sync equals the single-process run
    forced DENSE (``BIGDL_SPARSE=off``) — cross-process sync exactness
    and sparse-vs-dense numerics in one trajectory, duplicate indices
    and the padding index included."""
    mp = _run_cluster(tmp_path, "mp_sparse", BIGDL_TEST_SPARSE=1)
    sp = _run_single(tmp_path, "sp_sparse", BIGDL_TEST_SPARSE=1,
                     BIGDL_SPARSE="off")
    _assert_same_params(mp, sp)


@pytest.mark.deadline(240)
def test_two_process_zero1_matches_single_process(tmp_path):
    """ZeRO-1 optimizer-state sharding across the process boundary."""
    mp = _run_cluster(tmp_path, "mp_z1", BIGDL_TEST_ZERO1=1)
    sp = _run_single(tmp_path, "sp_z1")
    _assert_same_params(mp, sp)


@pytest.mark.deadline(240)
def test_two_process_fsdp_matches_single_process(tmp_path):
    """ZeRO-3: the PARAMETERS shard across the process boundary — no
    process holds a whole replica — and the trajectory still equals the
    single-process run (gather_replicated reassembles for the save)."""
    mp = _run_cluster(tmp_path, "mp_fsdp", BIGDL_TEST_FSDP=1)
    sp = _run_single(tmp_path, "sp_fsdp")
    _assert_same_params(mp, sp)


@pytest.mark.deadline(240)
def test_two_process_checkpoint_single_writer(tmp_path):
    """Checkpointing on a cluster: every process participates in the
    gathers but only the coordinator writes files."""
    ckpt = tmp_path / "ckpt"
    ckpt.mkdir()
    _run_cluster(tmp_path, "mp_ck", BIGDL_TEST_CKPT=str(ckpt))
    files = sorted(os.listdir(ckpt))
    assert any(f.startswith("model.") for f in files), files
    assert any(f.startswith("optimMethod.") for f in files), files


@pytest.mark.deadline(240)
def test_two_process_batch_feed_non_dp_layouts(tmp_path):
    """shard_local_batch must scale the global batch by how far the DATA
    axis spans processes, not by the raw process count (a multi-host
    model-parallel mesh feeds the full batch from every process)."""
    _run_cluster(tmp_path, "mp_scale", BIGDL_TEST_PROBE_SCALE=1)


def test_distributed_dataset_shards_partition():
    """Per-process shards cover the dataset exactly once."""
    from bigdl_tpu.dataset.dataset import DistributedDataSet

    data = list(range(10))
    shards = [DistributedDataSet(data, num_shards=3, shard_index=i)
              for i in range(3)]
    seen = sorted(x for s in shards for x in s._data)
    assert seen == data
    assert all(s.global_size() == 10 for s in shards)


def test_engine_single_process_defaults():
    from bigdl_tpu.utils.engine import Engine

    assert Engine.process_count() == 1
    assert Engine.process_index() == 0
    assert Engine.is_coordinator()
    assert len(Engine.local_devices()) == Engine.device_count()


@pytest.mark.deadline(600)
def test_two_process_preempt_resume_matches_uninterrupted(tmp_path):
    """The ISSUE 5 acceptance path: SIGTERM mid-run on the 2-process
    cluster, restart the cluster, and the resumed run's final params
    equal an uninterrupted run's — byte-for-byte training continuity
    across a preemption boundary.

    The SIGTERM is delivered by the fault plan (``preempt@6``: every
    worker signals ITSELF at the start of iteration 6 — the shape of a
    TPU-slice preemption notice, where every host gets the signal), so
    the kill lands mid-epoch-2 deterministically instead of racing the
    test harness against the training loop.  The grace handler finishes
    iteration 6, commits a final checkpoint whose meta carries the
    dataset/epoch position + RNG state, and the workers exit 0 WITHOUT
    publishing params.  The restarted cluster auto-resumes from that
    checkpoint, fast-forwards 32 records into epoch 2, and runs
    iterations 7 and 8."""
    ckpt = tmp_path / "ckpt"
    ckpt.mkdir()
    base = dict(BIGDL_TEST_ITERS=8, BIGDL_TEST_CKPT_EVERY=4)
    # 8 iterations x global batch 16 over 64 records = 2 epochs; epoch 2
    # is SHUFFLED (deterministically by (seed, epoch)) so the resume
    # must reproduce the mid-epoch order, not just a fresh epoch
    un = _run_cluster(tmp_path, "preempt_un",
                      BIGDL_TEST_CKPT=str(tmp_path / "ckpt_un"), **base)
    pre = _run_cluster(tmp_path, "preempt_pre", expect_out=False,
                       BIGDL_TEST_CKPT=str(ckpt),
                       BIGDL_FAULTS="preempt@6", **base)
    assert not os.path.exists(pre), "preempted run must not publish params"
    # final checkpoint landed at the preempted iteration
    assert any(f.startswith("model.6") for f in os.listdir(ckpt)), \
        sorted(os.listdir(ckpt))
    resumed = _run_cluster(tmp_path, "preempt_res",
                           BIGDL_TEST_CKPT=str(ckpt), **base)
    _assert_same_params(resumed, un)


@pytest.mark.deadline(420)
def test_two_process_fleet_observability_blames_slow_host(tmp_path):
    """The ISSUE 10 acceptance path, one live 2-process run covering the
    whole comms/fleet stack: process 1 carries a 250 ms/batch
    data-pipeline stall injected by the ``straggle`` fault plan (the
    deterministic slow-host kind, bigdl_tpu/faults.py — the test-only
    slow-host env knobs are gone), both workers write telemetry into ONE
    shared dir, and

    - the coordinator's live ``/status`` shows the ``fleet`` block with
      per-host rows and ``bigdl_fleet_*`` gauges on ``/metrics``
      (asserted inside the worker — FLEET_STATUS_OK);
    - both run logs validate against the schema, including the new
      ``comms`` events with nonzero collective bytes on the sharded
      step and the coordinator's ``cluster/skew`` instants;
    - the one-shot fleet view over the dir blames p1 with cause
      ``data_wait`` — not p0, whose inflated compute is just the
      collective waiting on the straggler."""
    tele = tmp_path / "tele"
    tele.mkdir()
    _run_cluster(tmp_path, "fleet",
                 BIGDL_TEST_FLEET=1, BIGDL_TEST_ITERS=10,
                 BIGDL_FAULTS="straggle@1:p1:250",
                 BIGDL_TELEMETRY=str(tele), BIGDL_METRICS_PORT=0,
                 BIGDL_FLEET_INTERVAL="0.3")
    import glob

    from bigdl_tpu.telemetry import schema
    from bigdl_tpu.telemetry.fleet import fleet_view

    logs = sorted(glob.glob(str(tele / "run-*.jsonl")))
    assert len(logs) == 2, logs
    loaded = []
    by_pidx = {}
    for path in logs:
        events, parse_errors = schema.read_events(path)
        assert parse_errors == [], parse_errors
        assert schema.validate_events(events) == [], path
        loaded.append((path, events))
        pidx = next(e["meta"].get("process_index") for e in events
                    if e.get("kind") == "run_start")
        by_pidx[pidx] = events
    # comms events with nonzero collective bytes on the sharded step
    for pidx, events in by_pidx.items():
        comms = [e for e in events if e.get("kind") == "comms"]
        assert comms, f"p{pidx} emitted no comms event"
        assert comms[-1]["bytes"] > 0 and comms[-1]["count"] > 0
        assert "data" in comms[-1].get("by_axis", {}), comms[-1]
    # the coordinator's live watcher called the divergence
    skews = [e for e in by_pidx[0]
             if e.get("kind") == "event" and e.get("name") == "cluster/skew"]
    assert skews, "coordinator emitted no cluster/skew instant"
    assert skews[-1]["laggard"] == 1 and skews[-1]["cause"] == "data_wait"
    # the one-shot fleet view reaches the same verdict
    view = fleet_view(loaded)
    assert set(view["hosts"]) == {"p0", "p1"}
    verdict = view["blame"]
    assert verdict is not None, view
    assert verdict["laggard"] == 1 and verdict["cause"] == "data_wait", \
        verdict


@pytest.mark.deadline(420)
def test_two_process_local_sgd_sheds_straggler(tmp_path):
    """The ISSUE 20 acceptance path — straggler-tolerant local SGD on a
    REAL 2-process cluster: both workers train with
    ``parameter_sync=local`` (H=4 local steps between averaging rounds,
    staleness bound S=2), and the fault plan makes p1 a persistent
    250 ms/fetch straggler from fetch 4 on (``straggle@4:p1:250``).
    p1's averaging rounds fall behind; when its lag hits S and it fails
    to catch up within the grace window, the SURVIVOR sheds it:

    - p0 finishes all iterations and publishes finite, actually-trained
      params (exit 0); p1 reads its shed marker and exits 43
      (EXIT_PEER_LOST — the planned-departure code the supervisor
      treats as clean);
    - both run logs validate against the schema and carry the shed
      protocol: ``cluster/shed`` from the survivor (role=survivor,
      peer=1) AND from the victim (role=victim), ``sync/average``
      rounds, and ``sync/staleness`` with the grace wait the ledger
      charges to straggler badput;
    - p1's final heartbeat status is ``shed`` — peers read the exit as
      planned, like done/preempted;
    - the fleet view blames p1 with cause ``data_wait`` — the straggle
      delay lands in the data pipeline, exactly where the blame
      machinery looks."""
    import glob
    import json

    tele = tmp_path / "tele_shed"
    tele.mkdir()
    cluster = tmp_path / "cluster_shed"
    cluster.mkdir()
    base = dict(BIGDL_TEST_LOCAL_SYNC=1, BIGDL_TEST_ITERS=32,
                BIGDL_LOCAL_SYNC_H=4, BIGDL_LOCAL_SYNC_STALE=2,
                BIGDL_LOCAL_SYNC_GRACE="0.5",
                BIGDL_HEARTBEAT_INTERVAL="0.2")
    healthy = _run_cluster(
        tmp_path, "shed_healthy",
        BIGDL_CLUSTER_DIR=str(tmp_path / "cluster_healthy"), **base)
    out = _run_cluster(
        tmp_path, "shed", codes={1: 43},
        BIGDL_FAULTS="straggle@4:p1:250",
        BIGDL_CLUSTER_DIR=str(cluster),
        BIGDL_TELEMETRY=str(tele), **base)

    def dataset_nll(path):
        # the worker's exact data (rng order matters) pushed through its
        # MLP host-side: the whole-dataset loss, not one noisy batch
        z = np.load(path)
        rng = np.random.RandomState(0)
        x = rng.randn(64, 8).astype(np.float32)
        y = rng.randint(0, 4, 64)
        h = np.tanh(x @ z["0.weight"].T + z["0.bias"])
        logits = h @ z["2.weight"].T + z["2.bias"]
        m = logits.max(axis=1, keepdims=True)
        logp = logits - m - np.log(
            np.exp(logits - m).sum(axis=1, keepdims=True))
        return float(-logp[np.arange(64), y].mean())

    # the survivor's params are finite and actually trained: the run
    # that shed its slow half must land within tolerance of the healthy
    # 2-process run (it saw half the data from the shed point on)
    z = np.load(out)
    for k in z.files:
        assert np.isfinite(z[k]).all(), f"non-finite {k}"
    shed_nll, healthy_nll = dataset_nll(out), dataset_nll(healthy)
    init_nll = np.log(4.0)
    assert shed_nll < init_nll - 0.05, (shed_nll, init_nll)
    assert shed_nll < healthy_nll + 0.15, (shed_nll, healthy_nll)
    # the victim's heartbeat closed with the PLANNED-departure status
    hb = json.load(open(cluster / "heartbeat.p1.json"))
    assert hb["status"] == "shed", hb
    # the shed marker names the survivor's verdict
    marker = json.load(open(cluster / "shed.p1.json"))
    assert marker["peer"] == 1 and marker["by"] == 0, marker
    assert marker["lag"] >= marker["stale"] == 2, marker

    from bigdl_tpu.telemetry import schema
    from bigdl_tpu.telemetry.fleet import fleet_view

    logs = sorted(glob.glob(str(tele / "run-*.jsonl")))
    assert len(logs) == 2, logs
    loaded, by_pidx = [], {}
    for path in logs:
        events, parse_errors = schema.read_events(path)
        assert parse_errors == [], parse_errors
        assert schema.validate_events(events) == [], path
        loaded.append((path, events))
        pidx = next(e["meta"].get("process_index") for e in events
                    if e.get("kind") == "run_start")
        by_pidx[pidx] = events

    def named(events, name):
        return [e for e in events if e.get("kind") == "event"
                and e.get("name") == name]

    # both sides of the shed protocol announced themselves
    survivor = named(by_pidx[0], "cluster/shed")
    assert survivor and survivor[-1]["role"] == "survivor" \
        and survivor[-1]["peer"] == 1, survivor
    victim = named(by_pidx[1], "cluster/shed")
    assert victim and victim[-1]["role"] == "victim" \
        and victim[-1]["peer"] == 1, victim
    # averaging rounds ran, and the survivor paid a grace wait at least
    # once before the shed (the wait the ledger charges to straggler)
    assert named(by_pidx[0], "sync/average"), "no averaging rounds"
    waits = [e for e in named(by_pidx[0], "sync/staleness")
             if e.get("waited_s", 0) > 0]
    assert waits, "survivor never held the door before shedding"
    # the fleet view reaches the blame verdict the shed acted on
    view = fleet_view(loaded)
    verdict = view["blame"]
    assert verdict is not None, view
    assert verdict["laggard"] == 1 and verdict["cause"] == "data_wait", \
        verdict


@pytest.mark.deadline(300)
def test_two_process_sharded_validation_matches_full(tmp_path):
    """Validation shards round-robin over processes and merges
    collectively (optim/DistriValidator.scala:35 re-scope): the cluster's
    merged score must equal the single process evaluating the FULL set,
    and the trained weights must stay equivalent."""
    mp = _run_cluster(tmp_path, "mp_val", BIGDL_TEST_SHARDED_VAL=1)
    sp = _run_single(tmp_path, "sp_val", BIGDL_TEST_SHARDED_VAL=1)
    a, b = np.load(mp), np.load(sp)
    np.testing.assert_allclose(a["__score"], b["__score"], rtol=1e-6)
    _assert_same_params(mp, sp)


@pytest.mark.deadline(600)
def test_four_process_preempt_resume_on_two_matches_uninterrupted(tmp_path):
    """The ISSUE 12 acceptance path — the PR-7 recovery contract
    GENERALIZED across mesh shapes: train on 4 processes, SIGTERM
    mid-epoch-2 (``preempt@6`` — the slice-wide preemption shape),
    then resume the SAME checkpoint dir on only 2 processes.  The
    checkpoint is topology-portable: the width-2 cluster restores the
    width-4 state (announced as a ``cluster/reshard`` instant),
    fast-forwards to the exact next global batch, and the final params
    equal the uninterrupted 4-process run's."""
    import glob

    ckpt = tmp_path / "ckpt"
    ckpt.mkdir()
    base = dict(BIGDL_TEST_ITERS=8, BIGDL_TEST_CKPT_EVERY=4)
    un = _run_cluster(tmp_path, "el_un", nproc=4,
                      BIGDL_TEST_CKPT=str(tmp_path / "ckpt_un"), **base)
    pre = _run_cluster(tmp_path, "el_pre", nproc=4, expect_out=False,
                       BIGDL_TEST_CKPT=str(ckpt),
                       BIGDL_FAULTS="preempt@6", **base)
    assert not os.path.exists(pre), "preempted run must not publish params"
    assert any(f.startswith("model.6") for f in os.listdir(ckpt)), \
        sorted(os.listdir(ckpt))
    tele = tmp_path / "tele_el"
    resumed = _run_cluster(tmp_path, "el_res", nproc=2,
                           BIGDL_TEST_CKPT=str(ckpt),
                           BIGDL_TELEMETRY=str(tele), **base)
    _assert_same_params(resumed, un)
    # both width-2 workers restored the width-4 checkpoint and said so
    from bigdl_tpu.telemetry.schema import read_events

    marks = []
    for path in glob.glob(str(tele / "run-*.jsonl")):
        events, _errs = read_events(path)
        marks += [e for e in events if e.get("kind") == "event"
                  and e.get("name") == "cluster/reshard"]
    assert len(marks) == 2, marks
    assert all(e["from_processes"] == 4 and e["to_processes"] == 2
               for e in marks), marks
