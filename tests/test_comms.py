"""Per-collective comms attribution (telemetry/comms.py, ISSUE 10).

Covers the HLO collective parser (both replica-groups spellings,
variadic operands, async -start forms), mesh-axis inference, the
byte-accounting acceptance criterion (within 10% of the analytic
parameter-payload expectation on 2-device sharded lenet/transformer
steps — the XLA cost_analysis bytes-accessed convention: operand +
output), module attribution of gradient collectives, the per-step
``comms`` event and its knob, the CLI views, the trace-time parser, and
the diff gate."""

import gzip
import json

import jax
import numpy as np
import pytest

import bigdl_tpu.nn as nn
import bigdl_tpu.optim as optim
from bigdl_tpu import telemetry
from bigdl_tpu.parallel.mesh import make_mesh
from bigdl_tpu.parallel.train_step import TrainStep
from bigdl_tpu.telemetry import comms, schema
from bigdl_tpu.utils.config import BigDLConfig, set_config


@pytest.fixture(autouse=True)
def _fresh_config():
    set_config(None)
    yield
    set_config(None)


# -- the HLO parser ----------------------------------------------------------
def test_parse_hlo_collectives_brace_and_iota_groups():
    hlo = """
  %all-reduce.1 = f32[4,8]{1,0} all-reduce(f32[4,8]{1,0} %p0), channel_id=1, replica_groups={{0,1},{2,3}}, use_global_device_ids=true, to_apply=%add, metadata={op_name="jit(step)/jit(main)/transpose(jvp(fc1))/dot_general"}
  %all-gather = f32[16,8]{1,0} all-gather(f32[8,8]{1,0} %p1), channel_id=2, replica_groups=[2,2]<=[4], dimensions={0}, metadata={op_name="jit(step)/jit(main)/jvp(fc2)/dot_general"}
  %reduce-scatter = f32[4]{0} reduce-scatter(f32[8]{0} %p2), channel_id=3, replica_groups=[1,2]<=[2], dimensions={0}, to_apply=%add
  %all-reduce-done = f32[4,8]{1,0} all-reduce-done(f32[4,8]{1,0} %ar)
"""
    colls = comms.parse_hlo_collectives(hlo, ("data", "model"), (2, 2))
    assert [c.opcode for c in colls] == ["all-reduce", "all-gather",
                                        "reduce-scatter"]
    ar, ag, rs = colls
    assert ar.payload_bytes == 4 * 8 * 4 and ar.bytes == 2 * 4 * 8 * 4
    assert ar.path == "fc1" and ar.direction == "bwd"
    assert ar.groups == [(0, 1), (2, 3)]
    # all-gather: out = in * group_size
    assert ag.payload_bytes == 8 * 8 * 4
    assert ag.bytes == 8 * 8 * 4 * (1 + 2)
    assert ag.direction == "fwd"
    # reduce-scatter: out = in / group_size
    assert rs.payload_bytes == 8 * 4 and rs.bytes == 8 * 4 + 4 * 4
    # the -done half of an async pair is never double-counted
    assert len(colls) == 3


def test_parse_hlo_variadic_and_start_forms():
    hlo = """
  %all-reduce = (f32[4]{0}, f32[2,2]{1,0}) all-reduce(f32[4]{0} %a, f32[2,2]{1,0} %b), channel_id=5, replica_groups={{0,1}}, to_apply=%add
  %all-reduce-start = f32[8]{0} all-reduce-start(f32[8]{0} %c), channel_id=6, replica_groups={{0,1}}, to_apply=%add
"""
    colls = comms.parse_hlo_collectives(hlo, ("data",), (2,))
    assert len(colls) == 2
    # the combiner's variadic all-reduce sums every operand
    assert colls[0].payload_bytes == (4 + 4) * 4
    assert colls[1].payload_bytes == 8 * 4
    assert all(c.axes == ("data",) for c in colls)


def test_infer_axes_subsets_and_permute_pairs():
    names, sizes = ("data", "model"), (2, 4)
    # model-axis groups on a (2,4) mesh: {0..3} and {4..7}
    assert comms.infer_axes([(0, 1, 2, 3), (4, 5, 6, 7)], names, sizes) \
        == ("model",)
    # data-axis groups pair positions 4 apart
    assert comms.infer_axes([(0, 4), (1, 5), (2, 6), (3, 7)],
                            names, sizes) == ("data",)
    # everything at once
    assert comms.infer_axes([tuple(range(8))], names, sizes) \
        == ("data", "model")
    # a permute ring along the model axis (not a partition)
    ring = [(0, 1), (1, 2), (2, 3), (3, 0)]
    assert comms.infer_axes(ring, names, sizes) == ("model",)
    # a pair crossing BOTH axes at once names nothing
    assert comms.infer_axes([(0, 7)], names, sizes) == ()
    assert comms.infer_axes(None, names, sizes) == ()


# -- acceptance: bytes within 10% of the analytic expectation ---------------
def _param_bytes(step):
    return sum(int(np.prod(np.shape(v))) * 4 for v in step.params.values())


@pytest.mark.parametrize("name,batch", [("lenet", 8), ("transformer", 2)])
def test_comms_bytes_match_cost_accounting(name, batch):
    """The acceptance criterion: on the 2-device batch-sharded lenet and
    transformer train steps, the walker's collective bytes-accessed
    must land within 10% of the analytic expectation — every f32
    gradient is all-reduced, and the bytes-accessed convention (operand
    + output, as XLA's cost analysis counts an op) makes that 2x the
    parameter bytes, modulo the scalar loss psum."""
    from bigdl_tpu.models import registry

    mesh = make_mesh((2,), ("data",), devices=jax.devices()[:2])
    model = registry.build_model(name)
    spec = registry.input_spec(name, batch)
    criterion, tspec = registry.train_pieces(name, batch)
    step = TrainStep(model, criterion,
                     optim.SGD(learning_rate=0.01, momentum=0.9),
                     mesh=mesh, parameter_sync="allreduce")
    out = comms.attribute_comms_train_step(step, spec, tspec)
    assert out["count"] > 0
    expected = 2 * _param_bytes(step)
    assert abs(out["bytes"] - expected) / expected < 0.10, \
        (out["bytes"], expected)
    # every byte crosses the data axis — the replica groups resolved
    assert out["by_axis"].get("data", 0) == out["bytes"]
    # gradient collectives attribute onto real modules, backward pass
    named = [r for r in out["rows"] if r["path"] != "(unattributed)"]
    assert named, out["rows"]
    assert sum(r["bytes"] for r in named) / out["bytes"] > 0.9
    text = comms.format_comms(out)
    assert "all-reduce" in text and "data" in text


def test_comms_zero1_moves_more_bytes_than_allreduce():
    """ZeRO-1 ('sharded') trades the plain gradient all-reduce for a
    reduce-scatter + sharded update + param all-gather — exactly the
    bytes-moved-per-axis accounting question of arXiv 2004.13336, and
    the walker must expose the difference so `diff` can gate it: more
    collective ops, more bytes accessed than the dense all-reduce, all
    still crossing the data axis."""
    from bigdl_tpu.models import registry

    mesh = make_mesh((2,), ("data",), devices=jax.devices()[:2])
    criterion, tspec = registry.train_pieces("lenet", 8)
    spec = registry.input_spec("lenet", 8)
    outs = {}
    for sync in ("allreduce", "sharded"):
        step = TrainStep(registry.build_model("lenet"), criterion,
                         optim.SGD(learning_rate=0.01, momentum=0.9),
                         mesh=mesh, parameter_sync=sync)
        outs[sync] = comms.attribute_comms_train_step(step, spec, tspec)
    dense, zero = outs["allreduce"], outs["sharded"]
    assert zero["bytes"] > dense["bytes"]
    assert zero["by_axis"].get("data", 0) == zero["bytes"]
    # the ZeRO layout introduces gather/scatter traffic beside (or
    # instead of) the plain all-reduce
    assert set(zero["by_op"]) != {"all-reduce"} or \
        zero["count"] > dense["count"], zero["by_op"]


def test_single_device_step_has_no_collectives():
    model = nn.Sequential(nn.Linear(6, 4), nn.LogSoftMax())
    step = TrainStep(model, nn.ClassNLLCriterion(),
                     optim.SGD(learning_rate=0.1))
    x = jax.ShapeDtypeStruct((4, 6), np.float32)
    y = jax.ShapeDtypeStruct((4,), np.int32)
    out = comms.attribute_comms_train_step(step, x, y)
    assert out["count"] == 0 and out["bytes"] == 0
    assert "no collectives" in comms.format_comms(out)


# -- the comms event + knob --------------------------------------------------
def _sharded_step_run(sink):
    mesh = make_mesh((2,), ("data",), devices=jax.devices()[:2])
    model = nn.Sequential(nn.Linear(6, 8), nn.Tanh(), nn.Linear(8, 4),
                          nn.LogSoftMax())
    step = TrainStep(model, nn.ClassNLLCriterion(),
                     optim.SGD(learning_rate=0.1), mesh=mesh)
    x = np.ones((8, 6), np.float32)
    y = np.zeros((8,), np.int64)
    with telemetry.run(sinks=[sink]):
        step.run(x, y, jax.random.key(0))


def test_comms_event_emitted_for_sharded_step_by_default():
    sink = telemetry.MemorySink()
    _sharded_step_run(sink)
    events = [e for e in sink.events if e.get("kind") == "comms"]
    assert len(events) == 1
    ev = events[0]
    assert schema.validate_event(ev) == []
    assert ev["count"] > 0 and ev["bytes"] > 0
    assert ev["by_axis"].get("data") == ev["bytes"]
    assert ev["program"] == "train_step"


def test_comms_on_knob_survives_device_facts_off():
    """BIGDL_COMMS=on must emit even with BIGDL_TELEMETRY_DEVICE=off —
    the two knobs are independent (review finding: the device-level
    early return used to mute comms too)."""
    set_config(BigDLConfig(telemetry_device="off", telemetry_comms="on"))
    sink = telemetry.MemorySink()
    _sharded_step_run(sink)
    kinds = [e.get("kind") for e in sink.events]
    assert "comms" in kinds
    assert "device_facts" not in kinds  # the device level still holds


def test_comms_event_off_knob_and_single_device_auto():
    set_config(BigDLConfig(telemetry_comms="off"))
    sink = telemetry.MemorySink()
    _sharded_step_run(sink)
    assert not [e for e in sink.events if e.get("kind") == "comms"]
    # auto + no mesh: nothing emitted either
    set_config(None)
    sink2 = telemetry.MemorySink()
    model = nn.Sequential(nn.Linear(6, 4), nn.LogSoftMax())
    step = TrainStep(model, nn.ClassNLLCriterion(),
                     optim.SGD(learning_rate=0.1))
    with telemetry.run(sinks=[sink2]):
        step.run(np.ones((4, 6), np.float32), np.zeros((4,), np.int64),
                 jax.random.key(0))
    assert not [e for e in sink2.events if e.get("kind") == "comms"]


def test_comms_event_rides_aot_scan_without_extra_compile():
    mesh = make_mesh((2,), ("data",), devices=jax.devices()[:2])
    model = nn.Sequential(nn.Linear(6, 8), nn.Tanh(), nn.Linear(8, 4),
                          nn.LogSoftMax())
    step = TrainStep(model, nn.ClassNLLCriterion(),
                     optim.SGD(learning_rate=0.1), mesh=mesh)
    x = np.ones((8, 6), np.float32)
    y = np.zeros((8,), np.int64)
    sink = telemetry.MemorySink()
    with telemetry.run(sinks=[sink]):
        step.aot_scan(x, y, jax.random.key(0), 3)
    events = [e for e in sink.events if e.get("kind") == "comms"]
    assert len(events) == 1
    assert events[0]["program"] == "aot_scan"
    # the scan body holds each collective once: per-iteration numbers
    assert events[0]["bytes"] > 0


# -- CLI ---------------------------------------------------------------------
def test_cli_attribute_comms_model_and_json(capsys):
    from bigdl_tpu.telemetry import __main__ as cli

    rc = cli.main(["attribute", "--comms", "--model", "lenet",
                   "--mesh", "2"])
    out = capsys.readouterr().out
    assert rc == 0
    assert "comms attribution" in out and "all-reduce" in out
    rc = cli.main(["attribute", "--comms", "--model", "lenet",
                   "--mesh", "2", "--json"])
    doc = json.loads(capsys.readouterr().out)
    assert rc == 0 and doc["count"] > 0
    assert doc["by_axis"]["data"] == doc["bytes"]


def test_cli_attribute_comms_from_run_log(tmp_path, capsys):
    from bigdl_tpu.telemetry import __main__ as cli

    log = tmp_path / "run.jsonl"
    _sharded_step_run(telemetry.JsonlSink(str(log)))
    rc = cli.main(["attribute", "--comms", str(log)])
    out = capsys.readouterr().out
    assert rc == 0 and "all-reduce" in out
    # a log without comms events exits 2 with a hint
    empty = tmp_path / "empty.jsonl"
    with telemetry.run(str(empty)):
        telemetry.instant("epoch", epoch=1)
    assert cli.main(["attribute", "--comms", str(empty)]) == 2


# -- measured wall time from a capture ---------------------------------------
def test_collective_times_from_trace_and_cli_enrichment(tmp_path, capsys):
    trace_dir = tmp_path / "profile-x"
    (trace_dir / "plugins").mkdir(parents=True)
    doc = {"traceEvents": [
        {"ph": "X", "name": "all-reduce.3", "dur": 1500.0, "ts": 0},
        {"ph": "X", "name": "fusion.allreduce_wrapper", "dur": 500.0,
         "ts": 10},
        {"ph": "X", "name": "reduce-scatter.1", "dur": 250.0, "ts": 20},
        {"ph": "X", "name": "dot_general", "dur": 9999.0, "ts": 30},
        {"ph": "i", "name": "all-reduce-instant-ignored", "ts": 40},
    ]}
    with gzip.open(trace_dir / "plugins" / "host.trace.json.gz", "wt",
                   encoding="utf-8") as fh:
        json.dump(doc, fh)
    times = comms.collective_times_from_trace(str(trace_dir))
    assert times["all-reduce"] == pytest.approx(2000.0 / 1e6)
    assert times["reduce-scatter"] == pytest.approx(250.0 / 1e6)
    assert "all-to-all" not in times
    # a perfetto-enabled capture may write BOTH spellings for the SAME
    # events: the perfetto file must win outright, never sum with the
    # chrome one (review finding: durations used to double)
    with gzip.open(trace_dir / "perfetto_trace.json.gz", "wt",
                   encoding="utf-8") as fh:
        json.dump(doc, fh)
    times = comms.collective_times_from_trace(str(trace_dir))
    assert times["all-reduce"] == pytest.approx(2000.0 / 1e6)

    # a run log naming the capture gets measured_s + achieved bandwidth
    from bigdl_tpu.telemetry import __main__ as cli

    log = tmp_path / "run.jsonl"
    with telemetry.run(str(log)):
        telemetry.emit("comms", count=2, bytes=4_000_000,
                       payload_bytes=2_000_000,
                       by_axis={"data": 4_000_000}, program="train_step")
        telemetry.instant("profile/armed", steps=2, dir=str(trace_dir),
                          source="http", perfetto=True)
        telemetry.instant("profile/captured", dir=str(trace_dir),
                          source="http", perfetto=True)
    rc = cli.main(["attribute", "--comms", str(log), "--json"])
    doc = json.loads(capsys.readouterr().out)
    assert rc == 0
    # 2.25 ms of collectives over 2 captured steps — and the per-op
    # split carries the SAME per-step unit as the total
    assert doc["measured_s"] == pytest.approx(2250.0 / 1e6 / 2)
    assert doc["measured_by_op"]["all-reduce"] == \
        pytest.approx(2000.0 / 1e6 / 2)
    assert sum(doc["measured_by_op"].values()) == \
        pytest.approx(doc["measured_s"])
    assert doc["measured_from"] == str(trace_dir)


def test_profiler_arm_perfetto_flag_roundtrip():
    from bigdl_tpu.telemetry import profiler

    ctl = profiler.ProfilerControl()
    assert ctl.arm(2, "/tmp/nowhere", perfetto=True)
    assert ctl.perfetto is True
    ctl.abort()
    assert ctl.state == profiler.IDLE


# -- diff gate ---------------------------------------------------------------
def _comms_log(path, nbytes, expected_s=None):
    with telemetry.run(str(path)):
        tr = telemetry.get()
        for i in range(1, 4):
            tr.emit("step", step=i, dur=0.01, records=8)
        fields = {"count": 4, "bytes": nbytes,
                  "payload_bytes": nbytes // 2}
        if expected_s is not None:
            fields["expected_s"] = expected_s
        tr.emit("comms", **fields)


def test_diff_flags_comms_bytes_regression(tmp_path, capsys):
    from bigdl_tpu.telemetry import __main__ as cli

    lean, fat = tmp_path / "lean.jsonl", tmp_path / "fat.jsonl"
    _comms_log(lean, 1_000_000, expected_s=0.001)
    _comms_log(fat, 1_500_000, expected_s=0.0015)
    rc = cli.main(["diff", str(lean), str(fat)])
    out = capsys.readouterr().out
    assert rc == 1
    assert "comms_bytes" in out and "REGRESSED" in out
    assert "comms_s" in out
    # fewer bytes moved is an improvement, not a regression
    assert cli.main(["diff", str(fat), str(lean)]) == 0


def test_bench_row_comms_fields_diff_by_suffix():
    from bigdl_tpu.telemetry.diff import bench_metrics, diff_metrics

    a = bench_metrics({"configs": {"x": {"images_per_sec": 10.0,
                                         "comms_bytes": 100.0,
                                         "comms_s": 0.01}}})
    b = bench_metrics({"configs": {"x": {"images_per_sec": 10.0,
                                         "comms_bytes": 200.0,
                                         "comms_s": 0.02}}})
    rows = {r["name"]: r for r in diff_metrics(a, b)}
    assert rows["x.comms_bytes"]["regressed"]
    assert rows["x.comms_s"]["regressed"]


# -- device table ------------------------------------------------------------
def test_peak_bw_override_and_table(monkeypatch):
    from bigdl_tpu.telemetry.device import peak_bw_per_device

    monkeypatch.delenv("BIGDL_PEAK_BW", raising=False)
    assert peak_bw_per_device("TPU v5 lite") == 2.0e11
    assert peak_bw_per_device("TPU v5p chip") == 6.0e11  # longest prefix
    assert peak_bw_per_device("cpu") is None
    monkeypatch.setenv("BIGDL_PEAK_BW", "1e9")
    assert peak_bw_per_device("anything") == 1e9
