"""Cluster-level fault-tolerance suite (ISSUE 7,
``bigdl_tpu/parallel/cluster.py`` + docs/fault_tolerance.md
"Distributed failures").

Unit layer: heartbeat publish/stale detection, incarnation hygiene,
the two-phase commit barrier (certify / bounded timeout), the
manifest-capped restore walk, /healthz turning 503 on degradation, the
supervisor's bounded-restart loop, and the interruptible retry
backoff.

E2E layer (real multi-process gloo clusters, every test carrying an
explicit ``deadline`` marker so a deadlocked collective can never eat
the tier-1 budget): ``peer_wedge`` → every host EXITS with the
distinct peer-lost code instead of hanging in the all-reduce;
``commit_crash`` → the cluster manifest makes the uncertified step-4
checkpoint structurally invisible, every host restores the SAME step,
and the finished run still matches the uninterrupted one;
``peer_kill`` under the supervisor → watchdog abort within the
deadline, full-cluster restart from the cluster-consistent
checkpoint, final params equal the uninterrupted run.
"""

import json
import os
import signal
import subprocess
import sys
import threading
import time
import urllib.request

import numpy as np
import pytest

import bigdl_tpu.nn as nn
import bigdl_tpu.optim as optim
from bigdl_tpu import faults, telemetry
from bigdl_tpu.dataset.sample import Sample
from bigdl_tpu.parallel import cluster
from bigdl_tpu.utils.config import set_config
from bigdl_tpu.utils.rng import RNG

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
WORKER = os.path.join(REPO, "tests", "multihost_worker.py")


def setup_function(_fn):
    faults.reset()
    cluster.deactivate()


def teardown_function(_fn):
    telemetry.end_run()
    set_config(None)
    faults.reset()
    cluster.deactivate()


# -- heartbeat + watchdog ----------------------------------------------------
def test_derive_deadline(monkeypatch):
    monkeypatch.setenv("BIGDL_CLUSTER_DEADLINE", "7.5")
    assert cluster.derive_deadline() == 7.5
    monkeypatch.delenv("BIGDL_CLUSTER_DEADLINE")
    monkeypatch.setenv("BIGDL_ITERATION_TIMEOUT", "30")
    assert cluster.derive_deadline() == 60.0  # 2x the straggler budget
    monkeypatch.setenv("BIGDL_ITERATION_TIMEOUT", "auto")
    assert cluster.derive_deadline() == 120.0  # conservative default


def test_heartbeat_stale_peer_detected_and_clean_exit_ignored(tmp_path):
    d = str(tmp_path)
    # monitor first: beats older than the monitor's start read as
    # previous-incarnation leftovers by design
    mon = cluster.ClusterMonitor(d, 0, 2, deadline=0.4, interval=0.1,
                                 abort=False)
    hb0 = cluster.HeartbeatPublisher(d, 0, interval=0.05).start()
    hb1 = cluster.HeartbeatPublisher(d, 1, interval=0.05).start()
    time.sleep(0.06)  # step beats ride the interval throttle
    hb0.beat(1)
    hb1.beat(1)
    mon._check(time.time())
    assert not mon.degraded()
    table = mon.peer_table()
    assert table["p1"]["step"] == 1 and table["p1"]["status"] == "running"
    time.sleep(0.6)  # p1 goes silent past the deadline
    mon._check(time.time())
    assert mon.degraded()
    assert "no heartbeat" in mon.peer_table()["p1"]["lost"]
    # a refreshed beat clears the verdict...
    hb1.beat(2)
    mon._check(time.time())
    assert not mon.degraded()
    # ...and a clean final status is NEVER a loss, however stale
    hb1.stop("done")
    time.sleep(0.6)
    mon._check(time.time())
    assert not mon.degraded()
    assert mon.peer_table()["p1"]["status"] == "done"


def test_failed_status_is_an_immediate_loss(tmp_path):
    d = str(tmp_path)
    mon = cluster.ClusterMonitor(d, 0, 2, deadline=30.0, interval=0.1,
                                 abort=False)
    cluster.HeartbeatPublisher(d, 1, interval=0.05).start().stop("failed")
    mon._check(time.time())
    assert mon.degraded()
    assert mon.peer_table()["p1"]["lost"] == "peer reported failed"


def test_monitor_ignores_previous_incarnation_heartbeats(tmp_path):
    """Stale files from a dead incarnation must not speak for a fresh
    one: the monitor only tracks beats newer than its own start."""
    d = str(tmp_path)
    path = os.path.join(d, "heartbeat.p1.json")
    with open(path, "w") as fh:
        json.dump({"process_index": 1, "step": 7, "status": "running",
                   "pid": 1, "ts": time.time() - 3600}, fh)
    mon = cluster.ClusterMonitor(d, 0, 2, deadline=0.2, interval=0.1,
                                 abort=False)
    time.sleep(0.3)
    mon._check(time.time())
    assert not mon.degraded()
    assert mon.peer_table()["p1"]["status"] == "unseen" or \
        "lost" not in mon.peer_table()["p1"]


def test_peer_lost_fire_emits_instant_and_flight_dump(tmp_path,
                                                      monkeypatch):
    """The (abort-disabled) firing path: ``cluster/peer_lost`` instant
    with the liveness snapshot + a flight dump with the peer table as
    evidence."""
    monkeypatch.setenv("BIGDL_TELEMETRY", str(tmp_path / "tele"))
    d = str(tmp_path / "hb")
    mon = cluster.ClusterMonitor(d, 0, 2, deadline=0.1, interval=0.05,
                                 abort=False)
    hb1 = cluster.HeartbeatPublisher(d, 1, interval=0.05).start()
    time.sleep(0.06)  # step beats ride the interval throttle
    hb1.beat(3)
    sink = telemetry.MemorySink()
    with telemetry.run(str(tmp_path / "tele"), sinks=[sink]):
        time.sleep(0.3)
        mon._check(time.time())
        assert mon.degraded()
        mon._fire()
    lost = [e for e in sink.events if e.get("kind") == "event"
            and e.get("name") == "cluster/peer_lost"]
    assert len(lost) == 1 and lost[0]["peers"] == [1]
    dumps = [f for f in os.listdir(tmp_path / "tele")
             if f.startswith("flight-")]
    assert len(dumps) == 1
    payload = json.loads((tmp_path / "tele" / dumps[0]).read_text())
    assert payload["reason"] == "peer_lost"
    assert payload["evidence"]["peer_table"]["p1"]["step"] == 3


# -- the commit barrier ------------------------------------------------------
def test_commit_barrier_certifies_only_with_all_acks(tmp_path):
    svc0 = cluster.ClusterService(str(tmp_path / "hb"), 0, 2,
                                  deadline=1.0, abort=False)
    svc1 = cluster.ClusterService(str(tmp_path / "hb"), 1, 2,
                                  deadline=1.0, abort=False)
    ck = str(tmp_path / "ckpt")
    os.makedirs(ck)
    assert cluster.manifest_step(ck) is None
    assert svc1.commit_step(ck, 4)                 # phase 1: peer ack
    assert svc0.commit_step(ck, 4,                 # phase 2: manifest
                            digests={"model.4": "sha"})
    assert cluster.manifest_step(ck) == 4
    manifest = json.loads(
        (tmp_path / "ckpt" / "cluster_manifest.json").read_text())
    assert manifest["acks"]["p0"]["digests"] == {"model.4": "sha"}
    # a missing ack leaves the manifest at the PREVIOUS step (bounded)
    t0 = time.time()
    assert not svc0.commit_step(ck, 8, timeout=0.3)
    assert time.time() - t0 < 2.0
    assert cluster.manifest_step(ck) == 4
    # committed-step acks pruned, newer (uncertified) acks retained
    names = sorted(os.listdir(ck))
    assert "commit.p0.8.json" in names


def test_latest_verified_step_dir_max_step_cap(tmp_path):
    """The cluster-consistent restore walk: steps above the manifest
    cap are skipped WITHOUT quarantine — intact, merely uncertified."""
    from bigdl_tpu.utils.sharded_ckpt import latest_verified_step_dir

    for n in (2, 4):
        d = tmp_path / f"sharded.{n}"
        d.mkdir()
        (d / "bigdl_meta.json").write_text(
            json.dumps({"extra": {"neval": n}, "digests": {}}))
    assert latest_verified_step_dir(str(tmp_path)).endswith("sharded.4")
    capped = latest_verified_step_dir(str(tmp_path), max_step=2)
    assert capped.endswith("sharded.2")
    # nothing was quarantined by the capped walk
    assert sorted(os.listdir(tmp_path)) == ["sharded.2", "sharded.4"]
    svc = cluster.ClusterService(str(tmp_path / "hb"), 0, 2,
                                 deadline=1.0, abort=False)
    # no manifest -> uncapped (pre-cluster dirs stay restorable)
    assert svc.latest_consistent_step_dir(
        str(tmp_path)).endswith("sharded.4")
    cluster._atomic_write_json(str(tmp_path / "cluster_manifest.json"),
                               {"step": 2})
    assert svc.latest_consistent_step_dir(
        str(tmp_path)).endswith("sharded.2")


def test_prune_old_never_deletes_the_manifest_step(tmp_path):
    """Retention must not strand the cluster: the manifest step stays
    on disk even when newer (possibly uncertified) checkpoints fill
    the keep window — cluster restores CAP at the manifest step, so
    deleting it would leave them nothing to restore."""
    from bigdl_tpu.utils.sharded_ckpt import prune_old

    for n in (2, 4, 6):
        d = tmp_path / f"sharded.{n}"
        d.mkdir()
        (d / "bigdl_meta.json").write_text(
            json.dumps({"extra": {"neval": n}, "digests": {}}))
    pruned = prune_old(str(tmp_path), keep=1, keep_step=2)
    assert [os.path.basename(p) for p in pruned] == ["sharded.4"]
    assert sorted(os.listdir(tmp_path)) == ["sharded.2", "sharded.6"]


# -- /healthz + /status ------------------------------------------------------
def test_healthz_503_and_status_peer_table_when_degraded(tmp_path,
                                                         monkeypatch):
    monkeypatch.setenv("BIGDL_METRICS_PORT", "0")
    d = str(tmp_path / "hb")
    svc = cluster.ClusterService(d, 0, 2, deadline=0.2, abort=False)
    svc.heartbeat.start()
    hb1 = cluster.HeartbeatPublisher(d, 1, interval=0.05).start()
    time.sleep(0.06)  # step beats ride the interval throttle
    hb1.beat(5)
    cluster._service = svc  # install without a full activate()
    try:
        with telemetry.run(str(tmp_path / "tele")):
            server = telemetry.metrics_server()
            assert server is not None
            base = f"http://127.0.0.1:{server.port}"

            def get(path):
                try:
                    with urllib.request.urlopen(base + path,
                                                timeout=5) as r:
                        return r.status, r.read().decode()
                except urllib.error.HTTPError as e:
                    return e.code, e.read().decode()

            code, _ = get("/healthz")
            assert code == 200
            time.sleep(0.4)  # p1 stalls past the deadline
            svc.monitor._check(time.time())
            assert svc.degraded()
            code, body = get("/healthz")
            assert code == 503 and "degraded" in body
            _, body = get("/status")
            st = json.loads(body)
            assert st["cluster"]["state"] == "degraded"
            assert st["cluster"]["peers"]["p1"]["lost"]
            assert st["cluster"]["peers"]["p1"]["step"] == 5
    finally:
        cluster._service = None


# -- the supervisor ----------------------------------------------------------
def _toy_worker(body: str) -> list:
    return [sys.executable, "-c", body]


def test_supervisor_restarts_until_clean_and_reports_history(tmp_path,
                                                             monkeypatch):
    """First incarnation fails, second succeeds: one restart, exit 0,
    and the exit history records both."""
    monkeypatch.setenv("BIGDL_RETRY_BACKOFF", "0.01")
    # marker is PER-PROCESS: a shared marker would race (whichever
    # worker starts first plants it before the other checks)
    marker = tmp_path / "already_failed"
    body = (f"import os, sys\n"
            f"m = {str(marker)!r} + os.environ['BIGDL_PROCESS_ID']\n"
            f"if not os.path.exists(m):\n"
            f"    open(m, 'w').close()\n"
            f"    sys.exit(7 if os.environ['BIGDL_PROCESS_ID'] == '1' "
            f"else 0)\n")
    sup = cluster.Supervisor(2, _toy_worker(body), max_restarts=3,
                             cluster_dir=str(tmp_path / "cl"),
                             settle_grace=5.0)
    assert sup.run() == 0
    assert sup.restarts == 1
    assert len(sup.exit_history) == 2
    assert 7 in sup.exit_history[0]
    assert sup.exit_history[1] == [0, 0]


def test_supervisor_restart_budget_exhausts(tmp_path, monkeypatch):
    monkeypatch.setenv("BIGDL_RETRY_BACKOFF", "0.01")
    sup = cluster.Supervisor(2, _toy_worker("import sys; sys.exit(5)"),
                             max_restarts=1,
                             cluster_dir=str(tmp_path / "cl"),
                             settle_grace=5.0)
    assert sup.run() == 1
    assert len(sup.exit_history) == 2  # original + 1 restart


def test_supervisor_clears_fault_plan_on_restart(tmp_path, monkeypatch):
    """An injected fault plan describes ONE scenario: replaying it every
    incarnation would make recovery impossible, so restarts clear
    ``BIGDL_FAULTS`` (``--keep-faults`` opts out)."""
    monkeypatch.setenv("BIGDL_RETRY_BACKOFF", "0.01")
    out = tmp_path / "plans"
    out.mkdir()
    body = (f"import os, sys\n"
            f"inc = os.environ['BIGDL_SUPERVISOR_INCARNATION']\n"
            f"pid = os.environ['BIGDL_PROCESS_ID']\n"
            f"open(os.path.join({str(out)!r}, f'inc{{inc}}.p{{pid}}'), "
            f"'w').write(os.environ.get('BIGDL_FAULTS', '<unset>'))\n"
            f"sys.exit(3 if inc == '0' and pid == '0' else 0)\n")
    env = dict(os.environ)
    env["BIGDL_FAULTS"] = "peer_kill@6:p2"
    sup = cluster.Supervisor(2, _toy_worker(body), max_restarts=2,
                             cluster_dir=str(tmp_path / "cl"),
                             settle_grace=5.0, env=env)
    assert sup.run() == 0
    assert (out / "inc0.p0").read_text() == "peer_kill@6:p2"
    assert (out / "inc1.p0").read_text() == ""


# -- interruptible retry backoff ---------------------------------------------
@pytest.mark.deadline(120)
def test_sigterm_interrupts_retry_backoff(tmp_path, monkeypatch):
    """Satellite bugfix: a SIGTERM during the retry-backoff sleep used
    to wait out the FULL sleep before the grace handler could act.  Now
    the backoff waits on the preempt guard's event: a crash with a
    ~15-30s backoff plus a SIGTERM at ~1.5s must return preempted in a
    few seconds, not tens."""
    monkeypatch.setenv("BIGDL_RETRY_BACKOFF", "60")  # >=15s after jitter
    monkeypatch.setenv("BIGDL_FAULTS", "crash@1")
    faults.reset()
    RNG.set_seed(11)
    rng = np.random.default_rng(0)
    x = rng.normal(size=(64, 4)).astype(np.float32)
    samples = [Sample(x[i], np.int64(i % 2)) for i in range(64)]
    model = nn.Sequential(nn.Linear(4, 8), nn.Tanh(), nn.Linear(8, 2),
                          nn.LogSoftMax())
    o = optim.LocalOptimizer(model, samples, nn.ClassNLLCriterion(),
                             batch_size=16,
                             end_trigger=optim.Trigger.max_iteration(4))
    o.set_optim_method(optim.SGD(learning_rate=0.1))
    sink = telemetry.MemorySink()
    timer = threading.Timer(
        1.5, lambda: os.kill(os.getpid(), signal.SIGTERM))
    t0 = time.perf_counter()
    timer.start()
    try:
        with telemetry.run(sinks=[sink]):
            o.optimize()
    finally:
        timer.cancel()
    elapsed = time.perf_counter() - t0
    assert o.preempted
    assert elapsed < 12.0, (
        f"backoff was not interrupted: took {elapsed:.1f}s")
    marks = [e for e in sink.events if e.get("kind") == "event"
             and e.get("name") == "run/preempted"]
    assert len(marks) == 1 and marks[0]["signum"] == signal.SIGTERM


# -- E2E: the distributed fault matrix on live clusters ----------------------
# the flock-serialized allocator with the recent-port ledger: concurrent
# test processes (and back-to-back clusters in one test) no longer race
# each other into the same coordinator port (deflake, ISSUE 20)
_free_port = cluster._free_port


def _worker_env(**extra) -> dict:
    env = {k: v for k, v in os.environ.items()
           if k not in ("XLA_FLAGS", "JAX_PLATFORMS", "BIGDL_FAULTS")}
    env["BIGDL_REPO"] = REPO
    env.update({k: str(v) for k, v in extra.items()})
    return env


def _launch_cluster(nproc: int, **extra) -> list:
    port = _free_port()
    return [subprocess.Popen(
        [sys.executable, WORKER],
        env=_worker_env(BIGDL_COORDINATOR_ADDRESS=f"127.0.0.1:{port}",
                        BIGDL_NUM_PROCESSES=nproc, BIGDL_PROCESS_ID=pid,
                        **extra),
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT)
        for pid in range(nproc)]


def _wait_all(procs, timeout: int):
    outs = []
    try:
        for p in procs:
            stdout, _ = p.communicate(timeout=timeout)
            outs.append(stdout.decode(errors="replace"))
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
                p.wait()
    return [p.returncode for p in procs], outs


def _events_by_process(tele_dir: str):
    """kind=='event' telemetry events per process index, from the
    per-process run logs."""
    from bigdl_tpu.telemetry.schema import read_events

    out = {}
    for f in sorted(os.listdir(tele_dir)):
        if not (f.startswith("run-") and f.endswith(".jsonl")):
            continue
        pidx = int(f.split("-p")[1].split("-")[0])
        events, _errs = read_events(os.path.join(tele_dir, f))
        out.setdefault(pidx, []).extend(
            e for e in events if e.get("kind") == "event")
    return out


def _assert_same_params(path_a: str, path_b: str, tol=1e-6):
    a, b = np.load(path_a), np.load(path_b)
    assert set(a.files) == set(b.files) and len(a.files) > 0
    for k in a.files:
        np.testing.assert_allclose(a[k], b[k], rtol=tol, atol=tol,
                                   err_msg=f"param {k} diverged")


@pytest.mark.deadline(240)
def test_peer_wedge_surviving_hosts_exit_instead_of_hanging(tmp_path):
    """``peer_wedge@3:p1`` on a live 2-process cluster with NO straggler
    budget set: the wedged host stalls inside its iteration, the
    survivor blocks in the dead all-reduce — and within the cluster
    deadline EVERY process exits with the distinct peer-lost code
    instead of hanging until the harness timeout.  The run logs carry
    ``cluster/peer_lost`` and a flight dump."""
    tele = tmp_path / "tele"
    procs = _launch_cluster(
        2, BIGDL_TEST_OUT=str(tmp_path / "never.npz"),
        BIGDL_TEST_ITERS=8, BIGDL_TEST_CKPT=str(tmp_path / "ckpt"),
        BIGDL_TEST_CKPT_EVERY=2, BIGDL_FAULTS="peer_wedge@3:p1",
        BIGDL_CLUSTER_DIR=str(tmp_path / "hb"),
        # deadline 6 not 3: under a loaded CI host the first tracing
        # step alone can stall a worker past 3 s of missed heartbeats
        # and fire a spurious peer_lost (deflake, ISSUE 20)
        BIGDL_CLUSTER_DEADLINE=6, BIGDL_HEARTBEAT_INTERVAL=0.2,
        BIGDL_TELEMETRY=str(tele), BIGDL_ASYNC_CHECKPOINT=0,
        BIGDL_RETRY_BACKOFF=0.05)
    codes, outs = _wait_all(procs, timeout=120)
    # the FIRST watchdog abort (43) takes the jax coordinator down with
    # it, and the other host's distributed-runtime client may then
    # SIGABRT on coordinator loss before its own watchdog wins the
    # race — either way it EXITED, which is the property: no hang
    assert all(c in (cluster.EXIT_PEER_LOST, -signal.SIGABRT)
               for c in codes), (codes, outs[0][-2000:],
                                 outs[1][-2000:])
    assert cluster.EXIT_PEER_LOST in codes, (codes, outs[0][-2000:])
    assert not (tmp_path / "never.npz").exists()
    by_proc = _events_by_process(str(tele))
    names = [e["name"] for events in by_proc.values() for e in events]
    assert "cluster/peer_lost" in names, names
    assert any(f.startswith("flight-") for f in os.listdir(tele))


@pytest.mark.deadline(360)
def test_commit_crash_never_yields_mixed_step_restore(tmp_path):
    """``commit_crash@4:p1``: p1 dies AFTER reaching the step-4 commit
    point but BEFORE its barrier ack, so the manifest stays at step 2
    even though the coordinator's step-4 checkpoint is durable and
    digest-verifies.  The restarted cluster must restore the SAME
    step-2 checkpoint on every host — model.4 exists on disk, and is
    still structurally invisible — and the finished run must match an
    uninterrupted one."""
    base = dict(BIGDL_TEST_ITERS=8, BIGDL_TEST_CKPT_EVERY=2,
                BIGDL_CLUSTER_DEADLINE=6, BIGDL_HEARTBEAT_INTERVAL=0.2,
                BIGDL_ASYNC_CHECKPOINT=0, BIGDL_RETRY_BACKOFF=0.05)
    # uninterrupted control
    un = str(tmp_path / "un.npz")
    codes, outs = _wait_all(_launch_cluster(
        2, BIGDL_TEST_OUT=un, BIGDL_TEST_CKPT=str(tmp_path / "ckpt_un"),
        BIGDL_CLUSTER_DIR=str(tmp_path / "hb_un"), **base), timeout=120)
    assert codes == [0, 0], (codes, outs[0][-2000:], outs[1][-2000:])
    # incarnation 0: dies in the commit window
    ckpt = str(tmp_path / "ckpt")
    codes, outs = _wait_all(_launch_cluster(
        2, BIGDL_TEST_OUT=str(tmp_path / "crashed.npz"),
        BIGDL_TEST_CKPT=ckpt, BIGDL_CLUSTER_DIR=str(tmp_path / "hb"),
        BIGDL_FAULTS="commit_crash@4:p1", **base), timeout=120)
    assert codes[1] == -signal.SIGKILL, (codes, outs[1][-2000:])
    assert codes[0] != 0, codes  # the survivor must NOT report success
    # the step-4 pair is durable, complete, digest-marked — yet
    # uncertified: a restore without the manifest WOULD pick it
    assert os.path.exists(os.path.join(ckpt, "model.4"))
    assert os.path.exists(os.path.join(ckpt, "ckptmeta.4.json"))
    assert cluster.manifest_step(ckpt) == 2, \
        "the barrier must not certify a step missing an ack"
    # incarnation 1: fresh cluster, no faults, same dirs
    tele = tmp_path / "tele"
    out = str(tmp_path / "resumed.npz")
    codes, outs = _wait_all(_launch_cluster(
        2, BIGDL_TEST_OUT=out, BIGDL_TEST_CKPT=ckpt,
        BIGDL_CLUSTER_DIR=str(tmp_path / "hb"),
        BIGDL_TELEMETRY=str(tele), **base), timeout=120)
    assert codes == [0, 0], (codes, outs[0][-2000:], outs[1][-2000:])
    by_proc = _events_by_process(str(tele))
    sources = {}
    for pidx, events in by_proc.items():
        resumed = [e for e in events if e["name"] == "run/resumed"]
        assert len(resumed) == 1, (pidx, [e["name"] for e in events])
        sources[pidx] = resumed[0]["step"]
    # NO MIXED STEPS: every host resumed at the manifest step, not at
    # the newer-but-uncertified one
    assert sources == {0: 2, 1: 2}, sources
    _assert_same_params(out, un)


@pytest.mark.deadline(420)
def test_supervised_peer_kill_restart_matches_uninterrupted(tmp_path):
    """The ISSUE 7 acceptance path, on the live 4-process cluster:
    SIGKILL one of 4 workers mid-epoch under the supervisor.  The
    surviving hosts' watchdogs fire within the deadline (distinct exit
    code — no indefinite collective hang), the supervisor restarts the
    full cluster, auto-resume lands on the cluster-consistent step-4
    checkpoint, and the final params equal the uninterrupted run's."""
    base = dict(BIGDL_TEST_ITERS=8, BIGDL_TEST_CKPT_EVERY=4,
                BIGDL_CLUSTER_DEADLINE=6, BIGDL_HEARTBEAT_INTERVAL=0.2,
                BIGDL_ASYNC_CHECKPOINT=0, BIGDL_RETRY_BACKOFF=0.05)
    un = str(tmp_path / "un.npz")
    codes, outs = _wait_all(_launch_cluster(
        4, BIGDL_TEST_OUT=un, BIGDL_TEST_CKPT=str(tmp_path / "ckpt_un"),
        BIGDL_CLUSTER_DIR=str(tmp_path / "hb_un"), **base), timeout=180)
    assert codes == [0, 0, 0, 0], (codes, outs[0][-2000:])
    for attempt in ("first", "last"):
        out = str(tmp_path / f"supervised_{attempt}.npz")
        env = _worker_env(BIGDL_TEST_OUT=out,
                          BIGDL_TEST_CKPT=str(tmp_path /
                                              f"ckpt_{attempt}"),
                          BIGDL_FAULTS="peer_kill@6:p2", **base)
        sup = cluster.Supervisor(4, [sys.executable, WORKER],
                                 max_restarts=3,
                                 cluster_dir=str(tmp_path /
                                                 f"cl_{attempt}"),
                                 settle_grace=30.0, env=env,
                                 log_dir=str(tmp_path /
                                             f"logs_{attempt}"))
        rc = sup.run()
        first = sup.exit_history[0]
        if -signal.SIGKILL not in first and attempt == "first":
            # incarnation 0 died before iteration 6 (a startup infra
            # flake under suite load — the injected kill never fired,
            # so none of the kill-specific properties apply); the
            # supervisor itself must still have recovered the cluster
            assert rc == 0, (sup.exit_history, rc)
            continue
        assert rc == 0, sup.exit_history
        assert sup.restarts == 1, sup.exit_history
        assert -signal.SIGKILL in first, first  # the injected kill
        # every survivor EXITED (no hang): via its own watchdog (43)
        # or SIGABRTed by the jax runtime when the first watchdog
        # abort took the coordinator down — and at least one abort
        # came from the watchdog itself, within its settle window
        survivors = [c for c in first if c != -signal.SIGKILL]
        assert all(c in (cluster.EXIT_PEER_LOST, -signal.SIGABRT)
                   for c in survivors), first
        assert cluster.EXIT_PEER_LOST in first, first
        assert sup.exit_history[1] == [0, 0, 0, 0], sup.exit_history
        assert os.path.exists(out), \
            "restarted cluster must publish params"
        _assert_same_params(out, un)
        break


# -- capacity-aware width (supervise --min-n, ISSUE 12) ----------------------
def test_supervisor_min_n_shrinks_after_repeated_same_casualty(
        tmp_path, monkeypatch):
    """Two consecutive incarnations dying on the SAME peer slot = the
    host isn't coming back: the next incarnation launches DEGRADED at
    --min-n instead of burning the restart budget at a doomed width."""
    monkeypatch.setenv("BIGDL_RETRY_BACKOFF", "0.01")
    body = ("import os, sys\n"
            "sys.exit(9 if os.environ['BIGDL_NUM_PROCESSES'] == '4' "
            "and os.environ['BIGDL_PROCESS_ID'] == '2' else 0)\n")
    sup = cluster.Supervisor(4, _toy_worker(body), max_restarts=3,
                             cluster_dir=str(tmp_path / "cl"),
                             settle_grace=5.0, min_nprocs=2)
    assert sup.run() == 0
    assert sup.width_history == [4, 4, 2]
    assert sup.restarts == 2
    assert [len(c) for c in sup.exit_history] == [4, 4, 2]
    assert sup.exit_history[0][2] == 9 and sup.exit_history[1][2] == 9
    assert sup.exit_history[2] == [0, 0]


def test_supervisor_min_n_grows_back_after_degraded_failure(
        tmp_path, monkeypatch):
    """A failure at degraded width retries the FULL -n first (capacity
    may have returned) — the cluster is never pinned small forever by a
    stale casualty verdict."""
    monkeypatch.setenv("BIGDL_RETRY_BACKOFF", "0.01")
    body = ("import os, sys\n"
            "n = os.environ['BIGDL_NUM_PROCESSES']\n"
            "pid = os.environ['BIGDL_PROCESS_ID']\n"
            "inc = os.environ['BIGDL_SUPERVISOR_INCARNATION']\n"
            "if n == '4' and pid == '2' and inc in ('0', '1'):\n"
            "    sys.exit(9)\n"
            "sys.exit(5 if n == '2' else 0)\n")
    sup = cluster.Supervisor(4, _toy_worker(body), max_restarts=4,
                             cluster_dir=str(tmp_path / "cl"),
                             settle_grace=5.0, min_nprocs=2)
    assert sup.run() == 0
    assert sup.width_history == [4, 4, 2, 4]
    assert sup.exit_history[3] == [0, 0, 0, 0]


def test_supervisor_min_n_distinct_casualties_do_not_shrink(
        tmp_path, monkeypatch):
    """Different slots dying in consecutive incarnations is churn, not
    a missing host — the width stays declared."""
    monkeypatch.setenv("BIGDL_RETRY_BACKOFF", "0.01")
    body = ("import os, sys\n"
            "inc = os.environ['BIGDL_SUPERVISOR_INCARNATION']\n"
            "pid = os.environ['BIGDL_PROCESS_ID']\n"
            "sys.exit(9 if (inc, pid) in (('0', '1'), ('1', '2')) "
            "else 0)\n")
    sup = cluster.Supervisor(3, _toy_worker(body), max_restarts=3,
                             cluster_dir=str(tmp_path / "cl"),
                             settle_grace=5.0, min_nprocs=1)
    assert sup.run() == 0
    assert sup.width_history == [3, 3, 3]


def test_supervisor_shed_exit_is_clean_completion(tmp_path, monkeypatch):
    """A ``shed.p<idx>.json`` marker (the staleness barrier's verdict,
    parallel/local_sync.py) makes that slot's exit-43 a PLANNED
    departure: survivors finishing 0 means the cluster COMPLETED
    (degraded) — no restart, exit 0."""
    monkeypatch.setenv("BIGDL_RETRY_BACKOFF", "0.01")
    body = ("import json, os, sys\n"
            "d = os.environ['BIGDL_CLUSTER_DIR']\n"
            "if os.environ['BIGDL_PROCESS_ID'] == '1':\n"
            "    with open(os.path.join(d, 'shed.p1.json'), 'w') as f:\n"
            "        json.dump({'peer': 1, 'by': 0, 'round': 3,\n"
            "                   'lag': 2, 'stale': 2}, f)\n"
            f"    sys.exit({cluster.EXIT_PEER_LOST})\n"
            "sys.exit(0)\n")
    sup = cluster.Supervisor(3, _toy_worker(body), max_restarts=2,
                             cluster_dir=str(tmp_path / "cl"),
                             settle_grace=5.0)
    assert sup.run() == 0
    assert sup.restarts == 0
    assert sup.width_history == [3]
    assert sup.exit_history == [[0, cluster.EXIT_PEER_LOST, 0]]


def test_supervisor_shed_failure_shrinks_to_min_n_immediately(
        tmp_path, monkeypatch):
    """Shrink-then-grow-back wiring for the shed verdict: a shed marker
    is an AFFIRMATIVE "this host is not coming back", so when the
    incarnation still fails the supervisor relaunches DEGRADED at
    ``--min-n`` at once — no two-round same-casualty signature needed."""
    monkeypatch.setenv("BIGDL_RETRY_BACKOFF", "0.01")
    body = ("import json, os, sys\n"
            "pid = os.environ['BIGDL_PROCESS_ID']\n"
            "d = os.environ['BIGDL_CLUSTER_DIR']\n"
            "if os.environ['BIGDL_NUM_PROCESSES'] == '3':\n"
            "    if pid == '1':\n"
            "        with open(os.path.join(d, 'shed.p1.json'), 'w') "
            "as f:\n"
            "            json.dump({'peer': 1, 'by': 0}, f)\n"
            f"        sys.exit({cluster.EXIT_PEER_LOST})\n"
            "    if pid == '0':\n"
            "        sys.exit(9)\n"
            "sys.exit(0)\n")
    sup = cluster.Supervisor(3, _toy_worker(body), max_restarts=3,
                             cluster_dir=str(tmp_path / "cl"),
                             settle_grace=5.0, min_nprocs=2)
    assert sup.run() == 0
    assert sup.restarts == 1
    assert sup.width_history == [3, 2], sup.exit_history
    assert sup.exit_history[1] == [0, 0]


def test_supervisor_min_n_validation():
    with pytest.raises(ValueError, match="min_nprocs"):
        cluster.Supervisor(4, _toy_worker("pass"), min_nprocs=5)
    with pytest.raises(ValueError, match="min_nprocs"):
        cluster.Supervisor(4, _toy_worker("pass"), min_nprocs=0)


@pytest.mark.deadline(420)
def test_supervised_peer_kill_min_n_recovers_at_reduced_width(tmp_path):
    """The ISSUE 12 acceptance path: on the live 4-process cluster a
    kept ``peer_kill@6:p2`` fault models a host that NEVER comes back
    (it fires in every full-width incarnation).  With ``--min-n 2`` the
    supervisor relaunches DEGRADED at width 2 after two consecutive
    losses of the same peer, the width-2 workers restore the width-4
    BTPU checkpoint (topology-portable — announced as cluster/reshard),
    and the finished run's params equal an uninterrupted run's, with
    zero manual intervention."""
    base = dict(BIGDL_TEST_ITERS=8, BIGDL_TEST_CKPT_EVERY=4,
                BIGDL_CLUSTER_DEADLINE=6, BIGDL_HEARTBEAT_INTERVAL=0.2,
                BIGDL_ASYNC_CHECKPOINT=0, BIGDL_RETRY_BACKOFF=0.05)
    un = str(tmp_path / "un.npz")
    codes, outs = _wait_all(_launch_cluster(
        2, BIGDL_TEST_OUT=un, BIGDL_TEST_CKPT=str(tmp_path / "ckpt_un"),
        BIGDL_CLUSTER_DIR=str(tmp_path / "hb_un"), **base), timeout=120)
    assert codes == [0, 0], (codes, outs[0][-2000:], outs[1][-2000:])
    tele = tmp_path / "tele"
    out = str(tmp_path / "degraded.npz")
    env = _worker_env(BIGDL_TEST_OUT=out,
                      BIGDL_TEST_CKPT=str(tmp_path / "ckpt"),
                      BIGDL_TELEMETRY=str(tele),
                      BIGDL_FAULTS="peer_kill@6:p2", **base)
    sup = cluster.Supervisor(4, [sys.executable, WORKER],
                             max_restarts=3, min_nprocs=2,
                             keep_faults=True,  # the host NEVER returns
                             cluster_dir=str(tmp_path / "cl"),
                             settle_grace=30.0, env=env,
                             log_dir=str(tmp_path / "logs"))
    rc = sup.run()
    assert rc == 0, sup.exit_history
    killed_incs = [i for i, codes in enumerate(sup.exit_history)
                   if -signal.SIGKILL in codes]
    if not killed_incs:
        # startup infra flake under suite load: the injected kill never
        # fired, so none of the width properties apply — the supervisor
        # itself still recovered the cluster
        return
    # two full-width incarnations lost the same peer, then the degraded
    # width-2 incarnation finished the job
    assert sup.width_history[:2] == [4, 4], sup.width_history
    assert sup.width_history[-1] == 2, sup.width_history
    assert sup.exit_history[-1] == [0, 0], sup.exit_history
    assert os.path.exists(out), "degraded cluster must publish params"
    # mixed-width trajectory (iters 1-4 at width 4, 5-8 at width 2) vs
    # the width-2 uninterrupted control: the cross-width tolerance the
    # process-count-invariance tests (tests/test_multihost.py) pin
    _assert_same_params(out, un, tol=2e-4)
    # the width-2 workers announced the reshard on restore
    by_proc = _events_by_process(str(tele))
    marks = [e for events in by_proc.values() for e in events
             if e["name"] == "cluster/reshard"]
    assert marks, "no cluster/reshard instant in the degraded run logs"
    assert any(e.get("from_processes") == 4 and e.get("to_processes") == 2
               for e in marks), marks


def test_supervisor_min_n_signature_survives_racing_survivors(
        tmp_path, monkeypatch):
    """Review hardening: which SURVIVOR reacts how is a race (watchdog
    43 vs gloo connection-reset generic exit), so the casualty sets of
    consecutive incarnations need not be EQUAL — the persistent slot
    (their intersection) is the missing host, and the shrink must still
    fire."""
    monkeypatch.setenv("BIGDL_RETRY_BACKOFF", "0.01")
    body = ("import os, sys\n"
            "n = os.environ['BIGDL_NUM_PROCESSES']\n"
            "pid = os.environ['BIGDL_PROCESS_ID']\n"
            "inc = os.environ['BIGDL_SUPERVISOR_INCARNATION']\n"
            "if n == '4' and pid == '2':\n"
            "    sys.exit(9)  # the host that never comes back\n"
            "if (inc, pid) in (('0', '1'), ('1', '3')):\n"
            "    sys.exit(7)  # a racing survivor, different each round\n"
            "sys.exit(0)\n")
    sup = cluster.Supervisor(4, _toy_worker(body), max_restarts=3,
                             cluster_dir=str(tmp_path / "cl"),
                             settle_grace=5.0, min_nprocs=2)
    assert sup.run() == 0
    assert sup.width_history == [4, 4, 2]
    assert sup.exit_history[2] == [0, 0]
