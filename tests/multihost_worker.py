"""Worker process for the multi-host equivalence test.

Runs the SAME deterministic training job in two modes:
- cluster mode: BIGDL_COORDINATOR_ADDRESS/BIGDL_NUM_PROCESSES/
  BIGDL_PROCESS_ID set -> Engine joins the 2-process CPU cluster, the
  global mesh spans 2x2=4 virtual devices, and each process feeds its
  shard of the global batch;
- single-process control: no coordinator env -> one process, 2 devices.

The coordinator writes the final parameters to BIGDL_TEST_OUT; the test
asserts both modes converge to the same weights (the reference's
RefDistriOptimizer equivalence discipline, SURVEY §4).
"""

import os
import sys

os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"

import jax

jax.config.update("jax_platforms", "cpu")

sys.path.insert(0, os.environ["BIGDL_REPO"])

import numpy as np  # noqa: E402

from bigdl_tpu.utils.engine import Engine  # noqa: E402

Engine.reset()
Engine.init()

import bigdl_tpu.nn as nn  # noqa: E402
import bigdl_tpu.optim as optim  # noqa: E402
from bigdl_tpu.dataset.sample import Sample  # noqa: E402
from bigdl_tpu.utils.rng import RNG  # noqa: E402


def probe_batch_scale():
    """Feed-path shapes for non-pure-DP layouts: when the data axis does
    not span processes (multi-host model/seq parallelism) every process
    feeds the FULL global batch; when it does, each feeds 1/P."""
    from bigdl_tpu.parallel.mesh import make_mesh, shard_local_batch

    # data axis size 1 -> model-parallel-only: local rows ARE the batch
    tp_mesh = make_mesh((1, 4), ("data", "model"), devices=jax.devices())
    arr = shard_local_batch(tp_mesh, np.ones((6, 3), np.float32))
    assert arr.shape == (6, 3), arr.shape
    # data axis across both processes: global batch is 2x the local rows
    dp_mesh = make_mesh((4, 1), ("data", "model"), devices=jax.devices())
    arr = shard_local_batch(dp_mesh, np.ones((6, 3), np.float32))
    assert arr.shape == (12, 3), arr.shape
    if Engine.is_coordinator():
        np.savez(os.environ["BIGDL_TEST_OUT"], ok=np.ones(1))
    print(f"worker {Engine.process_index()}/{Engine.process_count()} done",
          flush=True)


def main():
    expect_procs = int(os.environ.get("BIGDL_NUM_PROCESSES", "1"))
    assert Engine.process_count() == expect_procs, (
        Engine.process_count(), expect_procs)
    if os.environ.get("BIGDL_TEST_PROBE_SCALE"):
        probe_batch_scale()
        return

    # fleet-observability e2e (tests/test_multihost.py): the WORKER owns
    # the telemetry run (so it outlives optimize() and the coordinator
    # can read its own live /status fleet block before ending it)
    fleet_mode = bool(os.environ.get("BIGDL_TEST_FLEET"))
    if fleet_mode:
        from bigdl_tpu import telemetry

        telemetry.start_run(os.environ["BIGDL_TELEMETRY"])

    RNG.set_seed(7)
    rng = np.random.RandomState(0)
    if os.environ.get("BIGDL_TEST_SPARSE"):
        # sparse embedding-sync equivalence (tests/test_sparse.py's
        # acceptance, across a real process boundary): an embedding
        # classifier whose per-step lookups (16 rows x 6 tokens = 96)
        # sit under half the 256-row vocab, so the auto rule engages
        # the row-sparse (indices, rows) sync — incl. duplicate indices
        # and the padding index in every batch
        model = nn.Sequential(nn.LookupTable(256, 8, padding_idx=0),
                              nn.Select(1, -1), nn.Linear(8, 4),
                              nn.LogSoftMax())
        x = rng.randint(0, 256, (64, 6)).astype(np.int32)
        x[:, 0] = x[:, 1]  # duplicates in every row
        x[0, 2] = 0        # the padding index
    else:
        model = nn.Sequential(nn.Linear(8, 16), nn.Tanh(),
                              nn.Linear(16, 4), nn.LogSoftMax())
        x = rng.randn(64, 8).astype(np.float32)
    y = rng.randint(0, 4, 64)
    samples = [Sample(x[i], y[i]) for i in range(64)]

    # default: 4 iterations x global batch 16 = exactly one epoch (no
    # shuffle yet), so cluster and control runs see identical global
    # batch CONTENTS.  The fault/preemption tests run longer
    # (BIGDL_TEST_ITERS) — epoch ordering stays comparable across runs
    # because later epochs shuffle deterministically by (seed, epoch).
    iters = int(os.environ.get("BIGDL_TEST_ITERS", "4"))
    o = optim.Optimizer(model=model, dataset=samples,
                        criterion=nn.ClassNLLCriterion(), batch_size=16,
                        end_trigger=optim.Trigger.max_iteration(iters))
    o.set_optim_method(optim.SGD(learning_rate=0.1, momentum=0.9))
    if os.environ.get("BIGDL_TEST_ZERO1"):
        o.set_parameter_sync("sharded")
    if os.environ.get("BIGDL_TEST_FSDP"):
        o.set_parameter_sync("fsdp")
    if os.environ.get("BIGDL_TEST_SHARDED_VAL"):
        # validation batches round-robin across processes; the merged
        # result must equal the single-process full evaluation
        o.set_validation(optim.Trigger.several_iteration(4), samples,
                         [optim.Top1Accuracy(), optim.Loss()],
                         batch_size=8)
    ckpt = os.environ.get("BIGDL_TEST_CKPT")
    if ckpt:
        every = int(os.environ.get("BIGDL_TEST_CKPT_EVERY", "0"))
        trigger = optim.Trigger.several_iteration(every) if every \
            else optim.Trigger.every_epoch()
        # backend "sharded" = per-host writes (the layout where the
        # cluster commit barrier earns its keep, tests/test_cluster.py)
        o.set_checkpoint(ckpt, trigger,
                         backend=os.environ.get("BIGDL_TEST_CKPT_BACKEND",
                                                "btpu"))
        o.overwrite_checkpoint()
    if os.environ.get("BIGDL_TEST_LOCAL_SYNC"):
        # straggler-tolerant local-SGD (parallel/local_sync.py): H and S
        # come from BIGDL_LOCAL_SYNC_H / BIGDL_LOCAL_SYNC_STALE; a slow
        # host is injected with a deterministic `straggle` fault via
        # BIGDL_FAULTS — there is no test-only slow-host code path
        o.set_parameter_sync("local")
    trained = o.optimize()

    if os.environ.get("BIGDL_TEST_SPARSE") and \
            os.environ.get("BIGDL_SPARSE", "auto") != "off":
        # the equivalence claim is vacuous if the sparse path silently
        # stayed dense — require the engagement evidence
        stats = getattr(o.last_train_step, "_sparse_stats", None)
        assert stats and stats["tables"] == 1, (
            f"sparse sync did not engage: {stats}")

    if fleet_mode:
        import json as _json
        import time as _time
        import urllib.request

        from bigdl_tpu import telemetry

        try:
            if Engine.is_coordinator():
                # the run is still live: the coordinator's own /status
                # must carry the fleet block with BOTH hosts visible
                # (the peer's log flushes every 32 events, so give the
                # watcher a couple of poll intervals to catch up)
                srv = telemetry.metrics_server()
                assert srv is not None, "metrics server not live"
                fl = {}
                for _ in range(20):
                    _time.sleep(0.5)
                    st = _json.load(urllib.request.urlopen(
                        f"http://127.0.0.1:{srv.port}/status", timeout=5))
                    fl = st.get("fleet") or {}
                    hosts = fl.get("hosts") or {}
                    if len(hosts) >= 2 and all(
                            r.get("last_step", 0) >= 1
                            for r in hosts.values()):
                        break
                hosts = fl.get("hosts") or {}
                assert len(hosts) >= 2, f"fleet block incomplete: {fl}"
                assert all(r.get("last_step", 0) >= 1
                           for r in hosts.values()), hosts
                body = urllib.request.urlopen(
                    f"http://127.0.0.1:{srv.port}/metrics",
                    timeout=5).read().decode()
                assert "bigdl_fleet_last_step" in body, body[-2000:]
                print("FLEET_STATUS_OK", flush=True)
        finally:
            telemetry.end_run()

    if o.preempted:
        # graceful preemption: final checkpoint committed, exit 0; the
        # restarted cluster resumes and writes the params — a preempted
        # run must NOT publish mid-run params as final
        print(f"worker {Engine.process_index()}/{Engine.process_count()} "
              f"preempted at iteration {o.state['neval']}", flush=True)
        return

    if Engine.is_coordinator():
        from bigdl_tpu.nn.module import state_dict

        params = state_dict(trained, kind="param")
        extra = {"__loss": np.asarray(float(o.state.get("loss", np.nan)))}
        if os.environ.get("BIGDL_TEST_SHARDED_VAL"):
            extra["__score"] = np.asarray(o.state["score"])
        np.savez(os.environ["BIGDL_TEST_OUT"], **extra,
                 **{k: np.asarray(v) for k, v in params.items()})
    print(f"worker {Engine.process_index()}/{Engine.process_count()} done",
          flush=True)


if __name__ == "__main__":
    main()
