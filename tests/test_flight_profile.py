"""On-demand profiler capture + crash flight recorder
(docs/observability.md): the ProfilerControl state machine, POST
/profile against a LIVE training run producing a real trace directory
without interrupting training, flight-ring bounding, and the dump paths
(HealthError halt, straggler firing, retry exhaustion)."""

import glob
import json
import os
import threading
import time
import urllib.request

import numpy as np
import pytest

import bigdl_tpu.nn as nn
import bigdl_tpu.optim as optim
from bigdl_tpu import telemetry
from bigdl_tpu.dataset.dataset import DataSet
from bigdl_tpu.dataset.minibatch import MiniBatch
from bigdl_tpu.dataset.sample import Sample
from bigdl_tpu.dataset.transformer import SampleToMiniBatch, Transformer
from bigdl_tpu.optim.trigger import Trigger
from bigdl_tpu.parallel.train_step import TrainStep
from bigdl_tpu.telemetry import profiler, schema
from bigdl_tpu.telemetry.flight import FlightRecorder
from bigdl_tpu.telemetry.health import HealthError
from bigdl_tpu.utils.config import BigDLConfig, set_config


def teardown_function(_fn):
    telemetry.end_run()
    set_config(None)
    profiler.get().abort()


def _samples(n=64, dim=6, seed=0):
    rng = np.random.default_rng(seed)
    return [Sample(rng.normal(size=dim).astype(np.float32),
                   np.int64(i % 2)) for i in range(n)]


def _mlp(dim=6):
    from bigdl_tpu.utils.rng import RNG

    RNG.set_seed(11)
    return nn.Sequential(nn.Linear(dim, 8), nn.Tanh(), nn.Linear(8, 2),
                         nn.LogSoftMax())


class PoisonAt(Transformer):
    def __init__(self, at):
        self.at = at

    def apply(self, it):
        for i, batch in enumerate(it):
            if i >= self.at:
                batch = MiniBatch(
                    [np.full_like(a, np.nan) for a in batch.inputs],
                    list(batch.targets) or None)
            yield batch


# -- ProfilerControl unit ----------------------------------------------------
def test_profiler_control_arm_poll_capture(tmp_path):
    ctl = profiler.ProfilerControl()
    trace_dir = str(tmp_path / "trace")
    assert ctl.arm(2, trace_dir, source="test")
    assert not ctl.arm(1, trace_dir), "no queueing while armed"
    ctl.poll_begin()
    assert ctl.status()["state"] == "capturing"
    ctl.poll_end()
    assert ctl.status()["state"] == "capturing"  # 1 of 2 steps done
    ctl.poll_end()
    st = ctl.status()
    assert st["state"] == "idle" and st["captures"] == 1
    assert st["last_trace_dir"] == trace_dir
    assert os.path.isdir(trace_dir)
    # re-armable after completion
    assert ctl.arm(1, str(tmp_path / "trace2"))
    ctl.abort()  # armed-but-not-started cancels cleanly
    assert ctl.status()["state"] == "idle"


def test_profiler_control_rejects_bad_requests(tmp_path):
    ctl = profiler.ProfilerControl()
    assert not ctl.arm(0, str(tmp_path))
    assert not ctl.arm(3, "")


def test_profiler_abort_closes_open_capture(tmp_path):
    ctl = profiler.ProfilerControl()
    ctl.arm(100, str(tmp_path / "t"))
    ctl.poll_begin()
    assert ctl.status()["state"] == "capturing"
    ctl.abort()
    st = ctl.status()
    assert st["state"] == "idle" and st["captures"] == 1


def test_bigdl_profile_env_pre_arms_the_control(tmp_path):
    """BIGDL_PROFILE keeps working — it now pre-arms the on-demand
    control with the first N iterations instead of a private path."""
    trace_dir = str(tmp_path / "startup")
    set_config(BigDLConfig(profile_dir=trace_dir, profile_iters=2,
                           prefetch_batches=0))
    o = optim.LocalOptimizer(_mlp(), _samples(), nn.ClassNLLCriterion(),
                             batch_size=16,
                             end_trigger=Trigger.max_iteration(4))
    o.set_optim_method(optim.SGD(learning_rate=0.05))
    o.set_health_policy(None)
    o.optimize()
    st = profiler.get().status()
    assert st["captures"] >= 1 and st["state"] == "idle"
    assert glob.glob(os.path.join(trace_dir, "**", "*"), recursive=True)


# -- POST /profile against a live run (acceptance criterion) -----------------
def test_post_profile_during_live_run_produces_trace(tmp_path):
    tele_dir = str(tmp_path / "tele")
    trace_dir = str(tmp_path / "ondemand")
    set_config(BigDLConfig(telemetry_dir=tele_dir, metrics_port=0,
                           prefetch_batches=0, health_action="off"))
    stop = {"flag": False}
    o = optim.LocalOptimizer(
        _mlp(), _samples(256), nn.ClassNLLCriterion(), batch_size=8,
        end_trigger=Trigger(
            lambda s: stop["flag"] or s.get("neval", 0) >= 3000))
    o.set_optim_method(optim.SGD(learning_rate=0.05))
    result = {}

    baseline = profiler.get().status()["captures"]

    def drive():
        # wait for the run's endpoint, arm a 2-step capture, then poll
        # /status until the capture lands — training never pauses
        deadline = time.time() + 60
        while telemetry.metrics_server() is None:
            if time.time() > deadline:
                result["error"] = "metrics endpoint never came up"
                stop["flag"] = True
                return
            time.sleep(0.02)
        port = telemetry.metrics_server().port
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}/profile?steps=2&dir={trace_dir}",
            method="POST")
        result["post"] = json.load(urllib.request.urlopen(req, timeout=30))
        while time.time() < deadline:
            st = json.load(urllib.request.urlopen(
                f"http://127.0.0.1:{port}/status", timeout=30))
            result["status"] = st
            if st.get("profiler", {}).get("captures", 0) > baseline:
                break
            time.sleep(0.05)
        stop["flag"] = True

    t = threading.Thread(target=drive)
    t.start()
    o.optimize()
    t.join()
    assert "error" not in result, result["error"]
    assert result["post"]["armed"] is True
    prof = result["status"]["profiler"]
    assert prof["captures"] > baseline
    assert prof["last_trace_dir"] == trace_dir
    assert glob.glob(os.path.join(trace_dir, "**", "*"), recursive=True), \
        "no trace artifacts written"
    # /status also reports the flight recorder attached to the run
    assert result["status"]["flight"]["capacity"] > 0
    # training survived the capture and the log stays schema-valid
    runs = glob.glob(os.path.join(tele_dir, "run-*.jsonl"))
    n, errors = schema.validate_run(runs[0])
    assert errors == [] and n > 10
    events, _ = schema.read_events(runs[0])
    names = [e.get("name") for e in events if e["kind"] == "event"]
    assert "profile/armed" in names and "profile/captured" in names


def test_post_profile_busy_returns_409(tmp_path):
    set_config(BigDLConfig(metrics_port=0))
    with telemetry.run(sinks=[telemetry.MemorySink()]):
        port = telemetry.metrics_server().port
        ctl = profiler.get()
        assert ctl.arm(5, str(tmp_path / "t"), source="test")
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}/profile?steps=2", method="POST")
        with pytest.raises(urllib.error.HTTPError) as exc:
            urllib.request.urlopen(req, timeout=10)
        assert exc.value.code == 409
        ctl.abort()


# -- flight recorder ---------------------------------------------------------
def test_flight_ring_is_bounded_and_dumps(tmp_path):
    fr = FlightRecorder(capacity=8)
    for i in range(50):
        fr.emit({"kind": "step", "step": i})
    fr.emit({"kind": "health", "step": 50, "grad_norm": 1.0})
    path = fr.dump("unit_test", evidence={"why": "test"},
                   directory=str(tmp_path))
    assert path and os.path.exists(path)
    doc = json.load(open(path))
    assert len(doc["events"]) == 8, "ring must stay bounded"
    assert doc["events"][-1]["kind"] == "health"
    assert doc["reason"] == "unit_test"
    assert doc["evidence"] == {"why": "test"}
    assert doc["last_health"]["step"] == 50
    assert fr.status()["dumps"] == 1
    assert fr.status()["last_dump_path"] == path


def test_flight_recorder_attaches_to_runs_and_bigdl_flight_0_disables():
    set_config(BigDLConfig(flight_events=16))
    with telemetry.run(sinks=[telemetry.MemorySink()]):
        fr = telemetry.flight_recorder()
        assert fr is not None and fr.capacity == 16
        telemetry.instant("epoch", epoch=1)
        assert fr.status()["events_buffered"] >= 1
    assert telemetry.flight_recorder() is None, "detached at end_run"
    set_config(BigDLConfig(flight_events=0))
    with telemetry.run(sinks=[telemetry.MemorySink()]):
        assert telemetry.flight_recorder() is None


def test_health_halt_dumps_flight_with_evidence(tmp_path):
    tele_dir = str(tmp_path / "tele")
    set_config(BigDLConfig(telemetry_dir=tele_dir, health_action="halt",
                           health_halt_after=2, prefetch_batches=0,
                           failure_retry_times=3,
                           failure_retry_interval=60.0))
    ds = DataSet.array(_samples()).transform(
        SampleToMiniBatch(16)).transform(PoisonAt(2))
    o = optim.LocalOptimizer(_mlp(), ds, nn.ClassNLLCriterion(),
                             batch_size=16,
                             end_trigger=Trigger.max_iteration(20))
    o.set_optim_method(optim.SGD(learning_rate=0.1))
    with pytest.raises(HealthError):
        o.optimize()
    dumps = glob.glob(os.path.join(tele_dir, "flight-*.json"))
    assert len(dumps) == 1
    doc = json.load(open(dumps[0]))
    assert doc["reason"] == "health_halt"
    assert doc["evidence"]["nonfinite_grads"] > 0
    assert doc["last_health"].get("step") is not None
    kinds = {e.get("kind") for e in doc["events"]}
    assert "step" in kinds and "health" in kinds
    # the dump is announced in the run log itself
    runs = glob.glob(os.path.join(tele_dir, "run-*.jsonl"))
    events, _ = schema.read_events(runs[0])
    flights = [e for e in events
               if e["kind"] == "event" and e.get("name") == "flight/dump"]
    assert len(flights) == 1 and flights[0]["path"] == dumps[0]


def test_straggler_timeout_dumps_flight(tmp_path, monkeypatch):
    tele_dir = str(tmp_path / "tele")
    set_config(BigDLConfig(telemetry_dir=tele_dir, health_action="off",
                           iteration_timeout="0.2", prefetch_batches=0,
                           failure_retry_times=0,
                           failure_retry_interval=60.0))
    from bigdl_tpu.optim.optimizer import StragglerTimeout

    # slow down the GUARDED half of the iteration (the device step, not
    # the data wait): iteration 3 stalls past the straggler budget
    calls = {"n": 0}
    orig = TrainStep.run_sharded

    def wedged(self, x, y, key):
        calls["n"] += 1
        if calls["n"] >= 3:
            time.sleep(2.0)
        return orig(self, x, y, key)

    monkeypatch.setattr(TrainStep, "run_sharded", wedged)
    o = optim.LocalOptimizer(_mlp(), _samples(), nn.ClassNLLCriterion(),
                             batch_size=16,
                             end_trigger=Trigger.max_iteration(40))
    o.set_optim_method(optim.SGD(learning_rate=0.05))
    with pytest.raises(StragglerTimeout):
        o.optimize()
    dumps = glob.glob(os.path.join(tele_dir, "flight-*.json"))
    assert dumps, "straggler firing must leave a flight dump"
    reasons = {json.load(open(p))["reason"] for p in dumps}
    assert "straggler_timeout" in reasons


def test_health_escalation_arms_one_shot_profile(tmp_path):
    """BIGDL_PROFILE_ON_HEALTH: the first warn-level finding arms a
    one-shot capture so the diverging step itself gets traced."""
    prof_dir = str(tmp_path / "onhealth")
    set_config(BigDLConfig(telemetry_dir=str(tmp_path / "tele"),
                           health_action="warn", prefetch_batches=0,
                           profile_on_health=prof_dir))
    ds = DataSet.array(_samples()).transform(
        SampleToMiniBatch(16)).transform(PoisonAt(2))
    o = optim.LocalOptimizer(_mlp(), ds, nn.ClassNLLCriterion(),
                             batch_size=16,
                             end_trigger=Trigger.max_iteration(6))
    o.set_optim_method(optim.SGD(learning_rate=0.1))
    o.optimize()  # warn never halts
    st = profiler.get().status()
    assert st["captures"] >= 1
    assert glob.glob(os.path.join(prof_dir, "**", "*"), recursive=True)
