"""Native C++ library: build, bind, and verify against NumPy ground truth
(the analogue of the reference's MKL-vs-pure-Scala dual paths,
``tensor/DenseTensor.scala:917`` guard pattern)."""

import numpy as np
import pytest

from bigdl_tpu import native


def test_native_builds_and_loads():
    assert native.is_native_loaded(), "native toolchain present in image"


def test_crc32c_known_vectors():
    # RFC 3720 test vector: 32 bytes of zeros -> 0x8a9136aa
    assert native.crc32c(b"\x00" * 32) == 0x8A9136AA
    assert native.crc32c(b"123456789") == 0xE3069283
    # masked variant must round-trip the TFRecord mask formula
    c = native.crc32c(b"hello world")
    masked = native.masked_crc32c(b"hello world")
    assert masked == ((((c >> 15) | (c << 17)) + 0xA282EAD8) & 0xFFFFFFFF)


def test_crc32c_python_fallback_matches(monkeypatch):
    vec = b"The quick brown fox jumps over the lazy dog"
    want = native.crc32c(vec)
    monkeypatch.setattr(native, "_lib", None)
    monkeypatch.setattr(native, "_build_failed", True)
    assert native.crc32c(vec) == want


def test_gemm_vs_numpy():
    rng = np.random.default_rng(0)
    A = rng.normal(size=(5, 7)).astype(np.float32)
    B = rng.normal(size=(7, 3)).astype(np.float32)
    C = rng.normal(size=(5, 3)).astype(np.float32)
    got = native.gemm("N", "N", 2.0, A, B, 0.5, C.copy())
    np.testing.assert_allclose(got, 2.0 * A @ B + 0.5 * C, rtol=1e-5)
    got_t = native.gemm("T", "N", 1.0, A.T.copy(), B, 0.0,
                        np.zeros((5, 3), np.float32))
    np.testing.assert_allclose(got_t, A @ B, rtol=1e-5)


@pytest.mark.parametrize("op", ["Add", "Sub", "Mul", "Div"])
def test_vml_binary(op):
    rng = np.random.default_rng(1)
    a = rng.normal(size=100).astype(np.float32)
    b = rng.uniform(0.5, 2.0, 100).astype(np.float32)
    fns = {"Add": np.add, "Sub": np.subtract, "Mul": np.multiply,
           "Div": np.divide}
    np.testing.assert_allclose(native.vml(op, a, b), fns[op](a, b),
                               rtol=1e-6)


@pytest.mark.parametrize("op", ["Ln", "Exp", "Sqrt", "Tanh", "Log1p", "Abs"])
def test_vml_unary(op):
    rng = np.random.default_rng(2)
    a = rng.uniform(0.1, 3.0, 100).astype(np.float32)
    fns = {"Ln": np.log, "Exp": np.exp, "Sqrt": np.sqrt, "Tanh": np.tanh,
           "Log1p": np.log1p, "Abs": np.abs}
    np.testing.assert_allclose(native.vml(op, a), fns[op](a), rtol=1e-5)


def test_vml_powx():
    a = np.linspace(0.1, 2.0, 50, dtype=np.float32)
    np.testing.assert_allclose(native.vml("Powx", a, 2.5),
                               np.power(a, np.float32(2.5)), rtol=1e-5)


def test_im2col_matches_conv():
    """conv via native im2col + gemm == scipy-style direct conv (through
    jax reference)."""
    import jax.numpy as jnp
    from jax import lax

    rng = np.random.default_rng(3)
    x = rng.normal(size=(2, 8, 8)).astype(np.float32)
    w = rng.normal(size=(4, 2, 3, 3)).astype(np.float32)
    cols = native.im2col(x, 3, 3, 1, 1, 1, 1)
    out = (w.reshape(4, -1) @ cols).reshape(4, 8, 8)
    ref = lax.conv_general_dilated(
        jnp.asarray(x)[None], jnp.asarray(w), (1, 1), [(1, 1), (1, 1)],
        dimension_numbers=("NCHW", "OIHW", "NCHW"))[0]
    np.testing.assert_allclose(out, np.asarray(ref), rtol=1e-4, atol=1e-4)


def test_maxpool_fwd_matches_numpy():
    rng = np.random.default_rng(4)
    x = rng.normal(size=(3, 6, 6)).astype(np.float32)
    out, idx = native.maxpool_fwd(x, 2, 2, 2, 2, 0, 0)
    want = x.reshape(3, 3, 2, 3, 2).max(axis=(2, 4))
    np.testing.assert_allclose(out, want)
    assert idx.min() >= 0


def test_batch_crop_normalize():
    rng = np.random.default_rng(5)
    imgs = rng.integers(0, 255, (4, 10, 10, 3), dtype=np.uint8)
    oy = np.array([0, 1, 2, 0], np.int32)
    ox = np.array([1, 0, 2, 0], np.int32)
    flip = np.array([0, 1, 0, 1], np.uint8)
    mean = np.array([100.0, 110.0, 120.0], np.float32)
    std = np.array([50.0, 55.0, 60.0], np.float32)
    out = native.batch_crop_normalize(imgs, 8, 8, oy, ox, flip, mean, std)
    assert out.shape == (4, 3, 8, 8)
    patch = imgs[1, 1:9, 0:8, :][:, ::-1, :].astype(np.float32)
    want = ((patch - mean) / std).transpose(2, 0, 1)
    np.testing.assert_allclose(out[1], want, rtol=1e-6)


# -- batch tf.Example parsing (round 4: native ingest hot path) ------------

def _mk_example(img_bytes, label_vals, float_vals, packed=False):
    from bigdl_tpu.utils.protowire import emit_bytes, emit_float, emit_varint

    import struct

    def feature_bytes(b):
        return emit_bytes(1, emit_bytes(1, b))

    def feature_ints(vals):
        if packed:
            payload = b"".join(
                _varint_raw(v) for v in vals)
            return emit_bytes(3, emit_bytes(1, payload))
        return emit_bytes(3, b"".join(emit_varint(1, v) for v in vals))

    def feature_floats(vals):
        if packed:
            payload = b"".join(struct.pack("<f", v) for v in vals)
            return emit_bytes(2, emit_bytes(1, payload))
        return emit_bytes(2, b"".join(emit_float(1, v) for v in vals))

    feats = b""
    for k, v in (("image", feature_bytes(img_bytes)),
                 ("label", feature_ints(label_vals)),
                 ("x", feature_floats(float_vals))):
        feats += emit_bytes(1, emit_bytes(1, k.encode()) + emit_bytes(2, v))
    return emit_bytes(1, feats)


def _varint_raw(n):
    out = b""
    while True:
        b = n & 0x7F
        n >>= 7
        if n:
            out += bytes([b | 0x80])
        else:
            return out + bytes([b])


@pytest.mark.parametrize("packed", [False, True])
def test_parse_examples_fixed_native_and_fallback(packed, monkeypatch):
    """The C++ batch parser and the Python walker agree on packed and
    unpacked encodings (tf writes packed; our emit helpers write
    unpacked)."""
    rng = np.random.default_rng(3)
    recs, want_img, want_lab, want_x = [], [], [], []
    for _ in range(32):
        img = rng.integers(0, 255, 27, dtype=np.uint8)
        lab = [int(rng.integers(0, 9)), int(rng.integers(100, 10 ** 7))]
        x = [float(v) for v in rng.standard_normal(4)]
        recs.append(_mk_example(img.tobytes(), lab, x, packed=packed))
        want_img.append(img)
        want_lab.append(lab)
        want_x.append(x)
    spec = [("image", "bytes", 27), ("label", "int64", 2), ("x", "float", 4)]

    img, lab, x = native.parse_examples_fixed(recs, spec)
    np.testing.assert_array_equal(img, np.stack(want_img))
    np.testing.assert_array_equal(lab, np.asarray(want_lab))
    np.testing.assert_allclose(x, np.asarray(want_x, np.float32), rtol=1e-6)

    # force the Python fallback the way _try_load actually gates it (a
    # loaded _lib early-returns before the env knob is consulted)
    monkeypatch.setattr(native, "_lib", None)
    monkeypatch.setattr(native, "_build_failed", True)
    img2, lab2, x2 = native.parse_examples_fixed(recs, spec)
    np.testing.assert_array_equal(img, img2)
    np.testing.assert_array_equal(lab, lab2)
    np.testing.assert_allclose(x, x2)


def test_parse_examples_fixed_error_reporting():
    good = _mk_example(b"abc", [1], [0.5])
    bad = _mk_example(b"abcd", [1], [0.5])  # wrong bytes length
    spec = [("image", "bytes", 3), ("label", "int64", 1), ("x", "float", 1)]
    native.parse_examples_fixed([good], spec)
    with pytest.raises(ValueError, match="record 1"):
        native.parse_examples_fixed([good, bad], spec)
    with pytest.raises(ValueError, match="record 0"):
        native.parse_examples_fixed(
            [good], [("missing", "bytes", 3)] + spec[1:])


def test_multivalue_byteslist_rejected_by_both_paths(monkeypatch):
    """A BytesList with TWO values must fail the record identically in
    the C++ parser and the Python fallback — ADVICE r4: the native path
    silently took the first value while the fallback raised, so the same
    record parsed or failed based on build availability."""
    from bigdl_tpu.utils.protowire import emit_bytes, emit_varint

    # image feature whose BytesList carries two 3-byte values
    two_vals = emit_bytes(1, emit_bytes(1, b"abc") + emit_bytes(1, b"def"))
    feats = emit_bytes(1, emit_bytes(1, b"image") + emit_bytes(2, two_vals))
    feats += emit_bytes(1, emit_bytes(1, b"label")
                        + emit_bytes(2, emit_bytes(3, emit_varint(1, 1))))
    rec = emit_bytes(1, feats)
    spec = [("image", "bytes", 3), ("label", "int64", 1)]

    with pytest.raises(ValueError, match="record 0"):
        native.parse_examples_fixed([rec], spec)
    # force the Python fallback (a loaded _lib early-returns before the
    # BIGDL_TPU_NO_NATIVE knob is consulted)
    monkeypatch.setattr(native, "_lib", None)
    monkeypatch.setattr(native, "_build_failed", True)
    with pytest.raises(ValueError, match="record 0"):
        native.parse_examples_fixed([rec], spec)
