"""Graph-optimization pass tests (``bigdl_tpu/nn/fuse.py``): sibling-conv
merging must be exact — same outputs, same gradients, merged parameter
packing — across the Inception block shapes it exists for
(``models/inception/Inception_v1.scala`` inception fn)."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

import bigdl_tpu.nn as nn
from bigdl_tpu.nn.fuse import merge_sibling_convs, optimize_for_tpu
from bigdl_tpu.models.inception import build_inception_v1, inception_layer_v1
from bigdl_tpu.nn.module import state_dict
from bigdl_tpu.utils.rng import RNG


def _forward(m, x):
    return np.asarray(m.forward(jnp.asarray(x)))


def test_inception_block_merge_exact():
    RNG.set_seed(0)
    block = inception_layer_v1(192, [[64], [96, 128], [16, 32], [32]], "3a/")
    x = np.random.randn(2, 192, 14, 14).astype(np.float32)
    ref = _forward(block, x)
    fused = merge_sibling_convs(block)
    # merging regroups the GEMM tiling, so results are close, not
    # bit-identical
    np.testing.assert_allclose(_forward(fused, x), ref, rtol=1e-5, atol=1e-6)
    # three 1x1-leading branches merged into one conv; pool branch kept
    outer = fused.layers
    assert len(outer) == 2
    merged_conv = outer[0].get(0)
    assert isinstance(merged_conv, nn.SpatialConvolution)
    assert merged_conv.n_output_plane == 64 + 96 + 16


def test_merge_preserves_gradients():
    RNG.set_seed(1)
    block = inception_layer_v1(64, [[16], [24, 32], [8, 16], [16]], "g/")
    x = np.random.randn(2, 64, 9, 9).astype(np.float32)
    gy = np.random.randn(2, 16 + 32 + 16 + 16, 9, 9).astype(np.float32)
    g_ref = np.asarray(block.backward(jnp.asarray(x), jnp.asarray(gy)))
    RNG.set_seed(1)
    block2 = merge_sibling_convs(
        inception_layer_v1(64, [[16], [24, 32], [8, 16], [16]], "g/"))
    g_fused = np.asarray(block2.backward(jnp.asarray(x), jnp.asarray(gy)))
    np.testing.assert_allclose(g_fused, g_ref, rtol=1e-5, atol=1e-6)


def test_merge_param_count_preserved():
    RNG.set_seed(2)
    plain = inception_layer_v1(192, [[64], [96, 128], [16, 32], [32]], "p/")
    n_plain = sum(int(np.prod(v.shape)) for v in state_dict(plain).values())
    fused = merge_sibling_convs(
        inception_layer_v1(192, [[64], [96, 128], [16, 32], [32]], "p/"))
    n_fused = sum(int(np.prod(v.shape)) for v in state_dict(fused).values())
    assert n_plain == n_fused


def test_full_model_merge_and_train_step():
    RNG.set_seed(3)
    model = optimize_for_tpu(build_inception_v1(10))
    import bigdl_tpu.optim as optim
    from bigdl_tpu.parallel.train_step import TrainStep

    step = TrainStep(model, nn.ClassNLLCriterion(),
                     optim.SGD(learning_rate=0.01))
    x = jnp.asarray(np.random.randn(2, 3, 224, 224).astype(np.float32))
    y = jnp.asarray(np.random.randint(0, 10, 2))
    loss = step.run(x, y, jax.random.key(0))
    assert np.isfinite(float(loss))


def test_no_merge_when_signatures_differ():
    c = nn.Concat(1)
    c.add(nn.SpatialConvolution(8, 4, 1, 1))
    c.add(nn.SpatialConvolution(8, 4, 3, 3, 1, 1, 1, 1))  # different kernel
    merge_sibling_convs(c)
    assert len(c.layers) == 2
    assert all(isinstance(b, nn.SpatialConvolution) for b in c.layers)


def test_no_merge_on_frozen_or_regularized():
    from bigdl_tpu.optim.regularizer import L2Regularizer

    c = nn.Concat(1)
    c.add(nn.SpatialConvolution(8, 4, 1, 1))
    frozen = nn.SpatialConvolution(8, 4, 1, 1)
    frozen.freeze()
    c.add(frozen)
    merge_sibling_convs(c)
    assert len(c.layers) == 2  # frozen branch blocks the merge

    c2 = nn.Concat(1)
    c2.add(nn.SpatialConvolution(8, 4, 1, 1,
                                 w_regularizer=L2Regularizer(1e-4)))
    c2.add(nn.SpatialConvolution(8, 4, 1, 1))
    merge_sibling_convs(c2)
    assert len(c2.layers) == 2


def test_merge_wrong_axis_skipped():
    c = nn.Concat(2)  # concat along H, not channels
    c.add(nn.SpatialConvolution(8, 4, 1, 1))
    c.add(nn.SpatialConvolution(8, 4, 1, 1))
    merge_sibling_convs(c)
    assert len(c.layers) == 2


def _bn_with_stats(ch, seed):
    r = np.random.default_rng(seed)
    bn = nn.SpatialBatchNormalization(ch)
    bn.weight = jnp.asarray(r.normal(1.0, 0.2, ch).astype(np.float32))
    bn.bias = jnp.asarray(r.normal(0.0, 0.1, ch).astype(np.float32))
    bn.running_mean = jnp.asarray(r.normal(0.0, 0.5, ch).astype(np.float32))
    bn.running_var = jnp.asarray(r.uniform(0.5, 2.0, ch).astype(np.float32))
    return bn


def test_fold_batchnorm_matches_eval_forward():
    from bigdl_tpu.nn.fuse import fold_batchnorm

    RNG.set_seed(4)
    model = nn.Sequential(
        nn.SpatialConvolution(3, 8, 3, 3, 1, 1, 1, 1), _bn_with_stats(8, 0),
        nn.ReLU(True),
        nn.SpatialConvolution(8, 6, 1, 1), _bn_with_stats(6, 1))
    model.evaluate()
    x = np.random.randn(2, 3, 10, 10).astype(np.float32)
    ref = _forward(model, x)
    fold_batchnorm(model)
    assert len(model.layers) == 3  # both BNs folded away
    np.testing.assert_allclose(_forward(model, x), ref, rtol=1e-4, atol=1e-5)


def test_fold_batchnorm_nested_containers():
    from bigdl_tpu.nn.fuse import fold_batchnorm

    RNG.set_seed(5)
    inner = nn.Sequential(nn.SpatialConvolution(4, 4, 3, 3, 1, 1, 1, 1),
                          _bn_with_stats(4, 2), nn.ReLU(True))
    model = nn.Sequential(nn.Concat(1).add(inner).add(nn.Identity()))
    model.evaluate()
    x = np.random.randn(2, 4, 6, 6).astype(np.float32)
    ref = _forward(model, x)
    fold_batchnorm(model)
    assert len(inner.layers) == 2
    np.testing.assert_allclose(_forward(model, x), ref, rtol=1e-4, atol=1e-5)


def test_fold_batchnorm_biasless_conv():
    """conv(bias=False)+BN — the conventional pairing — folds by
    materializing the bias."""
    from bigdl_tpu.nn.fuse import fold_batchnorm

    RNG.set_seed(7)
    model = nn.Sequential(
        nn.SpatialConvolution(3, 8, 3, 3, 1, 1, 1, 1, with_bias=False),
        _bn_with_stats(8, 4))
    model.evaluate()
    x = np.random.randn(2, 3, 10, 10).astype(np.float32)
    ref = _forward(model, x)
    fold_batchnorm(model)
    assert len(model.layers) == 1
    assert model.get(0).with_bias
    np.testing.assert_allclose(_forward(model, x), ref, rtol=1e-4, atol=1e-5)


def test_fold_batchnorm_skips_non_adjacent():
    from bigdl_tpu.nn.fuse import fold_batchnorm

    RNG.set_seed(6)
    model = nn.Sequential(nn.SpatialConvolution(3, 5, 1, 1), nn.ReLU(True),
                          _bn_with_stats(5, 3))
    fold_batchnorm(model)
    assert len(model.layers) == 3  # ReLU between conv and BN: no fold
