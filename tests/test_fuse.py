"""Graph-optimization pass tests (``bigdl_tpu/nn/fuse.py``): sibling-conv
merging must be exact — same outputs, same gradients, merged parameter
packing — across the Inception block shapes it exists for
(``models/inception/Inception_v1.scala`` inception fn)."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

import bigdl_tpu.nn as nn
from bigdl_tpu.nn.fuse import merge_sibling_convs, optimize_for_tpu
from bigdl_tpu.models.inception import build_inception_v1, inception_layer_v1
from bigdl_tpu.nn.module import state_dict
from bigdl_tpu.utils.rng import RNG


def _forward(m, x):
    return np.asarray(m.forward(jnp.asarray(x)))


def test_inception_block_merge_exact():
    RNG.set_seed(0)
    block = inception_layer_v1(192, [[64], [96, 128], [16, 32], [32]], "3a/")
    x = np.random.randn(2, 192, 14, 14).astype(np.float32)
    ref = _forward(block, x)
    fused = merge_sibling_convs(block)
    # merging regroups the GEMM tiling, so results are close, not
    # bit-identical
    np.testing.assert_allclose(_forward(fused, x), ref, rtol=1e-5, atol=1e-6)
    # three 1x1-leading branches merged into one conv; pool branch kept
    outer = fused.layers
    assert len(outer) == 2
    merged_conv = outer[0].get(0)
    assert isinstance(merged_conv, nn.SpatialConvolution)
    assert merged_conv.n_output_plane == 64 + 96 + 16


def test_merge_preserves_gradients():
    RNG.set_seed(1)
    block = inception_layer_v1(64, [[16], [24, 32], [8, 16], [16]], "g/")
    x = np.random.randn(2, 64, 9, 9).astype(np.float32)
    gy = np.random.randn(2, 16 + 32 + 16 + 16, 9, 9).astype(np.float32)
    g_ref = np.asarray(block.backward(jnp.asarray(x), jnp.asarray(gy)))
    RNG.set_seed(1)
    block2 = merge_sibling_convs(
        inception_layer_v1(64, [[16], [24, 32], [8, 16], [16]], "g/"))
    g_fused = np.asarray(block2.backward(jnp.asarray(x), jnp.asarray(gy)))
    np.testing.assert_allclose(g_fused, g_ref, rtol=1e-5, atol=1e-6)


def test_merge_param_count_preserved():
    RNG.set_seed(2)
    plain = inception_layer_v1(192, [[64], [96, 128], [16, 32], [32]], "p/")
    n_plain = sum(int(np.prod(v.shape)) for v in state_dict(plain).values())
    fused = merge_sibling_convs(
        inception_layer_v1(192, [[64], [96, 128], [16, 32], [32]], "p/"))
    n_fused = sum(int(np.prod(v.shape)) for v in state_dict(fused).values())
    assert n_plain == n_fused


def test_full_model_merge_and_train_step():
    RNG.set_seed(3)
    model = optimize_for_tpu(build_inception_v1(10))
    import bigdl_tpu.optim as optim
    from bigdl_tpu.parallel.train_step import TrainStep

    step = TrainStep(model, nn.ClassNLLCriterion(),
                     optim.SGD(learning_rate=0.01))
    x = jnp.asarray(np.random.randn(2, 3, 224, 224).astype(np.float32))
    y = jnp.asarray(np.random.randint(0, 10, 2))
    loss = step.run(x, y, jax.random.key(0))
    assert np.isfinite(float(loss))


def test_no_merge_when_signatures_differ():
    c = nn.Concat(1)
    c.add(nn.SpatialConvolution(8, 4, 1, 1))
    c.add(nn.SpatialConvolution(8, 4, 3, 3, 1, 1, 1, 1))  # different kernel
    merge_sibling_convs(c)
    assert len(c.layers) == 2
    assert all(isinstance(b, nn.SpatialConvolution) for b in c.layers)


def test_no_merge_on_frozen_or_regularized():
    from bigdl_tpu.optim.regularizer import L2Regularizer

    c = nn.Concat(1)
    c.add(nn.SpatialConvolution(8, 4, 1, 1))
    frozen = nn.SpatialConvolution(8, 4, 1, 1)
    frozen.freeze()
    c.add(frozen)
    merge_sibling_convs(c)
    assert len(c.layers) == 2  # frozen branch blocks the merge

    c2 = nn.Concat(1)
    c2.add(nn.SpatialConvolution(8, 4, 1, 1,
                                 w_regularizer=L2Regularizer(1e-4)))
    c2.add(nn.SpatialConvolution(8, 4, 1, 1))
    merge_sibling_convs(c2)
    assert len(c2.layers) == 2


def test_merge_wrong_axis_skipped():
    c = nn.Concat(2)  # concat along H, not channels
    c.add(nn.SpatialConvolution(8, 4, 1, 1))
    c.add(nn.SpatialConvolution(8, 4, 1, 1))
    merge_sibling_convs(c)
    assert len(c.layers) == 2


def _bn_with_stats(ch, seed):
    r = np.random.default_rng(seed)
    bn = nn.SpatialBatchNormalization(ch)
    bn.weight = jnp.asarray(r.normal(1.0, 0.2, ch).astype(np.float32))
    bn.bias = jnp.asarray(r.normal(0.0, 0.1, ch).astype(np.float32))
    bn.running_mean = jnp.asarray(r.normal(0.0, 0.5, ch).astype(np.float32))
    bn.running_var = jnp.asarray(r.uniform(0.5, 2.0, ch).astype(np.float32))
    return bn


def test_fold_batchnorm_matches_eval_forward():
    from bigdl_tpu.nn.fuse import fold_batchnorm

    RNG.set_seed(4)
    model = nn.Sequential(
        nn.SpatialConvolution(3, 8, 3, 3, 1, 1, 1, 1), _bn_with_stats(8, 0),
        nn.ReLU(True),
        nn.SpatialConvolution(8, 6, 1, 1), _bn_with_stats(6, 1))
    model.evaluate()
    x = np.random.randn(2, 3, 10, 10).astype(np.float32)
    ref = _forward(model, x)
    fold_batchnorm(model)
    assert len(model.layers) == 3  # both BNs folded away
    np.testing.assert_allclose(_forward(model, x), ref, rtol=1e-4, atol=1e-5)


def test_fold_batchnorm_nested_containers():
    from bigdl_tpu.nn.fuse import fold_batchnorm

    RNG.set_seed(5)
    inner = nn.Sequential(nn.SpatialConvolution(4, 4, 3, 3, 1, 1, 1, 1),
                          _bn_with_stats(4, 2), nn.ReLU(True))
    model = nn.Sequential(nn.Concat(1).add(inner).add(nn.Identity()))
    model.evaluate()
    x = np.random.randn(2, 4, 6, 6).astype(np.float32)
    ref = _forward(model, x)
    fold_batchnorm(model)
    assert len(inner.layers) == 2
    np.testing.assert_allclose(_forward(model, x), ref, rtol=1e-4, atol=1e-5)


def test_fold_batchnorm_biasless_conv():
    """conv(bias=False)+BN — the conventional pairing — folds by
    materializing the bias."""
    from bigdl_tpu.nn.fuse import fold_batchnorm

    RNG.set_seed(7)
    model = nn.Sequential(
        nn.SpatialConvolution(3, 8, 3, 3, 1, 1, 1, 1, with_bias=False),
        _bn_with_stats(8, 4))
    model.evaluate()
    x = np.random.randn(2, 3, 10, 10).astype(np.float32)
    ref = _forward(model, x)
    fold_batchnorm(model)
    assert len(model.layers) == 1
    assert model.get(0).with_bias
    np.testing.assert_allclose(_forward(model, x), ref, rtol=1e-4, atol=1e-5)


def test_graph_sibling_merge_exact():
    """The DAG form (imported models): same-input fan-out convs merge
    into one node; consumers see Narrow slices."""
    from bigdl_tpu.nn.fuse import merge_sibling_convs
    from bigdl_tpu.nn.graph import Graph, Input

    RNG.set_seed(11)
    def build():
        inp = Input(name="in")
        b1 = nn.SpatialConvolution(16, 8, 1, 1).set_name("b1").inputs(inp)
        b2 = nn.SpatialConvolution(16, 12, 1, 1).set_name("b2").inputs(inp)
        b2b = nn.SpatialConvolution(12, 24, 3, 3, 1, 1, 1, 1)\
            .set_name("b2b").inputs(nn.ReLU(True).inputs(b2))
        b3 = nn.SpatialConvolution(16, 4, 1, 1).set_name("b3").inputs(inp)
        pool = nn.SpatialMaxPooling(3, 3, 1, 1, 1, 1).inputs(inp)
        join = nn.JoinTable(1).inputs(b1, b2b, b3, pool)
        return Graph(inp, join)

    x = np.random.randn(2, 16, 7, 7).astype(np.float32)
    RNG.set_seed(11)
    ref = _forward(build(), x)
    RNG.set_seed(11)
    fused = merge_sibling_convs(build())
    np.testing.assert_allclose(_forward(fused, x), ref, rtol=1e-5, atol=1e-6)
    # the three same-input 1x1 convs became ONE conv node
    n_convs = sum(1 for m in fused.layers
                  if isinstance(m, nn.SpatialConvolution))
    assert n_convs == 2  # merged(8+12+4) + b2b
    # gradients flow through the rewritten DAG
    gy = np.random.randn(2, 8 + 24 + 4 + 16, 7, 7).astype(np.float32)
    g = fused.backward(jnp.asarray(x), jnp.asarray(gy))
    assert np.asarray(g).shape == x.shape


def test_optimize_for_tpu_returns_rebuilt_graph():
    """optimize_for_tpu must propagate merge_sibling_convs' REBUILT
    Graph — returning the surgically-mutated original (stale topo order)
    produced a KeyError at forward time."""
    from bigdl_tpu.nn.fuse import optimize_for_tpu
    from bigdl_tpu.nn.graph import Graph, Input

    RNG.set_seed(13)
    inp = Input(name="in")
    a = nn.SpatialConvolution(6, 4, 1, 1).inputs(inp)
    b = nn.SpatialConvolution(6, 5, 1, 1).inputs(inp)
    join = nn.JoinTable(1).inputs(a, b)
    g = Graph(inp, join)
    x = np.random.randn(2, 6, 5, 5).astype(np.float32)
    ref = _forward(g, x)
    opt = optimize_for_tpu(g)
    assert opt is not g  # rebuilt root
    np.testing.assert_allclose(_forward(opt, x), ref, rtol=1e-5, atol=1e-6)


def test_graph_merge_unbatched_input():
    """SpatialConvolution supports unbatched CHW inputs; the Narrow
    slices must too (negative channel axis)."""
    from bigdl_tpu.nn.fuse import merge_sibling_convs
    from bigdl_tpu.nn.graph import Graph, Input

    RNG.set_seed(14)
    def build():
        inp = Input(name="in")
        a = nn.SpatialConvolution(6, 4, 1, 1).inputs(inp)
        b = nn.SpatialConvolution(6, 5, 1, 1).inputs(inp)
        return Graph(inp, nn.JoinTable(0).inputs(a, b))

    x3 = np.random.randn(6, 5, 5).astype(np.float32)  # CHW, no batch
    RNG.set_seed(14)
    ref = _forward(build(), x3)
    RNG.set_seed(14)
    fused = merge_sibling_convs(build())
    np.testing.assert_allclose(_forward(fused, x3), ref,
                               rtol=1e-5, atol=1e-6)


def test_graph_merge_inner_graph_reregistered():
    """An inner Graph rebuilt by the recursion must be re-registered in
    the outer Graph's module table, or training/state_dict would keep the
    dead pre-merge weights."""
    from bigdl_tpu.nn.fuse import merge_sibling_convs
    from bigdl_tpu.nn.graph import Graph, Input
    from bigdl_tpu.nn.module import state_dict

    RNG.set_seed(15)
    i_in = Input(name="i")
    ia = nn.SpatialConvolution(4, 3, 1, 1).inputs(i_in)
    ib = nn.SpatialConvolution(4, 2, 1, 1).inputs(i_in)
    inner = Graph(i_in, nn.JoinTable(1).inputs(ia, ib))

    o_in = Input(name="o")
    wrapped = inner.inputs(o_in)
    outer = Graph(o_in, nn.ReLU(True).inputs(wrapped))

    x = np.random.randn(2, 4, 5, 5).astype(np.float32)
    ref = _forward(outer, x)
    fused = merge_sibling_convs(outer)
    np.testing.assert_allclose(_forward(fused, x), ref, rtol=1e-5, atol=1e-6)
    # the LIVE merged conv's parameters are discoverable for training
    shapes = [tuple(v.shape) for v in state_dict(fused, kind="param").values()]
    assert (5, 4, 1, 1) in shapes, shapes  # merged 3+2 output channels


def test_graph_merge_shared_inner_graph():
    """A Siamese inner Graph wrapped by TWO nodes must map to ONE
    rebuilt object — not a rebuilt copy for the first node and a stale
    mutated original (with a dangling merged node) for the second."""
    from bigdl_tpu.nn.fuse import merge_sibling_convs
    from bigdl_tpu.nn.graph import Graph, Input, Node

    RNG.set_seed(18)
    i_in = Input(name="i")
    ia = nn.SpatialConvolution(4, 3, 1, 1).inputs(i_in)
    ib = nn.SpatialConvolution(4, 2, 1, 1).inputs(i_in)
    inner = Graph(i_in, nn.JoinTable(1).inputs(ia, ib))

    o1, o2 = Input(name="x1"), Input(name="x2")
    n1, n2 = Node(inner), Node(inner)  # shared tower
    n1.add_prev(o1)
    n2.add_prev(o2)
    join = nn.JoinTable(1).inputs(n1, n2)
    outer = Graph([o1, o2], join)

    xs = [jnp.asarray(np.random.randn(2, 4, 5, 5).astype(np.float32))
          for _ in range(2)]
    ref = np.asarray(outer.forward(xs))
    fused = merge_sibling_convs(outer)
    np.testing.assert_allclose(np.asarray(fused.forward(xs)), ref,
                               rtol=1e-5, atol=1e-6)
    assert n1.element is n2.element  # still ONE shared tower


def test_graph_rebuild_preserves_name_and_eval_mode():
    from bigdl_tpu.nn.fuse import merge_sibling_convs
    from bigdl_tpu.nn.graph import Graph, Input

    RNG.set_seed(19)
    inp = Input(name="in")
    a = nn.SpatialConvolution(4, 3, 1, 1).inputs(inp)
    b = nn.SpatialConvolution(4, 2, 1, 1).inputs(inp)
    g = Graph(inp, nn.JoinTable(1).inputs(a, b)).set_name("backbone")
    g.evaluate()
    fused = merge_sibling_convs(g)
    assert fused.get_name() == "backbone"
    assert not fused.is_training()


def test_graph_merge_skips_cross_group_weight_sharing():
    """A conv module wrapped by nodes in DIFFERENT groups (Siamese) must
    not be repacked — merging would fork the tied weights."""
    from bigdl_tpu.nn.fuse import merge_sibling_convs
    from bigdl_tpu.nn.graph import Graph, Input, Node

    RNG.set_seed(16)
    in1, in2 = Input(name="x1"), Input(name="x2")
    shared = nn.SpatialConvolution(4, 4, 1, 1)
    n1, n2 = Node(shared), Node(shared)
    n1.add_prev(in1)
    n2.add_prev(in2)
    other = nn.SpatialConvolution(4, 6, 1, 1).inputs(in1)  # same input as n1
    join = nn.JoinTable(1).inputs(n1, n2, other)
    g = Graph([in1, in2], join)
    xs = [jnp.asarray(np.random.randn(2, 4, 5, 5).astype(np.float32))
          for _ in range(2)]
    ref = np.asarray(g.forward(xs))
    fused = merge_sibling_convs(g)
    np.testing.assert_array_equal(np.asarray(fused.forward(xs)), ref)
    # the shared conv is still ONE object wherever it appears
    convs = [m for m in fused.layers if isinstance(m, nn.SpatialConvolution)]
    assert sum(1 for c in convs if c is shared) >= 1


def test_graph_merge_skips_weight_shared_clones():
    from bigdl_tpu.nn.fuse import merge_sibling_convs
    from bigdl_tpu.nn.graph import Graph, Input, Node

    RNG.set_seed(12)
    inp = Input(name="in")
    conv = nn.SpatialConvolution(4, 4, 1, 1)
    n1, n2 = Node(conv), Node(conv)  # same module object twice
    n1.add_prev(inp)
    n2.add_prev(inp)
    join = nn.JoinTable(1).inputs(n1, n2)
    g = Graph(inp, join)
    x = np.random.randn(2, 4, 5, 5).astype(np.float32)
    ref = _forward(g, x)
    fused = merge_sibling_convs(g)
    np.testing.assert_array_equal(_forward(fused, x), ref)


@pytest.mark.parametrize("h,w,k,s,p", [
    (224, 224, 7, 2, 3),   # the ImageNet conv1 shape
    (11, 11, 2, 2, 0),     # trailing row cropped (negative hi pad)
    (15, 13, 5, 3, 2),     # stride 3, asymmetric spatial extents
])
def test_space_to_depth_input_exact(h, w, k, s, p):
    from bigdl_tpu.nn.fuse import space_to_depth_input

    RNG.set_seed(8)
    # the grad-scatter comparison below sits at rtol=1e-4 — pin the
    # GLOBAL numpy stream too, or the draw (and thus the accumulated
    # rounding) depends on whichever test ran before in the process
    np.random.seed(8)
    conv = nn.SpatialConvolution(3, 8, k, k, s, s, p, p)
    ref_model = nn.Sequential(conv, nn.ReLU(True))
    x = np.random.randn(2, 3, h, w).astype(np.float32)
    ref = _forward(ref_model, x)
    # grads of the ORIGINAL parameterization
    gy = np.random.randn(*ref.shape).astype(np.float32)
    ref_model.zero_grad_parameters()
    ref_model.backward(jnp.asarray(x), jnp.asarray(gy))
    g_ref = np.asarray(conv._grads["weight"])

    RNG.set_seed(8)
    conv2 = nn.SpatialConvolution(3, 8, k, k, s, s, p, p)
    model = space_to_depth_input(nn.Sequential(conv2, nn.ReLU(True)))
    out = _forward(model, x)
    np.testing.assert_allclose(out, ref, rtol=1e-5, atol=1e-6)

    # training equivalence: dead slots stay zero, live slots get the
    # SAME gradients as the original packing
    inner = model.get(0)
    new_conv = inner.get(1)
    model.zero_grad_parameters()
    model.backward(jnp.asarray(x), jnp.asarray(gy))
    gw = np.asarray(new_conv._grads["weight"])
    mask = np.asarray(new_conv.weight_mask)[0]
    assert np.all(gw[:, mask == 0] == 0), "dead slots received gradient"
    # scatter the original grad into the repacked layout and compare
    kp = -(-k // s)
    for a_h in range(s):
        for a_w in range(s):
            for j_h in range(kp):
                dy = s * j_h + a_h
                if dy >= k:
                    continue
                for j_w in range(kp):
                    dx = s * j_w + a_w
                    if dx >= k:
                        continue
                    ch = (np.arange(3) * s + a_h) * s + a_w
                    # atol scales with the grad magnitude: a near-zero
                    # element is the CANCELLATION of ~h*w products of
                    # O(max|g|) — holding it to 1e-5 absolute asserts
                    # more precision than the f32 sum carries
                    np.testing.assert_allclose(
                        gw[:, ch, j_h, j_w], g_ref[:, :, dy, dx],
                        rtol=1e-4,
                        atol=1e-6 * max(1.0, np.abs(g_ref).max()))


def test_space_to_depth_on_graph_input_conv():
    """Imported DAGs: the conv1 node fed by an Input gets the s2d repack
    (element swapped for the pad+masked-conv Sequential)."""
    from bigdl_tpu.nn.fuse import optimize_for_tpu
    from bigdl_tpu.nn.graph import Graph, Input

    RNG.set_seed(17)
    def build():
        inp = Input(name="in")
        c1 = nn.SpatialConvolution(3, 8, 7, 7, 2, 2, 3, 3).inputs(inp)
        r = nn.ReLU(True).inputs(c1)
        deep = nn.SpatialConvolution(8, 6, 3, 3, 2, 2, 1, 1).inputs(r)
        return Graph(inp, deep)

    x = np.random.randn(2, 3, 32, 32).astype(np.float32)
    RNG.set_seed(17)
    ref = _forward(build(), x)
    RNG.set_seed(17)
    opt = optimize_for_tpu(build())
    np.testing.assert_allclose(_forward(opt, x), ref, rtol=1e-5, atol=1e-6)
    # conv1 repacked, deep conv (8 channels) untouched
    kinds = [type(m).__name__ for m in opt.layers]
    assert "Sequential" in kinds and kinds.count("SpatialConvolution") == 1


def test_space_to_depth_skips_wide_input_convs():
    from bigdl_tpu.nn.fuse import space_to_depth_input

    model = nn.Sequential(nn.SpatialConvolution(64, 64, 3, 3, 2, 2, 1, 1))
    assert space_to_depth_input(model) is model
    assert isinstance(model.get(0), nn.SpatialConvolution)


def test_space_to_depth_skips_same_padding():
    """pad == -1 (SAME) has different output-size math — must not rewrite."""
    from bigdl_tpu.nn.fuse import space_to_depth_input

    model = nn.Sequential(nn.SpatialConvolution(3, 8, 7, 7, 2, 2, -1, -1))
    ref = _forward(model, np.random.randn(2, 3, 32, 32).astype(np.float32))
    assert space_to_depth_input(model) is model
    assert isinstance(model.get(0), nn.SpatialConvolution)
    assert ref.shape == (2, 8, 16, 16)


def test_space_to_depth_unbatched_input():
    from bigdl_tpu.nn.fuse import space_to_depth_input

    RNG.set_seed(9)
    conv = nn.SpatialConvolution(3, 8, 7, 7, 2, 2, 3, 3)
    x3 = np.random.randn(3, 32, 32).astype(np.float32)
    ref = np.asarray(conv.forward(jnp.asarray(x3)))
    RNG.set_seed(9)
    model = space_to_depth_input(nn.SpatialConvolution(3, 8, 7, 7, 2, 2, 3, 3))
    out = np.asarray(model.forward(jnp.asarray(x3)))
    assert out.shape == ref.shape == (8, 16, 16)
    np.testing.assert_allclose(out, ref, rtol=1e-5, atol=1e-6)


def test_space_to_depth_model_serializes():
    """optimize_for_tpu output must stay BTPU-persistable (checkpoints)."""
    from bigdl_tpu.nn.fuse import space_to_depth_input
    from bigdl_tpu.utils import module_format

    RNG.set_seed(10)
    model = space_to_depth_input(nn.Sequential(
        nn.SpatialConvolution(3, 8, 7, 7, 2, 2, 3, 3), nn.ReLU(True)))
    x = np.random.randn(2, 3, 32, 32).astype(np.float32)
    ref = _forward(model, x)
    blob = module_format.dumps(model)
    loaded = module_format.loads(blob)
    np.testing.assert_array_equal(_forward(loaded, x), ref)


def test_fold_batchnorm_skips_non_adjacent():
    from bigdl_tpu.nn.fuse import fold_batchnorm

    RNG.set_seed(6)
    model = nn.Sequential(nn.SpatialConvolution(3, 5, 1, 1), nn.ReLU(True),
                          _bn_with_stats(5, 3))
    fold_batchnorm(model)
    assert len(model.layers) == 3  # ReLU between conv and BN: no fold


# --------------------------------------------------------------------------
# shape-invariant wiring (bigdl_tpu.analysis shape pass around the rewrites)
# --------------------------------------------------------------------------

def test_optimize_for_tpu_shape_invariant_resnet_inception():
    """Every fusion pass must prove it preserved output shapes/dtypes:
    before/after specs via the analyzer's abstract evaluation must be
    identical for the models the rewrites exist for."""
    from bigdl_tpu.analysis.shape_pass import output_spec, specs_equal
    from bigdl_tpu.models import build_resnet

    for build, spec in (
            (lambda: build_resnet(18, 100),
             jax.ShapeDtypeStruct((2, 3, 224, 224), jnp.float32)),
            (lambda: build_inception_v1(100),
             jax.ShapeDtypeStruct((2, 3, 224, 224), jnp.float32))):
        RNG.set_seed(3)
        before = output_spec(build(), spec)
        assert before is not None
        RNG.set_seed(3)
        fused = optimize_for_tpu(build(), example_input=spec)
        after = output_spec(fused, spec)
        assert specs_equal(before, after), (before, after)


def test_optimize_for_tpu_invariant_catches_broken_pass(monkeypatch):
    """The default-on invariant must actually trip when a rewrite breaks
    the model (guards against the check becoming a stub)."""
    from bigdl_tpu.nn import fuse as fuse_mod
    from bigdl_tpu.nn.fuse import ShapeInvariantError

    def breaking_pass(model):
        return nn.Sequential(model, nn.Narrow(1, 0, 1))  # chops channels

    monkeypatch.setattr(fuse_mod, "space_to_depth_input", breaking_pass)
    RNG.set_seed(4)
    block = nn.Sequential(
        nn.SpatialConvolution(3, 8, 3, 3, 1, 1, 1, 1), nn.ReLU(True))
    with pytest.raises(ShapeInvariantError):
        fuse_mod.optimize_for_tpu(
            block, example_input=jax.ShapeDtypeStruct((2, 3, 16, 16),
                                                      jnp.float32))


def test_optimize_for_tpu_rejects_uneval_example_input():
    """An explicitly pinned example_input the model cannot abstractly
    evaluate must raise, not silently skip the invariant."""
    from bigdl_tpu.nn.fuse import ShapeInvariantError

    RNG.set_seed(6)
    block = nn.Sequential(nn.SpatialConvolution(3, 8, 3, 3, 1, 1, 1, 1))
    with pytest.raises(ShapeInvariantError, match="abstract evaluation"):
        optimize_for_tpu(block, example_input=jax.ShapeDtypeStruct(
            (2, 5, 16, 16), jnp.float32))  # 5 channels into a 3-ch conv


def test_optimize_for_tpu_infers_spec_by_default():
    """No example input: the invariant still runs via inferred specs (the
    bench/tools call pattern `optimize_for_tpu(model)`)."""
    RNG.set_seed(5)
    model = optimize_for_tpu(build_inception_v1(100))
    out = model.evaluate().forward(jnp.ones((1, 3, 224, 224)))
    assert out.shape == (1, 100)
