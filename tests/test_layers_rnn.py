

def test_remat_cell_trajectory_equivalence():
    """remat_cell() recomputes the same ops — gradients must match the
    saved-activation path to float tolerance."""
    import numpy as np
    import jax
    import jax.numpy as jnp
    import bigdl_tpu.nn as nn
    from bigdl_tpu.nn.module import functional_call, state_dict

    rng = np.random.default_rng(11)
    x = jnp.asarray(rng.normal(size=(4, 12, 8)).astype(np.float32))
    y = jnp.asarray(rng.normal(size=(4, 3)).astype(np.float32))

    def grads(remat):
        from bigdl_tpu.utils.rng import RNG

        RNG.set_seed(3)
        rec = nn.Recurrent(nn.LSTM(8, 16))
        if remat:
            rec.remat_cell()
        model = nn.Sequential(rec, nn.Select(1, -1), nn.Linear(16, 3),
                              nn.LogSoftMax())
        sd = state_dict(model)

        def loss(s):
            out, _ = functional_call(model, s, x)
            return jnp.sum(out * y)

        return jax.grad(loss)(sd)

    g0, g1 = grads(False), grads(True)
    flat0 = jax.tree_util.tree_leaves(g0)
    flat1 = jax.tree_util.tree_leaves(g1)
    assert len(flat0) == len(flat1) and flat0
    for a, b in zip(flat0, flat1):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-5, atol=1e-5)
