"""run_scan: N training iterations inside one compiled dispatch
(lax.scan over the train step) must match N sequential run() calls —
the dispatch-amortized path used by the perf harness and remote-device
deployments."""

import jax
import numpy as np
import pytest

import bigdl_tpu.nn as nn
import bigdl_tpu.optim as optim
from bigdl_tpu.parallel.train_step import TrainStep
from bigdl_tpu.utils.rng import RNG


def _make(mesh=None):
    RNG.set_seed(5)
    model = nn.Sequential(nn.Linear(6, 16), nn.Tanh(),
                          nn.Linear(16, 3), nn.LogSoftMax())
    return TrainStep(model, nn.ClassNLLCriterion(),
                     optim.SGD(learning_rate=0.2, momentum=0.9), mesh=mesh)


def _data(batch=16):
    rng = np.random.RandomState(0)
    return (rng.randn(batch, 6).astype(np.float32),
            rng.randint(0, 3, batch))


def test_scan_matches_sequential_runs():
    x, y = _data()
    n = 5
    key = jax.random.key(42)

    step_a = _make()
    losses = np.asarray(step_a.run_scan(x, y, key, n))
    assert losses.shape == (n,)

    step_b = _make()
    seq = [float(step_b.run(x, y, jax.random.fold_in(key, i)))
           for i in range(n)]
    np.testing.assert_allclose(losses, seq, rtol=1e-5, atol=1e-6)
    for k in step_a.params:
        np.testing.assert_allclose(np.asarray(step_a.params[k]),
                                   np.asarray(step_b.params[k]),
                                   rtol=1e-5, atol=1e-6)


def test_scan_stacked_batches_match_sequential():
    n, batch = 4, 8
    rng = np.random.RandomState(1)
    xs = rng.randn(n, batch, 6).astype(np.float32)
    ys = rng.randint(0, 3, (n, batch))
    key = jax.random.key(7)

    step_a = _make()
    losses = np.asarray(step_a.run_scan(xs, ys, key, n, stacked=True))

    step_b = _make()
    seq = [float(step_b.run(xs[i], ys[i], jax.random.fold_in(key, i)))
           for i in range(n)]
    np.testing.assert_allclose(losses, seq, rtol=1e-5, atol=1e-6)


def test_scan_on_mesh():
    from bigdl_tpu.parallel.mesh import make_mesh

    mesh = make_mesh((8,), ("data",))
    step = _make(mesh=mesh)
    x, y = _data(batch=16)
    losses = step.run_scan(x, y, jax.random.key(0), 3)
    assert np.isfinite(np.asarray(losses)).all()


def test_aot_scan_cost_analysis():
    step = _make()
    x, y = _data()
    cost = step.aot_scan(x, y, jax.random.key(0), 4)
    assert cost is None or "flops" in cost
    losses = step.run_scan(x, y, jax.random.key(1), 4)
    assert np.isfinite(np.asarray(losses)).all()
