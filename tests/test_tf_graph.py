"""TF GraphDef interop: hand-encoded GraphDef import, export round-trip,
and trainable-const import."""

import struct
import tempfile

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import bigdl_tpu.nn as nn
from bigdl_tpu.utils import protowire as pw
from bigdl_tpu.utils.tf_graph import (load_graphdef, parse_graphdef,
                                      save_graphdef)

_DT_FLOAT, _DT_INT32 = 1, 3


def _attr(key, payload):
    return pw.emit_bytes(5, pw.emit_bytes(1, key.encode())
                         + pw.emit_bytes(2, payload))


def _tensor(arr, dt):
    arr = np.asarray(arr)
    shape = b"".join(pw.emit_bytes(2, pw.emit_varint(1, d))
                     for d in arr.shape)
    return (pw.emit_varint(1, dt) + pw.emit_bytes(2, shape)
            + pw.emit_bytes(4, arr.tobytes()))


def _node(name, op, inputs=(), attrs=b""):
    body = pw.emit_bytes(1, name.encode()) + pw.emit_bytes(2, op.encode())
    for i in inputs:
        body += pw.emit_bytes(3, i.encode())
    return pw.emit_bytes(1, body + attrs)


def _const(name, arr, dt=_DT_FLOAT):
    return _node(name, "Const", (),
                 _attr("dtype", pw.emit_varint(6, dt))
                 + _attr("value", pw.emit_bytes(8, _tensor(arr, dt))))


def _make_mlp_graphdef(w1, b1, w2):
    """x @ w1 + b1 -> relu -> @ w2 -> softmax"""
    gd = b""
    gd += _node("x", "Placeholder", (),
                _attr("dtype", pw.emit_varint(6, _DT_FLOAT)))
    gd += _const("w1", w1)
    gd += _const("b1", b1)
    gd += _const("w2", w2)
    gd += _node("mm1", "MatMul", ("x", "w1"))
    gd += _node("add1", "BiasAdd", ("mm1", "b1"))
    gd += _node("relu", "Relu", ("add1",))
    gd += _node("mm2", "MatMul", ("relu", "w2"))
    gd += _node("prob", "Softmax", ("mm2",))
    return gd


@pytest.fixture
def mlp_graphdef():
    rng = np.random.RandomState(0)
    w1 = rng.randn(6, 8).astype(np.float32)
    b1 = rng.randn(8).astype(np.float32)
    w2 = rng.randn(8, 3).astype(np.float32)
    return _make_mlp_graphdef(w1, b1, w2), (w1, b1, w2)


def test_parse_graphdef(mlp_graphdef):
    gd, (w1, b1, w2) = mlp_graphdef
    nodes = parse_graphdef(gd)
    byname = {n["name"]: n for n in nodes}
    assert byname["mm1"]["op"] == "MatMul"
    assert byname["mm1"]["inputs"] == ["x", "w1"]
    np.testing.assert_allclose(byname["w1"]["attrs"]["value"], w1)


def test_import_mlp_graphdef(mlp_graphdef):
    gd, (w1, b1, w2) = mlp_graphdef
    model = load_graphdef(gd, ["x"], ["prob"]).evaluate()
    x = np.random.RandomState(1).randn(4, 6).astype(np.float32)
    got = np.asarray(model.forward(x))
    h = np.maximum(x @ w1 + b1, 0)
    logits = h @ w2
    e = np.exp(logits - logits.max(-1, keepdims=True))
    expected = e / e.sum(-1, keepdims=True)
    np.testing.assert_allclose(got, expected, rtol=1e-5, atol=1e-6)


def test_import_train_consts(mlp_graphdef):
    from bigdl_tpu.nn.module import state_dict

    gd, _ = mlp_graphdef
    model = load_graphdef(gd, ["x"], ["prob"], train_consts=True)
    params = state_dict(model, kind="param")
    # w1, b1, w2 become trainable Variables
    assert len(params) == 3


def test_import_conv_pool_ops():
    rng = np.random.RandomState(2)
    w = rng.randn(3, 3, 2, 4).astype(np.float32)  # HWIO
    gd = b""
    gd += _node("x", "Placeholder", ())
    gd += _const("w", w)
    gd += _node("conv", "Conv2D", ("x", "w"),
                _attr("padding", pw.emit_bytes(2, b"SAME"))
                + _attr("strides", pw.emit_bytes(
                    1, b"".join(pw.emit_varint(3, i) for i in (1, 1, 1, 1)))))
    gd += _node("relu", "Relu", ("conv",))
    gd += _node("pool", "MaxPool", ("relu",),
                _attr("padding", pw.emit_bytes(2, b"VALID"))
                + _attr("ksize", pw.emit_bytes(
                    1, b"".join(pw.emit_varint(3, i) for i in (1, 2, 2, 1))))
                + _attr("strides", pw.emit_bytes(
                    1, b"".join(pw.emit_varint(3, i) for i in (1, 2, 2, 1)))))
    model = load_graphdef(gd, ["x"], ["pool"]).evaluate()
    x = rng.randn(1, 8, 8, 2).astype(np.float32)
    out = model.forward(x)
    assert out.shape == (1, 4, 4, 4)


def test_import_reshape_concat_mean():
    gd = b""
    gd += _node("x", "Placeholder", ())
    gd += _const("shape", np.asarray([-1, 4], np.int32), _DT_INT32)
    gd += _node("rs", "Reshape", ("x", "shape"))
    gd += _const("axis", np.asarray([1], np.int32), _DT_INT32)
    gd += _node("mean", "Mean", ("rs", "axis"),
                _attr("keep_dims", pw.emit_varint(5, 1)))
    model = load_graphdef(gd, ["x"], ["mean"]).evaluate()
    x = np.arange(8.0, dtype=np.float32).reshape(2, 2, 2)
    out = np.asarray(model.forward(x))
    np.testing.assert_allclose(out, x.reshape(2, 4).mean(1, keepdims=True))


def test_export_import_roundtrip():
    from bigdl_tpu.utils.rng import RNG

    RNG.set_seed(3)
    model = nn.Sequential(
        nn.Linear(6, 16), nn.ReLU(), nn.Linear(16, 4), nn.LogSoftMax(),
    ).evaluate()
    path = tempfile.mktemp(suffix=".pb")
    outputs = save_graphdef(model, path, input_name="input")
    re = load_graphdef(path, ["input"], outputs).evaluate()
    x = np.random.RandomState(4).randn(3, 6).astype(np.float32)
    np.testing.assert_allclose(np.asarray(re.forward(x)),
                               np.asarray(model.forward(x)),
                               rtol=1e-5, atol=1e-6)


def test_export_import_cnn_roundtrip():
    from bigdl_tpu.utils.rng import RNG

    RNG.set_seed(5)
    model = nn.Sequential(
        nn.SpatialConvolution(2, 4, 3, 3),  # VALID
        nn.ReLU(),
        nn.SpatialMaxPooling(2, 2),
        nn.InferReshape([0, -1]),
        nn.Linear(4 * 3 * 3, 5),
    ).evaluate()
    path = tempfile.mktemp(suffix=".pb")
    outputs = save_graphdef(model, path)
    re = load_graphdef(path, ["input"], outputs).evaluate()
    x = np.random.RandomState(6).randn(2, 2, 8, 8).astype(np.float32)
    np.testing.assert_allclose(np.asarray(re.forward(x)),
                               np.asarray(model.forward(x)),
                               rtol=1e-4, atol=1e-5)


# -------- new-op catalog closure, validated against REAL TensorFlow -------

def _tf_golden(build_fn, feeds, outputs):
    """Build a graph with real TF (v1 mode), return (graphdef_bytes,
    {output: value})."""
    import tensorflow as tf

    g = tf.Graph()
    with g.as_default():
        build_fn(tf.compat.v1)
    with tf.compat.v1.Session(graph=g) as sess:
        vals = sess.run(outputs, feeds)
    return g.as_graph_def().SerializeToString(), dict(zip(outputs, vals))


def test_import_split_pack_transpose_vs_tf():
    x_np = np.random.RandomState(0).randn(4, 6).astype(np.float32)

    def build(v1):
        x = v1.placeholder(np.float32, (4, 6), name="x")
        a, b, c = v1.split(x, 3, axis=1, name="split")
        s = v1.transpose(a + c, [1, 0], name="tr")
        v1.stack([s, v1.transpose(b, [1, 0])], axis=0, name="out")

    gd, golden = _tf_golden(build, {"x:0": x_np}, ["out:0"])
    model = load_graphdef(gd, ["x"], ["out"])
    got = np.asarray(model.forward(jnp.asarray(x_np)))
    np.testing.assert_allclose(got, golden["out:0"], rtol=1e-5, atol=1e-6)


def test_import_unpack_onehot_slice_vs_tf():
    idx_np = np.array([[0, 2, 1], [2, 1, 0]], np.int32)

    def build(v1):
        i = v1.placeholder(np.int32, (2, 3), name="i")
        rows = v1.unstack(i, axis=0, name="unpack")
        oh = v1.one_hot(rows[1], 3, on_value=2.0, off_value=-1.0,
                        name="onehot")
        v1.slice(oh, [0, 1], [2, 2], name="out")

    gd, golden = _tf_golden(build, {"i:0": idx_np}, ["out:0"])
    model = load_graphdef(gd, ["i"], ["out"])
    got = np.asarray(model.forward(jnp.asarray(idx_np)))
    np.testing.assert_allclose(got, golden["out:0"], rtol=1e-5)


def test_import_strided_slice_vs_tf():
    x_np = np.random.RandomState(1).randn(4, 5, 6).astype(np.float32)

    def build(v1):
        x = v1.placeholder(np.float32, (4, 5, 6), name="x")
        a = x[1:3, ::2, 4]           # shrink on last axis
        v1.identity(a, name="out")

    gd, golden = _tf_golden(build, {"x:0": x_np}, ["out:0"])
    model = load_graphdef(gd, ["x"], ["out"])
    got = np.asarray(model.forward(jnp.asarray(x_np)))
    np.testing.assert_allclose(got, golden["out:0"], rtol=1e-5)


def test_import_resize_bilinear_vs_tf():
    x_np = np.random.RandomState(2).rand(1, 4, 4, 3).astype(np.float32)

    def build(v1):
        x = v1.placeholder(np.float32, (1, 4, 4, 3), name="x")
        v1.image.resize_bilinear(x, [8, 8], name="out")

    gd, golden = _tf_golden(build, {"x:0": x_np}, ["out:0"])
    model = load_graphdef(gd, ["x"], ["out"])
    got = np.asarray(model.forward(jnp.asarray(x_np)))
    np.testing.assert_allclose(got, golden["out:0"], rtol=1e-4, atol=1e-5)


@pytest.mark.parametrize("padding,stride", [("SAME", 2), ("VALID", 2),
                                            ("SAME", 1)])
def test_import_conv2d_backprop_input_vs_tf(padding, stride):
    w_np = np.random.RandomState(3).randn(3, 3, 2, 5).astype(np.float32)
    out_h = 8
    in_h = (out_h // stride) if padding == "SAME" \
        else (out_h - 3) // stride + 1
    y_np = np.random.RandomState(4).randn(1, in_h, in_h, 5).astype(
        np.float32)

    def build(v1):
        y = v1.placeholder(np.float32, y_np.shape, name="y")
        w = v1.constant(w_np, name="w")
        v1.nn.conv2d_backprop_input(
            [1, out_h, out_h, 2], w, y, [1, stride, stride, 1], padding,
            name="out")

    gd, golden = _tf_golden(build, {"y:0": y_np}, ["out:0"])
    model = load_graphdef(gd, ["y"], ["out"])
    got = np.asarray(model.forward(jnp.asarray(y_np)))
    np.testing.assert_allclose(got, golden["out:0"], rtol=1e-4, atol=1e-4)


def test_import_decode_image():
    import io

    from PIL import Image

    from bigdl_tpu.utils.tf_graph import TensorflowLoader

    img = (np.random.RandomState(5).rand(6, 7, 3) * 255).astype(np.uint8)
    buf = io.BytesIO()
    Image.fromarray(img).save(buf, format="PNG")

    def build(v1):
        s = v1.placeholder(v1.string, (), name="s")
        v1.image.decode_png(s, channels=3, name="out")

    gd, _ = _tf_golden(build, {"s:0": buf.getvalue()}, [])
    model = load_graphdef(gd, ["s"], ["out"])
    got = np.asarray(model.forward(buf.getvalue()))
    np.testing.assert_array_equal(got, img)


def test_import_resize_bilinear_half_pixel_vs_tf():
    """TF2-style ResizeBilinear (half_pixel_centers=true) must import
    with the matching grid, not the legacy asymmetric one."""
    x_np = np.random.RandomState(3).rand(1, 5, 5, 2).astype(np.float32)

    def build(v1):
        import tensorflow as tf

        x = v1.placeholder(np.float32, (1, 5, 5, 2), name="x")
        out = tf.raw_ops.ResizeBilinear(images=x, size=[9, 7],
                                        align_corners=False,
                                        half_pixel_centers=True)
        v1.identity(out, name="out")

    gd, golden = _tf_golden(build, {"x:0": x_np}, ["out:0"])
    model = load_graphdef(gd, ["x"], ["out"])
    got = np.asarray(model.forward(jnp.asarray(x_np)))
    np.testing.assert_allclose(got, golden["out:0"], rtol=1e-4, atol=1e-5)


def test_imported_tf_graph_gets_sibling_merge():
    """TF imports keep TF-op fidelity (Conv2D takes its HWIO weight as a
    graph input from a Const/Variable node) — the merge concatenates the
    WEIGHT NODES on the O axis and slices with Narrow, for both frozen
    (Const) and trainable (train_consts=True -> Variable) imports."""
    from bigdl_tpu.nn.fuse import optimize_for_tpu

    rng = np.random.RandomState(3)
    wa = rng.randn(1, 1, 4, 3).astype(np.float32)  # HWIO
    wb = rng.randn(1, 1, 4, 5).astype(np.float32)
    strides = _attr("strides", pw.emit_bytes(
        1, b"".join(pw.emit_varint(3, i) for i in (1, 1, 1, 1))))
    pad = _attr("padding", pw.emit_bytes(2, b"VALID"))
    gd = b""
    gd += _node("x", "Placeholder", ())
    gd += _const("wa", wa)
    gd += _const("wb", wb)
    gd += _node("ca", "Conv2D", ("x", "wa"), pad + strides)
    gd += _node("cb", "Conv2D", ("x", "wb"), pad + strides)
    gd += _const("axis", np.asarray(3, np.int32), _DT_INT32)
    gd += _node("cat", "ConcatV2", ("ca", "cb", "axis"))
    from bigdl_tpu.nn import ops as nnops
    from bigdl_tpu.nn import tf as nntf
    from bigdl_tpu.nn.module import state_dict

    x = np.random.RandomState(4).randn(2, 6, 6, 4).astype(np.float32)
    for trainable in (False, True):
        model = load_graphdef(gd, ["x"], ["cat"],
                              train_consts=trainable).evaluate()
        ref = np.asarray(model.forward(x))
        opt = optimize_for_tpu(model)
        np.testing.assert_allclose(np.asarray(opt.forward(x)), ref,
                                   rtol=1e-5, atol=1e-6)
        convs = [m for m in opt.layers if isinstance(m, nnops.Conv2D)]
        assert len(convs) == 1  # ca+cb merged
        wcls = nntf.Variable if trainable else nntf.Const
        merged_w = [m for m in opt.layers if isinstance(m, wcls)
                    and getattr(m, "weight" if trainable else "value").shape[-1] == 8]
        assert merged_w, "merged HWIO weight node missing"
        if trainable:
            shapes = [tuple(v.shape)
                      for v in state_dict(opt, kind="param").values()]
            assert (1, 1, 4, 8) in shapes  # ONE trainable merged weight


def test_export_zoo_roundtrip():
    """The export side at the reference's BigDLToTensorflow breadth:
    LeNet (chain), ResNet-20 with conv shortcuts (ConcatTable+CAddTable
    DAG, BatchNorm folded to its frozen running-stats affine, explicit
    conv pads via Pad nodes, AvgPool), and an Inception-style Concat
    block — each saved to a GraphDef and reloaded through our own
    TensorflowLoader with forward equality."""
    import tempfile

    import jax.numpy as jnp

    from bigdl_tpu import models
    from bigdl_tpu.utils.rng import RNG
    from bigdl_tpu.utils.tf_graph import (TensorflowLoader, parse_graphdef,
                                          save_graphdef)

    def roundtrip(model, shape, tol=1e-6):
        x = np.random.default_rng(0).normal(
            size=(2,) + shape).astype(np.float32)
        path = tempfile.mktemp(".pb")
        outs = save_graphdef(model, path)
        nodes = parse_graphdef(open(path, "rb").read())
        reloaded = TensorflowLoader(nodes, ["input"], outs,
                                    train_consts=False).load()
        a = np.asarray(model.evaluate().forward(jnp.asarray(x)))
        b = np.asarray(reloaded.evaluate().forward(jnp.asarray(x)))
        np.testing.assert_allclose(a, b, rtol=tol, atol=tol)

    RNG.set_seed(0)
    roundtrip(models.build_lenet5(10), (1, 28, 28))
    RNG.set_seed(0)
    roundtrip(models.build_resnet_cifar(20, 10, shortcut_type="B"),
              (3, 32, 32))
    RNG.set_seed(0)
    # Inception-style multi-branch Concat (the padded-POOL branch of the
    # real inception layer is excluded: zero-padding a max pool is only
    # exact for non-negative inputs, so its export correctly raises)
    import bigdl_tpu.nn as nn

    inc = nn.Concat(1)
    inc.add(nn.Sequential(nn.SpatialConvolution(16, 8, 1, 1),
                          nn.ReLU(True)))
    inc.add(nn.Sequential(nn.SpatialConvolution(16, 8, 1, 1),
                          nn.ReLU(True),
                          nn.SpatialConvolution(8, 12, 3, 3, 1, 1, 1, 1),
                          nn.ReLU(True)))
    inc.add(nn.Sequential(nn.SpatialConvolution(16, 4, 1, 1),
                          nn.ReLU(True),
                          nn.SpatialConvolution(4, 8, 5, 5, 1, 1, 2, 2),
                          nn.ReLU(True)))
    block = nn.Sequential(
        nn.SpatialConvolution(3, 16, 3, 3, 1, 1, 1, 1),
        nn.SpatialBatchNormalization(16),
        nn.ReLU(True),
        inc,
        nn.SpatialAveragePooling(4, 4, 4, 4),
        nn.View(28 * 16).set_num_input_dims(3),
        nn.Linear(28 * 16, 10), nn.LogSoftMax())
    roundtrip(block, (3, 16, 16), tol=1e-5)


def test_export_guards_raise_cleanly():
    """Unsupported-structure exports fail with diagnosable errors, not
    silently-wrong graphs."""
    import tempfile

    import bigdl_tpu.nn as nn

    for model, match in (
            (nn.Sequential(nn.SpatialMaxPooling(2, 2, 2, 2).ceil()),
             "floor mode"),
            (nn.Sequential(nn.CAddTable()), "table input"),
            (nn.Sequential(nn.SpatialZeroPadding(-1, 0, 0, 0)),
             "negative"),
    ):
        from bigdl_tpu.utils.tf_graph import save_graphdef

        with pytest.raises(NotImplementedError, match=match):
            save_graphdef(model, tempfile.mktemp(".pb"))
