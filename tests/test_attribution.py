"""Per-module cost attribution (docs/observability.md): module-path
scopes in lowered HLO, the StableHLO cost parser, FLOPs fidelity vs
XLA's own cost_analysis, zero-retrace guarantee, Module.summary, and the
CLI surfaces."""

import json

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import bigdl_tpu.nn as nn
import bigdl_tpu.optim as optim
from bigdl_tpu import telemetry
from bigdl_tpu.analysis.retrace import trace_retraces
from bigdl_tpu.models.registry import input_spec, train_pieces
from bigdl_tpu.nn.module import stamp_scope_names
from bigdl_tpu.parallel.train_step import TrainStep, _jit_cache_size
from bigdl_tpu.telemetry import attribution, schema
from bigdl_tpu.telemetry.attribution import (attribute_model, format_attribution,
                                             scope_of)
from bigdl_tpu.utils.config import BigDLConfig, set_config


def teardown_function(_fn):
    telemetry.end_run()
    set_config(None)


def _mlp():
    from bigdl_tpu.utils.rng import RNG

    RNG.set_seed(3)
    return nn.Sequential(nn.Linear(6, 8), nn.Tanh(), nn.Linear(8, 2),
                         nn.LogSoftMax())


# -- scope plumbing ----------------------------------------------------------
def test_scope_of_unwraps_autodiff_frames():
    assert scope_of("jit(step)/jit(main)/jvp(4)/conv_general_dilated") \
        == ("4", "fwd")
    assert scope_of(
        "jit(step)/jit(main)/transpose(jvp(2))/jvp(attn)/dot_general") \
        == ("2.attn", "bwd")
    # function frames (jit(log_softmax)) are not module scopes
    assert scope_of(
        "jit(step)/jit(main)/jvp(jit(take_along_axis))/gather") \
        == ("", "fwd")
    assert scope_of("w") == ("", "fwd")


def test_stamp_scope_names_and_off_switch():
    m = _mlp()
    stamp_scope_names(m)
    labels = {name: mod.__dict__.get("_scope_name")
              for name, mod in m.named_modules()}
    assert labels[""] is None  # root carries no scope
    assert labels["0"] == "0" and labels["3"] == "3"
    stamp_scope_names(m, enabled=False)
    assert all(mod.__dict__.get("_scope_name") is None
               for _, mod in m.named_modules())


def test_scopes_add_zero_retraces():
    """The acceptance invariant: scopes are trace-time metadata, never
    jit cache-key material — N steady-state steps stay at one compiled
    executable with no retrace diagnostics."""
    step = TrainStep(_mlp(), nn.ClassNLLCriterion(),
                     optim.SGD(learning_rate=0.1))
    assert any(mod.__dict__.get("_scope_name")
               for _, mod in step.model.named_modules()), \
        "TrainStep must stamp scopes by default"
    x = jnp.ones((4, 6))
    y = jnp.zeros((4,), jnp.int32)
    with trace_retraces() as mon:
        for i in range(3):
            step.run(x, y, jax.random.key(i))
    assert mon.report.rules_fired() == []
    assert _jit_cache_size(step._compiled) == 1


def test_scopes_off_knob_respected_by_train_step():
    set_config(BigDLConfig(module_scopes=False))
    step = TrainStep(_mlp(), nn.ClassNLLCriterion(),
                     optim.SGD(learning_rate=0.1))
    assert all(mod.__dict__.get("_scope_name") is None
               for _, mod in step.model.named_modules())


# -- attribution fidelity (the acceptance criterion) -------------------------
@pytest.mark.parametrize("name,batch", [("lenet", 8), ("transformer", 2)])
def test_attribution_covers_layers_and_matches_cost_analysis(name, batch):
    """Every parameterized layer appears in the table, conv/linear/
    attention modules carry real FLOPs, and the estimate's total is
    within 10% of XLA's cost_analysis for the same lowered program."""
    result = attribute_model(name, batch=batch)
    rows = {r["path"]: r for r in result["rows"]}
    # every parameterized module has a row
    from bigdl_tpu.models.registry import build_model

    model = build_model(name)
    for path, mod in model.named_modules():
        if path and mod.__dict__["_params"]:
            assert path in rows, f"no attribution row for {path}"
    # compute-bearing layers are individually attributed
    hot_classes = ("SpatialConvolution", "Linear", "MultiHeadAttention")
    hot = [r for r in result["rows"] if r.get("class") in hot_classes]
    assert hot, "expected conv/linear/attention rows"
    # the self-attention QKV GEMM is fused into the attention module
    # (deliberate, see nn/layers/attention.py) — its projection rows
    # may read 0, but every OTHER hot row must carry flops, and the
    # attention row must absorb the fused cost
    for r in hot:
        if r["path"].endswith(("q_proj", "k_proj", "v_proj")):
            continue
        assert r["flops"] > 0, f"{r['path']} has no flops"
        assert r["flops_fwd"] > 0, f"{r['path']} missing forward flops"
        assert r["flops_bwd"] > 0, f"{r['path']} missing backward flops"
    # fidelity: within 10% of XLA's own counting
    assert result.get("cost_flops"), "cost_analysis total missing"
    est, cost = result["total_flops"], result["cost_flops"]
    assert abs(est - cost) / cost < 0.10, \
        f"estimate {est:.3g} vs cost_analysis {cost:.3g}"
    # the unattributed bucket stays a sliver, not the story
    un = rows.get("(unattributed)")
    if un is not None:
        assert un["flops"] / max(est, 1.0) < 0.10
    # table renders
    text = format_attribution(result)
    assert "cost_analysis" in text and name == result["model"]


def test_attribution_event_emitted_when_enabled():
    set_config(BigDLConfig(telemetry_attribution=True))
    sink = telemetry.MemorySink()
    with telemetry.run(sinks=[sink]):
        step = TrainStep(_mlp(), nn.ClassNLLCriterion(),
                         optim.SGD(learning_rate=0.1))
        step.run(jnp.ones((4, 6)), jnp.zeros((4,), jnp.int32),
                 jax.random.key(0))
    assert schema.validate_events(sink.events) == []
    events = [e for e in sink.events if e["kind"] == "attribution"]
    assert len(events) == 1
    rows = {r["path"]: r for r in events[0]["rows"]}
    assert rows["0"]["flops"] > 0 and rows["0"]["class"] == "Linear"
    assert rows["0"]["params"] == 6 * 8 + 8


def test_attribution_not_emitted_by_default():
    sink = telemetry.MemorySink()
    with telemetry.run(sinks=[sink]):
        step = TrainStep(_mlp(), nn.ClassNLLCriterion(),
                         optim.SGD(learning_rate=0.1))
        step.run(jnp.ones((4, 6)), jnp.zeros((4,), jnp.int32),
                 jax.random.key(0))
    assert [e for e in sink.events if e["kind"] == "attribution"] == []


def test_rows_from_events_reads_back_the_last_attribution():
    events = [{"kind": "attribution", "rows": [{"path": "0"}], "v": 1,
               "ts": 0.0, "pid": 1, "tid": 1, "total_flops": 5.0}]
    out = attribution.rows_from_events(events)
    assert out == {"rows": [{"path": "0"}], "total_flops": 5.0}
    assert attribution.rows_from_events([]) is None


# -- Module.summary ----------------------------------------------------------
def test_module_summary_table_shapes_and_params():
    model = _mlp()
    text = model.summary(jax.ShapeDtypeStruct((4, 6), jnp.float32))
    assert "Linear" in text and "LogSoftMax" in text
    assert "[4, 8] float32" in text      # hidden layer output shape
    assert "[4, 2] float32" in text      # head output shape
    total = 6 * 8 + 8 + 8 * 2 + 2
    assert f"total parameters: {total}" in text


def test_module_summary_without_input_spec_lists_params_only():
    text = _mlp().summary()
    assert "Linear" in text and "-" in text
    assert "total parameters" in text


def test_registry_summary_cli(capsys):
    from bigdl_tpu.models import cli

    cli.main(["summary", "--model", "lenet", "-b", "4"])
    out = capsys.readouterr().out
    assert "SpatialConvolution" in out
    assert "total parameters: 22,278" in out


# -- CLI surfaces ------------------------------------------------------------
def test_telemetry_attribute_cli_model_json(capsys):
    from bigdl_tpu.telemetry.__main__ import main

    rc = main(["attribute", "--model", "lenet", "-b", "4", "--json"])
    assert rc == 0
    doc = json.loads(capsys.readouterr().out)
    assert doc["model"] == "lenet"
    paths = [r["path"] for r in doc["rows"]]
    assert "1" in paths and "8" in paths
    assert abs(doc["total_flops"] - doc["cost_flops"]) \
        / doc["cost_flops"] < 0.10


def test_telemetry_attribute_cli_from_run_log(tmp_path, capsys):
    log = tmp_path / "run.jsonl"
    set_config(BigDLConfig(telemetry_attribution=True))
    with telemetry.run(str(log)):
        step = TrainStep(_mlp(), nn.ClassNLLCriterion(),
                         optim.SGD(learning_rate=0.1))
        step.run(jnp.ones((4, 6)), jnp.zeros((4,), jnp.int32),
                 jax.random.key(0))
    from bigdl_tpu.telemetry.__main__ import main

    rc = main(["attribute", str(log)])
    assert rc == 0
    out = capsys.readouterr().out
    assert "per-module cost attribution" in out and "Linear" in out
    # summary report shows the top-modules section for the same log
    rc = main([str(log)])
    assert rc == 0
    assert "per-module cost" in capsys.readouterr().out


def test_telemetry_attribute_cli_log_without_event(tmp_path, capsys):
    log = tmp_path / "run.jsonl"
    with telemetry.run(str(log)):
        telemetry.instant("epoch", epoch=1)
    from bigdl_tpu.telemetry.__main__ import main

    assert main(["attribute", str(log)]) == 2


def test_models_cli_attribute_forward(capsys):
    from bigdl_tpu.models import cli

    cli.main(["attribute", "--model", "lenet", "-b", "4", "--forward",
              "--json"])
    doc = json.loads(capsys.readouterr().out)
    assert doc["program"] == "forward"
    rows = {r["path"]: r for r in doc["rows"]}
    assert rows["1"]["flops"] > 0
    assert rows["1"]["flops_bwd"] == 0  # forward-only program
