"""Pipeline parallelism (``parallel/pipeline.py``) and expert-parallel
MoE (``nn/layers/moe.py``) on the virtual 8-device CPU mesh: pipelined /
expert-sharded execution must be numerically equivalent to the plain
sequential computation, including gradients."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import bigdl_tpu.nn as nn
from bigdl_tpu.parallel.mesh import make_mesh
from bigdl_tpu.parallel.pipeline import make_pipeline_fn


def _block(params, h):
    w, b = params
    return jnp.tanh(h @ w + b)


def _stacked_blocks(s, d, seed=0):
    rng = np.random.RandomState(seed)
    w = jnp.asarray(rng.randn(s, d, d).astype(np.float32) * 0.4)
    b = jnp.asarray(rng.randn(s, d).astype(np.float32) * 0.1)
    return (w, b)


def _sequential_ref(stacked, x):
    w, b = stacked

    def body(h, wb):
        return _block(wb, h), None

    h, _ = jax.lax.scan(body, x, (w, b))
    return h


@pytest.mark.parametrize("n_micro", [4, 8])
def test_pipeline_matches_sequential(n_micro):
    s, d, batch = 4, 6, 16
    mesh = make_mesh((s,), ("pipe",), devices=jax.devices()[:s])
    stacked = _stacked_blocks(s, d)
    x = jnp.asarray(np.random.RandomState(1).randn(batch, d)
                    .astype(np.float32))
    fn = make_pipeline_fn(_block, mesh, n_micro)
    got = fn(stacked, x)
    want = _sequential_ref(stacked, x)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-5, atol=1e-6)


def test_pipeline_gradients_match_sequential():
    """jax.grad through the ppermute schedule IS pipelined backprop; it
    must agree with plain backprop."""
    s, d, batch, n_micro = 4, 5, 8, 4
    mesh = make_mesh((s,), ("pipe",), devices=jax.devices()[:s])
    stacked = _stacked_blocks(s, d, seed=2)
    x = jnp.asarray(np.random.RandomState(3).randn(batch, d)
                    .astype(np.float32))
    fn = make_pipeline_fn(_block, mesh, n_micro)

    g_pipe = jax.grad(lambda p: jnp.sum(fn(p, x) ** 2))(stacked)
    g_ref = jax.grad(lambda p: jnp.sum(_sequential_ref(p, x) ** 2))(stacked)
    for a, b in zip(g_pipe, g_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-5)


def test_pipeline_under_jit_with_data_axis():
    """pipe composes with a data axis: jit the pipelined fn over a
    (data=2, pipe=4) mesh."""
    s, d, batch = 4, 4, 8
    mesh = make_mesh((2, s), ("data", "pipe"))
    stacked = _stacked_blocks(s, d, seed=4)
    x = jnp.asarray(np.random.RandomState(5).randn(batch, d)
                    .astype(np.float32))
    fn = jax.jit(make_pipeline_fn(_block, mesh, 4))
    np.testing.assert_allclose(np.asarray(fn(stacked, x)),
                               np.asarray(_sequential_ref(stacked, x)),
                               rtol=1e-5, atol=1e-6)


# ----------------------------- MoE ----------------------------------------

def _moe_reference(m, x):
    """Direct per-token computation honoring the router's dispatch/combine
    (including capacity drops)."""
    dispatch, combine = m._route(x)
    t, e, c = dispatch.shape
    y = np.zeros((t, m.d_model), np.float32)
    w1, b1 = np.asarray(m.experts_w1), np.asarray(m.experts_b1)
    w2, b2 = np.asarray(m.experts_w2), np.asarray(m.experts_b2)
    xd = np.asarray(x, np.float32)
    disp = np.asarray(dispatch)
    comb = np.asarray(combine)
    for ti in range(t):
        for ei in range(e):
            for ci in range(c):
                if disp[ti, ei, ci] > 0:
                    h = np.maximum(xd[ti] @ w1[ei] + b1[ei], 0.0)
                    y[ti] += comb[ti, ei, ci] * (h @ w2[ei] + b2[ei])
    return y


def test_moe_matches_per_token_reference():
    m = nn.MixtureOfExperts(8, 16, 4, top_k=2, capacity_factor=1.0)
    x = jnp.asarray(np.random.RandomState(6).randn(20, 8)
                    .astype(np.float32))
    got = np.asarray(m.forward(x))
    want = _moe_reference(m, x)
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)


def test_moe_capacity_drops_tokens():
    """With capacity_factor tiny, overflow tokens get zero output."""
    m = nn.MixtureOfExperts(4, 8, 2, top_k=1, capacity_factor=0.25)
    x = jnp.asarray(np.random.RandomState(7).randn(16, 4)
                    .astype(np.float32))
    dispatch, _ = m._route(x)
    routed = float(jnp.sum(dispatch))
    assert routed <= 2 * m.capacity(16)  # at most E * C slots filled
    assert routed < 16  # some tokens actually dropped


def test_moe_trains_expert_sharded():
    """MoE trains under the TrainStep with experts sharded over the
    'expert' mesh axis (all-to-all layout), and matches the same training
    run on a single device."""
    import bigdl_tpu.optim as optim
    from bigdl_tpu.nn.layers.moe import expert_sharding_rules
    from bigdl_tpu.parallel.train_step import TrainStep
    from bigdl_tpu.utils.rng import RNG

    def build():
        RNG.set_seed(11)
        return nn.Sequential(
            nn.Linear(6, 8), nn.MixtureOfExperts(8, 16, 4, top_k=2),
            nn.Linear(8, 3), nn.LogSoftMax())

    rng = np.random.RandomState(8)
    x = jnp.asarray(rng.randn(32, 6).astype(np.float32))
    y = jnp.asarray(rng.randint(0, 3, 32))

    mesh = make_mesh((2, 4), ("data", "expert"))
    step = TrainStep(build(), nn.ClassNLLCriterion(),
                     optim.SGD(learning_rate=0.2), mesh=mesh,
                     extra_sharding_rules=expert_sharding_rules())
    ref = TrainStep(build(), nn.ClassNLLCriterion(),
                    optim.SGD(learning_rate=0.2))
    for i in range(4):
        l_sharded = float(step.run(x, y, jax.random.key(i)))
        l_ref = float(ref.run(x, y, jax.random.key(i)))
    assert l_sharded == pytest.approx(l_ref, rel=1e-4)
    # expert stacks actually sharded over the expert axis
    w1 = step.params["1.experts_w1"]
    assert "expert" in str(w1.sharding.spec)
