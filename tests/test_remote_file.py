"""Remote-path branch of ``utils/file.py`` (``utils/File.scala:25`` HDFS/S3
equivalent) exercised against a fake in-memory fsspec, so the ``gs://``
code path is covered in the zero-egress test environment."""

import io
import sys
import types

import pytest

from bigdl_tpu.utils import file as file_mod


class _FakeOpenFile:
    """Mirrors fsspec.core.OpenFile: ``fsspec.open(path, mode).open()``."""

    def __init__(self, store, path, mode):
        self.store, self.path, self.mode = store, path, mode

    def open(self):
        if "r" in self.mode:
            if self.path not in self.store:
                raise FileNotFoundError(self.path)
            return io.BytesIO(self.store[self.path])
        store, path = self.store, self.path
        buf = io.BytesIO()
        orig_close = buf.close

        def close():
            store[path] = buf.getvalue()
            orig_close()

        buf.close = close
        return buf


@pytest.fixture
def fake_fsspec(monkeypatch):
    store = {}
    mod = types.ModuleType("fsspec")
    mod.open = lambda path, mode: _FakeOpenFile(store, path, mode)
    monkeypatch.setitem(sys.modules, "fsspec", mod)
    return store


def test_remote_round_trip(fake_fsspec):
    file_mod.save(b"\x00payload\xff", "gs://bucket/dir/model.btpu",
                  overwrite=True)
    assert fake_fsspec["gs://bucket/dir/model.btpu"] == b"\x00payload\xff"
    assert file_mod.load("gs://bucket/dir/model.btpu") == b"\x00payload\xff"


def test_remote_missing_file_raises(fake_fsspec):
    with pytest.raises(FileNotFoundError):
        file_mod.load("gs://bucket/absent")


def test_remote_without_fsspec_is_a_clear_error(monkeypatch):
    monkeypatch.setitem(sys.modules, "fsspec", None)
    with pytest.raises(RuntimeError, match="fsspec"):
        file_mod.load("gs://bucket/x")


def test_remote_save_type_check(fake_fsspec):
    with pytest.raises(TypeError):
        file_mod.save({"not": "bytes"}, "gs://bucket/x", overwrite=True)


# -- integration tier (round 4): the whole checkpoint/resume cycle over a
# -- remote scheme, the in-process analogue of integration/HdfsSpec.scala:46

@pytest.fixture
def memfs():
    """Real fsspec MemoryFileSystem, wiped per test."""
    fsspec = pytest.importorskip("fsspec")
    fs = fsspec.filesystem("memory")
    yield fs
    try:
        fs.rm("/", recursive=True)
    except Exception:
        pass


def test_checkpoint_resume_over_remote_scheme(memfs):
    """Train with a memory:// checkpoint dir, then resume a second run
    from the remote checkpoint — the reference trains against HDFS paths
    the same way (integration/HdfsSpec.scala:46; File.scala:67-171)."""
    import numpy as np

    import bigdl_tpu.nn as nn
    import bigdl_tpu.optim as optim
    from bigdl_tpu.dataset.sample import Sample
    from bigdl_tpu.nn.module import state_dict
    from bigdl_tpu.utils.rng import RNG
    from bigdl_tpu.utils.serializer import load_module, load_optim_method

    rng = np.random.default_rng(0)
    x = rng.normal(size=(64, 4)).astype(np.float32)
    y = (x[:, 0] > 0).astype(np.int64)
    samples = [Sample(x[i], np.int64(y[i])) for i in range(64)]

    RNG.set_seed(31)
    m = nn.Sequential(nn.Linear(4, 8), nn.Tanh(), nn.Linear(8, 2),
                      nn.LogSoftMax())
    ckpt = "memory://bigdl_ckpt/run1"
    o = optim.LocalOptimizer(m, samples, nn.ClassNLLCriterion(),
                             batch_size=16,
                             end_trigger=optim.Trigger.max_iteration(4))
    o.set_optim_method(optim.Adam(learning_rate=0.01))
    o.set_checkpoint(ckpt, optim.Trigger.several_iteration(2))
    o.overwrite_checkpoint()
    o.optimize()

    mfile = optim.Optimizer.get_latest_file(ckpt, "model")
    ofile = optim.Optimizer.get_latest_file(ckpt, "optimMethod")
    assert mfile == "memory://bigdl_ckpt/run1/model.4", mfile
    m2 = load_module(mfile)
    om2 = load_optim_method(ofile)
    p1, p2 = state_dict(m), state_dict(m2)
    for k in p1:
        np.testing.assert_allclose(np.asarray(p1[k]), np.asarray(p2[k]),
                                   rtol=1e-6)
    # resume continues the iteration count from the remote state
    o2 = optim.LocalOptimizer(m2, samples, nn.ClassNLLCriterion(),
                              batch_size=16,
                              end_trigger=optim.Trigger.max_iteration(6))
    o2.set_optim_method(om2)
    o2.set_state(om2.state["driver_state"])
    o2.optimize()
    assert o2.state["neval"] == 6


def test_retry_restores_from_remote_checkpoint(memfs):
    """An injected mid-training failure recovers from the memory://
    checkpoint through the retry loop (failure path + remote IO
    composed)."""
    import numpy as np

    import bigdl_tpu.nn as nn
    import bigdl_tpu.optim as optim
    from bigdl_tpu.dataset.sample import Sample
    from bigdl_tpu.utils.rng import RNG
    from tests.test_training_loop import ExceptionLayer

    rng = np.random.default_rng(1)
    x = rng.normal(size=(32, 4)).astype(np.float32)
    y = (x[:, 0] > 0).astype(np.int64)
    samples = [Sample(x[i], np.int64(y[i])) for i in range(32)]

    RNG.set_seed(33)
    ExceptionLayer.count = 0
    model = nn.Sequential(nn.Linear(4, 8), ExceptionLayer(fail_at=6),
                          nn.Tanh(), nn.Linear(8, 2), nn.LogSoftMax())
    o = optim.LocalOptimizer(model, samples, nn.ClassNLLCriterion(),
                             batch_size=16,
                             end_trigger=optim.Trigger.max_iteration(8))
    o.set_optim_method(optim.SGD(learning_rate=0.1))
    o.set_checkpoint("memory://bigdl_ckpt/retry",
                     optim.Trigger.several_iteration(2))
    o.overwrite_checkpoint()
    o.optimize()
    assert o.state["neval"] >= 8  # completed despite the injected failure
    assert memfs.exists("/bigdl_ckpt/retry")
