"""Remote-path branch of ``utils/file.py`` (``utils/File.scala:25`` HDFS/S3
equivalent) exercised against a fake in-memory fsspec, so the ``gs://``
code path is covered in the zero-egress test environment."""

import io
import sys
import types

import pytest

from bigdl_tpu.utils import file as file_mod


class _FakeOpenFile:
    """Mirrors fsspec.core.OpenFile: ``fsspec.open(path, mode).open()``."""

    def __init__(self, store, path, mode):
        self.store, self.path, self.mode = store, path, mode

    def open(self):
        if "r" in self.mode:
            if self.path not in self.store:
                raise FileNotFoundError(self.path)
            return io.BytesIO(self.store[self.path])
        store, path = self.store, self.path
        buf = io.BytesIO()
        orig_close = buf.close

        def close():
            store[path] = buf.getvalue()
            orig_close()

        buf.close = close
        return buf


@pytest.fixture
def fake_fsspec(monkeypatch):
    store = {}
    mod = types.ModuleType("fsspec")
    mod.open = lambda path, mode: _FakeOpenFile(store, path, mode)
    monkeypatch.setitem(sys.modules, "fsspec", mod)
    return store


def test_remote_round_trip(fake_fsspec):
    file_mod.save(b"\x00payload\xff", "gs://bucket/dir/model.btpu",
                  overwrite=True)
    assert fake_fsspec["gs://bucket/dir/model.btpu"] == b"\x00payload\xff"
    assert file_mod.load("gs://bucket/dir/model.btpu") == b"\x00payload\xff"


def test_remote_missing_file_raises(fake_fsspec):
    with pytest.raises(FileNotFoundError):
        file_mod.load("gs://bucket/absent")


def test_remote_without_fsspec_is_a_clear_error(monkeypatch):
    monkeypatch.setitem(sys.modules, "fsspec", None)
    with pytest.raises(RuntimeError, match="fsspec"):
        file_mod.load("gs://bucket/x")


def test_remote_save_type_check(fake_fsspec):
    with pytest.raises(TypeError):
        file_mod.save({"not": "bytes"}, "gs://bucket/x", overwrite=True)
