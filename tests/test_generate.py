"""The LLM decode subsystem (bigdl_tpu/serving/generate/,
docs/serving.md "Autoregressive generation"): cache-length buckets and
the stacked KV store, the q_len=1 attention routing rule, the
cache-correctness contract (KV-cached greedy decode == full-context
forward argmax, token for token), sampled-decode determinism keyed on
(seed, request), warm-executable + live-cache survival across a
same-shape weight rollout, and the live streamed-HTTP e2e with the
retrace detector armed and a graceful drain."""

import json
import os
import re
import signal
import subprocess
import sys
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from bigdl_tpu.serving.batcher import QueueFullError
from bigdl_tpu.serving.generate.kv_cache import (StackedKVCache,
                                                 cache_buckets)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

VOCAB = 50


def _model(seed=7):
    from bigdl_tpu.models.transformer import build_transformer_lm
    from bigdl_tpu.utils.rng import RNG

    RNG.set_seed(seed)
    return build_transformer_lm(vocab_size=VOCAB, num_layers=2,
                                embed_dim=32, num_heads=2, max_len=64,
                                scan=False).evaluate()


def _executor(model):
    from bigdl_tpu.serving.buckets import BucketPolicy
    from bigdl_tpu.serving.generate.decode import GenerateExecutor

    pol = BucketPolicy(max_batch=2, batch_buckets=[1, 2],
                       seq_buckets=[16])
    ex = GenerateExecutor(model, policy=pol, decode_buckets=[1, 2],
                          cache_buckets=[32])
    ex.warmup((16,), np.int32)
    return ex


@pytest.fixture(scope="module")
def gen_executor():
    model = _model()
    return model, _executor(model)


def _full_forward_greedy(model, prompt, n):
    """Reference: re-run the FULL context each step, argmax the last
    position — the numerics the KV cache must reproduce."""
    seq = list(np.asarray(prompt).reshape(-1))
    out_tokens = []
    for _ in range(n):
        out = np.asarray(model.forward(np.asarray([seq], np.int32)))
        tok = int(np.argmax(out[0, len(seq) - 1]))
        out_tokens.append(tok)
        seq.append(tok)
    return out_tokens


# -- cache buckets + stacked store -------------------------------------------
def test_cache_buckets_closed_doubling_set():
    assert cache_buckets(256, smallest=32) == (32, 64, 128, 256)
    assert cache_buckets(96, smallest=32) == (32, 64, 96)
    assert cache_buckets(16, smallest=64) == (16,)
    with pytest.raises(ValueError):
        cache_buckets(0)


def test_stacked_kv_cache_stack_pad_and_row_reuse():
    import jax.numpy as jnp

    # two layers, [B=2, H=1, C=4, D=2] source
    src = [(jnp.arange(16, dtype=jnp.float32).reshape(2, 1, 4, 2),
            jnp.arange(16, 32, dtype=jnp.float32).reshape(2, 1, 4, 2))
           for _ in range(2)]
    stack = StackedKVCache.stack([(src, 0, 3), (src, 1, 2)],
                                 bucket=8, batch=2)
    assert stack.lengths == [3, 2] and stack.bucket == 8
    k0 = np.asarray(stack.layers[0][0])
    assert k0.shape == (2, 1, 8, 2)
    np.testing.assert_array_equal(k0[0, :, :4], np.asarray(src[0][0][0]))
    assert k0[:, :, 4:].sum() == 0  # padded cells
    assert 0.0 < stack.occupancy() < 1.0
    # dropping row 0 and re-stacking reuses row 1's cells verbatim
    survivors = stack.row_sources([1])
    small = StackedKVCache.stack(survivors, bucket=8, batch=1)
    assert small.lengths == [2]
    np.testing.assert_array_equal(np.asarray(small.layers[0][0])[0],
                                  k0[1])
    with pytest.raises(ValueError):
        StackedKVCache.stack(survivors, bucket=8, batch=0)


# -- the routing table (satellite: q_len=1 never routes to flash) ------------
def test_attention_routing_table_decode_row(monkeypatch):
    from bigdl_tpu.ops.attention import select_attention_backend

    monkeypatch.delenv("BIGDL_KERNELS", raising=False)
    monkeypatch.delenv("BIGDL_FLASH_MIN_SEQ", raising=False)
    on_tpu = False
    try:
        from bigdl_tpu.ops.attention import is_tpu_device

        on_tpu = is_tpu_device()
    except Exception:  # noqa: BLE001 - no backend at all
        pass
    # (sq, sk, masked, env) -> expected backend; None = either reason
    rows = [
        # decode: q_len=1 NEVER flash, regardless of kv length or mode
        (1, 8192, False, None, "dense"),
        (1, 128, False, None, "dense"),
        (1, 8192, False, "pallas", "dense"),
        (1, 8192, True, None, "dense"),
        # the kill switch still forces dense everywhere
        (4096, 4096, False, "xla", "dense"),
        # dense masks always route dense
        (4096, 4096, True, None, "dense"),
        # forced pallas with a real q extent routes flash
        (512, 512, False, "pallas", "flash"),
        # auto off-TPU is dense; on TPU long seqs go flash
        (4096, 4096, False, None, "flash" if on_tpu else "dense"),
        (64, 64, False, None, "dense"),
    ]
    for sq, sk, masked, env, want in rows:
        if env is None:
            monkeypatch.delenv("BIGDL_KERNELS", raising=False)
        else:
            monkeypatch.setenv("BIGDL_KERNELS", env)
        got, reason = select_attention_backend(sq, sk, masked)
        assert got == want, (sq, sk, masked, env, got, reason)
    # the decode row carries its own reason so dispatch attribution
    # can see the choice was deliberate
    monkeypatch.delenv("BIGDL_KERNELS", raising=False)
    assert select_attention_backend(1, 4096)[1] == "decode:q_len=1"


# -- sampling ----------------------------------------------------------------
def test_sample_token_greedy_and_seeded_topk():
    from bigdl_tpu.serving.generate.batcher import sample_token

    logits = np.log(np.asarray([0.1, 0.6, 0.2, 0.1]))
    assert sample_token(logits, temperature=0.0) == 1
    with pytest.raises(ValueError):
        # a negative top_k would silently sample near the FULL vocab
        # (np.partition from the wrong end) — rejected instead
        sample_token(logits, 0.7, -3,
                     np.random.Generator(np.random.Philox(5)))
    r1 = np.random.Generator(np.random.Philox(5))
    r2 = np.random.Generator(np.random.Philox(5))
    seq1 = [sample_token(logits, 0.7, 2, r1) for _ in range(20)]
    seq2 = [sample_token(logits, 0.7, 2, r2) for _ in range(20)]
    assert seq1 == seq2          # same seed -> same stream
    assert set(seq1) <= {1, 2}   # top_k=2 keeps only the two best
    with pytest.raises(ValueError):
        sample_token(logits, temperature=0.5)  # sampled needs an rng


# -- the cache-correctness contract ------------------------------------------
def test_greedy_decode_matches_full_forward_argmax(gen_executor):
    model, ex = gen_executor
    rng = np.random.default_rng(0)
    prompt = rng.integers(1, VOCAB, size=(1, 6)).astype(np.int32)
    logits, caches = ex.prefill(prompt, [6])
    toks = [int(np.argmax(logits[0]))]
    stack = StackedKVCache.stack([(caches, 0, 6)], 32, 1)
    for _ in range(7):
        lg = ex.decode(stack, [toks[-1]])
        stack.lengths[0] += 1
        toks.append(int(np.argmax(lg[0])))
    assert toks == _full_forward_greedy(model, prompt, 8)


def test_batched_decode_rows_are_independent(gen_executor):
    """Two sequences decoding TOGETHER produce exactly what each
    produces alone — the per-row length mask isolates cache rows."""
    model, ex = gen_executor
    rng = np.random.default_rng(3)
    p1 = rng.integers(1, VOCAB, 4).astype(np.int32)
    p2 = rng.integers(1, VOCAB, 9).astype(np.int32)
    tokens = np.zeros((2, 9), np.int32)
    tokens[0, :4], tokens[1, :] = p1, p2
    logits, caches = ex.prefill(tokens, [4, 9])
    toks = [[int(np.argmax(logits[0]))], [int(np.argmax(logits[1]))]]
    stack = StackedKVCache.stack([(caches, 0, 4), (caches, 1, 9)], 32, 2)
    for _ in range(5):
        lg = ex.decode(stack, [toks[0][-1], toks[1][-1]])
        for r in range(2):
            stack.lengths[r] += 1
            toks[r].append(int(np.argmax(lg[r])))
    assert toks[0] == _full_forward_greedy(model, p1, 6)
    assert toks[1] == _full_forward_greedy(model, p2, 6)


# -- the generation batcher ---------------------------------------------------
def test_generation_batcher_greedy_and_slot_reuse(gen_executor):
    from bigdl_tpu.serving.generate.batcher import GenerationBatcher

    model, ex = gen_executor
    warm = ex.compile_count
    gb = GenerationBatcher(ex, max_wait_ms=1.0)
    try:
        rng = np.random.default_rng(1)
        prompts = [rng.integers(1, VOCAB, n).astype(np.int32)
                   for n in (3, 7, 5, 11)]  # > max_active: slots reuse
        reqs = [gb.submit(p, max_new_tokens=4) for p in prompts]
        for r in reqs:
            assert r.wait(60.0) and r.error is None
        for p, r in zip(prompts, reqs):
            assert r.tokens == _full_forward_greedy(model, p, 4)
            assert r.finish_reason == "length"
            assert r.ttft_ms() > 0
        assert ex.compile_count == warm  # zero steady-state compiles
        st = gb.stats()
        assert st["completed"] == 4 and st["gen_tokens"] == 16
        assert st["ttft_p50_ms"] > 0 and st["active_seqs"] == 0
    finally:
        gb.stop(drain=False)


def test_burst_larger_than_prefill_bucket_admits_over_rounds():
    """More waiting prompts than ``policy.max_batch`` while decode
    slots are free: admission is capped per round at the prefill
    batch-bucket ceiling, so the burst admits over successive rounds
    instead of handing ``BucketPolicy.pad`` an oversized prefill (which
    failed every newcomer in the burst)."""
    from bigdl_tpu.serving.buckets import BucketPolicy
    from bigdl_tpu.serving.generate.batcher import GenerationBatcher
    from bigdl_tpu.serving.generate.decode import GenerateExecutor

    model = _model()
    pol = BucketPolicy(max_batch=2, batch_buckets=[1, 2],
                       seq_buckets=[16])
    ex = GenerateExecutor(model, policy=pol, decode_buckets=[1, 2, 4],
                          cache_buckets=[32])
    ex.warmup((16,), np.int32)
    assert ex.max_active > pol.max_batch  # the seeded mismatch
    gb = GenerationBatcher(ex, max_wait_ms=1.0)
    try:
        rng = np.random.default_rng(8)
        prompts = [rng.integers(1, VOCAB, n).astype(np.int32)
                   for n in (3, 6, 4, 7)]
        reqs = [gb.submit(p, max_new_tokens=3) for p in prompts]
        for p, r in zip(prompts, reqs):
            assert r.wait(60.0) and r.error is None
            assert r.tokens == _full_forward_greedy(model, p, 3)
    finally:
        gb.stop(drain=False)


def test_submit_rejects_negative_top_k_and_bad_temperature(gen_executor):
    from bigdl_tpu.serving.generate.batcher import GenerationBatcher

    _, ex = gen_executor
    gb = GenerationBatcher(ex, max_wait_ms=1.0)
    try:
        with pytest.raises(ValueError, match="top_k"):
            gb.submit(np.asarray([1, 2], np.int32), top_k=-3)
        for t in (float("nan"), float("inf"), -0.5):
            with pytest.raises(ValueError, match="temperature"):
                gb.submit(np.asarray([1, 2], np.int32), temperature=t)
    finally:
        gb.stop(drain=False)


def test_tiny_temperature_degrades_to_greedy_not_nan():
    """A subnormal temperature overflows ``logits / t`` to inf; the
    shift-before-scale ordering keeps the distribution valid (it
    collapses onto the argmax) instead of raising on NaN probs."""
    from bigdl_tpu.serving.generate.batcher import sample_token

    logits = np.log(np.asarray([0.1, 0.6, 0.2, 0.1]))
    rng = np.random.Generator(np.random.Philox(0))
    assert sample_token(logits, 1e-300, 0, rng) == 1


def test_one_bad_sampler_does_not_kill_the_batch(gen_executor,
                                                 monkeypatch):
    """A host-side sampling failure on ONE request fails that request
    alone — its co-admitted and co-decoding neighbours keep streaming
    (and nobody is left in neither queue nor active to hang)."""
    import bigdl_tpu.serving.generate.batcher as gbm

    model, ex = gen_executor
    orig = gbm.sample_token
    calls = {"n": 0}

    def boom(logits, temperature=0.0, top_k=0, rng=None):
        if temperature == 0.123:       # fails at the TTFT draw (_admit)
            raise RuntimeError("poisoned at admit")
        if temperature == 0.456:       # fails on a decode draw (_step)
            calls["n"] += 1
            if calls["n"] > 1:
                raise RuntimeError("poisoned at step")
        return orig(logits, 0.0, 0, None)  # greedy underneath

    monkeypatch.setattr(gbm, "sample_token", boom)
    gb = gbm.GenerationBatcher(ex, max_wait_ms=1.0)
    try:
        good = gb.submit(np.asarray([1, 2, 3], np.int32),
                         max_new_tokens=6)
        bad_admit = gb.submit(np.asarray([4, 5], np.int32),
                              max_new_tokens=6, temperature=0.123,
                              seed=1)
        bad_step = gb.submit(np.asarray([6, 7], np.int32),
                             max_new_tokens=6, temperature=0.456,
                             seed=1)
        assert good.wait(60.0) and good.error is None
        assert good.tokens == _full_forward_greedy(model, [1, 2, 3], 6)
        assert bad_admit.wait(60.0) and "poisoned" in bad_admit.error
        assert bad_step.wait(60.0) and "poisoned" in bad_step.error
        assert bad_step.tokens  # it DID stream before the failure
        # the batcher survives: a fresh request still completes
        again = gb.submit(np.asarray([8, 9], np.int32),
                          max_new_tokens=2)
        assert again.wait(60.0) and again.error is None
        st = gb.stats()
        assert st["errors"] == 2 and st["completed"] == 2
    finally:
        gb.stop(drain=False)


def test_cache_full_uses_the_last_cache_cell(gen_executor):
    """A cache bucket of C buys exactly C positions of context: a
    16-token prompt against cache_buckets=[32] yields the TTFT token
    plus 16 decode tokens (the last k/v written at index 31) before
    finishing cache_full — not one fewer."""
    from bigdl_tpu.serving.generate.batcher import GenerationBatcher

    _, ex = gen_executor
    gb = GenerationBatcher(ex, max_wait_ms=1.0)
    try:
        r = gb.submit(np.arange(1, 17, dtype=np.int32),
                      max_new_tokens=40)
        assert r.wait(120.0) and r.error is None
        assert r.finish_reason == "cache_full"
        assert len(r.tokens) == 17  # 1 TTFT + (32 - 16) decode steps
    finally:
        gb.stop(drain=False)


def test_idle_batcher_gauges_read_zero(gen_executor):
    """Normal completion of the last active row must reset the
    serve/active_seqs and serve/cache_occupancy gauges — a consumer of
    the gauge stream would otherwise see a permanently busy replica."""
    from bigdl_tpu import telemetry
    from bigdl_tpu.serving.generate.batcher import GenerationBatcher

    _, ex = gen_executor
    sink = telemetry.MemorySink()
    with telemetry.run(sinks=[sink]):
        gb = GenerationBatcher(ex, max_wait_ms=1.0)
        try:
            r = gb.submit(np.asarray([1, 2, 3], np.int32),
                          max_new_tokens=3)
            assert r.wait(60.0) and r.error is None
        finally:
            gb.stop(drain=True)
    for name in ("serve/active_seqs", "serve/cache_occupancy"):
        vals = [e for e in sink.events if e.get("name") == name]
        assert vals and vals[-1]["value"] == 0, name


def test_decode_donates_cache_operands(gen_executor):
    """The decode executable updates the KV stack in place (donated
    operands) instead of copying every layer's [B,H,C,D] per token —
    the pre-call buffers must be deleted after the step."""
    _, ex = gen_executor
    logits, caches = ex.prefill(np.asarray([[1, 2, 3]], np.int32), [3])
    stack = StackedKVCache.stack([(caches, 0, 3)], 32, 1)
    old_k = stack.layers[0][0]
    ex.decode(stack, [int(np.argmax(logits[0]))])
    assert old_k.is_deleted()
    assert stack.layers[0][0] is not old_k


def test_generation_model_and_default_seq_buckets():
    """The front-end special case (unrolled transformer build + the
    halving seq-bucket default) lives ONCE in serving.generate."""
    import jax

    from bigdl_tpu.nn.layers.scan import ScanLayers
    from bigdl_tpu.serving.generate import (default_seq_buckets,
                                            generation_model)

    m = generation_model("transformer", 50)
    assert not any(isinstance(x, ScanLayers) for x in m.modules())
    with pytest.raises(ValueError, match="unknown model"):
        generation_model("no_such_model")
    spec = jax.ShapeDtypeStruct((1, 128), np.int32)
    assert default_seq_buckets(spec) == [32, 64, 128]
    spec = jax.ShapeDtypeStruct((1, 16), np.int32)
    assert default_seq_buckets(spec) == [16]


def test_sampled_decode_deterministic_on_seed(gen_executor):
    from bigdl_tpu.serving.generate.batcher import GenerationBatcher

    _, ex = gen_executor
    gb = GenerationBatcher(ex, max_wait_ms=1.0)
    try:
        prompt = np.asarray([5, 9, 2], np.int32)
        runs = []
        for _ in range(2):  # same (seed, request) twice -> identical
            r = gb.submit(prompt, max_new_tokens=6, temperature=0.9,
                          top_k=10, seed=1234)
            assert r.wait(60.0) and r.error is None
            runs.append(r.tokens)
        assert runs[0] == runs[1]
        other = gb.submit(prompt, max_new_tokens=6, temperature=0.9,
                          top_k=10, seed=99)
        assert other.wait(60.0)
        # a different seed is allowed to (and here does) diverge
        assert other.tokens != runs[0]
    finally:
        gb.stop(drain=False)


def test_generation_batcher_rejects_oversize_and_draining(gen_executor):
    from bigdl_tpu.serving.generate.batcher import GenerationBatcher

    _, ex = gen_executor
    gb = GenerationBatcher(ex, max_wait_ms=1.0)
    try:
        with pytest.raises(ValueError):
            gb.submit(np.ones(32, np.int32))  # no room in largest bucket
        r = gb.submit(np.asarray([1, 2], np.int32), max_new_tokens=2)
        assert gb.stop(drain=True)
        assert r.done.is_set() and r.error is None  # drained, answered
        with pytest.raises(QueueFullError):
            gb.submit(np.asarray([1], np.int32))
    finally:
        gb.stop(drain=False)


def test_refresh_state_keeps_decode_executables_and_live_caches():
    """The rollout contract: a same-shape weight swap mid-generation
    keeps every warm prefill/decode executable AND the in-flight KV
    caches — the generation completes with zero new compiles."""
    import jax.numpy as jnp

    from bigdl_tpu.nn.module import load_state_dict, state_dict
    from bigdl_tpu.serving.generate.batcher import GenerationBatcher

    model = _model()
    ex = _executor(model)
    warm = ex.compile_count
    gb = GenerationBatcher(ex, max_wait_ms=1.0)
    try:
        prompt = np.asarray([4, 8, 15, 16], np.int32)
        want = _full_forward_greedy(model, prompt, 20)
        r = gb.submit(prompt, max_new_tokens=20)
        # same VALUES, fresh arrays: identity check misses, the sig
        # check hits — executables survive, outputs stay comparable
        sd = state_dict(model)
        load_state_dict(model, {k: jnp.asarray(np.array(v))
                                for k, v in sd.items()})
        ex.refresh_state()
        assert r.wait(120.0) and r.error is None
        assert r.tokens == want
        assert ex.compile_count == warm
    finally:
        gb.stop(drain=False)


def test_refresh_state_shape_change_drops_executables():
    model = _model()
    ex = _executor(model)
    assert ex.warm_buckets() != []
    with ex._lock:
        ex._state_sig = dict(ex._state_sig,
                             **{next(iter(ex._state_sig)): ((9,), "?")})
        ex._place_state(dict(ex._state_src))
    # re-placing against a changed signature drops every executable
    # (prefill, decode, and plain predict alike) — the documented
    # full-redeploy path
    assert ex.warm_buckets() == []


# -- live HTTP e2e ------------------------------------------------------------
@pytest.fixture(scope="module")
def gen_server():
    import jax

    from bigdl_tpu.serving import serve_model

    model = _model()
    spec = jax.ShapeDtypeStruct((1, 16), np.int32)
    server = serve_model(model, spec, name="tlm", host="127.0.0.1",
                         port=0, max_batch=2, batch_buckets=[1, 2],
                         seq_buckets=[16], max_wait_ms=1.0,
                         generate=True, decode_buckets=[1, 2],
                         cache_buckets=[32])
    try:
        yield model, server
    finally:
        server.stop(drain=False)


def _generate(port, payload, timeout=60.0):
    """POST /v1/generate, collecting the streamed JSON lines."""
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/v1/generate",
        data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"}, method="POST")
    with urllib.request.urlopen(req, timeout=timeout) as r:
        return r.status, [json.loads(l) for l in r if l.strip()]


def test_http_streamed_generations_concurrent_mixed_prompts(gen_server):
    from bigdl_tpu.analysis.retrace import trace_retraces

    model, server = gen_server
    rng = np.random.default_rng(2)
    prompts = [rng.integers(1, VOCAB, n).tolist() for n in (3, 8, 13, 5)]
    warm = server.executor.compile_count
    results, errors = {}, []

    def client(i):
        try:
            code, lines = _generate(server.port,
                                    {"prompt": prompts[i],
                                     "max_new_tokens": 5})
            assert code == 200
            results[i] = lines
        except Exception as e:  # noqa: BLE001 - surfaced below
            errors.append(e)

    with trace_retraces() as mon:
        threads = [threading.Thread(target=client, args=(i,))
                   for i in range(len(prompts))]
        for t in threads:
            t.start()
        for t in threads:
            t.join(120.0)
    assert errors == []
    for i, lines in results.items():
        toks = [ev["token"] for ev in lines if "token" in ev]
        done = lines[-1]
        assert done["done"] is True and done["tokens"] == toks
        assert done["ttft_ms"] > 0 and done["n_tokens"] == 5
        # the acceptance contract: streamed greedy == full-forward
        # argmax per token, under concurrency and mixed prompt lengths
        assert toks == _full_forward_greedy(model, prompts[i], 5)
    # zero steady-state compiles with the retrace detector armed
    assert server.executor.compile_count == warm
    assert len(mon.report.diagnostics) == 0


def test_http_generate_nonstream_status_metrics_and_errors(gen_server):
    _, server = gen_server
    code, lines = _generate(server.port,
                            {"prompt": [1, 2, 3], "max_new_tokens": 3,
                             "stream": False})
    assert code == 200 and len(lines) == 1
    assert len(lines[0]["tokens"]) == 3
    st = json.load(urllib.request.urlopen(
        f"http://127.0.0.1:{server.port}/status", timeout=10))
    gen = st["serving"]["generate"]
    assert gen["completed"] >= 1 and gen["gen_tokens"] >= 3
    assert gen["decode_buckets"] == [1, 2]
    assert gen["cache_buckets"] == [32]
    assert "active_seqs" in gen and "cache_occupancy" in gen
    body = urllib.request.urlopen(
        f"http://127.0.0.1:{server.port}/metrics", timeout=10
    ).read().decode()
    assert "bigdl_gen_tokens_total" in body
    for bad in ({"prompt": []}, {"prompt": "text"}, {"wrong": 1},
                {"prompt": [1], "max_new_tokens": 0},
                {"prompt": [1, 2], "top_k": -3},  # rejected up front
                {"prompt": list(range(40))}):  # over the cache bucket
        with pytest.raises(urllib.error.HTTPError) as ei:
            _generate(server.port, bad)
        assert ei.value.code == 400, bad


def test_stream_is_http11_chunked(gen_server):
    """Chunked transfer encoding is undefined for HTTP/1.0 — the
    response must be HTTP/1.1 or strict clients/proxies deliver raw
    chunk framing to the user."""
    import http.client

    _, server = gen_server
    conn = http.client.HTTPConnection("127.0.0.1", server.port,
                                      timeout=60)
    try:
        conn.request("POST", "/v1/generate",
                     body=json.dumps({"prompt": [1, 2, 3],
                                      "max_new_tokens": 2}),
                     headers={"Content-Type": "application/json"})
        r = conn.getresponse()
        assert r.version == 11
        assert r.getheader("Transfer-Encoding") == "chunked"
        lines = [json.loads(l) for l in r.read().splitlines() if l]
        assert lines[-1].get("done") is True
    finally:
        conn.close()


def test_generate_events_are_schema_valid():
    from bigdl_tpu import telemetry
    from bigdl_tpu.serving.generate.batcher import GenerationBatcher
    from bigdl_tpu.telemetry import schema

    sink = telemetry.MemorySink()
    model = _model()
    with telemetry.run(sinks=[sink]):
        ex = _executor(model)
        gb = GenerationBatcher(ex, max_wait_ms=1.0)
        try:
            r = gb.submit(np.asarray([3, 1, 4], np.int32),
                          max_new_tokens=3)
            assert r.wait(60.0)
        finally:
            gb.stop(drain=True)
    kinds = {e.get("kind") for e in sink.events}
    assert "generate" in kinds and "compile" in kinds
    names = {e.get("name") for e in sink.events}
    assert {"serve/generate", "serve/active_seqs",
            "serve/cache_occupancy"} <= names
    assert schema.validate_events(sink.events) == []
    gen = [e for e in sink.events if e.get("kind") == "generate"]
    assert gen and gen[0]["tokens"] == 3 and gen[0]["ttft_ms"] > 0


def test_metrics_sink_and_fleet_fold_generation_events():
    from bigdl_tpu.telemetry.fleet import HostState
    from bigdl_tpu.telemetry.metrics_http import MetricsSink

    ev = {"v": 1, "ts": time.time(), "pid": 1, "tid": 1,
          "kind": "generate", "tokens": 12, "dur": 0.5,
          "ttft_ms": 41.0, "itl_p99_ms": 9.0, "finish": "length"}
    sink = MetricsSink()
    sink.emit(ev)
    st = sink.status()
    assert st["gen_tokens"] == 12 and st["gen_requests"] == 1
    assert st["last_gen"]["ttft_ms"] == 41.0
    om = sink.openmetrics()
    assert "bigdl_gen_tokens_total" in om
    assert "bigdl_gen_itl_p99_ms" in om
    host = HostState("run.jsonl")
    host.fold([ev])
    row = host.row()
    assert row["gen_tokens"] == 12 and row["gen_ttft_ms"] == 41.0
    assert row["gen_tokens_s"] > 0


@pytest.mark.deadline(240)
def test_cli_serve_generate_live_e2e_with_sigterm_drain():
    """The acceptance path: `cli serve --generate`, real streamed HTTP
    from another process with mixed prompt lengths, KV-cached greedy
    equal to the full-forward argmax, SIGTERM drain finishing the
    in-flight generation, exit 0."""
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.pop("BIGDL_SCAN_LAYERS", None)
    proc = subprocess.Popen(
        [sys.executable, "-m", "bigdl_tpu.models.cli", "serve",
         "--model", "transformer", "--generate", "--num-classes",
         str(VOCAB), "--port", "0", "-b", "2", "--buckets", "1,2",
         "--seq-buckets", "16", "--decode-buckets", "1,2",
         "--cache-buckets", "32", "--max-wait-ms", "1", "--seed", "7"],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        env=env, cwd=REPO)
    try:
        port = None
        deadline = time.time() + 180
        while time.time() < deadline:
            line = proc.stdout.readline()
            if not line:
                break
            m = re.search(r"serving transformer on port (\d+)", line)
            if m:
                assert "generate decode=[1, 2] cache=[32]" in line
                port = int(m.group(1))
                break
        assert port, "no ready line from cli serve --generate"
        # the CLI seeds RNG with --seed 7 then builds the registry-
        # default transformer (4 layers, 256 embed) unrolled — rebuild
        # the identical reference here
        from bigdl_tpu.models.transformer import build_transformer_lm
        from bigdl_tpu.utils.rng import RNG

        RNG.set_seed(7)
        model = build_transformer_lm(vocab_size=VOCAB,
                                     scan=False).evaluate()
        rng = np.random.default_rng(6)
        prompts = [rng.integers(1, VOCAB, n).tolist() for n in (4, 9)]
        results = {}

        def client(i):
            code, lines = _generate(port, {"prompt": prompts[i],
                                           "max_new_tokens": 4})
            results[i] = (code, lines)

        threads = [threading.Thread(target=client, args=(i,))
                   for i in range(2)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(60.0)
        for i, (code, lines) in results.items():
            assert code == 200
            toks = [ev["token"] for ev in lines if "token" in ev]
            assert toks == _full_forward_greedy(model, prompts[i], 4)
        # SIGTERM mid-generation: the in-flight stream finishes before
        # the process exits 0
        slow = [None]

        def long_client():
            slow[0] = _generate(port, {"prompt": prompts[0],
                                       "max_new_tokens": 12})

        t = threading.Thread(target=long_client)
        t.start()
        time.sleep(0.15)  # let the generation get in flight
        proc.send_signal(signal.SIGTERM)
        t.join(60.0)
        assert slow[0] is not None
        code, lines = slow[0]
        assert code == 200 and lines[-1].get("done") is True
        assert len([ev for ev in lines if "token" in ev]) == 12
        out, _ = proc.communicate(timeout=60)
        assert proc.returncode == 0, out
        assert "drained" in out
    finally:
        if proc.poll() is None:
            proc.kill()
