"""Visualization subsystem: TFRecord framing, event round-trip, Summary
API, and Optimizer integration (SURVEY §2.10 / §4 visualization spec)."""

import os
import struct
import threading

import numpy as np

from bigdl_tpu import native
from bigdl_tpu.visualization import (FileWriter, RecordWriter, TrainSummary,
                                     ValidationSummary, read_scalar)
from bigdl_tpu.visualization import proto


def test_tfrecord_framing(tmp_path):
    p = tmp_path / "rec"
    with open(p, "wb") as f:
        RecordWriter(f).write(b"payload")
    raw = p.read_bytes()
    (length,) = struct.unpack("<Q", raw[:8])
    assert length == 7
    (hcrc,) = struct.unpack("<I", raw[8:12])
    assert hcrc == native.masked_crc32c(raw[:8])
    assert raw[12:19] == b"payload"
    (dcrc,) = struct.unpack("<I", raw[19:23])
    assert dcrc == native.masked_crc32c(b"payload")


def test_event_proto_roundtrip():
    ev = proto.encode_event(123.5, step=7, scalars=[("Loss", 0.25),
                                                    ("Acc", 0.75)])
    got = proto.decode_event(ev)
    assert got["step"] == 7
    assert got["wall_time"] == 123.5
    assert ("Loss", 0.25) in got["scalars"]
    assert ("Acc", 0.75) in got["scalars"]


def test_filewriter_scalar_readback(tmp_path):
    d = str(tmp_path / "logs")
    w = FileWriter(d)
    for i in range(5):
        w.add_scalar("Loss", 1.0 / (i + 1), i)
    w.close()
    rows = read_scalar(d, "Loss")
    assert [r[0] for r in rows] == [0, 1, 2, 3, 4]
    np.testing.assert_allclose([r[1] for r in rows],
                               [1.0 / (i + 1) for i in range(5)], rtol=1e-6)


def test_histogram_event(tmp_path):
    d = str(tmp_path / "logs")
    w = FileWriter(d)
    w.add_histogram("weights", np.random.default_rng(0).normal(size=1000), 1)
    w.close()
    # file exists and parses as records without error
    files = [f for f in os.listdir(d) if "tfevents" in f]
    assert files
    from bigdl_tpu.visualization.tensorboard import _iter_records

    recs = list(_iter_records(os.path.join(d, files[0])))
    assert len(recs) == 2  # version header + histogram event


def _decode_histogram(buf: bytes):
    """Parse a HistogramProto payload back into (min, max, num, limits,
    buckets) via the repo's own wire codec."""
    mn = mx = num = None
    limits, buckets = [], []
    for field, wire, val in proto._fields(buf):
        if wire == 1:
            (x,) = struct.unpack("<d", val)
            if field == 1:
                mn = x
            elif field == 2:
                mx = x
            elif field == 3:
                num = x
        elif wire == 2 and field in (6, 7):
            xs = [struct.unpack("<d", val[i:i + 8])[0]
                  for i in range(0, len(val), 8)]
            (limits if field == 6 else buckets).extend(xs)
    return mn, mx, num, limits, buckets


def test_histogram_all_zero_has_valid_range():
    from bigdl_tpu.visualization.summary import histogram_proto

    mn, mx, num, limits, buckets = _decode_histogram(
        histogram_proto(np.zeros(100)))
    assert num == 100
    assert mn < mx, "all-zero input must not produce an empty range"
    assert len(limits) == len(buckets) >= 1
    assert limits == sorted(limits)
    assert all(a < b for a, b in zip(limits, limits[1:]))
    assert sum(buckets) == 100


def test_histogram_constant_has_valid_range():
    from bigdl_tpu.visualization.summary import histogram_proto

    mn, mx, num, limits, buckets = _decode_histogram(
        histogram_proto(np.full(50, 3.14)))
    assert num == 50
    assert mn < 3.14 < mx, "constant input must not invert min/max"
    assert sum(buckets) == 50
    assert all(a < b for a, b in zip(limits, limits[1:]))


def test_histogram_nonfinite_and_empty_inputs():
    from bigdl_tpu.visualization.summary import histogram_proto

    # non-finite values have no finite bucket: dropped, not corrupting
    mn, mx, num, limits, buckets = _decode_histogram(
        histogram_proto(np.asarray([np.nan, np.inf, -np.inf, 1.0])))
    assert num == 1 and sum(buckets) == 1
    assert mn <= 1.0 <= mx
    # empty input degrades to a single-zero histogram, not a crash
    mn, mx, num, limits, buckets = _decode_histogram(histogram_proto([]))
    assert num == 1 and mn < mx


def test_histogram_limits_init_is_thread_safe():
    from bigdl_tpu.visualization import summary as summary_mod
    from bigdl_tpu.visualization.summary import histogram_proto

    summary_mod._LIMITS = None  # force a fresh racey initialization
    data = np.random.default_rng(1).normal(size=256)
    results = [None] * 8
    barrier = threading.Barrier(8)

    def worker(i):
        barrier.wait()
        results[i] = histogram_proto(data)

    threads = [threading.Thread(target=worker, args=(i,))
               for i in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert all(r == results[0] for r in results)
    assert summary_mod._LIMITS is not None


def test_train_summary_trigger_gating(tmp_path):
    from bigdl_tpu.optim.trigger import Trigger

    ts = TrainSummary(str(tmp_path), "app")
    ts.set_summary_trigger("Parameters", Trigger.several_iteration(2))
    assert ts.should_write("Loss", {"neval": 1})
    assert not ts.should_write("Parameters", {"neval": 1})
    assert ts.should_write("Parameters", {"neval": 2})
    ts.close()


def test_optimizer_writes_summaries(tmp_path):
    import bigdl_tpu.nn as nn
    import bigdl_tpu.optim as optim
    from bigdl_tpu.dataset.sample import Sample
    from bigdl_tpu.optim.trigger import Trigger

    rng = np.random.default_rng(0)
    samples = [Sample(rng.normal(size=4).astype(np.float32),
                      np.int64(rng.integers(0, 3))) for _ in range(32)]
    model = nn.Sequential(nn.Linear(4, 3), nn.LogSoftMax())
    ts = TrainSummary(str(tmp_path), "run1")
    vs = ValidationSummary(str(tmp_path), "run1")
    opt = (optim.LocalOptimizer(model, samples, nn.ClassNLLCriterion(),
                                batch_size=8)
           .set_optim_method(optim.SGD(learning_rate=0.1))
           .set_end_when(Trigger.max_iteration(6))
           .set_train_summary(ts)
           .set_validation_summary(vs)
           .set_validation(Trigger.several_iteration(2), samples,
                           [optim.Top1Accuracy()], batch_size=8))
    opt.optimize()
    loss_rows = ts.read_scalar("Loss")
    assert len(loss_rows) == 6
    assert ts.read_scalar("Throughput")
    assert ts.read_scalar("LearningRate")
    acc_rows = vs.read_scalar("Top1Accuracy")
    assert acc_rows, "validation scalars written"
    ts.close()
    vs.close()
