"""Async input prefetch (VERDICT r3 item 4; reference capability
``dataset/image/MTLabeledBGRImgToBatch.scala:31``): the Optimizer loop
must overlap host transform + h2d with the device step, without changing
training semantics."""

import time

import numpy as np

import bigdl_tpu.nn as nn
import bigdl_tpu.optim as optim
from bigdl_tpu.dataset.sample import Sample
from bigdl_tpu.dataset.transformer import Transformer
from bigdl_tpu.nn.module import state_dict
from bigdl_tpu.optim.trigger import Trigger
from bigdl_tpu.utils.config import BigDLConfig, set_config


def teardown_function(_fn):
    set_config(None)


def _make_data(n=64, dim=4, seed=0):
    rng = np.random.default_rng(seed)
    x = rng.normal(size=(n, dim)).astype(np.float32)
    y = (x[:, 0] > 0).astype(np.int64)
    return [Sample(x[i], np.int64(y[i])) for i in range(n)]


def _mlp(dim=4, width=16, seed=42):
    from bigdl_tpu.utils.rng import RNG

    RNG.set_seed(seed)
    return nn.Sequential(nn.Linear(dim, width), nn.Tanh(),
                         nn.Linear(width, 2), nn.LogSoftMax())


def _train(prefetch: int, seed=7, iters=12):
    set_config(BigDLConfig(prefetch_batches=prefetch))
    from bigdl_tpu.utils.rng import RNG

    samples = _make_data()
    m = _mlp(seed=seed)
    RNG.set_seed(99)  # data shuffling + dropout keys identical per run
    o = optim.LocalOptimizer(m, samples, nn.ClassNLLCriterion(),
                             batch_size=16,
                             end_trigger=Trigger.max_iteration(iters))
    o.set_optim_method(optim.SGD(learning_rate=0.5, momentum=0.9))
    o.optimize()
    return {k: np.asarray(v) for k, v in state_dict(m).items()}, o.metrics


def test_prefetch_matches_sync_trajectory():
    """Double-buffered input must reproduce the synchronous trajectory
    bit-for-bit in expectation (same batches, same keys, same updates)."""
    p_params, p_metrics = _train(prefetch=2)
    s_params, s_metrics = _train(prefetch=0)
    for k in s_params:
        np.testing.assert_allclose(p_params[k], s_params[k],
                                   rtol=1e-5, atol=1e-6, err_msg=k)
    # both paths record the full stage set; h2d is driver-side stall
    # when sync, explicitly-overlapped producer time when prefetching
    for m, h2d in ((p_metrics, "host to device time (overlapped)"),
                   (s_metrics, "host to device time")):
        for want in ("data time", h2d, "dispatch time", "computing time"):
            assert want in m.stages(), (want, m.stages())


class SlowTransform(Transformer):
    """Host-side transform with a fixed per-batch cost (stands in for
    JPEG decode + augmentation)."""

    def __init__(self, delay_s: float):
        self.delay_s = delay_s

    def apply(self, it):
        for batch in it:
            time.sleep(self.delay_s)
            yield batch


def test_prefetch_hides_slow_input():
    """With a device step at least as long as the host transform, the
    transform must vanish from the driver's data-wait stage (the VERDICT
    'data-wait ~ 0' artifact condition)."""
    from bigdl_tpu.dataset.dataset import DataSet
    from bigdl_tpu.dataset.transformer import SampleToMiniBatch

    delay, iters = 0.05, 6
    rng = np.random.default_rng(3)
    dim, width = 256, 1024  # heavy enough that a CPU step >> delay
    samples = [Sample(rng.normal(size=(dim,)).astype(np.float32),
                      np.int64(i % 2)) for i in range(64)]

    def run(prefetch):
        set_config(BigDLConfig(prefetch_batches=prefetch))
        ds = DataSet.array(samples).transform(
            SampleToMiniBatch(32)).transform(SlowTransform(delay))
        o = optim.LocalOptimizer(_mlp(dim=dim, width=width, seed=5), ds,
                                 nn.ClassNLLCriterion(), batch_size=32,
                                 end_trigger=Trigger.max_iteration(iters))
        o.set_optim_method(optim.SGD(learning_rate=0.1))
        o.optimize()
        # drop the first sample: it pays compile (sync) or pipe-fill
        # (prefetch) either way
        waits = [w for w in o.metrics._scalars["data time"]][1:]
        return sum(waits) / len(waits)

    # wall-clock assertion -> retry under load: a busy machine (parallel
    # suites, bench sweeps) can deschedule the prefetch worker and blow
    # the ratio; the property holds whenever ONE attempt gets fair CPU.
    # Sync pays the full delay per iteration; the overlapped wait must
    # drop well below it (the production artifact of record for the
    # tight bound is the on-TPU realdata run: 0.02% data-wait).
    attempts = []
    for _ in range(4):
        sync_wait = run(0)
        prefetch_wait = run(2)
        attempts.append((prefetch_wait, sync_wait))
        if sync_wait > 0.8 * delay and prefetch_wait < 0.6 * sync_wait:
            return
    raise AssertionError(f"prefetch never beat sync by >40%: {attempts}")


def test_prefetch_surfaces_producer_errors():
    """A failure inside the input pipeline must reach the retry loop like
    a compute failure, not hang the driver."""
    class Boom(Transformer):
        def apply(self, it):
            for i, batch in enumerate(it):
                if i == 2:
                    raise RuntimeError("injected input failure")
                yield batch

    from bigdl_tpu.dataset.dataset import DataSet
    from bigdl_tpu.dataset.transformer import SampleToMiniBatch

    set_config(BigDLConfig(prefetch_batches=2, failure_retry_times=1,
                           failure_retry_interval=60.0))
    ds = DataSet.array(_make_data()).transform(
        SampleToMiniBatch(16)).transform(Boom())
    o = optim.LocalOptimizer(_mlp(), ds, nn.ClassNLLCriterion(),
                             batch_size=16,
                             end_trigger=Trigger.max_iteration(10))
    o.set_optim_method(optim.SGD(learning_rate=0.1))
    import pytest

    with pytest.raises(RuntimeError, match="injected input failure"):
        o.optimize()
