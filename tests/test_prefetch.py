"""Async input prefetch (VERDICT r3 item 4; reference capability
``dataset/image/MTLabeledBGRImgToBatch.scala:31``): the Optimizer loop
must overlap host transform + h2d with the device step, without changing
training semantics."""

import time

import numpy as np

import bigdl_tpu.nn as nn
import bigdl_tpu.optim as optim
from bigdl_tpu.dataset.sample import Sample
from bigdl_tpu.dataset.transformer import Transformer
from bigdl_tpu.nn.module import state_dict
from bigdl_tpu.optim.trigger import Trigger
from bigdl_tpu.utils.config import BigDLConfig, set_config


def teardown_function(_fn):
    set_config(None)


def _make_data(n=64, dim=4, seed=0):
    rng = np.random.default_rng(seed)
    x = rng.normal(size=(n, dim)).astype(np.float32)
    y = (x[:, 0] > 0).astype(np.int64)
    return [Sample(x[i], np.int64(y[i])) for i in range(n)]


def _mlp(dim=4, width=16, seed=42):
    from bigdl_tpu.utils.rng import RNG

    RNG.set_seed(seed)
    return nn.Sequential(nn.Linear(dim, width), nn.Tanh(),
                         nn.Linear(width, 2), nn.LogSoftMax())


def _train(prefetch: int, seed=7, iters=12):
    set_config(BigDLConfig(prefetch_batches=prefetch))
    from bigdl_tpu.utils.rng import RNG

    samples = _make_data()
    m = _mlp(seed=seed)
    RNG.set_seed(99)  # data shuffling + dropout keys identical per run
    o = optim.LocalOptimizer(m, samples, nn.ClassNLLCriterion(),
                             batch_size=16,
                             end_trigger=Trigger.max_iteration(iters))
    o.set_optim_method(optim.SGD(learning_rate=0.5, momentum=0.9))
    o.optimize()
    return {k: np.asarray(v) for k, v in state_dict(m).items()}, o.metrics


def test_prefetch_matches_sync_trajectory():
    """Double-buffered input must reproduce the synchronous trajectory
    bit-for-bit in expectation (same batches, same keys, same updates)."""
    p_params, p_metrics = _train(prefetch=2)
    s_params, s_metrics = _train(prefetch=0)
    for k in s_params:
        np.testing.assert_allclose(p_params[k], s_params[k],
                                   rtol=1e-5, atol=1e-6, err_msg=k)
    # both paths record the full stage set; h2d is driver-side stall
    # when sync, explicitly-overlapped producer time when prefetching
    for m, h2d in ((p_metrics, "host to device time (overlapped)"),
                   (s_metrics, "host to device time")):
        for want in ("data time", h2d, "dispatch time", "computing time"):
            assert want in m.stages(), (want, m.stages())


class SlowTransform(Transformer):
    """Host-side transform with a fixed per-batch cost (stands in for
    JPEG decode + augmentation)."""

    def __init__(self, delay_s: float):
        self.delay_s = delay_s

    def apply(self, it):
        for batch in it:
            time.sleep(self.delay_s)
            yield batch


def test_prefetch_hides_slow_input():
    """Deflaked (ISSUE 3 satellite): the old version asserted a
    wall-clock ratio (overlapped wait < 60% of sync wait), which a
    loaded machine blew ~1 run in 4 by descheduling the producer
    thread.  The property that makes the overlap real is scheduling-
    independent: the producer demonstrably runs AHEAD of the driver
    (queue depth reaches >= 1 while the driver is busy — the first step
    alone holds the driver in XLA compile for ~100ms while the producer
    only pays the ~20ms transform), every batch flows through the queue
    (producer-side h2d samples, zero driver-side ones), and the queue
    keeps being refilled DURING training, not just in the warmup fill."""
    from bigdl_tpu import telemetry
    from bigdl_tpu.dataset.dataset import DataSet
    from bigdl_tpu.dataset.transformer import SampleToMiniBatch

    delay, iters = 0.02, 6
    rng = np.random.default_rng(3)
    samples = [Sample(rng.normal(size=(16,)).astype(np.float32),
                      np.int64(i % 2)) for i in range(64)]
    set_config(BigDLConfig(prefetch_batches=2))
    ds = DataSet.array(samples).transform(
        SampleToMiniBatch(16)).transform(SlowTransform(delay))
    o = optim.LocalOptimizer(_mlp(dim=16, seed=5), ds,
                             nn.ClassNLLCriterion(), batch_size=16,
                             end_trigger=Trigger.max_iteration(iters))
    o.set_optim_method(optim.SGD(learning_rate=0.1))
    sink = telemetry.MemorySink()
    with telemetry.run(sinks=[sink]):
        o.optimize()

    # 1) the producer ran ahead: some put sampled a non-empty queue
    depths = [e["value"] for e in sink.events
              if e["kind"] == "gauge"
              and e["name"] == "prefetch/queue_depth"]
    assert depths, "producer never enqueued a batch"
    assert max(depths) >= 1, f"producer never got ahead: {depths}"
    # 2) every consumed batch came through the queue: h2d happened on
    # the producer thread, never as a driver-side stall
    m = o.metrics
    assert m.count("host to device time (overlapped)") >= iters
    assert m.count("host to device time") == 0
    assert m.count("data time") == iters  # the driver's queue-pop waits
    # 3) sustained overlap: the queue was refilled after the first step
    # completed, not only during the pre-training pipe fill
    first_step = next(i for i, e in enumerate(sink.events)
                      if e["kind"] == "step")
    assert any(e["kind"] == "gauge"
               and e["name"] == "prefetch/queue_depth"
               for e in sink.events[first_step + 1:]), \
        "no queue activity after the first step"


def test_prefetch_surfaces_producer_errors():
    """A failure inside the input pipeline must reach the retry loop like
    a compute failure, not hang the driver."""
    class Boom(Transformer):
        def apply(self, it):
            for i, batch in enumerate(it):
                if i == 2:
                    raise RuntimeError("injected input failure")
                yield batch

    from bigdl_tpu.dataset.dataset import DataSet
    from bigdl_tpu.dataset.transformer import SampleToMiniBatch

    set_config(BigDLConfig(prefetch_batches=2, failure_retry_times=1,
                           failure_retry_interval=60.0))
    ds = DataSet.array(_make_data()).transform(
        SampleToMiniBatch(16)).transform(Boom())
    o = optim.LocalOptimizer(_mlp(), ds, nn.ClassNLLCriterion(),
                             batch_size=16,
                             end_trigger=Trigger.max_iteration(10))
    o.set_optim_method(optim.SGD(learning_rate=0.1))
    import pytest

    with pytest.raises(RuntimeError, match="injected input failure"):
        o.optimize()
