"""Torch7 .t7 format: read a hand-encoded reference-format fixture (bytes
laid out exactly as Lua torch.save emits them), round-trip tensors,
tables, and module trees, and cross-validate numerics against torch
(PyTorch) layer implementations."""

import struct
import tempfile

import jax.numpy as jnp
import numpy as np
import pytest
import torch
import torch.nn.functional as F

import bigdl_tpu.nn as nn
from bigdl_tpu.utils.torch_file import (TorchObject, TorchTensor, load_torch,
                                        save_torch)


# -- fixture builder: emit bytes the way Lua torch.File:writeObject does --

class _LuaWriter:
    def __init__(self):
        self.b = b""
        self.idx = 0

    def i32(self, v):
        self.b += struct.pack("<i", v)

    def i64(self, v):
        self.b += struct.pack("<q", v)

    def f64(self, v):
        self.b += struct.pack("<d", v)

    def s(self, text):
        self.i32(len(text))
        self.b += text.encode()

    def string(self, text):
        self.i32(2)
        self.s(text)

    def number(self, v):
        self.i32(1)
        self.f64(v)

    def torch_header(self, cls):
        self.i32(4)
        self.idx += 1
        self.i32(self.idx)
        self.s("V 1")   # version + class are RAW strings (no type tag)
        self.s(cls)

    def float_tensor(self, arr):
        arr = np.asarray(arr, np.float32)
        self.torch_header("torch.FloatTensor")
        self.i32(arr.ndim)
        for d in arr.shape:
            self.i64(d)
        strides = [int(s // 4) for s in np.ascontiguousarray(arr).strides]
        for st in strides:
            self.i64(st)
        self.i64(1)  # storageOffset (1-based)
        self.torch_header("torch.FloatStorage")
        self.i64(arr.size)
        self.b += np.ascontiguousarray(arr).tobytes()

    def table(self, pairs):
        """pairs: list of (key_writer, value_writer) thunks."""
        self.i32(3)
        self.idx += 1
        self.i32(self.idx)
        self.i32(len(pairs))
        for k, v in pairs:
            k()
            v()


def test_read_lua_format_linear_module():
    """A nn.Linear written byte-for-byte in the Lua layout loads into our
    Linear and matches torch numerics."""
    w = np.random.RandomState(0).randn(3, 4).astype(np.float32)
    b = np.random.RandomState(1).randn(3).astype(np.float32)
    lw = _LuaWriter()
    lw.torch_header("nn.Linear")
    lw.table([
        (lambda: lw.string("weight"), lambda: lw.float_tensor(w)),
        (lambda: lw.string("bias"), lambda: lw.float_tensor(b)),
        (lambda: (lw.i32(5), lw.i32(1))[0], lambda: lw.string("train")),
    ][:2])
    path = tempfile.mktemp(suffix=".t7")
    with open(path, "wb") as f:
        f.write(lw.b)
    m = load_torch(path)
    assert isinstance(m, nn.Linear)
    x = np.random.RandomState(2).randn(5, 4).astype(np.float32)
    ref = F.linear(torch.tensor(x), torch.tensor(w), torch.tensor(b))
    np.testing.assert_allclose(np.asarray(m.forward(jnp.asarray(x))),
                               ref.numpy(), rtol=1e-5, atol=1e-6)


def test_read_lua_format_sequential_and_legacy_header():
    """Sequential with modules table; also exercises a LEGACY header
    (class name without the 'V 1' version record)."""
    w = np.eye(3, dtype=np.float32) * 2.0
    lw = _LuaWriter()
    # legacy header: torch object whose first raw string IS the class
    lw.i32(4)
    lw.idx += 1
    lw.i32(lw.idx)
    lw.s("nn.Sequential")
    lin_writer = _LuaWriter()  # inner objects share the outer memo space

    def write_linear():
        lw.torch_header("nn.Linear")
        lw.table([(lambda: lw.string("weight"),
                   lambda: lw.float_tensor(w))])

    def write_tanh():
        lw.torch_header("nn.Tanh")
        lw.table([])

    lw.table([
        (lambda: lw.string("modules"),
         lambda: lw.table([(lambda: lw.number(1), write_linear),
                           (lambda: lw.number(2), write_tanh)])),
    ])
    path = tempfile.mktemp(suffix=".t7")
    with open(path, "wb") as f:
        f.write(lw.b)
    m = load_torch(path)
    assert isinstance(m, nn.Sequential)
    x = np.random.RandomState(3).randn(2, 3).astype(np.float32)
    np.testing.assert_allclose(np.asarray(m.forward(jnp.asarray(x))),
                               np.tanh(x @ w.T), rtol=1e-5, atol=1e-6)


def test_tensor_table_scalar_roundtrip():
    path = tempfile.mktemp(suffix=".t7")
    t = np.random.RandomState(4).randn(2, 3, 4).astype(np.float32)
    save_torch({"x": t, "n": 7, "s": "hi", "flag": True,
                "longs": np.arange(5, dtype=np.int64),
                "doubles": np.linspace(0, 1, 4)}, path)
    back = load_torch(path)
    np.testing.assert_allclose(back["x"].array, t)
    assert back["n"] == 7 and back["s"] == "hi" and back["flag"] is True
    assert back["longs"].array.dtype == np.int64
    np.testing.assert_allclose(back["doubles"].array,
                               np.linspace(0, 1, 4))


def test_shared_tensor_memoization_roundtrip():
    t = np.random.RandomState(5).randn(3, 3).astype(np.float32)
    path = tempfile.mktemp(suffix=".t7")
    save_torch({"a": t, "b": t}, path)  # same object twice
    back = load_torch(path)
    assert back["a"] is back["b"]  # memo index resolved to one object


def test_module_tree_roundtrip_forward_identity():
    model = nn.Sequential(
        nn.SpatialConvolution(2, 4, 3, 3, 1, 1, 1, 1),
        nn.ReLU(),
        nn.SpatialMaxPooling(2, 2, 2, 2),
        nn.SpatialBatchNormalization(4),
        nn.Reshape([4 * 3 * 3]),
        nn.Linear(4 * 3 * 3, 6),
        nn.LogSoftMax(),
    ).evaluate()
    x = np.random.RandomState(6).randn(2, 2, 6, 6).astype(np.float32)
    want = np.asarray(model.forward(jnp.asarray(x)))
    path = tempfile.mktemp(suffix=".t7")
    save_torch(model, path)
    back = load_torch(path).evaluate()
    got = np.asarray(back.forward(jnp.asarray(x)))
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)


def test_concat_and_unknown_class():
    model = nn.Sequential(
        nn.Concat(1).add(nn.SpatialConvolution(2, 2, 1, 1))
        .add(nn.SpatialAveragePooling(3, 3, 1, 1, 1, 1)))
    x = np.random.RandomState(7).randn(1, 2, 5, 5).astype(np.float32)
    want = np.asarray(model.forward(jnp.asarray(x)))
    path = tempfile.mktemp(suffix=".t7")
    save_torch(model, path)
    back = load_torch(path)
    np.testing.assert_allclose(np.asarray(back.forward(jnp.asarray(x))),
                               want, rtol=1e-5, atol=1e-6)
    # unknown class stays a TorchObject instead of erroring
    path2 = tempfile.mktemp(suffix=".t7")
    save_torch(TorchObject("nn.SomethingExotic", {"gamma": 2.5}), path2)
    exotic = load_torch(path2)
    assert isinstance(exotic, TorchObject)
    assert exotic.table["gamma"] == 2.5


def test_overwrite_guard():
    path = tempfile.mktemp(suffix=".t7")
    save_torch(1.5, path)
    with pytest.raises(Exception):
        save_torch(2.5, path)  # overwrite defaults to False
    save_torch(2.5, path, overwrite=True)
    assert load_torch(path) == 2.5


def test_grouped_conv_roundtrip():
    m = nn.SpatialConvolution(4, 6, 3, 3, 1, 1, 1, 1, n_group=2)
    x = np.random.RandomState(8).randn(2, 4, 5, 5).astype(np.float32)
    want = np.asarray(m.forward(jnp.asarray(x)))
    path = tempfile.mktemp(suffix=".t7")
    save_torch(m, path)
    back = load_torch(path)
    assert back.n_group == 2
    np.testing.assert_allclose(np.asarray(back.forward(jnp.asarray(x))),
                               want, rtol=1e-5, atol=1e-6)
