"""Typed config object (``utils/config.py``) — the unified BIGDL_* knob
surface (``utils/Engine.scala:113-154`` system-property parity)."""

import pytest

from bigdl_tpu.utils.config import BigDLConfig, get_config, set_config


def test_defaults_without_env(monkeypatch):
    for k in ("BIGDL_FAILURE_RETRY_TIMES", "BIGDL_ITERATION_TIMEOUT",
              "BIGDL_LOCAL_MODE", "BIGDL_COORDINATOR_ADDRESS"):
        monkeypatch.delenv(k, raising=False)
    cfg = get_config()
    assert cfg.failure_retry_times == 5
    assert cfg.failure_retry_interval == 120.0
    assert cfg.iteration_timeout == ""
    assert cfg.coordinator_address is None
    assert not cfg.local_mode


def test_env_resolution(monkeypatch):
    monkeypatch.setenv("BIGDL_FAILURE_RETRY_TIMES", "2")
    monkeypatch.setenv("BIGDL_ITERATION_TIMEOUT", " auto ")
    monkeypatch.setenv("BIGDL_LOCAL_MODE", "true")
    monkeypatch.setenv("BIGDL_COORDINATOR_ADDRESS", "h:1234")
    monkeypatch.setenv("BIGDL_NUM_PROCESSES", "4")
    cfg = get_config()
    assert cfg.failure_retry_times == 2
    assert cfg.iteration_timeout == "auto"  # stripped
    assert cfg.local_mode
    assert cfg.coordinator_address == "h:1234"
    assert cfg.num_processes == 4


def test_env_mutations_visible_per_call(monkeypatch):
    monkeypatch.setenv("BIGDL_PROFILE_ITERS", "7")
    assert get_config().profile_iters == 7
    monkeypatch.setenv("BIGDL_PROFILE_ITERS", "9")
    assert get_config().profile_iters == 9  # re-resolved, not cached


def test_explicit_override_wins(monkeypatch):
    monkeypatch.setenv("BIGDL_FAILURE_RETRY_TIMES", "2")
    try:
        set_config(BigDLConfig(failure_retry_times=11))
        assert get_config().failure_retry_times == 11
    finally:
        set_config(None)
    assert get_config().failure_retry_times == 2


def test_bench_make_step_applies_graph_passes():
    """The shared perf-tool recipe (bench.make_step) must bench the
    graph-OPTIMIZED model — tools drifting onto the unfused model is how
    the round-3 profile/bench mismatch happened."""
    import sys
    sys.path.insert(0, ".")
    import bench
    import bigdl_tpu.nn as nn

    step, x, y = bench.make_step("inception_v1_imagenet", batch=2)
    names = [m.get_name() or "" for m in step.model.modules()]
    assert any("+" in n for n in names), "no merged sibling convs in bench model"
    assert any(n.endswith("/s2d") for n in names), "no s2d conv1 in bench model"
    assert x.shape[0] == 2


def test_bench_infer_legs_run_and_account():
    """Both inference legs (bf16, int8-quantized) of the bench's
    int8-vs-bf16 table run end-to-end and report throughput + op
    accounting — guards the quantize()+EvalStep+AOT wiring from rot
    between hardware windows."""
    import sys
    sys.path.insert(0, ".")
    import bench

    for quantized in (False, True):
        row = bench.run_infer_config("vgg16_cifar10", batch=8, iters=1,
                                     quantized=quantized)
        assert row["img_s"] > 0, row
        # cost accounting present (cpu has no peak, so no utilization)
        assert "achieved_tops" in row, row
