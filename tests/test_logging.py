"""LoggerFilter analogue (``utils/LoggerFilter.scala:33-134``): noisy
third-party INFO goes to the file, console keeps only their ERRORs and
framework logs; property knobs disable/redirect."""

import logging
import os

import bigdl_tpu.utils.logging as blog
from bigdl_tpu.utils.config import BigDLConfig, set_config


def teardown_function(_fn):
    blog.undo_redirect()
    set_config(None)


def _records_in(path):
    with open(path) as f:
        return f.read()


def test_redirect_sends_thirdparty_info_to_file_not_console(tmp_path, capsys):
    log_file = str(tmp_path / "bigdl.log")
    out = blog.redirect_thirdparty_logs(log_file)
    assert out == log_file

    # no manual setLevel: the redirect itself must make noisy INFO
    # records reach the file (NOTSET would inherit root's WARNING)
    noisy = logging.getLogger("jax")
    noisy.info("compile chatter %d", 7)
    noisy.error("device exploded")
    fw = logging.getLogger("bigdl_tpu")
    fw.info("epoch 1 done")

    captured = capsys.readouterr().out
    assert "compile chatter" not in captured      # INFO spam off console
    assert "device exploded" in captured          # third-party ERROR kept
    assert "epoch 1 done" in captured             # framework INFO kept

    content = _records_in(log_file)
    assert "compile chatter 7" in content
    assert "epoch 1 done" in content


def test_redirect_disable_knob(tmp_path):
    set_config(BigDLConfig(log_disable=True))
    assert blog.redirect_thirdparty_logs(str(tmp_path / "x.log")) is None
    assert not os.path.exists(tmp_path / "x.log")


def test_redirect_log_file_knob_and_no_thirdparty(tmp_path):
    target = str(tmp_path / "override.log")
    set_config(BigDLConfig(log_file=target, log_thirdparty=False))
    out = blog.redirect_thirdparty_logs(str(tmp_path / "ignored.log"))
    assert out == target

    noisy = logging.getLogger("tensorflow")
    noisy.info("import banner")
    logging.getLogger("bigdl_tpu").info("still filed")

    content = _records_in(target)
    assert "import banner" not in content   # enableSparkLog=false analogue
    assert "still filed" in content


def test_no_duplicate_framework_lines(tmp_path, capsys):
    """A child framework logger that self-installed a fallback handler
    before the redirect (bigdl_tpu.optim does) must not emit twice."""
    import bigdl_tpu.optim  # noqa: F401 — installs its fallback handler

    blog.redirect_thirdparty_logs(str(tmp_path / "bigdl.log"))
    lg = logging.getLogger("bigdl_tpu.optim")
    lg.info("once-only progress line")
    cap = capsys.readouterr()
    assert (cap.out + cap.err).count("once-only progress line") == 1


def test_redirect_idempotent(tmp_path, capsys):
    log_file = str(tmp_path / "bigdl.log")
    blog.redirect_thirdparty_logs(log_file)
    blog.redirect_thirdparty_logs(log_file)  # second call replaces handlers

    lg = logging.getLogger("absl")
    lg.setLevel(logging.ERROR)
    lg.error("once")
    assert capsys.readouterr().out.count("once") == 1
