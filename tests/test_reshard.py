"""Elastic resharding: topology-portable checkpoints (ISSUE 12,
``bigdl_tpu/utils/ckpt_topology.py`` + docs/fault_tolerance.md
"Elastic recovery").

The reshard round-trip matrix: a checkpoint written under one mesh
restores BIT-EXACTLY onto a larger or smaller one (4→2, 4→8) for
dense, ZeRO-1-sharded, and ``ScanLayers``-stacked state — and a
restore the target width cannot take (2→3 with ZeRO shards) fails
LOUDLY pre-load with :class:`TopologyMismatchError`, without
quarantining the (intact) checkpoint.  Plus: the topology record is
digest-covered like the payload hashes, the discovery walk and
retention respect per-width restorability in mixed-topology dirs, an
accepted reshard announces itself as a ``cluster/reshard`` instant,
and the BTPU backend records the same topology + prints the elastic
resume hint.  The live multi-process legs ride
``tests/test_multihost.py`` (4-proc → preempt → resume 2-proc) and
``tests/test_cluster.py`` (supervised ``peer_kill`` with ``--min-n``).
"""

import json
import os

import jax
import numpy as np
import pytest

import bigdl_tpu.nn as nn
import bigdl_tpu.optim as optim
from bigdl_tpu import telemetry
from bigdl_tpu.dataset.sample import Sample
from bigdl_tpu.optim.trigger import Trigger
from bigdl_tpu.parallel.mesh import make_mesh
from bigdl_tpu.parallel.train_step import TrainStep
from bigdl_tpu.utils import ckpt_topology
from bigdl_tpu.utils.ckpt_topology import TopologyMismatchError
from bigdl_tpu.utils.sharded_ckpt import (CorruptCheckpointError,
                                          latest_verified_step_dir,
                                          prune_old, read_topology,
                                          restorable_onto_fn,
                                          restore_train_step,
                                          save_train_step)


def _mlp(seed):
    from bigdl_tpu.utils.rng import RNG

    RNG.set_seed(seed)
    return nn.Sequential(nn.Linear(8, 16), nn.Tanh(),
                         nn.Linear(16, 2), nn.LogSoftMax())


def _scan_model(seed):
    from bigdl_tpu.nn.layers.scan import ScanLayers
    from bigdl_tpu.utils.rng import RNG

    RNG.set_seed(seed)
    blocks = [nn.Sequential(nn.Linear(16, 16), nn.Tanh())
              for _ in range(4)]
    return nn.Sequential(nn.Linear(8, 16), ScanLayers(*blocks),
                         nn.Linear(16, 2), nn.LogSoftMax())


def _data(n=32, seed=0):
    rng = np.random.default_rng(seed)
    x = rng.normal(size=(n, 8)).astype(np.float32)
    y = (x[:, 0] > 0).astype(np.int64)
    return x, y


def _mesh(n):
    return make_mesh(devices=jax.devices()[:n])


def _step(build, mesh, sync):
    return TrainStep(build(3), nn.ClassNLLCriterion(),
                     optim.Adam(learning_rate=0.05), mesh=mesh,
                     parameter_sync=sync)


def _snapshot(step):
    return {"params": {k: np.asarray(v) for k, v in step.params.items()},
            "m": {k: np.asarray(v)
                  for k, v in step.opt_state["m"].items()},
            "buffers": {k: np.asarray(v)
                        for k, v in step.buffers.items()}}


def _assert_state_equal(step, want):
    for k, v in want["params"].items():
        np.testing.assert_array_equal(np.asarray(step.params[k]), v,
                                      err_msg=f"param {k}")
    for k, v in want["m"].items():
        np.testing.assert_array_equal(np.asarray(step.opt_state["m"][k]),
                                      v, err_msg=f"moment {k}")
    for k, v in want["buffers"].items():
        np.testing.assert_array_equal(np.asarray(step.buffers[k]), v,
                                      err_msg=f"buffer {k}")


# -- the round-trip matrix ----------------------------------------------------
@pytest.mark.parametrize("sync", ["allreduce", "sharded"])
@pytest.mark.parametrize("target_n", [2, 8])
def test_reshard_4_to_n_bit_exact(tmp_path, sync, target_n):
    """A 4-device checkpoint restores bit-exactly (params, ZeRO
    moments, buffers) onto 2 and 8 devices, and training CONTINUES the
    same trajectory — the writing run's next loss equals the restored
    run's next loss."""
    x, y = _data()
    step = _step(_mlp, _mesh(4), sync)
    for i in range(3):
        step.run(x, y, jax.random.key(i))
    d = str(tmp_path / "sharded.3")
    save_train_step(step, d, extra={"neval": 3})
    want = _snapshot(step)

    step2 = _step(_mlp, _mesh(target_n), sync)
    assert restore_train_step(step2, d) == {"neval": 3}
    _assert_state_equal(step2, want)
    l_src = float(step.run(x, y, jax.random.key(9)))
    l_dst = float(step2.run(x, y, jax.random.key(9)))
    assert abs(l_src - l_dst) < 1e-6


def test_reshard_scanlayers_stacked_state(tmp_path):
    """PR-9 stacked scan params ([n_layers, ...] leaves, the natural
    ZeRO layout) survive the 4→2 reshard bit-exactly too."""
    x, y = _data()
    step = _step(_scan_model, _mesh(4), "sharded")
    for i in range(2):
        step.run(x, y, jax.random.key(i))
    d = str(tmp_path / "sharded.2")
    save_train_step(step, d, extra={"neval": 2})
    want = _snapshot(step)
    # the stacked leaves exist and at least one is recorded sharded
    topo = read_topology(d)
    stacked = [p for p in topo["leaves"] if ".body." in p or "body." in p]
    assert stacked, sorted(topo["leaves"])[:8]

    step2 = _step(_scan_model, _mesh(2), "sharded")
    restore_train_step(step2, d)
    _assert_state_equal(step2, want)
    l_src = float(step.run(x, y, jax.random.key(9)))
    l_dst = float(step2.run(x, y, jax.random.key(9)))
    assert abs(l_src - l_dst) < 1e-6


def test_reshard_2_to_3_fails_loudly_without_quarantine(tmp_path):
    """ZeRO shards that cannot re-shard at the target width (16 % 3)
    raise ``TopologyMismatchError`` BEFORE any state is touched — and
    the checkpoint is NOT quarantined: it is intact, merely not
    restorable here."""
    x, y = _data()
    step = _step(_mlp, _mesh(2), "sharded")
    step.run(x, y, jax.random.key(0))
    d = str(tmp_path / "sharded.1")
    save_train_step(step, d, extra={"neval": 1})

    step3 = _step(_mlp, _mesh(3), "sharded")
    before = {k: np.asarray(v) for k, v in step3.params.items()}
    with pytest.raises(TopologyMismatchError, match="cannot re-shard"):
        restore_train_step(step3, d)
    # never partially loaded, and the dir is untouched (no *.corrupt)
    for k, v in before.items():
        np.testing.assert_array_equal(np.asarray(step3.params[k]), v)
    assert sorted(os.listdir(tmp_path)) == ["sharded.1"]
    # the walk-level predicate reaches the same verdict without loading
    assert restorable_onto_fn(_mesh(3))(d) is False
    assert restorable_onto_fn(_mesh(2))(d) is True
    assert restorable_onto_fn(None)(d) is True  # single device = gather


def test_reshard_rejects_different_model(tmp_path):
    """Topology portability is about MESHES, not models: a target with
    different leaf shapes fails loudly pre-load."""
    x, y = _data()
    step = _step(_mlp, _mesh(2), "allreduce")
    step.run(x, y, jax.random.key(0))
    d = str(tmp_path / "sharded.1")
    save_train_step(step, d, extra={"neval": 1})

    from bigdl_tpu.utils.rng import RNG

    RNG.set_seed(9)
    other = nn.Sequential(nn.Linear(8, 32), nn.Tanh(),
                          nn.Linear(32, 2), nn.LogSoftMax())
    step2 = TrainStep(other, nn.ClassNLLCriterion(),
                      optim.Adam(learning_rate=0.05), mesh=_mesh(2))
    with pytest.raises(TopologyMismatchError, match="shape"):
        restore_train_step(step2, d)


# -- topology record integrity ------------------------------------------------
def test_topology_recorded_and_digest_covered(tmp_path):
    """The meta carries the writing mesh + per-leaf PartitionSpecs,
    covered by its own digest: a mangled topology record fails
    verification like a torn payload."""
    x, y = _data()
    step = _step(_mlp, _mesh(4), "sharded")
    step.run(x, y, jax.random.key(0))
    d = str(tmp_path / "sharded.1")
    save_train_step(step, d, extra={"neval": 1})

    topo = read_topology(d)
    assert topo["mesh"] == {"data": 4}
    assert topo["device_count"] == 4
    assert topo["parameter_sync"] == "sharded"
    sharded_leaves = {p: r for p, r in topo["leaves"].items()
                      if r.get("spec")}
    assert sharded_leaves, "ZeRO state must record sharded specs"
    assert all(r["spec"][0] == "data" for r in sharded_leaves.values())
    assert "params/0.weight" in topo["leaves"]
    assert topo["leaves"]["params/0.weight"]["shape"] == [16, 8]
    # every recorded-sharded dim here is 16 → widths dividing 16
    assert ckpt_topology.restorable_mesh_sizes(topo) == [1, 2, 4, 8, 16]

    # tamper with the topology record only — the payload digests still
    # match, yet the checkpoint must now fail verification
    meta_path = os.path.join(d, "bigdl_meta.json")
    with open(meta_path) as fh:
        meta = json.load(fh)
    meta["topology"]["mesh"]["data"] = 2
    with open(meta_path, "w") as fh:
        json.dump(meta, fh)
    step2 = _step(_mlp, _mesh(4), "sharded")
    with pytest.raises(CorruptCheckpointError, match="topology"):
        restore_train_step(step2, d)


def test_reshard_restore_emits_cluster_reshard_instant(tmp_path,
                                                       monkeypatch):
    """An accepted cross-topology restore announces old→new topology
    (the instant the fleet view folds) — carrying the supervisor's
    declared width when exported; a same-topology restore stays
    silent."""
    x, y = _data()
    step = _step(_mlp, _mesh(4), "sharded")
    step.run(x, y, jax.random.key(0))
    d = str(tmp_path / "sharded.1")
    save_train_step(step, d, extra={"neval": 1})

    monkeypatch.setenv("BIGDL_SUPERVISOR_DECLARED_N", "4")
    sink = telemetry.MemorySink()
    with telemetry.run(sinks=[sink]):
        restore_train_step(_step(_mlp, _mesh(2), "sharded"), d)
        restore_train_step(_step(_mlp, _mesh(4), "sharded"), d)
    marks = [e for e in sink.events if e.get("kind") == "event"
             and e.get("name") == "cluster/reshard"]
    assert len(marks) == 1, marks
    assert marks[0]["source"] == "restore"
    assert marks[0]["from_devices"] == 4 and marks[0]["to_devices"] == 2
    assert marks[0]["from_mesh"] == {"data": 4}
    assert marks[0]["declared_n"] == 4


# -- mixed-topology discovery + retention ------------------------------------
def _fabricate_step_dir(tmp_path, n, leaf_dim, width):
    """A complete-looking sharded.N whose topology says one ZeRO leaf of
    leading dim ``leaf_dim`` was sharded over data=``width``."""
    d = tmp_path / f"sharded.{n}"
    d.mkdir()
    topo = {"format": 1, "process_count": 1, "device_count": width,
            "mesh": {"data": width}, "parameter_sync": "sharded",
            "leaves": {"opt_state/m/w": {"shape": [leaf_dim, 4],
                                         "dtype": "float32",
                                         "spec": ["data"]}}}
    meta = {"extra": {"neval": n}, "digests": {}, "topology": topo,
            "topology_digest": ckpt_topology.digest(topo)}
    (d / "bigdl_meta.json").write_text(json.dumps(meta))
    return str(d)


def test_discovery_walk_skips_unrestorable_without_quarantine(tmp_path):
    """Mixed-topology dir: the newest verified step whose topology the
    current width cannot take is skipped (NOT quarantined) in favor of
    the newest restorable one."""
    _fabricate_step_dir(tmp_path, 2, leaf_dim=6, width=2)   # 6 % 3 == 0
    _fabricate_step_dir(tmp_path, 4, leaf_dim=8, width=4)   # 8 % 3 != 0
    fn3 = restorable_onto_fn(_mesh(3))
    got = latest_verified_step_dir(str(tmp_path), restorable_fn=fn3)
    assert got.endswith("sharded.2")
    assert sorted(os.listdir(tmp_path)) == ["sharded.2", "sharded.4"]
    # without the predicate (or onto a width that takes it): newest wins
    assert latest_verified_step_dir(str(tmp_path)).endswith("sharded.4")


def test_prune_never_deletes_last_current_width_restorable(tmp_path):
    """Retention across mixed-topology step dirs: when every survivor
    in the keep window carries a topology the current width cannot
    take, the newest restorable victim is retained as the elastic
    fallback anchor."""
    _fabricate_step_dir(tmp_path, 2, leaf_dim=6, width=2)
    _fabricate_step_dir(tmp_path, 4, leaf_dim=6, width=2)
    _fabricate_step_dir(tmp_path, 6, leaf_dim=8, width=4)
    _fabricate_step_dir(tmp_path, 8, leaf_dim=8, width=4)
    pruned = prune_old(str(tmp_path), keep=2,
                       restorable_fn=restorable_onto_fn(_mesh(3)))
    # sharded.4 is the newest width-3-restorable checkpoint: retained;
    # sharded.2 is genuinely redundant: pruned
    assert [os.path.basename(p) for p in pruned] == ["sharded.2"]
    assert sorted(os.listdir(tmp_path)) == ["sharded.4", "sharded.6",
                                            "sharded.8"]
    # same dir, a width the survivors DO fit: plain keep=2 semantics
    pruned = prune_old(str(tmp_path), keep=2,
                       restorable_fn=restorable_onto_fn(_mesh(4)))
    assert [os.path.basename(p) for p in pruned] == ["sharded.4"]


# -- BTPU backend: topology + resume hint ------------------------------------
def test_btpu_meta_records_topology_and_resume_hint(tmp_path):
    """The BTPU marker carries the same digest-covered topology record,
    and ``Optimizer.resume_hint()`` prints the restorable widths + the
    ``supervise --min-n`` recipe the preemption exit hint shows."""
    x, y = _data()
    samples = [Sample(x[i], np.int64(y[i])) for i in range(32)]
    o = optim.DistriOptimizer(_mlp(5), samples, nn.ClassNLLCriterion(),
                              batch_size=16,
                              end_trigger=Trigger.max_iteration(2),
                              mesh=_mesh(4))
    o.set_optim_method(optim.SGD(learning_rate=0.1, momentum=0.9))
    o.set_parameter_sync("sharded")
    o.set_checkpoint(str(tmp_path), Trigger.several_iteration(2))
    o.overwrite_checkpoint()
    o.optimize()
    meta = json.loads((tmp_path / "ckptmeta.2.json").read_text())
    topo = meta["topology"]
    assert topo["mesh"] == {"data": 4}
    assert meta["topology_digest"] == ckpt_topology.digest(topo)
    assert any(r.get("spec") for r in topo["leaves"].values())
    hint = o.resume_hint()
    assert hint is not None and "checkpoint topology" in hint
    assert "4 device(s)" in hint
    # a tampered topology record fails the pair's verification
    meta["topology"]["device_count"] = 2
    (tmp_path / "ckptmeta.2.json").write_text(json.dumps(meta))
    ok, problems = o._btpu_verify(str(tmp_path), 2)
    assert not ok and any("topology" in p for p in problems)


def test_btpu_restore_across_widths_announces_reshard(tmp_path):
    """BTPU state is gathered whole-model — restoring a 4-device
    checkpoint onto a 2-device mesh works by construction, continues
    the exact trajectory, and announces the reshard."""
    x, y = _data(n=64)
    samples = [Sample(x[i], np.int64(y[i])) for i in range(64)]

    def train(mesh, ckpt, iters, sink=None):
        o = optim.DistriOptimizer(
            _mlp(5), samples, nn.ClassNLLCriterion(), batch_size=16,
            end_trigger=Trigger.max_iteration(iters), mesh=mesh)
        o.set_optim_method(optim.SGD(learning_rate=0.1, momentum=0.9))
        o.set_checkpoint(str(ckpt), Trigger.several_iteration(2))
        o.overwrite_checkpoint()
        if sink is not None:
            with telemetry.run(sinks=[sink]):
                o.optimize()
        else:
            o.optimize()
        from bigdl_tpu.nn.module import state_dict

        return {k: np.asarray(v)
                for k, v in state_dict(o.model, kind="param").items()}

    want = train(_mesh(4), tmp_path / "un", iters=4)
    train(_mesh(4), tmp_path / "ck", iters=2)       # writes model.2
    sink = telemetry.MemorySink()
    got = train(_mesh(2), tmp_path / "ck", iters=4, sink=sink)  # resumes
    marks = [e for e in sink.events if e.get("kind") == "event"
             and e.get("name") == "cluster/reshard"]
    assert marks and marks[0]["from_devices"] == 4 \
        and marks[0]["to_devices"] == 2
    resumed = [e for e in sink.events if e.get("kind") == "event"
               and e.get("name") == "run/resumed"]
    assert resumed and resumed[0]["step"] == 2
    for k, v in want.items():
        np.testing.assert_allclose(got[k], v, rtol=1e-6, atol=1e-7,
                                   err_msg=f"param {k}")


# -- width-invariant data trajectory ------------------------------------------
def test_distributed_epoch_order_is_width_invariant():
    """The data half of elastic recovery: every epoch's global batch
    CONTENTS are a pure function of (seed, epoch, global size) —
    independent of how many processes feed them — so a resumed run at a
    different width consumes the exact batches the writing run would
    have (``DistributedDataSet`` global-permutation order)."""
    from bigdl_tpu.dataset.dataset import DistributedDataSet
    from bigdl_tpu.utils.rng import RNG

    data = list(range(48))
    batch = 12

    def epoch_batches(nproc, epoch):
        RNG.set_seed(7)
        shards = [DistributedDataSet(data, num_shards=nproc,
                                     shard_index=p).set_position(epoch)
                  for p in range(nproc)]
        iters = [s.data(train=True) for s in shards]
        local = batch // nproc
        out = []
        for _k in range(len(data) // batch):
            rows = set()
            for it in iters:
                rows.update(next(it) for _ in range(local))
            out.append(frozenset(rows))
        return out

    for epoch in (0, 1, 2):
        b2, b4 = epoch_batches(2, epoch), epoch_batches(4, epoch)
        assert b2 == b4, f"epoch {epoch} batch contents differ by width"
        assert set().union(*b2) == set(data)
    # shuffled epochs really are shuffled (not the identity order)
    assert epoch_batches(2, 1) != epoch_batches(2, 0)
    # epoch 0 keeps the classic stride-shard order exactly
    RNG.set_seed(7)
    ds = DistributedDataSet(data, num_shards=4, shard_index=1)
    it = ds.data(train=True)
    assert [next(it) for _ in range(4)] == [1, 5, 9, 13]


def test_cli_train_preempt_exit_prints_topology_hint(tmp_path, capsys,
                                                     monkeypatch):
    """The ``cli train`` preemption exit prints the topology the
    checkpoint can restore onto (not just "re-run me")."""
    from bigdl_tpu import faults
    from bigdl_tpu.models import cli as models_cli

    monkeypatch.setenv("BIGDL_FAULTS", "preempt@2")
    faults.reset()
    try:
        models_cli.main(["train", "--model", "lenet", "-b", "256",
                         "--max-epoch", "1",
                         "--checkpoint", str(tmp_path / "ckpt")])
    finally:
        faults.reset()
    out = capsys.readouterr().out
    assert "rerun to resume" in out
    assert "checkpoint topology" in out
    assert "restores onto" in out


def test_zero_checkpoint_rejects_silently_replicated_restore(tmp_path):
    """Review hardening: a ZeRO-sharded checkpoint restored by an
    ``allreduce`` run on a multi-device mesh would silently replicate
    every moment shard (N× the writing run's per-device memory) — the
    gate fails it loudly; a single-device target stays exempt (the
    gather path holds everything anyway), and a DENSE checkpoint may
    freely restore into a sharded layout (memory only improves)."""
    x, y = _data()
    step = _step(_mlp, _mesh(4), "sharded")
    step.run(x, y, jax.random.key(0))
    d = str(tmp_path / "sharded.1")
    save_train_step(step, d, extra={"neval": 1})

    with pytest.raises(TopologyMismatchError, match="REPLICATED"):
        restore_train_step(_step(_mlp, _mesh(4), "allreduce"), d)
    # gather-restore exemption: one device holds the whole state
    restore_train_step(_step(_mlp, _mesh(1), "allreduce"), d)
    # dense -> sharded is allowed
    dense = _step(_mlp, _mesh(2), "allreduce")
    dense.run(x, y, jax.random.key(1))
    d2 = str(tmp_path / "dense.1")
    save_train_step(dense, d2, extra={"neval": 1})
    restore_train_step(_step(_mlp, _mesh(4), "sharded"), d2)


def test_restore_raises_when_no_checkpoint_fits_current_width(tmp_path):
    """Review hardening: when checkpoints EXIST but none restores at
    the current width (e.g. a --min-n width outside the restorable
    sizes), the restore walk raises instead of silently restarting
    training from step 0 — and the error is never retried (the verdict
    is deterministic)."""
    _fabricate_step_dir(tmp_path, 4, leaf_dim=8, width=4)  # 8 % 3 != 0
    samples = [Sample(np.zeros(8, np.float32), np.int64(0))
               for _ in range(12)]
    o = optim.DistriOptimizer(_mlp(5), samples, nn.ClassNLLCriterion(),
                              batch_size=12,
                              end_trigger=Trigger.max_iteration(1),
                              mesh=_mesh(3))
    o.set_checkpoint(str(tmp_path), Trigger.every_epoch(),
                     backend="sharded")
    with pytest.raises(TopologyMismatchError, match="none is restorable"):
        o._restore_from(str(tmp_path))
    # a width the checkpoint fits selects it instead
    o4 = optim.DistriOptimizer(_mlp(5), samples, nn.ClassNLLCriterion(),
                               batch_size=12,
                               end_trigger=Trigger.max_iteration(1),
                               mesh=_mesh(4))
    o4.set_checkpoint(str(tmp_path), Trigger.every_epoch(),
                      backend="sharded")
    assert o4._restore_from(str(tmp_path)) is True
    assert o4._pending_sharded_restore.endswith("sharded.4")
    # and a genuinely empty dir is still just "nothing to resume"
    empty = tmp_path / "empty"
    empty.mkdir()
    o3 = optim.DistriOptimizer(_mlp(5), samples, nn.ClassNLLCriterion(),
                               batch_size=12,
                               end_trigger=Trigger.max_iteration(1),
                               mesh=_mesh(3))
    o3.set_checkpoint(str(empty), Trigger.every_epoch(),
                      backend="sharded")
    assert o3._restore_from(str(empty)) is False


def test_distributed_stream_width_invariant_with_indivisible_size():
    """Review hardening: the width-invariance guarantee must survive
    global sizes NOT divisible by the width — the stride runs over the
    CONCATENATED epoch stream, so batches crossing epoch boundaries
    assemble the same contents at every width."""
    from bigdl_tpu.dataset.dataset import DistributedDataSet
    from bigdl_tpu.utils.rng import RNG

    data = list(range(10))  # 10 % 4 != 0
    batch = 4

    def stream_batches(nproc, num_batches):
        RNG.set_seed(7)
        shards = [DistributedDataSet(data, num_shards=nproc,
                                     shard_index=p)
                  for p in range(nproc)]
        iters = [s.data(train=True) for s in shards]
        local = batch // nproc
        out = []
        for _k in range(num_batches):
            rows = []
            for it in iters:
                rows.extend(next(it) for _ in range(local))
            out.append(tuple(sorted(rows)))  # multiset per batch
        return out

    # 7 batches x 4 = 28 records = 2 epoch boundaries crossed
    assert stream_batches(2, 7) == stream_batches(4, 7)
    # 5 batches = 20 records = exactly two epochs: every record seen
    # exactly twice (each epoch covers the dataset exactly once)
    flat = [r for b in stream_batches(2, 5) for r in b]
    assert sorted(flat) == sorted(data + data)


def test_resume_hint_min_n_is_restorable(tmp_path):
    """Review hardening: the printed --min-n recipe must name a width
    the checkpoint can actually restore onto — nproc // 2 is wrong for
    e.g. a 5-process ZeRO checkpoint whose shards only divide by 5."""
    samples = [Sample(np.zeros(8, np.float32), np.int64(0))
               for _ in range(10)]
    o = optim.LocalOptimizer(_mlp(5), samples, nn.ClassNLLCriterion(),
                             batch_size=10,
                             end_trigger=Trigger.max_iteration(1))
    o.set_checkpoint(str(tmp_path), Trigger.every_epoch())
    o.overwrite_checkpoint()
    o._init_checkpoint_dir()
    topo = {"format": 1, "process_count": 5, "device_count": 5,
            "mesh": {"data": 5}, "parameter_sync": "sharded",
            "leaves": {"opt_state/m/w": {"shape": [5, 4],
                                         "dtype": "float32",
                                         "spec": ["data"]}}}
    meta = {"neval": 2, "digests": {}, "topology": topo,
            "topology_digest": ckpt_topology.digest(topo)}
    (tmp_path / "ckptmeta.2.json").write_text(json.dumps(meta))
    hint = o.resume_hint()
    # restorable mesh sizes are {1, 5}: the only degraded process
    # count below 5 is 1 — never the naive 5 // 2 = 2
    assert "--min-n 1" in hint, hint
    # a 4-wide dim-16 checkpoint keeps the half-capacity suggestion
    topo["process_count"] = topo["device_count"] = 4
    topo["mesh"] = {"data": 4}
    topo["leaves"]["opt_state/m/w"]["shape"] = [16, 4]
    meta["topology_digest"] = ckpt_topology.digest(topo)
    (tmp_path / "ckptmeta.2.json").write_text(json.dumps(meta))
    assert "--min-n 2" in o.resume_hint()
