"""Model-zoo shape/loss tests (the reference's ``models/`` specs,
SURVEY §4 'models/ (7: model graphs produce expected shapes/loss)')."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import bigdl_tpu.nn as nn
from bigdl_tpu import models
from bigdl_tpu.nn.module import functional_call, state_dict


def _check_train_step(model, x_shape, n_classes, rtol_loss=0.6):
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.normal(size=x_shape).astype(np.float32))
    y = jnp.asarray(rng.integers(0, n_classes, x_shape[0]))
    crit = nn.ClassNLLCriterion()
    p = state_dict(model)

    def loss_fn(p):
        out, _ = functional_call(model, p, x, training=True,
                                 rng=jax.random.key(0))
        return crit.update_output(out, y)

    loss, grads = jax.jit(jax.value_and_grad(loss_fn))(p)
    expected = np.log(n_classes)
    assert abs(float(loss) - expected) < rtol_loss * expected, float(loss)
    gnorm = sum(float(jnp.sum(jnp.abs(g))) for g in grads.values())
    assert gnorm > 0


def test_lenet5():
    m = models.build_lenet5(10)
    out = m.forward(jnp.ones((2, 28 * 28)))
    assert out.shape == (2, 10)
    _check_train_step(m, (4, 1, 28, 28), 10)


def test_vgg_cifar():
    m = models.build_vgg_for_cifar10(10)
    out = m.evaluate().forward(jnp.ones((2, 3, 32, 32)))
    assert out.shape == (2, 10)


def test_inception_v1():
    m = models.build_inception_v1(1000)
    out = m.evaluate().forward(jnp.ones((2, 3, 224, 224)))
    assert out.shape == (2, 1000)


def test_inception_v1_aux():
    m = models.build_inception_v1(100, with_aux=True)
    outs = m.evaluate().forward(jnp.ones((1, 3, 224, 224)))
    assert isinstance(outs, list) and len(outs) == 3
    for o in outs:
        assert o.shape == (1, 100)


def test_inception_v2():
    m = models.build_inception_v2(1000)
    out = m.evaluate().forward(jnp.ones((1, 3, 224, 224)))
    assert out.shape == (1, 1000)


@pytest.mark.parametrize("depth,block_out", [(18, 512), (50, 2048)])
def test_resnet_imagenet(depth, block_out):
    m = models.build_resnet(depth, 1000)
    out = m.evaluate().forward(jnp.ones((1, 3, 224, 224)))
    assert out.shape == (1, 1000)


def test_resnet_cifar_shortcut_a():
    m = models.build_resnet_cifar(20, 10, shortcut_type="A")
    out = m.evaluate().forward(jnp.ones((2, 3, 32, 32)))
    assert out.shape == (2, 10)
    _check_train_step(m.train(), (2, 3, 32, 32), 10)


def test_simple_rnn_and_lstm_classifier():
    m = models.build_simple_rnn(100, 16, 100)
    out = m.forward(jnp.ones((2, 5, 100)))
    assert out.shape == (2, 5, 100)
    clf = models.build_lstm_classifier(vocab_size=50, embed_dim=8,
                                       hidden_size=12, class_num=3)
    tokens = jnp.asarray(np.random.randint(0, 50, (4, 7)))
    out = clf.forward(tokens)
    assert out.shape == (4, 3)


def test_autoencoder_trains():
    m = models.build_autoencoder(32)
    x = jnp.asarray(np.random.rand(8, 784).astype(np.float32))
    out = m.forward(x)
    assert out.shape == (8, 784)
    crit = nn.MSECriterion()
    p = state_dict(m)

    def loss_fn(p):
        out, _ = functional_call(m, p, x)
        return crit.update_output(out, x)

    l0 = float(loss_fn(p))
    g = jax.grad(loss_fn)(p)
    p2 = {k: p[k] - 0.5 * g[k] for k in p}
    assert float(loss_fn(p2)) < l0


def test_transformer_lm_forward_and_shapes():
    import numpy as np

    from bigdl_tpu import models
    from bigdl_tpu.utils.rng import RNG

    RNG.set_seed(0)
    lm = models.build_transformer_lm(vocab_size=50, num_layers=2,
                                     embed_dim=32, num_heads=4, max_len=16,
                                     backend="dense")
    tokens = np.random.RandomState(0).randint(0, 50, (2, 12))
    out = lm.forward(tokens)
    assert out.shape == (2, 12, 50)
    # log-probs normalize over vocab
    import jax.numpy as jnp

    np.testing.assert_allclose(np.asarray(jnp.exp(out).sum(-1)), 1.0,
                               rtol=1e-4)


def test_transformer_lm_trains_with_sequence_parallel_mesh():
    import jax
    import numpy as np

    import bigdl_tpu.nn as nn
    import bigdl_tpu.optim as optim
    from bigdl_tpu import models
    from bigdl_tpu.parallel.mesh import make_mesh
    from bigdl_tpu.parallel.train_step import TrainStep
    from bigdl_tpu.utils.rng import RNG

    RNG.set_seed(1)
    mesh = make_mesh((8,), ("seq",))
    lm = models.build_transformer_lm(vocab_size=32, num_layers=1,
                                     embed_dim=16, num_heads=2, max_len=32,
                                     sp_mesh=mesh)
    crit = nn.TimeDistributedCriterion(nn.ClassNLLCriterion(), size_average=True)
    step = TrainStep(lm, crit, optim.SGD(learning_rate=0.5))
    rng = np.random.RandomState(1)
    tokens = rng.randint(0, 32, (4, 32))
    # learn to echo the input (predict current token) — learnable fast
    losses = [float(step.run(tokens, tokens, jax.random.key(i)))
              for i in range(8)]
    assert losses[-1] < losses[0]


def test_cli_perf_sequence_models(capsys):
    """ADVICE r1: cmd_perf must feed token-shaped data to lstm/transformer."""
    from bigdl_tpu.models import cli

    cli.main(["perf", "--model", "lstm", "-b", "2", "-i", "1",
              "--warmup", "1", "--no-bf16"])
    assert "records/sec" in capsys.readouterr().out
    cli.main(["perf", "--model", "transformer", "-b", "2", "-i", "1",
              "--warmup", "1", "--no-bf16"])
    assert "records/sec" in capsys.readouterr().out


def test_cli_token_data_shapes():
    from bigdl_tpu.models import cli

    x, y = cli._load_data("lstm", None, "train")
    assert x.ndim == 2 and x.dtype.kind == "i" and len(x) == len(y)
    xt, yt = cli._load_data("transformer", None, "test")
    assert xt.shape == yt.shape and xt.shape[1] == cli.LM_SEQ_LEN


def test_textclassification_example_learns():
    """example/textclassification parity (TextClassifier.scala conv
    stack): the synthetic 5-topic corpus must be learnable."""
    import examples.textclassification as tc

    _, _, _, acc = tc.main(["--max-epoch", "4", "--seq-len", "150",
                            "--synthetic-size", "250", "--batch-size", "16",
                            "--learning-rate", "0.05"])
    assert acc >= 0.7, acc


def test_udfpredictor_example_udf_and_query():
    """example/udfpredictor parity: the predict-UDF query flow (a quick
    1-epoch model — the full training quality is covered by the
    textclassification test above)."""
    import examples.textclassification as tc
    import examples.udfpredictor as up

    model, word_index, table, _ = tc.main(
        ["--max-epoch", "1", "--seq-len", "150",
         "--synthetic-size", "100", "--batch-size", "16"])
    udf = up.make_predict_udf(model, word_index, table, 150)
    rows = [{"id": i, "text": "rocket orbit nasa launch"} for i in range(3)]
    preds = udf([r["text"] for r in rows])
    assert preds.shape == (3,)
    kept, preds2 = up.query(rows, "text", udf, {int(preds[0])})
    assert len(kept) == 3  # identical texts -> identical class
    assert all(r["predicted"] == int(preds[0]) for r in kept)
    kept_none, _ = up.query(rows, "text", udf,
                            {int(preds[0]) + 1000})
    assert kept_none == []
