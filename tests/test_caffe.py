"""Caffe import: prototxt text-format parsing, binary caffemodel blob
decoding, DAG building, and a numeric oracle comparison against torch."""

import struct
import tempfile

import numpy as np
import pytest

from bigdl_tpu.utils.caffe import (CaffeLoader, load_caffe,
                                   load_caffemodel_blobs, parse_prototxt)

PROTOTXT = """
name: "testnet"  # a comment
input: "data"
input_dim: 1
input_dim: 3
input_dim: 8
input_dim: 8
layer {
  name: "conv1"
  type: "Convolution"
  bottom: "data"
  top: "conv1"
  convolution_param { num_output: 4 kernel_size: 3 pad: 1 stride: 1 }
}
layer {
  name: "relu1"
  type: "ReLU"
  bottom: "conv1"
  top: "conv1"
}
layer {
  name: "pool1"
  type: "Pooling"
  bottom: "conv1"
  top: "pool1"
  pooling_param { pool: MAX kernel_size: 2 stride: 2 }
}
layer {
  name: "ip1"
  type: "InnerProduct"
  bottom: "pool1"
  top: "ip1"
  inner_product_param { num_output: 5 }
}
layer {
  name: "prob"
  type: "Softmax"
  bottom: "ip1"
  top: "prob"
}
"""


def _varint(n):
    out = b""
    while True:
        b7 = n & 0x7F
        n >>= 7
        out += bytes([b7 | (0x80 if n else 0)])
        if not n:
            return out


def _ld(field, payload):
    return _varint((field << 3) | 2) + _varint(len(payload)) + payload


def _blob(arr):
    arr = np.asarray(arr, np.float32)
    shape_msg = b"".join(_varint((1 << 3) | 0) + _varint(d)
                         for d in arr.shape)
    data = struct.pack(f"<{arr.size}f", *arr.reshape(-1))
    return _ld(7, shape_msg) + _ld(5, data)


def _layer_v2(name, blobs):
    body = _ld(1, name.encode())
    for b in blobs:
        body += _ld(7, _blob(b))
    return _ld(100, body)


def _make_caffemodel(path, weights):
    buf = b"".join(_layer_v2(n, bs) for n, bs in weights.items())
    with open(path, "wb") as f:
        f.write(buf)


@pytest.fixture
def caffe_files():
    rng = np.random.RandomState(0)
    w = {
        "conv1": [rng.randn(4, 3, 3, 3).astype(np.float32),
                  rng.randn(4).astype(np.float32)],
        "ip1": [rng.randn(5, 4 * 4 * 4).astype(np.float32),
                rng.randn(5).astype(np.float32)],
    }
    proto = tempfile.mktemp(suffix=".prototxt")
    model = tempfile.mktemp(suffix=".caffemodel")
    with open(proto, "w") as f:
        f.write(PROTOTXT)
    _make_caffemodel(model, w)
    return proto, model, w


def test_parse_prototxt():
    net = parse_prototxt(PROTOTXT)
    assert net["name"] == "testnet"
    assert net["input"] == "data"
    assert net["input_dim"] == [1, 3, 8, 8]
    layers = net["layer"]
    assert [l["type"] for l in layers] == \
        ["Convolution", "ReLU", "Pooling", "InnerProduct", "Softmax"]
    assert layers[0]["convolution_param"]["num_output"] == 4
    assert layers[2]["pooling_param"]["pool"] == "MAX"


def test_caffemodel_blob_roundtrip(caffe_files):
    _, model, w = caffe_files
    blobs = load_caffemodel_blobs(model)
    assert set(blobs) == {"conv1", "ip1"}
    np.testing.assert_allclose(blobs["conv1"][0], w["conv1"][0])
    np.testing.assert_allclose(blobs["ip1"][1], w["ip1"][1])


def test_load_caffe_oracle_vs_torch(caffe_files):
    torch = pytest.importorskip("torch")
    import torch.nn as tnn

    proto, model_path, w = caffe_files
    model = load_caffe(proto, model_path).evaluate()
    x = np.random.RandomState(1).randn(1, 3, 8, 8).astype(np.float32)
    got = np.asarray(model.forward(x))

    ref = tnn.Sequential(
        tnn.Conv2d(3, 4, 3, padding=1), tnn.ReLU(), tnn.MaxPool2d(2, 2),
        tnn.Flatten(), tnn.Linear(4 * 4 * 4, 5), tnn.Softmax(dim=-1))
    with torch.no_grad():
        ref[0].weight.copy_(torch.from_numpy(w["conv1"][0]))
        ref[0].bias.copy_(torch.from_numpy(w["conv1"][1]))
        ref[4].weight.copy_(torch.from_numpy(w["ip1"][0]))
        ref[4].bias.copy_(torch.from_numpy(w["ip1"][1]))
        expected = ref(torch.from_numpy(x)).numpy()
    np.testing.assert_allclose(got, expected, rtol=1e-4, atol=1e-5)


def test_load_caffe_branching_eltwise():
    proto_text = """
input: "data"
input_shape { dim: 1 dim: 2 dim: 4 dim: 4 }
layer { name: "c1" type: "Convolution" bottom: "data" top: "c1"
        convolution_param { num_output: 2 kernel_size: 1 } }
layer { name: "c2" type: "Convolution" bottom: "data" top: "c2"
        convolution_param { num_output: 2 kernel_size: 1 } }
layer { name: "sum" type: "Eltwise" bottom: "c1" bottom: "c2" top: "sum"
        eltwise_param { operation: SUM } }
layer { name: "cat" type: "Concat" bottom: "c1" bottom: "sum" top: "cat" }
"""
    proto = tempfile.mktemp(suffix=".prototxt")
    with open(proto, "w") as f:
        f.write(proto_text)
    model = load_caffe(proto).evaluate()
    x = np.random.RandomState(2).randn(1, 2, 4, 4).astype(np.float32)
    out = model.forward(x)
    assert out.shape == (1, 4, 4, 4)  # concat of 2+2 channels


def test_train_phase_layers_skipped():
    proto_text = """
input: "data"
input_shape { dim: 1 dim: 3 dim: 4 dim: 4 }
layer { name: "c" type: "Convolution" bottom: "data" top: "c"
        convolution_param { num_output: 2 kernel_size: 1 } }
layer { name: "trainaug" type: "Dropout" bottom: "c" top: "c"
        include { phase: TRAIN } }
"""
    proto = tempfile.mktemp(suffix=".prototxt")
    with open(proto, "w") as f:
        f.write(proto_text)
    loader = CaffeLoader(proto)
    model, ins, outs = loader.load()
    names = [m.get_name() for m in model.__dict__["_modules"].values()]
    assert "trainaug" not in names


def test_customized_converter_hook():
    import bigdl_tpu.nn as nn

    proto_text = """
input: "data"
input_shape { dim: 1 dim: 3 dim: 4 dim: 4 }
layer { name: "c" type: "Convolution" bottom: "data" top: "c"
        convolution_param { num_output: 2 kernel_size: 1 } }
layer { name: "dummy" type: "Dummy" bottom: "c" top: "d" }
"""
    proto = tempfile.mktemp(suffix=".prototxt")
    with open(proto, "w") as f:
        f.write(proto_text)
    loader = CaffeLoader(
        proto, customized_converters={
            "Dummy": lambda lay, in_ch, blobs: (nn.ReLU(), in_ch)})
    model, _, _ = loader.load()
    x = np.random.RandomState(3).randn(1, 3, 4, 4).astype(np.float32)
    assert model.forward(x).shape == (1, 2, 4, 4)


def test_global_pooling_and_eltwise_coeff_and_concat_axis():
    proto_text = """
# leading comment
input: "data"
input_shape { dim: 1 dim: 2 dim: 4 dim: 4 }
layer { name: "gmax" type: "Pooling" bottom: "data" top: "gmax"
        pooling_param { pool: MAX global_pooling: true } }
# trailing comment"""
    proto = tempfile.mktemp(suffix=".prototxt")
    with open(proto, "w") as f:
        f.write(proto_text)
    model = load_caffe(proto).evaluate()
    x = np.random.RandomState(4).randn(1, 2, 4, 4).astype(np.float32)
    out = np.asarray(model.forward(x))
    assert out.shape == (1, 2, 1, 1)
    np.testing.assert_allclose(out.reshape(2), x.max(axis=(2, 3)).reshape(2))

    proto_text2 = """
input: "data"
input_shape { dim: 1 dim: 2 dim: 4 dim: 4 }
layer { name: "c1" type: "Convolution" bottom: "data" top: "c1"
        convolution_param { num_output: 2 kernel_size: 1 } }
layer { name: "diff" type: "Eltwise" bottom: "data" bottom: "c1" top: "d"
        eltwise_param { operation: SUM coeff: 1 coeff: -1 } }
layer { name: "cat2" type: "Concat" bottom: "d" bottom: "c1" top: "cat"
        concat_param { axis: 2 } }
"""
    proto2 = tempfile.mktemp(suffix=".prototxt")
    with open(proto2, "w") as f:
        f.write(proto_text2)
    model2 = load_caffe(proto2).evaluate()
    out2 = model2.forward(x)
    assert out2.shape == (1, 2, 8, 4)  # concat along axis 2


# ----------------------------- export (CaffePersister) --------------------

def _roundtrip(model, input_shape, x):
    """save -> reload with our own loader -> compare forward outputs
    (the reference round-trip contract, ``CaffePersister.scala:47``)."""
    import jax.numpy as jnp

    from bigdl_tpu.utils.caffe_persister import save_caffe

    proto = tempfile.mktemp(suffix=".prototxt")
    weights = tempfile.mktemp(suffix=".caffemodel")
    save_caffe(model, proto, weights, input_shapes=input_shape)
    reloaded, _, _ = CaffeLoader(proto, weights).load()
    reloaded.evaluate()
    model.evaluate()
    a = np.asarray(model.forward(jnp.asarray(x)))
    b = np.asarray(reloaded.forward(jnp.asarray(x)))
    np.testing.assert_allclose(a, b, rtol=1e-5, atol=1e-6)
    return proto, weights


def test_persister_unnamed_modules_get_fresh_unique_names():
    """``get_name()``'s fallback derives from ``id() % 1e5``, so two
    unnamed modules can collide and silently shadow each other's layer
    + blobs in the prototxt — the cause of the intermittent inception_v1
    roundtrip failure (wrong channel wiring / dangling nodes on reload,
    dependent on heap layout).  The persister must mint its own fresh
    names for unnamed modules and keep only user-set ones."""
    import re

    import bigdl_tpu.nn as nn
    from bigdl_tpu.utils.caffe_persister import CaffePersister

    model = nn.Sequential(
        nn.SpatialConvolution(3, 4, 1, 1).set_name("conv_explicit"),
        nn.ReLU(),
        nn.SpatialConvolution(4, 4, 1, 1),
        nn.SpatialConvolution(4, 2, 1, 1))
    p = CaffePersister(model, input_shapes=(1, 3, 8, 8))
    p.build()
    names = [lay["name"] for lay in p.layers]
    assert "conv_explicit" in names
    assert len(names) == len(set(names))
    for nm in names:
        if nm != "conv_explicit":
            # persister-scoped counter names, never id-derived ones
            assert not re.fullmatch(r"(SpatialConvolution|ReLU)\d+", nm), nm

    # minted names must also dodge user-set ones wherever they appear in
    # the model ("conv1" here would be the counter's first conv pick)
    clash = nn.Sequential(
        nn.SpatialConvolution(3, 4, 1, 1),
        nn.SpatialConvolution(4, 4, 1, 1).set_name("conv1"))
    p = CaffePersister(clash, input_shapes=(1, 3, 8, 8))
    p.build()
    names = [lay["name"] for lay in p.layers]
    assert len(names) == len(set(names)), names
    assert "conv1" in names


def test_persister_sequential_cnn_roundtrip():
    import bigdl_tpu.nn as nn

    model = nn.Sequential(
        nn.SpatialConvolution(3, 4, 3, 3, 1, 1, 1, 1).set_name("conv1"),
        nn.ReLU(),
        nn.SpatialMaxPooling(2, 2, 2, 2),
        nn.SpatialCrossMapLRN(3, 0.001, 0.75),
        nn.SpatialConvolution(4, 6, 3, 3, 2, 2, 0, 0, n_group=2,
                              with_bias=False),
        nn.Sigmoid(),
        nn.SpatialAveragePooling(2, 2, 1, 1),
        nn.InferReshape([0, -1]),
        nn.Linear(6, 5).set_name("fc"),
        nn.SoftMax(),
    )
    x = np.random.RandomState(0).randn(2, 3, 12, 12).astype(np.float32)
    proto, _ = _roundtrip(model, (1, 3, 12, 12), x)
    # named layers keep their names in the prototxt
    text = open(proto).read()
    assert 'name: "conv1"' in text and 'name: "fc"' in text


def test_persister_batchnorm_eps_and_1d_roundtrip():
    """Non-default eps must survive the round-trip (it is part of the
    normalization math, 1.2e-3 divergence when dropped), for BOTH the
    spatial and the dense (N,C) BatchNormalization variants — realistic
    running stats, not fresh-init."""
    import jax.numpy as jnp

    import bigdl_tpu.nn as nn

    rs = np.random.RandomState(11)
    bn = nn.BatchNormalization(6, eps=1e-3)
    bn.weight = jnp.asarray(rs.rand(6) + 0.5, jnp.float32)
    bn.bias = jnp.asarray(rs.randn(6), jnp.float32)
    bn.running_mean = jnp.asarray(rs.randn(6), jnp.float32)
    bn.running_var = jnp.asarray(rs.rand(6) * 1e-2, jnp.float32)  # eps matters
    model = nn.Sequential(nn.Linear(3, 6), bn, nn.ReLU())
    x = rs.randn(4, 3).astype(np.float32)
    _roundtrip(model, (1, 3), x)


def test_persister_batchnorm_scale_roundtrip():
    import jax.numpy as jnp

    import bigdl_tpu.nn as nn

    bn = nn.SpatialBatchNormalization(4)
    bn.weight = jnp.asarray(np.random.RandomState(1).rand(4) + 0.5,
                            jnp.float32)
    bn.bias = jnp.asarray(np.random.RandomState(2).randn(4), jnp.float32)
    bn.running_mean = jnp.asarray(np.random.RandomState(3).randn(4),
                                  jnp.float32)
    bn.running_var = jnp.asarray(np.random.RandomState(4).rand(4) + 0.5,
                                 jnp.float32)
    model = nn.Sequential(nn.SpatialConvolution(2, 4, 1, 1), bn, nn.ReLU())
    x = np.random.RandomState(5).randn(2, 2, 5, 5).astype(np.float32)
    _roundtrip(model, (1, 2, 5, 5), x)


def test_persister_graph_dag_roundtrip():
    import bigdl_tpu.nn as nn
    from bigdl_tpu.nn.graph import node_from_module

    inp = nn.Input(name="data")
    c1 = node_from_module(nn.SpatialConvolution(3, 4, 1, 1).set_name("b1"),
                          [inp])
    c2 = node_from_module(nn.SpatialConvolution(3, 4, 3, 3, 1, 1, 1, 1)
                          .set_name("b2"), [inp])
    add = node_from_module(nn.CAddTable().set_name("sum"), [c1, c2])
    cat = node_from_module(nn.JoinTable(1, 0).set_name("cat"), [add, c1])
    out = node_from_module(nn.ReLU().set_name("out"), [cat])
    model = nn.Graph([inp], [out])
    x = np.random.RandomState(6).randn(2, 3, 6, 6).astype(np.float32)
    _roundtrip(model, (1, 3, 6, 6), x)


def test_persister_concat_container_and_floor_pooling():
    import bigdl_tpu.nn as nn

    model = nn.Sequential(
        nn.Concat(1)
        .add(nn.Sequential(nn.SpatialConvolution(2, 3, 1, 1), nn.ReLU()))
        .add(nn.Sequential(nn.SpatialMaxPooling(3, 3, 1, 1, 1, 1),
                           nn.SpatialConvolution(2, 2, 1, 1))),
        nn.SpatialMaxPooling(3, 3, 2, 2),  # floor mode must round-trip
    )
    x = np.random.RandomState(7).randn(2, 2, 7, 7).astype(np.float32)
    _roundtrip(model, (1, 2, 7, 7), x)


def test_prototxt_writer_parses_back():
    from bigdl_tpu.utils.caffe_persister import to_prototxt

    net = {"name": "n", "layer": [
        {"name": "p", "type": "Pooling", "bottom": ["d"], "top": "p",
         "pooling_param": {"pool": "MAX", "kernel_h": 3, "kernel_w": 3,
                           "stride_h": 2, "stride_w": 2}},
        {"name": "e", "type": "Eltwise", "bottom": ["p", "d"], "top": "e",
         "eltwise_param": {"operation": "SUM", "coeff": [1.0, -1.0]}},
    ]}
    parsed = parse_prototxt(to_prototxt(net))
    assert parsed["name"] == "n"
    layers = parsed["layer"]
    assert layers[0]["bottom"] == "d"
    assert layers[1]["bottom"] == ["p", "d"]
    assert layers[1]["eltwise_param"]["coeff"] == [1.0, -1.0]
    assert layers[0]["pooling_param"]["pool"] == "MAX"


@pytest.mark.parametrize("name,build,shape", [
    ("lenet", lambda: _zoo().build_lenet5(10), (1, 28, 28)),
    ("vgg16_cifar", lambda: _zoo().build_vgg_for_cifar10(10), (3, 32, 32)),
    ("inception_v1", lambda: _zoo().build_inception_v1(100), (3, 224, 224)),
])
def test_persister_zoo_roundtrip(name, build, shape):
    """VERDICT r4 next-step #8: the models that matter round-trip
    through prototxt+caffemodel with numeric equivalence (reference
    contract ``CaffePersister.scala:47``).  Exercises the LogSoftMax ->
    Softmax+Log emission, the 1-D BatchNormalization emitter, and the
    left-aligned Scale reload."""
    from bigdl_tpu.utils.rng import RNG

    RNG.set_seed(0)
    model = build()
    x = np.random.default_rng(0).normal(size=(2,) + shape).astype(np.float32)
    _roundtrip(model, (1,) + shape, x)


def _zoo():
    from bigdl_tpu import models
    return models
