"""Managed persistent compile cache (utils/compile_cache.py,
docs/compile.md): a same-config second process over the same cache dir
must LOAD its executables (cache hits > 0, measurably lower compile
seconds) for both the training aot_scan path and the serving warmup
path; hits/misses land in the run log as schema-valid instants; and
the compile budget (`telemetry diff` / `bench.py --compile-budget`)
flags an injected compile_s regression with a nonzero exit."""

import json
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

#: one training process: build a TrainStep, AOT-compile a 3-iteration
#: scan, print the cache monitor snapshot + the run-log path as JSON
_TRAIN_CHILD = """
import json, sys
import numpy as np, jax
from bigdl_tpu import telemetry
import bigdl_tpu.nn as nn, bigdl_tpu.optim as optim
from bigdl_tpu.parallel.train_step import TrainStep
from bigdl_tpu.utils.rng import RNG

with telemetry.run(sys.argv[1]):
    RNG.set_seed(0)
    m = nn.Sequential(nn.Linear(16, 64), nn.Tanh(), nn.Linear(64, 4),
                      nn.LogSoftMax())
    step = TrainStep(m, nn.ClassNLLCriterion(),
                     optim.SGD(learning_rate=0.1))
    x = np.random.RandomState(0).randn(32, 16).astype(np.float32)
    y = np.random.RandomState(0).randint(0, 4, 32)
    step.aot_scan(x, y, jax.random.key(0), 3)
from bigdl_tpu.utils import compile_cache as cc
print(json.dumps({"run_log": telemetry.last_run_path(),
                  **cc.monitor().snapshot()}))
"""

#: one serving process: warm a 2-bucket executor, print the snapshot
_SERVE_CHILD = """
import json, sys
import numpy as np
import bigdl_tpu.nn as nn
from bigdl_tpu.serving.buckets import BucketPolicy
from bigdl_tpu.serving.executor import BucketedExecutor
from bigdl_tpu.utils.rng import RNG

RNG.set_seed(0)
model = nn.Sequential(nn.Linear(6, 32), nn.Tanh(), nn.Linear(32, 3),
                      nn.LogSoftMax()).evaluate()
ex = BucketedExecutor(model, policy=BucketPolicy(batch_buckets=[2, 4]))
warm_s = ex.warmup((6,), np.float32)
out = ex.run(np.ones((3, 6), np.float32))
assert np.asarray(out).shape[0] == 3
from bigdl_tpu.utils import compile_cache as cc
print(json.dumps({"warmup_s": warm_s, "buckets": len(ex.warm_buckets()),
                  **cc.monitor().snapshot()}))
"""


def _run_child(code, cache_dir, tmp_path, *args):
    """Fresh interpreter, single CPU device (the persistent cache's
    supported CPU shape — the tier-1 rig's forced 8-device host
    platform is exactly what the implicit gate keeps away from it),
    explicit cache opt-in."""
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)  # single device in the child
    env.update(JAX_PLATFORMS="cpu",
               BIGDL_COMPILE_CACHE=str(cache_dir),
               BIGDL_COMPILE_CACHE_MIN_S="0",
               PYTHONPATH=REPO)
    proc = subprocess.run(
        [sys.executable, "-c", code, *[str(a) for a in args]],
        capture_output=True, text=True, timeout=180, env=env,
        cwd=str(tmp_path))
    assert proc.returncode == 0, proc.stderr[-2000:]
    return json.loads(proc.stdout.strip().splitlines()[-1])


@pytest.mark.deadline(420)
def test_second_process_aot_scan_hits_cache(tmp_path):
    cache = tmp_path / "cache"
    cold = _run_child(_TRAIN_CHILD, cache, tmp_path, tmp_path / "run1")
    warm = _run_child(_TRAIN_CHILD, cache, tmp_path, tmp_path / "run2")
    assert cold["misses"] > 0 and cold["hits"] == 0, cold
    assert warm["hits"] > 0, warm
    assert warm["misses"] == 0, warm
    # the headline contract: a warm restart's compile bill collapses
    assert warm["compile_s"] < cold["compile_s"], (cold, warm)

    # hits/misses are per-run telemetry, schema-valid
    from bigdl_tpu.telemetry import schema

    for snap, name in ((cold, "compile/cache_miss"),
                       (warm, "compile/cache_hit")):
        n, errors = schema.validate_run(snap["run_log"])
        assert errors == [], errors[:3]
        events, _ = schema.read_events(snap["run_log"])
        names = [e.get("name") for e in events if e.get("kind") == "event"]
        assert name in names, (name, names)
        assert "compile/cache" in names, "ingredients not announced"

    # and `telemetry diff` sees the warm run's lower compile_s
    from bigdl_tpu.telemetry import diff

    a = diff.run_log_metrics(cold["run_log"])
    b = diff.run_log_metrics(warm["run_log"])
    assert b["compile_s"] < a["compile_s"]


@pytest.mark.deadline(420)
def test_second_process_serving_warmup_reuses_cache(tmp_path):
    cache = tmp_path / "cache"
    cold = _run_child(_SERVE_CHILD, cache, tmp_path)
    warm = _run_child(_SERVE_CHILD, cache, tmp_path)
    assert cold["buckets"] == warm["buckets"] == 2
    assert cold["misses"] > 0 and cold["hits"] == 0, cold
    assert warm["hits"] > 0 and warm["misses"] == 0, warm
    assert warm["compile_s"] < cold["compile_s"], (cold, warm)


# -- the compile budget ------------------------------------------------------
def _bench_doc(compile_s, images_per_sec=1000.0):
    return {"metric": "x_train_throughput", "value": images_per_sec,
            "configs": {"lenet_mnist": {
                "images_per_sec": images_per_sec,
                "compile_s": compile_s,
                "stages_s": {"compile": compile_s}}}}


def test_diff_flags_injected_compile_regression(tmp_path):
    """Acceptance: `telemetry diff` exits nonzero on a compile_s
    regression beyond the compile budget."""
    from bigdl_tpu.telemetry import diff

    a = tmp_path / "a.json"
    b = tmp_path / "b.json"
    a.write_text(json.dumps(_bench_doc(10.0)))
    b.write_text(json.dumps(_bench_doc(100.0)))  # 10x: the outlier class
    assert diff.main([str(a), str(b)]) == 1
    # within the default 50% budget: no regression
    b.write_text(json.dumps(_bench_doc(12.0)))
    assert diff.main([str(a), str(b)]) == 0
    # a tightened budget flags it
    assert diff.main([str(a), str(b),
                      "--compile-threshold-pct", "10"]) == 1


def test_bench_metrics_reads_banked_stages_fallback():
    """Pre-budget banked artifacts (stages_s only, no compile_s field)
    stay comparable."""
    from bigdl_tpu.telemetry import diff

    doc = {"configs": {"lenet_mnist": {"images_per_sec": 1.0,
                                       "stages_s": {"compile": 445.7}}}}
    m = diff.bench_metrics(doc)
    assert m["lenet_mnist.compile_s"] == pytest.approx(445.7)


def test_diff_metrics_compile_threshold_param():
    from bigdl_tpu.telemetry.diff import diff_metrics

    a = {"compile_s": 10.0}
    b = {"compile_s": 14.0}  # +40%
    rows = diff_metrics(a, b)
    assert not rows[0]["regressed"]  # default 50% budget
    rows = diff_metrics(a, b, compile_threshold_pct=25.0)
    assert rows[0]["regressed"]
    # the runtime threshold does NOT govern compile_s
    rows = diff_metrics(a, b, threshold_pct=1.0)
    assert not rows[0]["regressed"]


def test_metrics_sink_exports_compile_cache_counters():
    """/metrics + /status carry bigdl_compile_cache_hits/misses and
    cumulative compile seconds (the satellite contract)."""
    from bigdl_tpu.telemetry.metrics_http import MetricsSink

    sink = MetricsSink()
    base = {"v": 1, "ts": 0.0, "pid": 1, "tid": 1}
    sink.emit({**base, "kind": "compile", "name": "TrainStep.run",
               "dur": 2.5})
    sink.emit({**base, "kind": "compile", "name": "TrainStep.run",
               "dur": 0.5})
    sink.emit({**base, "kind": "event", "name": "compile/cache_hit"})
    sink.emit({**base, "kind": "event", "name": "compile/cache_miss"})
    sink.emit({**base, "kind": "event", "name": "compile/cache_miss"})
    status = sink.status()
    assert status["compile_s"] == pytest.approx(3.0)
    assert status["compile_cache"] == {"hits": 1, "misses": 2}
    text = sink.openmetrics()
    assert "bigdl_compile_seconds_total" in text
    assert 'bigdl_compile_cache_hits_total{process_index="0"} 1' in text
    assert 'bigdl_compile_cache_misses_total{process_index="0"} 2' in text


def test_cache_key_ingredients_name_the_key():
    from bigdl_tpu.utils.compile_cache import cache_key_ingredients

    ing = cache_key_ingredients()
    assert "jax" in ing and "jaxlib" in ing
    assert "cache_dir" in ing and "min_compile_s" in ing


def test_implicit_enable_stays_off_cpu(monkeypatch, tmp_path):
    """The hot-path spelling must not flip the cache on for plain-CPU
    processes (tier-1's forced 8-device host platform is unsafe to
    serialize on this jaxlib) — only an explicit BIGDL_COMPILE_CACHE
    opts CPU in."""
    import jax

    from bigdl_tpu.utils.engine import enable_compile_cache

    monkeypatch.delenv("BIGDL_COMPILE_CACHE", raising=False)
    if jax.config.jax_compilation_cache_dir:
        pytest.skip("cache already configured process-wide")
    assert enable_compile_cache(implicit=True) == ""
    assert not jax.config.jax_compilation_cache_dir
