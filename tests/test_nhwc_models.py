"""NHWC (channels-last) model-zoo coverage: the layout variant of
Inception v1 (``models/inception.py`` ``format="NHWC"``) must thread the
format through EVERY spatial layer and agree with the NCHW build on
transposed inputs (same parameters — the conv transposes weights
internally)."""

import numpy as np
import jax.numpy as jnp

import bigdl_tpu.nn as nn
from bigdl_tpu.models.inception import (_aux_head, build_inception_v1,
                                        inception_layer_v1)
from bigdl_tpu.nn.module import Container, load_state_dict, state_dict
from bigdl_tpu.utils.rng import RNG


def _formats(model):
    """Every `format` attribute in the module tree."""
    found = []

    def walk(m):
        fmt = m.__dict__.get("format")
        if isinstance(fmt, str):
            found.append((type(m).__name__, fmt))
        if isinstance(m, Container):
            for child in m.layers:
                walk(child)

    walk(model)
    return found


def test_nhwc_threads_every_spatial_layer():
    for with_aux in (False, True):
        model = build_inception_v1(10, with_aux=with_aux, format="NHWC")
        fmts = _formats(model)
        assert fmts, "no format-bearing layers found"
        wrong = [(n, f) for n, f in fmts if f != "NHWC"]
        assert not wrong, f"layers left on NCHW: {wrong}"


def test_nhwc_stack_matches_nchw():
    """Forward equivalence over the layer kinds Inception composes:
    conv, ceil-mode maxpool, LRN, inception block, avg pool."""
    RNG.set_seed(0)
    def build(fmt):
        return nn.Sequential(
            nn.SpatialConvolution(3, 16, 3, 3, 2, 2, 1, 1, format=fmt),
            nn.ReLU(True),
            nn.SpatialMaxPooling(3, 3, 2, 2, format=fmt).ceil(),
            nn.SpatialCrossMapLRN(5, 0.0001, 0.75, format=fmt),
            inception_layer_v1(16, [[8], [8, 12], [4, 8], [8]], "b/", fmt),
            nn.SpatialAveragePooling(3, 3, 2, 2, format=fmt),
        )

    m_c = build("NCHW")
    m_l = build("NHWC")
    load_state_dict(m_l, state_dict(m_c))
    x = np.random.randn(2, 3, 33, 33).astype(np.float32)
    out_c = np.asarray(m_c.forward(jnp.asarray(x)))
    out_l = np.asarray(m_l.forward(jnp.asarray(x.transpose(0, 2, 3, 1))))
    np.testing.assert_allclose(out_l.transpose(0, 3, 1, 2), out_c,
                               rtol=1e-5, atol=1e-6)


def test_nhwc_aux_head_matches_nchw_with_shared_weights():
    """The aux classifier flattens spatial maps into an fc — the NHWC
    build must transpose back to channel-first before the flatten so the
    SAME fc weights produce the SAME logits (checkpoint portability
    across layouts)."""
    RNG.set_seed(1)
    h_c = _aux_head(32, "loss1", 7, "NCHW").evaluate()
    RNG.set_seed(2)
    h_l = _aux_head(32, "loss1", 7, "NHWC").evaluate()
    # the NHWC build has an extra (parameterless) Transpose, so positional
    # state paths shift by one — map parameters in traversal order
    src, dst = state_dict(h_c), state_dict(h_l)
    assert len(src) == len(dst)
    def _key(p):
        head, leaf = p.split(".", 1)
        return (int(head), leaf)
    remapped = {dk: src[sk] for sk, dk in
                zip(sorted(src, key=_key), sorted(dst, key=_key))}
    load_state_dict(h_l, remapped)
    # aux pool 5x5 stride 3 over a 14x14 map -> 4x4, as in the real model
    x = np.random.randn(2, 32, 14, 14).astype(np.float32)
    out_c = np.asarray(h_c.forward(jnp.asarray(x)))
    out_l = np.asarray(h_l.forward(jnp.asarray(x.transpose(0, 2, 3, 1))))
    np.testing.assert_allclose(out_l, out_c, rtol=1e-5, atol=1e-6)
