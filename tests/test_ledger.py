"""Goodput ledger (bigdl_tpu/telemetry/ledger.py, ISSUE 18): run-level
wall-time accounting.

The contract under test is *conservation*: compute plus every badput
category must sum to the wall time the run held the hardware, within
the pinned tolerance — per incarnation, and across a supervised
restart chain where the inter-incarnation gaps are classified
(supervisor backoff vs restart overhead) without counting any second
twice.  Plus the consumption surfaces: the per-run ``goodput`` event,
the ``telemetry goodput`` CLI, the report section, the diff/bench
gates, and the chrome-trace badput lanes.
"""

import json
import os
import signal
import sys
import time

import pytest

from bigdl_tpu.telemetry import ledger

TOL = ledger.DEFAULT_TOLERANCE_PCT


def _ev(kind, ts, pid=100, **fields):
    d = {"v": 1, "ts": ts, "pid": pid, "tid": 1, "kind": kind}
    d.update(fields)
    return d


def _single_incarnation():
    """10s wall: 2s compile, 4s of steps (0.5s of it input-stalled),
    2s checkpoint, 2s unexplained."""
    return [
        _ev("run_start", 0.0, meta={"process_index": 0, "incarnation": 0}),
        _ev("compile", 2.0, name="train_step", dur=2.0),
        _ev("step", 4.0, step=0, dur=2.0),
        _ev("span_end", 4.0, name="data_wait", span=1, dur=0.5),
        _ev("step", 6.0, step=1, dur=2.0),
        _ev("span_end", 8.5, name="checkpoint", span=2, dur=2.0),
        _ev("run_end", 10.0, dur=10.0),
    ]


def _incarnation_chain():
    """p0 dies at t=10 (SIGKILL), supervisor books 3s backoff at t=12,
    incarnation 1 restarts at t=15: a 5s gap = 3s backoff + 2s restart
    overhead."""
    inc0 = [
        _ev("run_start", 0.0, 100,
            meta={"process_index": 0, "incarnation": 0}),
        _ev("compile", 2.0, 100, name="train_step", dur=2.0),
        _ev("step", 4.0, 100, step=0, dur=2.0),
        _ev("step", 6.0, 100, step=1, dur=2.0),
        _ev("step", 8.0, 100, step=2, dur=2.0),
        _ev("step", 10.0, 100, step=3, dur=2.0),
    ]
    sup = [
        _ev("run_start", 0.0, 50, meta={"cmd": "supervise",
                                        "role": "supervisor",
                                        "declared_n": 1}),
        _ev("event", 12.0, 50, name="cluster/restart", incarnation=0,
            restart=1, budget=5, width=1, declared_n=1, exits=[-9],
            backoff_s=3.0),
        _ev("run_end", 30.0, 50, dur=30.0),
    ]
    inc1 = [
        _ev("run_start", 15.0, 200,
            meta={"process_index": 0, "incarnation": 1}),
        _ev("stage", 15.5, 200, name="checkpoint/restore", dur=0.5,
            source="ckpt"),
        _ev("stage", 16.0, 200, name="resume/fast_forward", dur=0.5,
            records=128),
        _ev("step", 18.0, 200, step=4, dur=2.0),
        _ev("step", 20.0, 200, step=5, dur=2.0),
        _ev("step", 22.0, 200, step=6, dur=2.0),
        _ev("step", 24.0, 200, step=7, dur=2.0),
        _ev("run_end", 25.0, 200, dur=10.0),
    ]
    return [("inc0.jsonl", inc0), ("sup.jsonl", sup),
            ("inc1.jsonl", inc1)]


def _assert_conserves(report, tol=TOL):
    total = report["compute_s"] + sum(report["badput"].values())
    assert abs(total - report["wall_s"]) <= report["wall_s"] * tol / 100
    assert report["conservation_err_pct"] <= tol


# -- conservation ------------------------------------------------------------
def test_single_run_categories_sum_to_wall():
    r = ledger.goodput_from_events(_single_incarnation())
    assert r["wall_s"] == pytest.approx(10.0)
    _assert_conserves(r)
    # every instrument landed in its category, unexplained time in idle
    assert r["badput"]["compile"] == pytest.approx(2.0)
    assert r["badput"]["data_wait"] == pytest.approx(0.5)
    assert r["badput"]["checkpoint"] == pytest.approx(2.0)
    assert r["badput"]["idle"] == pytest.approx(4.0)
    assert r["compute_s"] == pytest.approx(1.5)
    assert r["goodput_pct"] == pytest.approx(15.0)
    assert r["blame"]["cause"] == "idle"


def test_in_step_carve_never_exceeds_step_time():
    """A mis-scaled instrument (comms seconds > the whole step) must
    not push in-step badput past the time the steps took."""
    events = [
        _ev("run_start", 0.0, meta={"process_index": 0}),
        _ev("step", 1.0, step=0, dur=1.0),
        _ev("comms", 1.0, measured_s=50.0),
        _ev("run_end", 2.0, dur=2.0),
    ]
    r = ledger.goodput_from_events(events)
    assert r["badput"]["comms"] <= 1.0
    _assert_conserves(r)


def test_retry_backoff_killed_mid_sleep_is_trimmed_to_wall():
    """``run/retry`` fires BEFORE its sleep: a process killed mid-backoff
    (the supervised peer-kill shape — found live by the verify drive)
    charged badput past its own wall and broke conservation; the
    unelapsed tail must be trimmed, while fully-slept retries keep
    their face value."""
    events = [
        _ev("run_start", 0.0, meta={"process_index": 0}),
        _ev("step", 1.0, step=0, dur=1.0),
        # slept in full: the next event is past ts + backoff_s
        _ev("event", 2.0, name="run/retry", attempt=1, backoff_s=1.0),
        _ev("step", 4.0, step=1, dur=1.0),
        # killed 0.5s into a 5s backoff — the log simply ends
        _ev("event", 4.5, name="run/retry", attempt=2, backoff_s=5.0),
        _ev("event", 5.0, name="straggler/timeout", budget_s=0.0),
    ]
    r = ledger.goodput_from_events(events)
    assert r["wall_s"] == pytest.approx(5.0)
    # 1.0 fully slept + only the 0.5 of the second backoff the wall saw
    assert r["badput"]["retry_backoff"] == pytest.approx(1.5)
    _assert_conserves(r)


def test_staleness_barrier_wait_charged_to_straggler_badput():
    """A fast host holding the local-SGD door open for a laggard
    (``sync/staleness`` ``waited_s`` — parallel/local_sync.py) lands in
    the SAME ``straggler`` blame column as a straggler-guard trip, and
    the in-step carve still caps it at the time the steps took."""
    events = [
        _ev("run_start", 0.0, meta={"process_index": 0}),
        _ev("step", 1.0, step=0, dur=1.0),
        _ev("step", 2.0, step=1, dur=1.0),
        # the survivor waited 0.7s of those steps at the barrier; a
        # zero-wait round must NOT count as a straggler incident
        _ev("event", 2.0, name="sync/staleness", round=1, waited_s=0.7,
            lag=1, stale=2, step=2),
        _ev("event", 2.0, name="sync/staleness", round=2, waited_s=0.0,
            lag=0, stale=2, step=4),
        _ev("run_end", 3.0, dur=3.0),
    ]
    r = ledger.goodput_from_events(events)
    assert r["badput"]["straggler"] == pytest.approx(0.7)
    assert r["counts"]["stragglers"] == 1
    _assert_conserves(r)
    # mis-scaled waits can never push the carve past the step time
    huge = list(events)
    huge[3] = _ev("event", 2.0, name="sync/staleness", round=1,
                  waited_s=99.0, lag=3, stale=2, step=2)
    r2 = ledger.goodput_from_events(huge)
    assert r2["badput"]["straggler"] <= 2.0
    _assert_conserves(r2)


def test_chain_stitches_gap_into_backoff_plus_restart():
    r = ledger.ledger_from_events(_incarnation_chain())
    assert r["conservation"]["ok"]
    chain = r["chains"][0]
    assert chain["process_index"] == 0
    assert chain["incarnations"] == 2
    # wall = 10s (inc0) + 5s gap + 10s (inc1): every second once,
    # none twice across the restart boundary
    assert chain["wall_s"] == pytest.approx(25.0)
    assert r["badput"]["backoff"] == pytest.approx(3.0)
    assert r["badput"]["restart"] == pytest.approx(2.0)
    assert r["badput"]["replay"] == pytest.approx(0.5)
    assert r["counts"]["restarts"] == 1
    assert r["counts"]["incarnations"] == 2
    _assert_conserves(r)
    _assert_conserves(chain)
    # the supervisor log classified the gap but contributed no wall
    assert r["n_supervisor_runs"] == 1
    assert "sup.jsonl" not in chain["paths"]


def test_streaming_fold_matches_offline_fold():
    events = _single_incarnation()
    fold = ledger.LedgerFold()
    for ev in events:
        fold.emit(ev)  # the sink protocol path the runtime uses
    live = fold.event_fields()
    offline = ledger.goodput_from_events(events)
    assert live == offline


def test_blame_names_dominant_category_with_evidence():
    r = ledger.ledger_from_events(_incarnation_chain())
    blame = r["blame"]
    assert blame["cause"] in ("backoff", "restart")
    assert blame["seconds"] > 0
    assert blame["evidence"]
    # negligible badput -> no blame
    quiet = ledger.goodput_from_events([
        _ev("run_start", 0.0, meta={}),
        _ev("step", 10.0, step=0, dur=10.0),
        _ev("run_end", 10.0, dur=10.0),
    ])
    assert quiet["blame"]["cause"] == "none"


# -- runtime wiring ----------------------------------------------------------
def test_end_run_writes_goodput_event(tmp_path):
    from bigdl_tpu import telemetry
    from bigdl_tpu.telemetry import schema

    with telemetry.run(str(tmp_path), meta={"cmd": "test"}):
        with telemetry.span("data_wait"):
            time.sleep(0.02)
        telemetry.emit("step", step=0, dur=0.05)
        time.sleep(0.05)
        telemetry.emit("step", step=1, dur=0.05)
        live = telemetry.goodput()
        assert live is not None and live["wall_s"] > 0
    assert telemetry.goodput() is None  # detached with the run
    events, errors = schema.read_events(telemetry.last_run_path())
    assert errors == []
    gp = [e for e in events if e["kind"] == "goodput"]
    assert len(gp) == 1
    assert gp[0]["goodput_pct"] == pytest.approx(live["goodput_pct"],
                                                 abs=5.0)
    assert gp[0]["blame"]["cause"] in ("none",) + \
        tuple(ledger.BADPUT_CATEGORIES)
    _assert_conserves(gp[0])


def test_report_includes_goodput_section(tmp_path):
    from bigdl_tpu.telemetry import report

    summary = report.summarize(_single_incarnation())
    assert summary["goodput"]["goodput_pct"] == pytest.approx(15.0)
    text = report.format_summary(summary)
    assert "-- goodput --" in text
    assert "blame" in text


# -- CLI ---------------------------------------------------------------------
def _write_log(path, events):
    with open(path, "w") as f:
        for ev in events:
            f.write(json.dumps(ev) + "\n")


def test_goodput_cli_folds_chain(tmp_path, capsys):
    for name, events in _incarnation_chain():
        _write_log(tmp_path / name, events)
    rc = ledger.goodput_main([str(tmp_path / n) for n, _ in
                              _incarnation_chain()] + ["--json"])
    out = json.loads(capsys.readouterr().out)
    assert rc == 0
    assert out["conservation"]["ok"]
    assert out["badput"]["restart"] > 0
    # text renderer names the chain and the blame
    rc = ledger.goodput_main([str(tmp_path / n) for n, _ in
                              _incarnation_chain()])
    text = capsys.readouterr().out
    assert rc == 0
    assert "chain p0: 2 incarnation(s)" in text
    assert "blame:" in text


def test_goodput_cli_exit_codes(tmp_path, capsys):
    assert ledger.goodput_main([]) == 2  # nothing to read
    # instruments summing way past wall -> conservation violation -> 1
    bad = [
        _ev("run_start", 0.0, meta={"process_index": 0}),
        _ev("span_end", 5.0, name="checkpoint", span=1, dur=20.0),
        _ev("run_end", 10.0, dur=10.0),
    ]
    _write_log(tmp_path / "bad.jsonl", bad)
    assert ledger.goodput_main([str(tmp_path / "bad.jsonl")]) == 1
    capsys.readouterr()


def test_supervise_dir_discovers_logs(tmp_path, capsys):
    sub = tmp_path / "telemetry"
    sub.mkdir()
    for name, events in _incarnation_chain():
        _write_log(sub / f"run-{name}", events)
    rc = ledger.goodput_main(["--supervise-dir", str(tmp_path),
                              "--json"])
    assert rc == 0
    out = json.loads(capsys.readouterr().out)
    assert out["n_runs"] == 3


# -- diff / bench gates ------------------------------------------------------
def test_diff_gates_goodput_regression(tmp_path, capsys):
    from bigdl_tpu.telemetry import diff as tdiff

    base = {"metric": "m", "value": 100.0, "goodput_pct": 90.0,
            "badput_s": 10.0}
    cand = {"metric": "m", "value": 100.0, "goodput_pct": 70.0,
            "badput_s": 30.0}
    a = tmp_path / "base.json"
    b = tmp_path / "cand.json"
    a.write_text(json.dumps(base))
    b.write_text(json.dumps(cand))
    rc = tdiff.main([str(a), str(b), "--json"])
    out = json.loads(capsys.readouterr().out)
    assert rc == 1  # 90 -> 70 goodput is way past the 5% threshold
    assert out["goodput_threshold_pct"] == \
        tdiff.DEFAULT_GOODPUT_THRESHOLD_PCT
    regressed = {r["name"] for r in out["rows"] if r["regressed"]}
    assert "goodput_pct" in regressed
    # within threshold -> no gate
    cand2 = dict(base, goodput_pct=89.0, badput_s=10.2)
    b.write_text(json.dumps(cand2))
    assert tdiff.main([str(a), str(b)]) == 0
    capsys.readouterr()


def test_run_log_metrics_carries_goodput(tmp_path):
    from bigdl_tpu.telemetry import diff as tdiff

    _write_log(tmp_path / "run.jsonl", _single_incarnation())
    m = tdiff.run_log_metrics(str(tmp_path / "run.jsonl"))
    assert m["goodput_pct"] == pytest.approx(15.0)
    assert m["badput_s"] == pytest.approx(8.5)


def test_bench_metrics_carries_goodput():
    from bigdl_tpu.telemetry import diff as tdiff

    doc = {"metric": "m", "value": 1.0, "goodput_pct": 88.5,
           "badput_s": 12.25,
           "configs": {"lenet": {"images_per_sec": 10.0,
                                 "goodput_pct": 88.5}}}
    m = tdiff.bench_metrics(doc)
    assert m["goodput_pct"] == 88.5
    assert m["badput_s"] == 12.25


# -- chrome trace ------------------------------------------------------------
def test_chrome_trace_renders_badput_lanes():
    from bigdl_tpu.telemetry import chrome_trace

    merged = [ev for _, events in _incarnation_chain() for ev in events]
    trace = chrome_trace.chrome_trace(merged)["traceEvents"]
    lanes = {e["args"]["name"] for e in trace
             if e.get("ph") == "M" and
             str(e["args"].get("name", "")).startswith("badput:")}
    assert {"badput:compile", "badput:checkpoint",
            "badput:replay"} <= lanes
    # the incarnation gap is stitched into restart + backoff slices
    slices = {e["name"]: e for e in trace
              if e.get("cat") == "badput" and e.get("ph") == "X"}
    assert slices["backoff"]["dur"] == pytest.approx(3.0 * 1e6)
    assert slices["restart"]["dur"] == pytest.approx(2.0 * 1e6)
    # the supervisor's own lane contributes no restart slice: the gap
    # belongs to the reborn worker pid
    assert slices["restart"]["pid"] == 200


# -- live e2e: supervised run with an injected kill --------------------------
@pytest.mark.deadline(120)
def test_supervised_kill_shows_restart_badput(tmp_path, monkeypatch):
    """End to end: a 2-process supervised run whose p0 SIGKILLs itself
    in incarnation 0.  Folding the telemetry dir (supervisor log +
    every incarnation's worker logs) must show nonzero restart/backoff
    badput, blame it, and still conserve."""
    from bigdl_tpu import telemetry
    from bigdl_tpu.parallel import cluster

    monkeypatch.setenv("BIGDL_RETRY_BACKOFF", "0.05")
    tdir = tmp_path / "telemetry"
    tdir.mkdir()
    body = (
        "import os, signal, time\n"
        "from bigdl_tpu import telemetry\n"
        "pidx = int(os.environ['BIGDL_PROCESS_ID'])\n"
        "inc = int(os.environ['BIGDL_SUPERVISOR_INCARNATION'])\n"
        f"tr = telemetry.start_run({str(tdir)!r},\n"
        "                          meta={'process_index': pidx})\n"
        "for i in range(3):\n"
        "    t0 = time.perf_counter()\n"
        "    time.sleep(0.05)\n"
        "    telemetry.emit('step', step=inc * 3 + i,\n"
        "                   dur=time.perf_counter() - t0)\n"
        "    for s in tr._sinks:\n"
        "        s.flush()\n"
        "if inc == 0 and pidx == 0:\n"
        "    os.kill(os.getpid(), signal.SIGKILL)\n"
        "telemetry.end_run()\n")
    sup = cluster.Supervisor(2, [sys.executable, "-c", body],
                             max_restarts=3,
                             cluster_dir=str(tmp_path / "cl"),
                             settle_grace=5.0, env=dict(os.environ))
    with telemetry.run(str(tdir), meta={"cmd": "supervise",
                                        "role": "supervisor",
                                        "declared_n": 2}):
        rc = sup.run()
    assert rc == 0
    assert sup.restarts >= 1

    paths = ledger.discover_logs(str(tmp_path))
    assert len(paths) >= 4  # supervisor + >= 3 worker incarnation logs
    from bigdl_tpu.telemetry import schema
    runs = [(p, schema.read_events(p)[0]) for p in paths]
    report = ledger.ledger_from_events(runs)
    assert report["conservation"]["ok"], report["conservation"]
    assert report["n_supervisor_runs"] >= 1
    # the killed chain carries the restart: gap time classified, not
    # dropped and not double-counted
    killed = [c for c in report["chains"] if c["incarnations"] >= 2]
    assert killed, report["chains"]
    gap = report["badput"]["restart"] + report["badput"]["backoff"]
    assert gap > 0
    assert report["badput"]["backoff"] > 0  # supervisor booked its sleep
    assert report["counts"]["restarts"] >= 1
    # with steps covering nearly all in-run time, the respawn gap
    # dominates: blame must point at the restart machinery
    assert report["blame"]["cause"] in ("restart", "backoff")
    assert "restart" in report["blame"]["evidence"] \
        or "backoff" in report["blame"]["evidence"]
