"""Guard-surface tests for the TF-session reader families that need
NO TensorFlow (hand-built node dicts + pure-numpy paths) — kept outside
test_tf_session.py, whose module-level importorskip would silently skip
them on TF-less environments."""

import numpy as np
import pytest

from bigdl_tpu.utils.tf_session import TFTrainingSession


def _node(name, op, inputs=(), **attrs):
    return {"name": name, "op": op, "inputs": list(inputs), "attrs": attrs}


def test_reader_guards_without_tf():
    """The honest-error guard surface of the new reader families, driven
    on hand-built node dicts (no TF needed): string CSV fields,
    hop_bytes, wrong reader for a source kind, and incompatible
    multi-enqueue sources."""
    # string CSV record_default -> NotImplementedError
    nodes = [
        _node("files", "Const", value=np.asarray([b"f.csv"])),
        _node("fq", "FIFOQueueV2"),
        _node("enq_f", "QueueEnqueueV2", ["fq", "files"]),
        _node("rdr", "TextLineReaderV2"),
        _node("read", "ReaderReadV2", ["rdr", "fq"]),
        _node("d0", "Const", value=np.asarray([b"x"])),  # string default
        _node("csv", "DecodeCSV", ["read:1", "d0"]),
    ]
    sess = TFTrainingSession(nodes)
    with pytest.raises(NotImplementedError, match="string CSV"):
        sess._csv_source(sess.by_name["csv"])

    # hop_bytes on a fixed-length reader -> NotImplementedError
    nodes2 = [
        _node("files", "Const", value=np.asarray([b"f.bin"])),
        _node("fq", "FIFOQueueV2"),
        _node("enq_f", "QueueEnqueueV2", ["fq", "files"]),
        _node("rdr", "FixedLengthRecordReaderV2",
              record_bytes=10, hop_bytes=5),
        _node("read", "ReaderReadV2", ["rdr", "fq"]),
    ]
    sess2 = TFTrainingSession(nodes2)
    with pytest.raises(NotImplementedError, match="hop_bytes"):
        sess2._fixedlen_source(sess2.by_name["read"])

    # a TFRecord reader is not a valid CSV source (and vice versa)
    nodes3 = [n.copy() for n in nodes2]
    nodes3[3] = _node("rdr", "TFRecordReaderV2")
    sess3 = TFTrainingSession(nodes3)
    with pytest.raises(NotImplementedError, match="want FixedLengthRecordReader"):
        sess3._fixedlen_source(sess3.by_name["read"])
    with pytest.raises(NotImplementedError, match="want TextLineReader"):
        sess3._csv_source(_node("csv", "DecodeCSV", ["read:1"]))


def test_incompatible_multi_enqueue_sources_raise():
    """Two enqueues into one queue whose sources differ in KIND (or CSV
    config) must refuse to union (the _union_sources guard)."""
    from bigdl_tpu.utils.tf_session import _Source, _union_sources

    a = _Source("tfrecord", ["a.tfrecord"])
    b = _Source("textline", ["b.csv"], 0, ",", (("<f4", 0.0),))
    with pytest.raises(NotImplementedError, match="incompatible"):
        _union_sources(a, b)
    # same kind, different delimiter: still incompatible
    c = _Source("textline", ["c.csv"], 0, ";", (("<f4", 0.0),))
    with pytest.raises(NotImplementedError, match="incompatible"):
        _union_sources(b, c)
    # same config: files union
    d = _Source("textline", ["d.csv"], 0, ",", (("<f4", 0.0),))
    u = _union_sources(b, d)
    assert u.files == ["b.csv", "d.csv"] and u.kind == "textline"


def test_fixedlen_partial_tail_warns_and_drops(tmp_path, caplog):
    """TF's FixedLengthRecordReader drops a partial trailing record;
    ours must do the same (with a warning), not raise."""
    import logging

    from bigdl_tpu.utils.tf_session import _Source

    p = str(tmp_path / "t.bin")
    with open(p, "wb") as f:
        f.write(bytes(range(10)) + b"\x01\x02\x03")  # 2.x records of 4
    src = _Source("fixedlen", [p], 0, "", (4, 0))
    sess = TFTrainingSession([])
    comps = [((1, ()), np.uint8, [],
              [lambda v: np.frombuffer(bytes(v), np.uint8)])]
    with caplog.at_level(logging.WARNING, logger="bigdl_tpu"):
        rows = sess._fixedlen_rows(src, comps)
    assert len(rows) == 3  # 13 bytes -> 3 whole records, 1 byte dropped
    assert any("trailing bytes" in r.message for r in caplog.records)
