"""ModelValidator example — the reference's interop acceptance harness
(``example/loadmodel/ModelValidator.scala:44``): one model saved through
every serialization format must report identical Top-1/Top-5 over the
same validation folder."""

import os
import sys

import numpy as np
import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import bigdl_tpu.nn as nn
from examples.model_validator import (load_model, load_validation_samples,
                                      main, validate)


@pytest.fixture(scope="module")
def trained_cnn():
    from bigdl_tpu.utils.rng import RNG

    RNG.set_seed(8)
    return nn.Sequential(
        nn.SpatialConvolution(3, 4, 3, 3, 1, 1, -1, -1).set_name("conv1"),
        nn.ReLU(),
        nn.SpatialMaxPooling(2, 2, 2, 2),
        nn.Reshape([4 * 4 * 4]),
        nn.Linear(4 * 4 * 4, 8).set_name("fc"),
        nn.SoftMax(),  # Caffe has no LogSoftmax layer; argmax order is equal
    ).evaluate()


@pytest.fixture(scope="module")
def val_folder(tmp_path_factory):
    """Class-subdir validation tree of .npy features (8 classes x 4)."""
    root = tmp_path_factory.mktemp("val")
    rng = np.random.RandomState(0)
    for c in range(8):
        d = root / f"class_{c}"
        d.mkdir()
        for i in range(4):
            np.save(d / f"{i}.npy",
                    rng.randn(3, 8, 8).astype(np.float32))
    return str(root)


def _save_all_formats(model, tmpdir):
    from bigdl_tpu.utils.caffe_persister import save_caffe
    from bigdl_tpu.utils.serializer import save_module
    from bigdl_tpu.utils.tf_graph import save_graphdef
    from bigdl_tpu.utils.torch_file import save_torch

    paths = {}
    btpu = os.path.join(tmpdir, "m.btpu")
    save_module(model, btpu)
    paths["bigdl"] = dict(model_path=btpu)

    proto = os.path.join(tmpdir, "m.prototxt")
    weights = os.path.join(tmpdir, "m.caffemodel")
    save_caffe(model, proto, weights, input_shapes=(1, 3, 8, 8))
    paths["caffe"] = dict(model_path=weights, caffe_def_path=proto)

    t7 = os.path.join(tmpdir, "m.t7")
    save_torch(model, t7)
    paths["torch"] = dict(model_path=t7)

    pb = os.path.join(tmpdir, "m.pb")
    outs = save_graphdef(model, pb, input_name="input")
    paths["tf"] = dict(model_path=pb, tf_input="input", tf_output=outs[0])
    return paths


def test_all_four_formats_agree(trained_cnn, val_folder, tmp_path):
    samples = load_validation_samples(val_folder)
    assert len(samples) == 32
    fmts = _save_all_formats(trained_cnn, str(tmp_path))
    scores = {}
    for fmt, kw in fmts.items():
        model = load_model(fmt, **kw)
        scores[fmt] = validate(model, samples, batch_size=16)
        assert set(scores[fmt]) == {"Top1Accuracy", "Top5Accuracy"}
    ref = scores["bigdl"]
    for fmt in ("caffe", "torch", "tf"):
        for k in ref:
            assert scores[fmt][k] == pytest.approx(ref[k], abs=1e-6), (fmt, k)
    # 8 balanced random classes: top-5 must beat top-1 on any real model
    assert ref["Top5Accuracy"] >= ref["Top1Accuracy"]


def test_cli_end_to_end_npz(trained_cnn, tmp_path, capsys):
    """The CLI path over an .npz validation file."""
    rng = np.random.RandomState(1)
    x = rng.randn(16, 3, 8, 8).astype(np.float32)
    y = rng.randint(0, 8, 16)
    npz = str(tmp_path / "val.npz")
    np.savez(npz, x=x, y=y)
    fmts = _save_all_formats(trained_cnn, str(tmp_path))
    scores = main(["-t", "bigdl", "--modelPath", fmts["bigdl"]["model_path"],
                   "-f", npz, "-b", "8"])
    out = capsys.readouterr().out
    assert "Top1Accuracy" in out and "Top5Accuracy" in out
    assert 0.0 <= scores["Top1Accuracy"] <= 1.0


def test_quantized_validation(trained_cnn, val_folder, tmp_path):
    """--quantize evaluates the int8 model; Top-1 stays within a few
    points of float (bigquant acceptance bar)."""
    samples = load_validation_samples(val_folder)
    fmts = _save_all_formats(trained_cnn, str(tmp_path))
    model = load_model("bigdl", **fmts["bigdl"])
    float_scores = validate(model, samples, batch_size=16)
    from bigdl_tpu.nn.quantized import quantize

    q_scores = validate(quantize(load_model("bigdl", **fmts["bigdl"])),
                        samples, batch_size=16)
    assert abs(q_scores["Top1Accuracy"]
               - float_scores["Top1Accuracy"]) <= 0.1


def test_mean_file_subtraction(trained_cnn, val_folder, tmp_path):
    mean = np.full((3, 8, 8), 0.5, np.float32)
    mean_path = str(tmp_path / "mean.npy")
    np.save(mean_path, mean)
    plain = load_validation_samples(val_folder)
    shifted = load_validation_samples(val_folder, mean_file=mean_path)
    np.testing.assert_allclose(np.asarray(shifted[0].feature) + 0.5,
                               np.asarray(plain[0].feature), rtol=1e-6)
