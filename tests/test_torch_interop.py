"""Torch interop: oracle comparisons of imported/exported module trees
(the analogue of the reference's Torch-oracle specs, ``torch/TH.scala``)."""

import numpy as np
import pytest

torch = pytest.importorskip("torch")
import torch.nn as tnn  # noqa: E402

from bigdl_tpu.utils.torch_interop import from_torch, to_torch  # noqa: E402


def _assert_matches(tmod, x, rtol=1e-4, atol=1e-5):
    tmod = tmod.eval()
    with torch.no_grad():
        expected = tmod(torch.from_numpy(x)).numpy()
    m = from_torch(tmod).evaluate()
    got = np.asarray(m.forward(x))
    np.testing.assert_allclose(got, expected, rtol=rtol, atol=atol)
    return m


def test_import_mlp():
    torch.manual_seed(0)
    tmod = tnn.Sequential(tnn.Linear(8, 16), tnn.ReLU(), tnn.Linear(16, 4),
                          tnn.LogSoftmax(dim=-1))
    x = np.random.RandomState(0).randn(5, 8).astype(np.float32)
    _assert_matches(tmod, x)


def test_import_cnn():
    torch.manual_seed(1)
    tmod = tnn.Sequential(
        tnn.Conv2d(3, 8, 3, stride=1, padding=1),
        tnn.BatchNorm2d(8),
        tnn.ReLU(),
        tnn.MaxPool2d(2, 2),
        tnn.Conv2d(8, 16, 3, padding=1, groups=2),
        tnn.AvgPool2d(2, 2),
        tnn.Flatten(),
        tnn.Linear(16 * 2 * 2, 10),
    )
    # populate BN running stats with a training pass
    tmod.train()
    with torch.no_grad():
        tmod(torch.randn(8, 3, 8, 8))
    x = np.random.RandomState(1).randn(4, 3, 8, 8).astype(np.float32)
    _assert_matches(tmod, x)


def test_import_activations_embedding():
    torch.manual_seed(2)
    for act in [tnn.Sigmoid(), tnn.Tanh(), tnn.ELU(0.7), tnn.LeakyReLU(0.1),
                tnn.ReLU6(), tnn.Softmax(dim=-1)]:
        tmod = tnn.Sequential(tnn.Linear(6, 6), act)
        x = np.random.RandomState(3).randn(3, 6).astype(np.float32)
        _assert_matches(tmod, x)

    emb = tnn.Embedding(20, 8)
    m = from_torch(emb)
    idx = np.array([[1, 5, 19], [0, 2, 3]])
    with torch.no_grad():
        expected = emb(torch.from_numpy(idx)).numpy()
    np.testing.assert_allclose(np.asarray(m.forward(idx)), expected,
                               rtol=1e-6)


def test_import_transposed_and_dilated_conv():
    torch.manual_seed(3)
    tmod = tnn.Sequential(tnn.ConvTranspose2d(4, 6, 3, stride=2, padding=1,
                                              output_padding=1))
    x = np.random.RandomState(4).randn(2, 4, 5, 5).astype(np.float32)
    _assert_matches(tmod, x)

    tmod = tnn.Sequential(tnn.Conv2d(3, 6, 3, padding=2, dilation=2))
    x = np.random.RandomState(5).randn(2, 3, 9, 9).astype(np.float32)
    _assert_matches(tmod, x)


def test_import_layernorm():
    torch.manual_seed(4)
    tmod = tnn.Sequential(tnn.Linear(12, 12), tnn.LayerNorm(12))
    x = np.random.RandomState(6).randn(4, 12).astype(np.float32)
    _assert_matches(tmod, x)


def test_export_roundtrip():
    import bigdl_tpu.nn as nn
    from bigdl_tpu.utils.rng import RNG

    RNG.set_seed(5)
    model = nn.Sequential(
        nn.SpatialConvolution(3, 6, 3, 3, 1, 1, 1, 1),
        nn.SpatialBatchNormalization(6),
        nn.ReLU(),
        nn.SpatialMaxPooling(2, 2),
        nn.InferReshape([0, -1]),
        nn.Linear(6 * 4 * 4, 5),
        nn.LogSoftMax(),
    ).evaluate()
    x = np.random.RandomState(7).randn(2, 3, 8, 8).astype(np.float32)
    expected = np.asarray(model.forward(x))
    tmod = to_torch(model).eval()
    with torch.no_grad():
        got = tmod(torch.from_numpy(x)).numpy()
    np.testing.assert_allclose(got, expected, rtol=1e-4, atol=1e-5)


def test_import_no_bias_and_edge_cases():
    torch.manual_seed(5)
    # bias=False variants must not leak random biases
    tmod = tnn.Sequential(tnn.Conv2d(3, 6, 3, padding=2, dilation=2,
                                     bias=False))
    x = np.random.RandomState(8).randn(2, 3, 9, 9).astype(np.float32)
    _assert_matches(tmod, x)
    tmod = tnn.Sequential(tnn.ConvTranspose2d(3, 6, 3, stride=2, padding=1,
                                              output_padding=1, bias=False))
    x = np.random.RandomState(9).randn(2, 3, 5, 5).astype(np.float32)
    _assert_matches(tmod, x)
    # string padding
    tmod = tnn.Sequential(tnn.Conv2d(3, 4, 3, padding="same"))
    x = np.random.RandomState(10).randn(2, 3, 8, 8).astype(np.float32)
    _assert_matches(tmod, x)
    # ceil-mode avg pool shape parity
    tmod = tnn.Sequential(tnn.AvgPool2d(2, 2, ceil_mode=True))
    x = np.random.RandomState(11).randn(1, 3, 5, 5).astype(np.float32)
    _assert_matches(tmod, x)
    # softmax over last dim of 3-D input
    tmod = tnn.Sequential(tnn.Softmax(dim=-1))
    x = np.random.RandomState(12).randn(2, 4, 5).astype(np.float32)
    _assert_matches(tmod, x)


def test_import_unsupported_configs_raise():
    with pytest.raises(NotImplementedError):
        from_torch(tnn.MaxPool2d(3, 1, dilation=2))
    with pytest.raises(NotImplementedError):
        from_torch(tnn.LayerNorm((4, 5)))
    with pytest.raises(NotImplementedError):
        from_torch(tnn.ConvTranspose2d(4, 4, 3, groups=2))
    with pytest.raises(NotImplementedError):
        from_torch(tnn.Conv2d(4, 4, 3, dilation=2, groups=2))
