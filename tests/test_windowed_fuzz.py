"""Randomized-config oracle fuzz for the windowed ops — pooling
(floor/ceil, asymmetric overflow padding, count_include_pad) and
convolution (padding/stride/group combinations) against PyTorch over
many sampled shapes.  These are the paths where off-by-one window
arithmetic historically hides (the reference dedicates whole spec
families to them, ``SpatialMaxPoolingSpec``/``SpatialConvolutionSpec``);
the fixed-case oracles in test_layers_oracle.py pin the known cases,
this sweep walks the config space."""

import numpy as np
import pytest
import torch
import torch.nn.functional as F

import bigdl_tpu.nn as nn


def _c(ours, theirs, rtol=1e-4, atol=1e-5):
    np.testing.assert_allclose(np.asarray(ours), np.asarray(theirs),
                               rtol=rtol, atol=atol)


def _pool_cases(n, seed):
    rng = np.random.RandomState(seed)
    for _ in range(n):
        k = int(rng.randint(1, 5))
        d = int(rng.randint(1, k + 2))
        p = int(rng.randint(0, (k + 1) // 2 + 1))
        p = min(p, k // 2)  # torch requires pad <= kernel/2
        h = int(rng.randint(max(k - p, 2), 14))
        ceil = bool(rng.randint(0, 2))
        if ceil and p == 0 and (h - k) % d:
            # reference divergence from torch: BigDL clips the ceil-mode
            # last window ONLY when padding is nonzero
            # (nn/Utils.scala:346-349), torch clips always — we follow
            # the reference; pinned in
            # test_ceil_no_pad_follows_reference_not_torch
            continue
        yield k, d, p, h, ceil


@pytest.mark.parametrize("seed", [0, 1])
def test_maxpool_fuzz_vs_torch(seed):
    rng = np.random.RandomState(100 + seed)
    for k, d, p, h, ceil in _pool_cases(25, seed):
        x = rng.randn(2, 3, h, h).astype(np.float32)
        want = F.max_pool2d(torch.tensor(x), k, d, p, ceil_mode=ceil)
        if 0 in want.shape:
            continue
        layer = nn.SpatialMaxPooling(k, k, d, d, p, p)
        if ceil:
            layer.ceil()
        got = layer.forward(x)
        _c(got, want.numpy()), (k, d, p, h, ceil)


@pytest.mark.parametrize("seed", [0, 1])
def test_avgpool_fuzz_vs_torch(seed):
    rng = np.random.RandomState(200 + seed)
    for k, d, p, h, include in _pool_cases(25, seed):
        x = rng.randn(2, 3, h, h).astype(np.float32)
        want = F.avg_pool2d(torch.tensor(x), k, d, p,
                            ceil_mode=False, count_include_pad=include)
        if 0 in want.shape:
            continue
        got = nn.SpatialAveragePooling(
            k, k, d, d, p, p, count_include_pad=include).forward(x)
        _c(got, want.numpy()), (k, d, p, h, include)


def test_ceil_no_pad_follows_reference_not_torch():
    """k=1, d=2, h=2, p=0, ceil: the reference's output-size rule
    (``nn/Utils.scala:338-349``) yields ceil((h-k)/d)+1 = 2 because its
    last-window clip is gated on nonzero padding; torch clips always and
    yields 1.  We implement the REFERENCE semantics."""
    x = np.arange(2 * 1 * 2 * 2, dtype=np.float32).reshape(2, 1, 2, 2)
    got = np.asarray(nn.SpatialMaxPooling(1, 1, 2, 2).ceil().forward(x))
    assert got.shape == (2, 1, 2, 2)  # reference formula, not torch's 1x1
    ref = F.max_pool2d(torch.tensor(x), 1, 2, 0, ceil_mode=True)
    assert tuple(ref.shape) == (2, 1, 1, 1)
    # where the grids overlap the values agree
    _c(got[:, :, :1, :1], ref.numpy())


def test_conv_fuzz_vs_torch():
    rng = np.random.RandomState(7)
    for _ in range(20):
        k = int(rng.randint(1, 5))
        s = int(rng.randint(1, 3))
        p = int(rng.randint(0, 3))
        g = int(rng.choice([1, 1, 2]))
        cin = int(rng.randint(1, 4)) * g
        cout = int(rng.randint(1, 4)) * g
        h = int(rng.randint(k + 1, 12))
        x = rng.randn(2, cin, h, h).astype(np.float32)
        m = nn.SpatialConvolution(cin, cout, k, k, s, s, p, p, n_group=g)
        w = np.asarray(m.weight)
        b = np.asarray(m.bias)
        want = F.conv2d(torch.tensor(x), torch.tensor(w), torch.tensor(b),
                        stride=s, padding=p, groups=g)
        got = m.evaluate().forward(x)
        _c(got, want.numpy(), rtol=2e-4, atol=2e-4), (k, s, p, g, cin, cout, h)


def test_conv_backward_fuzz_vs_torch():
    """Gradients too: input + weight grads across sampled configs (the
    autodiff path through conv_general_dilated's transpose)."""
    import jax
    import jax.numpy as jnp

    from bigdl_tpu.nn.module import functional_call, state_dict

    rng = np.random.RandomState(8)
    for _ in range(8):
        k = int(rng.randint(1, 4))
        s = int(rng.randint(1, 3))
        p = int(rng.randint(0, 2))
        cin, cout = int(rng.randint(1, 4)), int(rng.randint(1, 4))
        h = int(rng.randint(k + 1, 10))
        x = rng.randn(2, cin, h, h).astype(np.float32)
        m = nn.SpatialConvolution(cin, cout, k, k, s, s, p, p)
        params = state_dict(m, kind="param")

        def loss(p_, x_):
            out, _ = functional_call(m, p_, x_)
            return jnp.sum(out ** 2)

        gp, gx = jax.grad(loss, argnums=(0, 1))(params, jnp.asarray(x))

        tx = torch.tensor(x, requires_grad=True)
        tw = torch.tensor(np.asarray(m.weight), requires_grad=True)
        tb = torch.tensor(np.asarray(m.bias), requires_grad=True)
        tout = F.conv2d(tx, tw, tb, stride=s, padding=p)
        tout.pow(2).sum().backward()
        _c(gx, tx.grad.numpy(), rtol=2e-3, atol=2e-3)
        _c(gp["weight"], tw.grad.numpy(), rtol=2e-3, atol=2e-3)
        _c(gp["bias"], tb.grad.numpy(), rtol=2e-3, atol=2e-3)


@pytest.mark.parametrize("seed", [0, 1])
def test_dilated_conv_fuzz_vs_torch(seed):
    """Atrous conv over sampled (kernel, stride, pad, dilation) configs
    — forward AND input/weight gradients (the effective-window
    arithmetic k_eff = (k-1)*dil + 1 is where off-by-ones hide)."""
    rng = np.random.RandomState(300 + seed)
    for _ in range(12):
        # RECTANGULAR everywhere: kw!=kh, per-axis stride/pad/dilation
        # and h!=w inputs are what catch transposed-axis arithmetic
        kw, kh = int(rng.randint(1, 4)), int(rng.randint(1, 4))
        sw, sh = int(rng.randint(1, 3)), int(rng.randint(1, 3))
        dw_, dh_ = int(rng.randint(1, 4)), int(rng.randint(1, 4))
        kweff, kheff = (kw - 1) * dw_ + 1, (kh - 1) * dh_ + 1
        pw, ph = int(rng.randint(0, kweff)), int(rng.randint(0, kheff))
        w_in = int(rng.randint(kweff + 1, kweff + 8))
        h_in = int(rng.randint(kheff + 1, kheff + 8))
        cin, cout = int(rng.randint(1, 4)), int(rng.randint(1, 4))
        x = rng.randn(2, cin, h_in, w_in).astype(np.float32)
        layer = nn.SpatialDilatedConvolution(
            cin, cout, kw, kh, sw, sh, pw, ph, dw_, dh_)
        w = np.asarray(layer.weight)
        b = np.asarray(layer.bias)
        tx = torch.tensor(x, requires_grad=True)
        tw = torch.tensor(w, requires_grad=True)
        tb = torch.tensor(b, requires_grad=True)
        want = F.conv2d(tx, tw, tb, stride=(sh, sw), padding=(ph, pw),
                        dilation=(dh_, dw_))
        got = layer.forward(x)
        _c(got, want.detach().numpy())
        # gradients through the same config
        g = rng.randn(*want.shape).astype(np.float32)
        want.backward(torch.tensor(g))
        layer.zero_grad_parameters()
        gin = layer.backward(x, g)
        _c(gin, tx.grad.numpy(), rtol=1e-3, atol=1e-4)
        _c(layer._grads["weight"], tw.grad.numpy(), rtol=1e-3, atol=1e-4)
        _c(layer._grads["bias"], tb.grad.numpy(), rtol=1e-3, atol=1e-4)


@pytest.mark.parametrize("seed", [0, 1])
def test_full_conv_fuzz_vs_torch(seed):
    """Transposed conv over sampled (kernel, stride, pad, adj/out-pad,
    group) configs vs torch ConvTranspose2d — forward + gradients."""
    rng = np.random.RandomState(400 + seed)
    for _ in range(12):
        kw, kh = int(rng.randint(1, 4)), int(rng.randint(1, 4))
        sw, sh = int(rng.randint(1, 3)), int(rng.randint(1, 3))
        pw, ph = int(rng.randint(0, kw)), int(rng.randint(0, kh))
        adjw = int(rng.randint(0, sw))  # torch: output_padding < stride
        adjh = int(rng.randint(0, sh))
        grp = int(rng.choice([1, 2]))
        cin, cout = 2 * grp, 2 * grp
        h_in, w_in = int(rng.randint(3, 9)), int(rng.randint(3, 9))
        x = rng.randn(2, cin, h_in, w_in).astype(np.float32)
        layer = nn.SpatialFullConvolution(
            cin, cout, kw, kh, sw, sh, pw, ph, adjw, adjh, n_group=grp)
        w = np.asarray(layer.weight)
        b = np.asarray(layer.bias)
        tx = torch.tensor(x, requires_grad=True)
        # torch weight layout (in, out/groups, kh, kw) matches ours
        tw = torch.tensor(w, requires_grad=True)
        tb = torch.tensor(b, requires_grad=True)
        want = F.conv_transpose2d(tx, tw, tb, stride=(sh, sw),
                                  padding=(ph, pw),
                                  output_padding=(adjh, adjw),
                                  groups=grp)
        got = layer.forward(x)
        _c(got, want.detach().numpy(), rtol=1e-3, atol=1e-4)
        g = rng.randn(*want.shape).astype(np.float32)
        want.backward(torch.tensor(g))
        layer.zero_grad_parameters()
        gin = layer.backward(x, g)
        _c(gin, tx.grad.numpy(), rtol=1e-3, atol=1e-4)
        _c(layer._grads["weight"], tw.grad.numpy(), rtol=1e-3, atol=1e-4)
        _c(layer._grads["bias"], tb.grad.numpy(), rtol=1e-3, atol=1e-4)


@pytest.mark.parametrize("seed", [0, 1])
def test_batchnorm_trainmode_fuzz_vs_torch(seed):
    """Train-mode BN over sampled (eps, momentum, affine, rank) configs:
    outputs AND the running-stat update rule vs torch (BigDL momentum =
    torch momentum; unbiased-variance bookkeeping is where
    implementations quietly differ)."""
    rng = np.random.RandomState(500 + seed)
    for _ in range(10):
        c = int(rng.randint(1, 6))
        eps = float(10.0 ** rng.uniform(-5, -2))
        mom = float(rng.uniform(0.05, 0.5))
        affine = bool(rng.randint(0, 2))
        spatial = bool(rng.randint(0, 2))
        if spatial:
            x = rng.randn(3, c, 4, 5).astype(np.float32)
            ours = nn.SpatialBatchNormalization(c, eps, mom, affine=affine)
            theirs = torch.nn.BatchNorm2d(c, eps=eps, momentum=mom,
                                          affine=affine)
        else:
            x = rng.randn(8, c).astype(np.float32)
            ours = nn.BatchNormalization(c, eps, mom, affine=affine)
            theirs = torch.nn.BatchNorm1d(c, eps=eps, momentum=mom,
                                          affine=affine)
        if affine:
            w = rng.rand(c).astype(np.float32) + 0.5
            b_ = rng.randn(c).astype(np.float32)
            ours.weight, ours.bias = w, b_
            with torch.no_grad():
                theirs.weight.copy_(torch.tensor(w))
                theirs.bias.copy_(torch.tensor(b_))
        theirs.train()
        for it in range(2):  # two steps: the update rule must COMPOSE
            want = theirs(torch.tensor(x))
            got = ours.forward(x)
            _c(got, want.detach().numpy(), rtol=1e-3, atol=1e-4)
        _c(ours.running_mean, theirs.running_mean.numpy(),
           rtol=1e-4, atol=1e-5)
        _c(ours.running_var, theirs.running_var.numpy(),
           rtol=1e-4, atol=1e-5)
        # eval mode uses the accumulated stats
        theirs.eval()
        ours.evaluate()
        _c(ours.forward(x), theirs(torch.tensor(x)).detach().numpy(),
           rtol=1e-3, atol=1e-4)


@pytest.mark.parametrize("seed", [0, 1])
def test_recurrent_shape_fuzz_vs_torch(seed):
    """LSTM/GRU through the lax.scan Recurrent over sampled
    (batch, seq, input, hidden) shapes — fwd + input grads vs torch.
    The fixed oracles pin one shape each; hidden==input, seq==1, and
    wide-vs-tall shapes each stress different scan/broadcast paths."""
    import bigdl_tpu.nn as bnn
    import jax.numpy as jnp

    rng = np.random.RandomState(700 + seed)
    for _ in range(5):
        b = int(rng.randint(1, 5))
        s = int(rng.randint(1, 9))
        inp = int(rng.randint(1, 7))
        hid = int(rng.randint(1, 8))
        x = rng.randn(b, s, inp).astype(np.float32)
        gy = rng.randn(b, s, hid).astype(np.float32)

        # LSTM
        cell = bnn.LSTM(inp, hid)
        rec = bnn.Recurrent(cell)
        from tests.test_layers_oracle import sync_lstm_to_torch

        tl = torch.nn.LSTM(inp, hid, batch_first=True)
        sync_lstm_to_torch(cell, tl)
        out = rec.forward(jnp.asarray(x))
        tx = torch.tensor(x, requires_grad=True)
        ref, _ = tl(tx)
        _c(out, ref.detach().numpy(), rtol=1e-3, atol=1e-4)
        gx = rec.backward(jnp.asarray(x), jnp.asarray(gy))
        ref.backward(torch.tensor(gy))
        _c(gx, tx.grad.numpy(), rtol=1e-3, atol=1e-4)

        # GRU
        cell = bnn.GRU(inp, hid)
        rec = bnn.Recurrent(cell)
        from tests.test_layers_oracle import sync_gru_to_torch

        tg = torch.nn.GRU(inp, hid, batch_first=True)
        sync_gru_to_torch(cell, tg)
        out = rec.forward(jnp.asarray(x))
        tx = torch.tensor(x, requires_grad=True)
        ref, _ = tg(tx)
        _c(out, ref.detach().numpy(), rtol=1e-3, atol=1e-4)
        gx = rec.backward(jnp.asarray(x), jnp.asarray(gy))
        ref.backward(torch.tensor(gy))
        _c(gx, tx.grad.numpy(), rtol=1e-3, atol=1e-4)
